"""ShardedCluster: N consensus groups on one sim clock, one verifier fleet.

Horizontal sharding of the consensus plane: each group is a full
:class:`~consensus_tpu.testing.app.Cluster` (n replicas, its own
SimNetwork, its own WALs, its own ledger) and tenants are partitioned
across groups by the rendezvous directory
(:class:`~consensus_tpu.groups.directory.GroupDirectory`).  Three things
are deliberately SHARED:

* **The clock** — every group runs on ONE :class:`SimScheduler`, so
  cross-group facts ("group A committed before group B aborted") are
  totally ordered and the chaos engine can interleave per-group faults
  deterministically.  Each group keeps its own SimNetwork: a partition in
  group A cannot leak into group B.
* **The cross-group witness** — one :class:`CrossGroupRegistry` receives
  every group's 2PC participant transitions; each group's
  :class:`~consensus_tpu.testing.invariants.InvariantMonitor` mirrors its
  atomicity violations at every delivery (``attach_cross_group``).
* **The verifier fleet** — replicas of ALL groups verify through one
  multi-tenant wave former.  The deployment win this harness measures:
  with the group id part of the admission identity, one fused device
  launch serves quorum certs from several groups at once
  (:class:`~consensus_tpu.models.engine.FairShareWaveFormer` — SAFETY §7
  holds because waves are formed from whole submissions, so no cert ever
  mixes engines).

**Determinism.** Group i's consensus run is byte-identical to a
standalone ``Cluster`` built with the same derived seed: the shared
scheduler only interleaves events of different groups, never reorders one
group's own events, and SimNetworks draw from per-group RNGs.  The fleet
accounting (:meth:`ShardedCluster.drive_shared_fleet`) REPLAYS the
committed cert workload through the shared wave former on one OS thread
per group — the deployment shape, where each group's replicas are
separate processes hammering the same sidecars — so wave composition can
never perturb sim-time behavior: ledgers first, launches second.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Optional, Sequence

from consensus_tpu.groups.directory import GroupDirectory
from consensus_tpu.groups.router import GroupRouter
from consensus_tpu.groups.twopc import CrossGroupRegistry, TwoPhaseCoordinator, TwoPhaseParticipant
from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.testing.app import Cluster, make_request
from consensus_tpu.testing.invariants import InvariantMonitor

#: Seed-derivation tag: group i's Cluster seed under shard seed s.
_GROUP_SEED_TAG = 0x6709


def group_seed(seed: int, index: int) -> int:
    """Group ``index``'s private Cluster seed — a pure function of the
    shard seed, so a standalone Cluster with this seed replays the group
    byte-for-byte."""
    return seed ^ (_GROUP_SEED_TAG + 7919 * index)


class _CountingEngine:
    """Wraps a verify engine, recording every launch's signature count —
    the fleet-accounting gates assert on launches, not wall time."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self.launch_sizes: list[int] = []

    @property
    def launches(self) -> int:
        return len(self.launch_sizes)

    @property
    def total_signatures(self) -> int:
        return sum(self.launch_sizes)

    def verify_batch(self, messages, signatures, public_keys):
        self.launch_sizes.append(len(messages))
        return self._inner.verify_batch(messages, signatures, public_keys)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ShardedCluster:
    """N consensus groups over one scheduler, registry, and fleet."""

    def __init__(
        self,
        n_groups: int = 2,
        *,
        n: int = 4,
        seed: int = 0,
        config_tweaks: Optional[dict] = None,
        durability_window: float = 0.0,
        sync_mode: str = "wire",
        metrics=None,
        monitors: bool = True,
        check_durability: bool = True,
    ) -> None:
        if n_groups < 1:
            raise ValueError("need at least one group")
        self.seed = seed
        self.n = n
        self.scheduler = SimScheduler()
        self.directory = GroupDirectory.of_size(n_groups)
        #: Optional full Metrics facade; the groups bundle books routing,
        #: 2PC, and shared-fleet wave composition.
        self.metrics = metrics
        gm = metrics.groups if metrics is not None else None
        self.router = GroupRouter(self.directory, metrics=gm)
        self.registry = CrossGroupRegistry(now=self.scheduler.now, metrics=gm)
        self.groups: dict[str, Cluster] = {}
        self.participants: dict[str, TwoPhaseParticipant] = {}
        self.monitors: dict[str, InvariantMonitor] = {}
        for gi, gid in enumerate(self.directory.groups()):
            cluster = Cluster(
                n,
                seed=group_seed(seed, gi),
                config_tweaks=config_tweaks,
                durability_window=durability_window,
                sync_mode=sync_mode,
                scheduler=self.scheduler,
            )
            participant = TwoPhaseParticipant(gid, registry=self.registry)
            # Hook order matters: the participant updates the registry
            # FIRST, then the monitor (appended below) judges the updated
            # cross-group state at the very same delivery.
            cluster.delivery_hooks.append(participant.on_delivery)
            self.groups[gid] = cluster
            self.participants[gid] = participant
        if monitors:
            for gid, cluster in self.groups.items():
                monitor = InvariantMonitor(
                    cluster, check_durability=check_durability
                )
                monitor.attach_cross_group(self.registry, gid)
                self.monitors[gid] = monitor
        self.coordinator = TwoPhaseCoordinator(self.groups, self.registry)
        self._rids: dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for cluster in self.groups.values():
            cluster.start()

    def group_ids(self) -> tuple:
        return self.directory.groups()

    # -- driving -------------------------------------------------------------

    def submit(self, tenant: str, payload: bytes = b"") -> str:
        """Admit-then-route: submit one request for ``tenant`` to every
        replica of its owning group; returns the group id."""
        group = self.router.route(tenant)
        rid = self._rids.get(tenant, 0) + 1
        self._rids[tenant] = rid
        self.groups[group].submit_to_all(make_request(tenant, rid, payload))
        return group

    def heights(self) -> dict:
        """Per-group ledger height (minimum across running replicas)."""
        out = {}
        for gid, cluster in self.groups.items():
            running = [nd for nd in cluster.nodes.values() if nd.running]
            out[gid] = min((len(nd.app.ledger) for nd in running), default=0)
        return out

    def run_until(self, predicate: Callable[[], bool], *, max_time: float = 600.0) -> bool:
        return self.scheduler.run_until(predicate, max_time=max_time)

    def run_until_heights(self, expected, *, max_time: float = 600.0) -> bool:
        """Advance until every group's ledger reaches ``expected`` (an int
        for all groups, or a {group id: height} map)."""
        if isinstance(expected, int):
            expected = {gid: expected for gid in self.groups}

        def done() -> bool:
            h = self.heights()
            return all(h[g] >= want for g, want in expected.items())

        return self.scheduler.run_until(done, max_time=max_time)

    # -- observation ---------------------------------------------------------

    def ledger_digests(self) -> dict:
        """{group id: {node id: (proposal digests...)}} — the byte-identity
        artifact the sharded-vs-private gates compare."""
        return {
            gid: {
                nid: tuple(d.proposal.digest() for d in node.app.ledger)
                for nid, node in sorted(cluster.nodes.items())
            }
            for gid, cluster in sorted(self.groups.items())
        }

    def health_fields(self) -> dict:
        """Obs-plane health fields for the shard as a whole: feeds the
        ``cross_group_stall`` detector.  The age key is present only while
        some transaction is unresolved, so the detector's latch clears the
        moment everything resolves."""
        fields = {}
        age = self.registry.oldest_unresolved_age()
        if age is not None:
            fields["groups_twopc_oldest_age"] = age
        return fields

    def assert_clean(self) -> None:
        """Every group's monitor clean AND cross-group atomicity holds."""
        for monitor in self.monitors.values():
            monitor.assert_clean()
        self.registry.assert_atomic()

    # -- shared-fleet accounting --------------------------------------------
    #
    # The sharding thesis, measured: identical committed cert work, driven
    # once through ONE shared wave former (group id in the admission
    # identity) and once through per-group private formers.  Shared must
    # book strictly fewer, larger launches — that is the fleet the groups
    # are paying for.

    def _cert_signer(self, gid: str, signer_id: int):
        from consensus_tpu.models import Ed25519Signer

        return Ed25519Signer(
            signer_id,
            hashlib.sha512(
                b"ctpu/groups-cert-key/%d/%s/%d"
                % (self.seed, gid.encode(), signer_id)
            ).digest()[:32],
        )

    def cert_workload(self) -> dict:
        """Per-group verify workload, derived from the committed ledgers:
        for every delivered decision, one batch re-expressing its quorum
        cert as real Ed25519 signatures (deterministic keys from the shard
        seed).  Identical ledgers -> identical workload, so the shared and
        private drives verify the exact same bytes."""
        workload: dict[str, list] = {}
        for gid, cluster in sorted(self.groups.items()):
            signers = {
                nid: self._cert_signer(gid, nid) for nid in cluster.nodes
            }
            batches = []
            ledger = cluster.nodes[1].app.ledger
            for decision in ledger:
                digest = decision.proposal.digest().encode()
                messages, signatures, keys = [], [], []
                for sig in decision.signatures:
                    signer = signers[sig.id]
                    msg = b"ctpu/groups-cert|%s|%s|%d" % (
                        gid.encode(), digest, sig.id,
                    )
                    messages.append(msg)
                    signatures.append(signer.sign_raw(msg))
                    keys.append(signer.public_bytes)
                if messages:
                    batches.append((messages, signatures, keys))
            workload[gid] = batches
        return workload

    def drive_shared_fleet(
        self,
        *,
        window: float = 0.05,
        max_wave: int = 8192,
        engine=None,
        workload: Optional[dict] = None,
    ) -> dict:
        """Replay the cert workload through ONE shared wave former, one OS
        thread per group (the deployment shape: each group's replicas are
        separate processes sharing the sidecar fleet).  Returns the launch
        accounting; books ``groups_wave_span`` / multi-group counters when
        a metrics facade is attached."""
        from consensus_tpu.models.engine import FairShareWaveFormer

        if workload is None:
            workload = self.cert_workload()
        counting = _CountingEngine(
            engine if engine is not None else _host_engine()
        )
        gm = self.metrics.groups if self.metrics is not None else None
        group_waves: list[dict] = []
        lock = threading.Lock()

        def on_group_wave(group_counts: dict, total: int) -> None:
            with lock:
                group_waves.append(dict(group_counts))

        former = FairShareWaveFormer(
            counting,
            window=window,
            max_wave=max_wave,
            groups_metrics=gm,
            on_group_wave=on_group_wave,
            name="groups-shared-fleet",
        )
        errors: list[BaseException] = []
        barrier = threading.Barrier(len(workload) or 1)

        def run_group(gid: str, batches) -> None:
            try:
                barrier.wait()
                for messages, signatures, keys in batches:
                    result = former.submit(
                        f"{gid}/certs", messages, signatures, keys, group=gid
                    )
                    if not all(result):
                        raise AssertionError(f"cert verify failed in {gid}")
            except BaseException as exc:  # surfaced after join
                with lock:
                    errors.append(exc)

        threads = [
            threading.Thread(
                target=run_group, args=(gid, batches),
                name=f"fleet-{gid}", daemon=True,
            )
            for gid, batches in sorted(workload.items())
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        former.close()
        if errors:
            raise errors[0]
        return {
            "launches": counting.launches,
            "total_signatures": counting.total_signatures,
            "launch_sizes": tuple(counting.launch_sizes),
            "group_waves": tuple(
                tuple(sorted(w.items())) for w in group_waves
            ),
            "multi_group_launches": sum(
                1 for w in group_waves if len(w) >= 2
            ),
        }

    def drive_private_fleets(
        self,
        *,
        window: float = 0.02,
        max_wave: int = 8192,
        engine_factory: Optional[Callable[[], object]] = None,
        workload: Optional[dict] = None,
    ) -> dict:
        """The baseline: the SAME workload through one PRIVATE wave former
        per group (no cross-group admission identity, no sharing).  Every
        cert batch launches alone — the fleet cost of not sharing."""
        from consensus_tpu.models.engine import FairShareWaveFormer

        if workload is None:
            workload = self.cert_workload()
        factory = engine_factory if engine_factory is not None else _host_engine
        launches = 0
        total = 0
        sizes: list[int] = []
        for gid, batches in sorted(workload.items()):
            counting = _CountingEngine(factory())
            former = FairShareWaveFormer(
                counting, window=window, max_wave=max_wave,
                name=f"groups-private-{gid}",
            )
            try:
                for messages, signatures, keys in batches:
                    result = former.submit(
                        f"{gid}/certs", messages, signatures, keys
                    )
                    if not all(result):
                        raise AssertionError(f"cert verify failed in {gid}")
            finally:
                former.close()
            launches += counting.launches
            total += counting.total_signatures
            sizes.extend(counting.launch_sizes)
        return {
            "launches": launches,
            "total_signatures": total,
            "launch_sizes": tuple(sizes),
        }


def _host_engine():
    from consensus_tpu.models.ed25519 import Ed25519BatchVerifier

    return Ed25519BatchVerifier(min_device_batch=10**9)


__all__ = ["ShardedCluster", "group_seed"]
