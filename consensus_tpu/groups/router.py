"""Admit-then-route: the ingress step that picks a request's owning group.

The :class:`~consensus_tpu.ingress.driver.IngressDriver` admits a request
first (rate limit + dedup — admission is global, not per-group, so a
flooding client cannot escape its budget by hashing into a quiet group)
and THEN asks the router which consensus group owns the tenant.  Routing
is a pure function of the directory, so the driver, every replica, and
every test agree on ownership without coordination.

Each routed request is triple-booked: the pinned ``groups_routed_total``
counter (per-group children via ``with_labels``), a ``groups.route``
trace instant when a tracer is attached, and the router's own per-group
tally (the summary artifact).
"""

from __future__ import annotations

from typing import Optional

from consensus_tpu.groups.directory import GroupDirectory


class GroupRouter:
    """Routes admitted requests to their owning consensus group."""

    def __init__(
        self,
        directory: GroupDirectory,
        *,
        metrics=None,
        tracer=None,
    ) -> None:
        if len(directory) < 1:
            raise ValueError("router needs at least one group")
        self.directory = directory
        self.metrics = metrics
        self.tracer = tracer
        #: group id -> requests routed there (insertion-ordered by first
        #: route; summaries sort it).
        self.routed: dict[str, int] = {}
        if metrics is not None:
            metrics.group_count.set(float(len(directory)))

    def route(self, tenant: str) -> str:
        """The owning group for ``tenant`` (books the route)."""
        group = self.directory.assign(tenant)
        self.routed[group] = self.routed.get(group, 0) + 1
        if self.metrics is not None:
            self.metrics.count_routed.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "groups", "groups.route", tenant=tenant, group=group
            )
        return group

    def counts(self) -> dict:
        """Sorted group -> routed-count map (the summary artifact)."""
        return dict(sorted(self.routed.items()))


__all__ = ["GroupRouter"]
