"""Seeded cross-group chaos: faults composed per-group, judged shard-wide.

The single-group chaos engine (:mod:`consensus_tpu.testing.chaos`) attacks
one cluster; this engine attacks a :class:`ShardedCluster` mid-way through
a cross-group 2PC transaction with a vocabulary scoped PER GROUP — a
partition in group A's SimNetwork never touches group B — plus the one
genuinely cross-group fault: killing the transaction coordinator (a plain
process, kill -9 in deployment terms).

Run shape (fully deterministic on the shared sim clock):

1. **Warm up** every group to its first ordered block.
2. **Start** a cross-group transaction spanning the first two groups
   (prepare submitted to both quorums).
3. **Apply the schedule** — crash/restart/partition/heal/delay inside a
   chosen group, or ``kill_coordinator`` — interleaved with filler
   requests so every group keeps ordering.
4. **Quiesce**: heal every group, restart crashed members, settle.
5. **Resolve**: a live coordinator decides (commit iff both groups
   prepared); a killed one is replaced by presumed-abort recovery over the
   replicated participant states.  The run then waits for BOTH groups to
   reach the same terminal phase.
6. **Verdict**: per-group invariant monitors (which mirror the shared
   :class:`CrossGroupRegistry`'s atomicity check at every delivery) must
   be clean, the transaction must resolve with agreement, and every group
   must make post-heal progress.

``sentinel_one_sided=True`` plants the classic coordinator bug (commit to
one group, abort to the other); :func:`shrink_group_schedule` ddmins a
failing schedule to a minimal action subset, and :func:`format_group_repro`
emits a paste-able reproduction.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from consensus_tpu.groups.cluster import ShardedCluster
from consensus_tpu.groups.twopc import TwoPhaseCoordinator
from consensus_tpu.testing.app import make_request
from consensus_tpu.utils.quorum import compute_quorum

#: The cross-group adversary vocabulary.  Per-group kinds carry a
#: ``group`` arg; ``kill_coordinator`` is shard-wide.
GROUP_CHAOS_KINDS = (
    "kill_coordinator",
    "partition_leader",
    "crash",
    "restart",
    "heal",
    "delay",
)


@dataclasses.dataclass(frozen=True)
class GroupChaosAction:
    """One adversary action at an absolute sim-time (repr is paste-able
    Python, same contract as testing.chaos.ChaosAction)."""

    at: float
    kind: str
    args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class GroupChaosSchedule:
    """A complete cross-group adversary: shard shape + ordered actions."""

    seed: int
    n_groups: int = 2
    n: int = 4
    actions: tuple = ()

    @classmethod
    def generate(
        cls,
        seed: int,
        *,
        n_groups: int = 2,
        n: int = 4,
        steps: int = 8,
        start: float = 10.0,
    ) -> "GroupChaosSchedule":
        """Derive a feasible schedule from ``seed``: cumulative uniform
        (4, 25) gaps, per-group targets, at most ``f`` replicas down per
        group at once, and at most one ``kill_coordinator`` per schedule
        (a process dies once)."""
        if n_groups < 2:
            raise ValueError("cross-group chaos needs at least two groups")
        rng = random.Random(seed)
        gids = [f"group-{i}" for i in range(n_groups)]
        ids = list(range(1, n + 1))
        _, f = compute_quorum(n)
        kinds = list(GROUP_CHAOS_KINDS)
        weights = [1.0, 1.5, 1.5, 1.5, 1.5, 1.5]
        down: dict[str, set] = {g: set() for g in gids}
        killed = False
        t = start
        actions = []
        for _ in range(steps):
            t += rng.uniform(4.0, 25.0)
            kind = rng.choices(kinds, weights)[0]
            gid = rng.choice(gids)
            if kind == "kill_coordinator" and killed:
                kind = "heal"
            if kind == "crash" and len(down[gid]) >= f:
                kind = "restart" if down[gid] else "heal"
            if kind == "restart" and not down[gid]:
                kind = "heal"

            if kind == "kill_coordinator":
                killed = True
                actions.append(GroupChaosAction(at=t, kind="kill_coordinator"))
            elif kind == "partition_leader":
                # Isolate the group's CURRENT view-1 leader (node 1 at
                # boot); the group must view-change around it while the
                # 2PC prepare is in flight.
                actions.append(GroupChaosAction(
                    at=t, kind="partition_leader", args={"group": gid},
                ))
            elif kind == "crash":
                node = rng.choice([i for i in ids if i not in down[gid]])
                down[gid].add(node)
                actions.append(GroupChaosAction(
                    at=t, kind="crash", args={"group": gid, "node": node},
                ))
            elif kind == "restart":
                node = rng.choice(sorted(down[gid]))
                down[gid].discard(node)
                actions.append(GroupChaosAction(
                    at=t, kind="restart", args={"group": gid, "node": node},
                ))
            elif kind == "delay":
                a, b = rng.sample(ids, 2)
                d = round(rng.uniform(0.05, 0.4), 3)
                actions.append(GroupChaosAction(
                    at=t, kind="delay",
                    args={"group": gid, "a": a, "b": b, "d": d},
                ))
            else:  # heal
                actions.append(GroupChaosAction(
                    at=t, kind="heal", args={"group": gid},
                ))
        return cls(seed=seed, n_groups=n_groups, n=n, actions=tuple(actions))


@dataclasses.dataclass
class GroupChaosResult:
    """Outcome of one cross-group run.  ``resolution`` maps the two
    participant groups to their terminal phase; agreement is the verdict."""

    ok: bool
    violation: Optional[object]  # testing.invariants.Violation or None
    event_log: bytes
    ledgers: dict  # group id -> {node id: (digests...)}
    schedule: GroupChaosSchedule
    resolution: dict  # group id -> phase (participant view)
    txid: str
    deliveries: int


class GroupChaosEngine:
    """Executes one :class:`GroupChaosSchedule` to a :class:`GroupChaosResult`."""

    REQUESTS_PER_ACTION = 1
    WARMUP_REQUESTS = 3
    WARMUP_BUDGET = 300.0
    SETTLE_TIME = 60.0
    RESOLVE_BUDGET = 600.0
    LIVENESS_BUDGET = 900.0

    def __init__(
        self,
        schedule: GroupChaosSchedule,
        *,
        config_tweaks: Optional[dict] = None,
        sentinel_one_sided: bool = False,
        metrics=None,
    ) -> None:
        # Same leaner timers the single-group chaos engine runs with.
        from consensus_tpu.testing.chaos import DEFAULT_TWEAKS

        self.schedule = schedule
        self.config_tweaks = dict(
            config_tweaks if config_tweaks is not None else DEFAULT_TWEAKS
        )
        self.sentinel_one_sided = sentinel_one_sided
        self.metrics = metrics
        self.shard: Optional[ShardedCluster] = None
        self._log: list[str] = []
        self._fill = 0

    # -- bookkeeping ---------------------------------------------------------

    def _emit(self, line: str) -> None:
        self._log.append(line)
        for monitor in self.shard.monitors.values():
            monitor.history.append(line)

    def _now(self) -> float:
        return self.shard.scheduler.now()

    def _fill_requests(self, k: int) -> None:
        """Keep every group ordering: k plain requests per group."""
        for gid, cluster in self.shard.groups.items():
            for _ in range(k):
                self._fill += 1
                cluster.submit_to_all(
                    make_request(f"fill-{gid}", self._fill)
                )

    def _first_violation(self):
        for gid in sorted(self.shard.monitors):
            monitor = self.shard.monitors[gid]
            if monitor.violations:
                return monitor.violations[0]
        return None

    # -- actions -------------------------------------------------------------

    def _apply(self, action: GroupChaosAction) -> bool:
        kind, args = action.kind, action.args
        if kind == "kill_coordinator":
            if not self.shard.coordinator.alive:
                return False
            self.shard.coordinator.kill()
            return True
        cluster = self.shard.groups.get(args.get("group"))
        if cluster is None:
            return False
        _, f = compute_quorum(len(cluster.nodes))
        dead = sum(1 for nd in cluster.nodes.values() if not nd.running)
        if kind == "partition_leader":
            cluster.network.partition([1])
            return True
        if kind == "crash":
            node = cluster.nodes.get(args["node"])
            if node is None or not node.running or dead >= f:
                return False
            node.crash()
            return True
        if kind == "restart":
            node = cluster.nodes.get(args["node"])
            if node is None or node.running:
                return False
            node.restart()
            return True
        if kind == "delay":
            cluster.network.set_delay(args["a"], args["b"], args["d"])
            return True
        if kind == "heal":
            cluster.network.heal()
            return True
        return False

    # -- the run -------------------------------------------------------------

    def run(self) -> GroupChaosResult:
        sched = self.schedule
        self.shard = ShardedCluster(
            sched.n_groups,
            n=sched.n,
            seed=sched.seed ^ 0xCA05,
            config_tweaks=self.config_tweaks,
            metrics=self.metrics,
        )
        shard = self.shard
        shard.coordinator.sentinel_one_sided = self.sentinel_one_sided
        shard.start()
        self._emit(
            f"{self._now():10.4f} start groups={sched.n_groups} n={sched.n} "
            f"seed={sched.seed}"
            + (" sentinel=one-sided" if self.sentinel_one_sided else "")
        )

        # Warm up: every group orders a block before the adversary acts.
        self._fill_requests(self.WARMUP_REQUESTS)
        if not shard.run_until_heights(1, max_time=self.WARMUP_BUDGET):
            for gid, monitor in shard.monitors.items():
                if shard.heights()[gid] < 1:
                    monitor.record(
                        "liveness", None,
                        f"[{gid}] no block ordered within "
                        f"{self.WARMUP_BUDGET}s sim-time BEFORE any action",
                    )
        self._emit(f"{self._now():10.4f} warmup done heights={shard.heights()}")

        # The transaction under attack: spans the first two groups.
        gids = shard.group_ids()
        participants = (gids[0], gids[1])
        txid = f"tx-{sched.seed}"
        shard.coordinator.start(txid, participants)
        self._emit(
            f"{self._now():10.4f} 2pc start txid={txid} "
            f"groups={list(participants)}"
        )

        for action in sched.actions:
            if self._first_violation() is not None:
                break
            gap = action.at - self._now()
            if gap > 0:
                shard.scheduler.advance(gap)
            if self._first_violation() is not None:
                break
            applied = self._apply(action)
            self._emit(
                f"{self._now():10.4f} "
                f"{'apply' if applied else 'skip '} "
                f"{action.kind} {action.args if action.args else ''}".rstrip()
            )
            self._fill_requests(self.REQUESTS_PER_ACTION)

        if self._first_violation() is None:
            # Quiesce: every group heals, every member restarts, settle.
            for cluster in shard.groups.values():
                cluster.network.heal()
                for node in cluster.nodes.values():
                    if not node.running:
                        node.restart()
            self._emit(f"{self._now():10.4f} quiesce: healed + restarted")
            shard.scheduler.advance(self.SETTLE_TIME)

            # Resolution: live coordinator decides; a killed one is
            # replaced by presumed-abort recovery over replicated state.
            coordinator = shard.coordinator
            if coordinator.alive:
                shard.run_until(
                    lambda: coordinator.all_prepared(txid),
                    max_time=self.RESOLVE_BUDGET,
                )
                outcome = coordinator.decide(txid)
                self._emit(f"{self._now():10.4f} coordinator decide {outcome}")
            else:
                outcome = TwoPhaseCoordinator.recover(
                    shard.groups, shard.registry, txid
                )
                self._emit(f"{self._now():10.4f} recovery decide {outcome}")
            shard.run_until(
                lambda: shard.registry.resolved(txid) is not None
                or shard.registry.violations,
                max_time=self.RESOLVE_BUDGET,
            )
            if (
                shard.registry.resolved(txid) is None
                and not shard.registry.violations
            ):
                tx = shard.registry.transactions.get(txid, {})
                for gid in participants:
                    shard.monitors[gid].record(
                        "liveness", None,
                        f"[{gid}] 2pc {txid} unresolved "
                        f"{self.RESOLVE_BUDGET}s after the decision "
                        f"(decisions so far: {tx.get('decisions')})",
                    )

        if self._first_violation() is None:
            # Post-heal liveness: every group must still make progress.
            floors = shard.heights()
            self._fill_requests(2)
            progressed = shard.run_until_heights(
                {g: h + 1 for g, h in floors.items()},
                max_time=self.LIVENESS_BUDGET,
            )
            if not progressed:
                heights = shard.heights()
                for gid, monitor in shard.monitors.items():
                    if heights[gid] < floors[gid] + 1:
                        monitor.record(
                            "liveness", None,
                            f"[{gid}] no post-heal progress within "
                            f"{self.LIVENESS_BUDGET}s sim-time",
                        )

        violation = self._first_violation()
        if violation is not None:
            self._emit(
                f"{violation.sim_time:10.4f} VIOLATION {violation.invariant}: "
                f"{violation.detail}"
            )
        resolution = {
            gid: shard.participants[gid].state.get(txid)
            for gid in participants
        }
        ledgers = shard.ledger_digests()
        for gid, by_node in ledgers.items():
            height = len(by_node[1])
            self._emit(f"{self._now():10.4f} ledger {gid} height={height}")
        return GroupChaosResult(
            ok=violation is None,
            violation=violation,
            event_log="\n".join(self._log).encode() + b"\n",
            ledgers=ledgers,
            schedule=sched,
            resolution=resolution,
            txid=txid,
            deliveries=sum(
                m.deliveries for m in shard.monitors.values()
            ),
        )


# --- shrinking --------------------------------------------------------------


def shrink_group_schedule(
    schedule: GroupChaosSchedule,
    *,
    invariant: Optional[str] = None,
    engine_kwargs: Optional[dict] = None,
    max_runs: int = 60,
) -> tuple[GroupChaosSchedule, GroupChaosResult]:
    """ddmin a failing cross-group schedule to a minimal action subset
    still violating the same invariant (same contract as
    ``testing.chaos.shrink``)."""
    kwargs = dict(engine_kwargs or {})
    runs = [0]

    def failing(actions) -> Optional[GroupChaosResult]:
        if runs[0] >= max_runs:
            return None
        runs[0] += 1
        sub = dataclasses.replace(schedule, actions=tuple(actions))
        res = GroupChaosEngine(sub, **kwargs).run()
        if res.violation is not None and (
            invariant is None or res.violation.invariant == invariant
        ):
            return res
        return None

    best_res = failing(schedule.actions)
    if best_res is None:
        raise ValueError(
            "schedule does not fail"
            + (f" with invariant {invariant!r}" if invariant else "")
            + " — nothing to shrink"
        )
    if invariant is None:
        invariant = best_res.violation.invariant
    best = list(schedule.actions)

    granularity = 2
    while len(best) >= 2:
        chunk = max(1, len(best) // granularity)
        reduced = False
        i = 0
        while i < len(best):
            candidate = best[:i] + best[i + chunk:]
            res = failing(candidate)
            if res is not None:
                best, best_res = candidate, res
                granularity = max(granularity - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if granularity >= len(best):
                break
            granularity = min(len(best), granularity * 2)
        if runs[0] >= max_runs:
            break
    # A sentinel failure needs no actions at all: try the empty schedule.
    if best:
        res = failing(())
        if res is not None:
            best, best_res = [], res
    return dataclasses.replace(schedule, actions=tuple(best)), best_res


def format_group_repro(result: GroupChaosResult) -> str:
    """A paste-able snippet reproducing ``result``'s schedule byte-for-byte."""
    s = result.schedule
    lines = [
        "from consensus_tpu.groups.chaos import (",
        "    GroupChaosAction, GroupChaosEngine, GroupChaosSchedule,",
        ")",
        "",
        "schedule = GroupChaosSchedule(",
        f"    seed={s.seed!r},",
        f"    n_groups={s.n_groups!r},",
        f"    n={s.n!r},",
        "    actions=(",
    ]
    for a in s.actions:
        lines.append(f"        {a!r},")
    lines += [
        "    ),",
        ")",
        "result = GroupChaosEngine(schedule).run()",
        "print(result.violation or 'run is clean')",
    ]
    return "\n".join(lines)


__all__ = [
    "GROUP_CHAOS_KINDS",
    "GroupChaosAction",
    "GroupChaosEngine",
    "GroupChaosResult",
    "GroupChaosSchedule",
    "format_group_repro",
    "shrink_group_schedule",
]
