"""Cross-group atomic commits: a 2PC overlay on ordered per-group requests.

A cross-group transaction touches tenants owned by two (or more) consensus
groups.  No group can order the other's requests, so atomicity is layered
ON TOP of per-group total order, classic two-phase commit style:

* **Prepare** — the coordinator submits a ``prepare`` request to every
  participant group.  Each group ORDERS it like any other request (full
  PBFT: quorum cert, WAL, the lot), so "group G is prepared" is itself a
  replicated, crash-durable fact — not a volatile ack.
* **Decide** — once every participant group has ordered its prepare, the
  coordinator submits ``commit`` to all of them; if it concludes a group
  cannot prepare, ``abort`` to all.  The decision requests are again
  ordered per group.
* **Recover** — a dead coordinator presumes abort: a recovery coordinator
  reads the replicated participant states and submits ``commit`` to the
  undecided groups only if some group already ordered a commit (the
  decision point had been passed), otherwise ``abort`` everywhere.

The participant state machine (:class:`TwoPhaseParticipant`) hangs off a
group's commit-path delivery hooks and persists every transition as a
versioned :class:`~consensus_tpu.wire.SavedTwoPC` wire record
(``encode_saved`` — the v4 record; SAFETY.md §15) in a dedicated per-group
2PC WAL, so a restarted harness can replay its transaction states without
touching the consensus WAL.  The :class:`CrossGroupRegistry` is the
cross-group witness: every participant transition lands there, and the
atomicity invariant — **never one group commits while another aborts the
same transaction** — is re-checked at every delivery (the per-group
:class:`~consensus_tpu.testing.invariants.InvariantMonitor` mirrors
registry violations via ``attach_cross_group``).

Payloads ride the standard test request format (``client:rid|payload``)
with a recognizable ``2pc|`` marker, so ordinary requests and 2PC control
requests coexist in one ledger.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

from consensus_tpu.testing.app import make_request, unpack_batch
from consensus_tpu.wire import SavedTwoPC, decode_saved, encode_saved

#: Payload marker distinguishing 2PC control requests from app requests.
TWOPC_MARKER = b"2pc|"

#: Control-request kinds, in protocol order.
TWOPC_KINDS = ("prepare", "commit", "abort")

#: Control kind -> the participant state it drives a group into.
_KIND_TO_PHASE = {"prepare": "prepared", "commit": "committed", "abort": "aborted"}

#: Participant states that end a transaction for that group.
TERMINAL_PHASES = ("committed", "aborted")


def twopc_payload(
    kind: str, txid: str, groups: Sequence[str], coordinator: str = "coord-0"
) -> bytes:
    """Encode one 2PC control payload (the part after ``client:rid|``)."""
    if kind not in TWOPC_KINDS:
        raise ValueError(f"unknown 2PC kind {kind!r}")
    if not txid or "|" in txid or "," in txid:
        raise ValueError(f"bad txid {txid!r}")
    for g in groups:
        if "|" in g or "," in g:
            raise ValueError(f"bad group id {g!r}")
    return TWOPC_MARKER + b"|".join(
        (kind.encode(), txid.encode(), ",".join(groups).encode(), coordinator.encode())
    )


def parse_twopc_payload(payload: bytes) -> Optional[dict]:
    """Decode a 2PC control payload; None when ``payload`` is not one."""
    if not payload.startswith(TWOPC_MARKER):
        return None
    parts = payload[len(TWOPC_MARKER):].split(b"|")
    if len(parts) != 4:
        raise ValueError(f"malformed 2PC payload {payload!r}")
    kind = parts[0].decode()
    if kind not in TWOPC_KINDS:
        raise ValueError(f"malformed 2PC payload {payload!r}: kind {kind!r}")
    return {
        "kind": kind,
        "txid": parts[1].decode(),
        "groups": tuple(g for g in parts[2].decode().split(",") if g),
        "coordinator": parts[3].decode(),
    }


@dataclasses.dataclass(frozen=True)
class AtomicityViolation:
    """One cross-group atomicity failure: the same transaction committed
    in one group and aborted in another."""

    txid: str
    detail: str

    def __str__(self) -> str:
        return f"cross-group atomicity violated for {self.txid}: {self.detail}"


class CrossGroupRegistry:
    """The cross-group witness: per-transaction participant decisions,
    resolution tracking, and the atomicity check.

    ``metrics`` is a :class:`~consensus_tpu.metrics.MetricsGroups` bundle
    (duck-typed): transaction starts and resolutions book the pinned
    ``groups_twopc_*`` counters.  ``now`` is the sim clock; it stamps
    transaction starts so :meth:`oldest_unresolved_age` can feed the
    obs plane's ``cross_group_stall`` detector health field.
    """

    def __init__(self, *, now=None, metrics=None) -> None:
        self._now = now if now is not None else (lambda: 0.0)
        self.metrics = metrics
        #: txid -> {"groups", "coordinator", "started", "decisions",
        #:          "booked"}; decisions maps group id -> latest phase.
        self.transactions: dict[str, dict] = {}
        self.violations: list[AtomicityViolation] = []
        #: Atomicity evaluations run (every delivery re-checks).
        self.checks = 0
        self._flagged: set[str] = set()

    # -- lifecycle -----------------------------------------------------------

    def begin(self, txid: str, groups: Sequence[str], coordinator: str = "") -> None:
        """Register a transaction at coordinator start time."""
        tx = self._tx(txid)
        tx["groups"] = tuple(groups)
        tx["coordinator"] = coordinator
        if self.metrics is not None:
            self.metrics.count_twopc_started.add(1)

    def _tx(self, txid: str) -> dict:
        tx = self.transactions.get(txid)
        if tx is None:
            # A participant can report before begin() (recovery replay):
            # groups fill in from the delivered payload via record().
            tx = self.transactions[txid] = {
                "groups": (),
                "coordinator": "",
                "started": self._now(),
                "decisions": {},
                "booked": False,
            }
        return tx

    def record(
        self, group: str, txid: str, phase: str, *, groups: Sequence[str] = ()
    ) -> None:
        """One participant transition; re-runs the atomicity check and
        books the resolution counters when the transaction completes."""
        tx = self._tx(txid)
        if groups and not tx["groups"]:
            tx["groups"] = tuple(groups)
        tx["decisions"][group] = phase
        self.check(txid)
        outcome = self.resolved(txid)
        if outcome is not None and not tx["booked"]:
            tx["booked"] = True
            if self.metrics is not None:
                if outcome == "committed":
                    self.metrics.count_twopc_committed.add(1)
                else:
                    self.metrics.count_twopc_aborted.add(1)

    # -- the invariant -------------------------------------------------------

    def check(self, txid: str) -> Optional[AtomicityViolation]:
        """THE cross-group atomicity check, run at every delivery: no
        transaction may be committed in one group and aborted in another."""
        self.checks += 1
        tx = self.transactions.get(txid)
        if tx is None:
            return None
        decided = tx["decisions"]
        committed = sorted(g for g, p in decided.items() if p == "committed")
        aborted = sorted(g for g, p in decided.items() if p == "aborted")
        if committed and aborted and txid not in self._flagged:
            self._flagged.add(txid)
            violation = AtomicityViolation(
                txid=txid,
                detail=(
                    f"committed in {committed} but aborted in {aborted} "
                    f"(participants {list(tx['groups'])}, "
                    f"coordinator {tx['coordinator']!r})"
                ),
            )
            self.violations.append(violation)
            return violation
        return None

    def resolved(self, txid: str) -> Optional[str]:
        """The transaction's outcome ("committed"/"aborted") once EVERY
        participant group reached the SAME terminal phase; None before
        then (and None forever for a flagged atomicity violation)."""
        tx = self.transactions.get(txid)
        if tx is None or not tx["groups"]:
            return None
        phases = {tx["decisions"].get(g) for g in tx["groups"]}
        if len(phases) == 1:
            (phase,) = phases
            if phase in TERMINAL_PHASES:
                return phase
        return None

    def oldest_unresolved_age(self, now: Optional[float] = None) -> Optional[float]:
        """Age of the oldest transaction still lacking a resolution — the
        obs plane's ``groups_twopc_oldest_age`` health field (None when
        everything is resolved, which keeps the detector's latch clear)."""
        t = self._now() if now is None else now
        ages = [
            t - tx["started"]
            for txid, tx in self.transactions.items()
            if self.resolved(txid) is None
        ]
        return max(ages) if ages else None

    def assert_atomic(self) -> None:
        if self.violations:
            raise AssertionError(str(self.violations[0]))


class TwoPhaseParticipant:
    """One group's 2PC state machine, driven by commit-path deliveries.

    Hangs off ``Cluster.delivery_hooks``; for every ordered 2PC control
    request naming this group it applies the transition, persists it as a
    :class:`~consensus_tpu.wire.SavedTwoPC` record in the group's 2PC WAL
    (``wal`` — anything with ``append(bytes)``; defaults to an internal
    list-backed log), and reports to the :class:`CrossGroupRegistry`.
    Transitions are idempotent under re-delivery across the group's n
    replicas: only the FIRST delivery of a phase change persists/reports.
    """

    def __init__(
        self,
        group_id: str,
        *,
        registry: Optional[CrossGroupRegistry] = None,
        wal=None,
        tracer=None,
    ) -> None:
        self.group_id = group_id
        self.registry = registry
        self.wal = wal if wal is not None else _ListWAL()
        self.tracer = tracer
        #: txid -> current phase ("prepared" | "committed" | "aborted").
        self.state: dict[str, str] = {}
        #: Out-of-protocol transitions observed (commit without prepare,
        #: abort after commit) — harness-level red flags, not exceptions.
        self.errors: list[str] = []
        self.deliveries = 0

    # -- delivery hook -------------------------------------------------------

    def on_delivery(self, node_id: int, decision) -> None:
        """``Cluster.delivery_hooks`` signature."""
        self.deliveries += 1
        for raw in unpack_batch(decision.proposal.payload):
            split = raw.split(b"|", 1)
            if len(split) != 2:
                continue
            try:
                rec = parse_twopc_payload(split[1])
            except ValueError:
                continue
            if rec is None or self.group_id not in rec["groups"]:
                continue
            self._apply(rec)

    def _apply(self, rec: dict) -> None:
        txid, kind = rec["txid"], rec["kind"]
        cur = self.state.get(txid)
        new = _KIND_TO_PHASE[kind]
        if cur == new:
            return  # re-delivery on another replica of this group
        if kind == "prepare" and cur is not None:
            return  # late prepare after the decision: stale, ignored
        if kind == "commit" and cur != "prepared":
            self.errors.append(
                f"{txid}: commit delivered in state {cur!r} (expected prepared)"
            )
        if kind == "abort" and cur == "committed":
            # The one transition that must NEVER happen: an ordered commit
            # is final for this group.  Keep the committed state — the
            # registry's cross-group check judges the pair.
            self.errors.append(f"{txid}: abort delivered after commit (kept commit)")
            return
        self.state[txid] = new
        self.wal.append(
            encode_saved(
                SavedTwoPC(
                    txid=txid,
                    phase=new,
                    groups=tuple(rec["groups"]),
                    coordinator=rec["coordinator"],
                )
            )
        )
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant(
                "groups", "groups.twopc", txid=txid, group=self.group_id, phase=new
            )
        if self.registry is not None:
            self.registry.record(
                self.group_id, txid, new, groups=rec["groups"]
            )

    # -- restart realism -----------------------------------------------------

    def replay(self, entries: Sequence[bytes]) -> None:
        """Rebuild transaction state from persisted ``SavedTwoPC`` records
        (last record per txid wins — the WAL is append-only)."""
        for entry in entries:
            msg = decode_saved(entry)
            if isinstance(msg, SavedTwoPC):
                self.state[msg.txid] = msg.phase


class _ListWAL:
    """Minimal append-only log backing a participant by default."""

    def __init__(self) -> None:
        self._entries: list[bytes] = []

    def append(self, entry: bytes) -> None:
        self._entries.append(entry)

    @property
    def entries(self) -> list[bytes]:
        return list(self._entries)


class TwoPhaseCoordinator:
    """Drives cross-group transactions by submitting ordered control
    requests to every participant group.

    ``clusters`` maps group id -> anything with ``submit_to_all(raw)``
    (a :class:`~consensus_tpu.testing.app.Cluster`).  The coordinator is a
    plain process in the fault model: :meth:`kill` models a kill -9 —
    every later ``start``/``decide`` is a silent no-op, and recovery goes
    through the replicated participant states (:meth:`recover`).

    ``sentinel_one_sided=True`` plants the classic 2PC coordinator bug —
    commit to the first group, abort to the rest — used by the chaos
    sentinel gate to prove the atomicity invariant actually catches a
    one-sided commit.
    """

    def __init__(
        self,
        clusters: Mapping[str, object],
        registry: CrossGroupRegistry,
        *,
        coordinator_id: str = "coord-0",
        client: str = "txn-coord",
        sentinel_one_sided: bool = False,
    ) -> None:
        self.clusters = dict(clusters)
        self.registry = registry
        self.coordinator_id = coordinator_id
        self.client = client
        self.sentinel_one_sided = sentinel_one_sided
        self.alive = True
        self._rid = 0

    def kill(self) -> None:
        """kill -9: the coordinator stops mid-protocol, leaving in-flight
        transactions to :meth:`recover`."""
        self.alive = False

    def _submit(self, group: str, kind: str, txid: str, groups: Sequence[str]) -> None:
        self._rid += 1
        raw = make_request(
            self.client,
            f"{txid}.{kind}.{group}.{self._rid}",
            twopc_payload(kind, txid, groups, self.coordinator_id),
        )
        self.clusters[group].submit_to_all(raw)

    def start(self, txid: str, groups: Sequence[str]) -> None:
        """Phase 1: submit ``prepare`` to every participant group."""
        if not self.alive:
            return
        groups = tuple(groups)
        for g in groups:
            if g not in self.clusters:
                raise KeyError(f"unknown group {g!r}")
        self.registry.begin(txid, groups, coordinator=self.coordinator_id)
        for g in groups:
            self._submit(g, "prepare", txid, groups)

    def all_prepared(self, txid: str) -> bool:
        tx = self.registry.transactions.get(txid)
        if tx is None or not tx["groups"]:
            return False
        return all(
            tx["decisions"].get(g) in ("prepared",) + TERMINAL_PHASES
            for g in tx["groups"]
        )

    def decide(self, txid: str) -> Optional[str]:
        """Phase 2: ``commit`` everywhere iff every group prepared, else
        ``abort`` everywhere.  Returns the submitted outcome kind."""
        if not self.alive:
            return None
        tx = self.registry.transactions[txid]
        groups = tx["groups"]
        outcome = "commit" if self.all_prepared(txid) else "abort"
        if self.sentinel_one_sided and outcome == "commit" and len(groups) >= 2:
            # Planted bug: a one-sided commit the atomicity invariant must
            # catch (and ddmin must shrink to).
            self._submit(groups[0], "commit", txid, groups)
            for g in groups[1:]:
                self._submit(g, "abort", txid, groups)
            return "commit"
        for g in groups:
            self._submit(g, outcome, txid, groups)
        return outcome

    @classmethod
    def recover(
        cls,
        clusters: Mapping[str, object],
        registry: CrossGroupRegistry,
        txid: str,
        *,
        coordinator_id: str = "coord-recovery",
        client: str = "txn-recovery",
    ) -> str:
        """Presumed-abort recovery after a coordinator death: commit the
        undecided groups only if some group already ordered a commit (the
        dead coordinator had passed its decision point), otherwise abort
        everywhere undecided.  Safe to run repeatedly."""
        tx = registry.transactions.get(txid)
        if tx is None or not tx["groups"]:
            raise KeyError(f"unknown transaction {txid!r}")
        decisions = tx["decisions"]
        outcome = (
            "commit"
            if any(p == "committed" for p in decisions.values())
            else "abort"
        )
        recovery = cls(
            clusters, registry, coordinator_id=coordinator_id, client=client
        )
        for g in tx["groups"]:
            if decisions.get(g) not in TERMINAL_PHASES:
                recovery._submit(g, outcome, txid, tx["groups"])
        return outcome


__all__ = [
    "AtomicityViolation",
    "CrossGroupRegistry",
    "TERMINAL_PHASES",
    "TWOPC_KINDS",
    "TWOPC_MARKER",
    "TwoPhaseCoordinator",
    "TwoPhaseParticipant",
    "parse_twopc_payload",
    "twopc_payload",
]
