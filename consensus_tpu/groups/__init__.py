"""Horizontal consensus sharding: many PBFT groups, one verifier fleet.

One consensus group tops out at a single leader's ordering pipeline no
matter how fast batch verification gets; the verifier is the shareable
resource.  This package is the unit of horizontal scale built on that
observation:

* :mod:`~consensus_tpu.groups.directory` — :class:`GroupDirectory`, the
  tenant→group rendezvous map under the ``ctpu/groups/placement/v1``
  domain (a sibling of the ingress ``ctpu/ingress/placement/v1`` domain,
  so the server-leave remap bounds carry over verbatim).
* :mod:`~consensus_tpu.groups.router` — :class:`GroupRouter`, the
  admit-then-route step the ingress driver runs per request.
* :mod:`~consensus_tpu.groups.cluster` — :class:`ShardedCluster`, N
  simulated consensus groups on ONE shared :class:`SimScheduler`, all
  verifying through one shared :class:`FairShareWaveFormer` so waves
  coalesce across GROUPS, not just tenants (SAFETY §7 still holds: a
  submission is never split, so no quorum cert ever mixes engines).
* :mod:`~consensus_tpu.groups.twopc` — the minimal cross-group atomic
  commit (2PC over ordered per-group records) plus the cross-group
  atomicity registry the invariant monitors consult at every delivery.
* :mod:`~consensus_tpu.groups.chaos` — the per-group chaos vocabulary
  (kill a coordinator, partition one group's leader mid-2PC) with ddmin
  shrinking to paste-able reproducers.
* :mod:`~consensus_tpu.groups.deploy` — the process-per-replica sharding
  of the PR-16 rig: N per-group ``ClusterSpec`` documents sharing one
  sidecar fleet, one launcher per group, zero orphans at teardown.
"""

from consensus_tpu.groups.chaos import (
    GroupChaosAction,
    GroupChaosResult,
    GroupChaosSchedule,
    GroupChaosEngine,
    format_group_repro,
    shrink_group_schedule,
)
from consensus_tpu.groups.cluster import ShardedCluster
from consensus_tpu.groups.deploy import ShardedClusterLauncher, ShardedDeploySpec
from consensus_tpu.groups.directory import GROUPS_PLACEMENT_DOMAIN, GroupDirectory
from consensus_tpu.groups.router import GroupRouter
from consensus_tpu.groups.twopc import (
    CrossGroupRegistry,
    TwoPhaseCoordinator,
    TwoPhaseParticipant,
    twopc_payload,
    parse_twopc_payload,
)

__all__ = [
    "GROUPS_PLACEMENT_DOMAIN",
    "GroupDirectory",
    "GroupRouter",
    "ShardedCluster",
    "ShardedClusterLauncher",
    "ShardedDeploySpec",
    "CrossGroupRegistry",
    "TwoPhaseCoordinator",
    "TwoPhaseParticipant",
    "twopc_payload",
    "parse_twopc_payload",
    "GroupChaosAction",
    "GroupChaosResult",
    "GroupChaosSchedule",
    "GroupChaosEngine",
    "format_group_repro",
    "shrink_group_schedule",
]
