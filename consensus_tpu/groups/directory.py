"""Tenant→consensus-group placement: the sharding map itself.

Same rendezvous (highest-random-weight) construction as the ingress
fleet's tenant→sidecar map (:mod:`consensus_tpu.ingress.placement`), under
a sibling hash domain so the two maps are independent draws: a tenant's
sidecar and its consensus group are uncorrelated, and the remap bound
carries over verbatim — retiring one group moves ONLY the tenants whose
top-scoring group was the retired one (~1/N of them, exactly), because
every other tenant's ranking among the survivors is untouched.  No ring
state, no RNG: placement is a pure function of the (group id, tenant id)
strings, so the ingress router, every replica, and every test compute the
same map independently.
"""

from __future__ import annotations

import hashlib
from typing import Iterable

#: Hash domain for tenant→group scores.  Sibling of the ingress domain
#: ``ctpu/ingress/placement/v1`` — bump the version suffix, never reuse it,
#: if the scoring construction ever changes.
GROUPS_PLACEMENT_DOMAIN = b"ctpu/groups/placement/v1"


def _group_score(group: str, tenant: str) -> int:
    """64-bit rendezvous weight for placing ``tenant`` in ``group``."""
    digest = hashlib.sha256(
        GROUPS_PLACEMENT_DOMAIN + b"\x00"
        + group.encode() + b"\x00" + tenant.encode()
    ).digest()
    return int.from_bytes(digest[:8], "big")


def group_ids(n: int) -> tuple[str, ...]:
    """The canonical id set for an ``n``-group deployment."""
    if n < 1:
        raise ValueError("a deployment needs at least one group")
    return tuple(f"group-{i}" for i in range(n))


class GroupDirectory:
    """Rendezvous-hash tenant→group map over a mutable group set."""

    def __init__(self, groups: Iterable[str] = ()) -> None:
        self._groups: set[str] = set()
        for g in groups:
            self.add(g)

    @classmethod
    def of_size(cls, n: int) -> "GroupDirectory":
        return cls(group_ids(n))

    def add(self, group: str) -> None:
        if not group:
            raise ValueError("group id must be non-empty")
        self._groups.add(group)

    def remove(self, group: str) -> None:
        self._groups.discard(group)

    def groups(self) -> tuple[str, ...]:
        return tuple(sorted(self._groups))

    def __len__(self) -> int:
        return len(self._groups)

    def candidates(self, tenant: str) -> list[str]:
        """Every group, best placement first; ties (astronomically
        unlikely) break on the group id so the order is total."""
        if not self._groups:
            raise ValueError("group directory has no groups")
        return sorted(
            self._groups, key=lambda g: (-_group_score(g, tenant), g)
        )

    def assign(self, tenant: str) -> str:
        return self.candidates(tenant)[0]

    def assignment_map(self, tenants: Iterable[str]) -> dict[str, str]:
        """tenant -> group for a whole tenant population (the remap-bound
        tests diff two of these across a group join/leave)."""
        return {t: self.assign(t) for t in tenants}


__all__ = ["GROUPS_PLACEMENT_DOMAIN", "GroupDirectory", "group_ids"]
