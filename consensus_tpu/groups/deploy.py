"""Sharded deployment rig: N groups as real OS-process clusters, one fleet.

The sim harness (:mod:`consensus_tpu.groups.cluster`) shards on one
virtual clock; this module is the same topology over the real deploy rig
(:mod:`consensus_tpu.deploy`): every group is a full process-per-replica
cluster — its own replicas, WAL directories, consensus/sync/control
ports — while the sidecar verifier FLEET is shared by all of them:

* One :class:`~consensus_tpu.deploy.spec.PortReservation` covers every
  port in the shard (3 per replica x n x groups + 2 per sidecar), held
  bound from generate to just-before-spawn, so two shards generating
  concurrently can never collide (the free_ports TOCTOU fix).
* All groups share one ``auth_secret`` (the sidecar service authenticates
  every group's replicas with it) and the SAME sidecar address list;
  each group gets its OWN ``key_namespace`` (``<ns>-g<i>``) so replica
  identities never collide across groups.
* Group 0's :class:`~consensus_tpu.deploy.launcher.ClusterLauncher` owns
  the fleet (spawns + audits the sidecar processes); every other group
  runs with ``spawn_sidecars=False`` and merely dials it.

Teardown stops the non-owning groups first, the fleet owner last, and
every launcher's zero-orphan / zero-leaked-port audit runs as usual.
"""

from __future__ import annotations

import os
import secrets
from typing import Dict, Optional

from consensus_tpu.deploy.launcher import ClusterLauncher
from consensus_tpu.deploy.spec import (
    ClusterSpec,
    PortReservation,
    ReplicaSpec,
    SidecarSpec,
)
from consensus_tpu.groups.directory import group_ids


class ShardedDeploySpec:
    """Per-group :class:`ClusterSpec`s minted together over one held
    reservation, sharing the sidecar fleet and the auth secret."""

    def __init__(self, specs: Dict[str, ClusterSpec], reservation=None) -> None:
        if not specs:
            raise ValueError("need at least one group spec")
        self.specs = dict(specs)
        self._reservation = reservation

    @classmethod
    def generate(
        cls,
        n_groups: int,
        n: int,
        n_sidecars: int,
        base_dir: str,
        *,
        clients: int = 8,
        host: str = "127.0.0.1",
        config_overrides: Optional[dict] = None,
    ) -> "ShardedDeploySpec":
        os.makedirs(base_dir, exist_ok=True)
        base_dir = os.path.abspath(base_dir)
        reservation = PortReservation(
            3 * n * n_groups + 2 * n_sidecars, host=host
        )
        ports = reservation.ports
        auth_secret_hex = secrets.token_hex(16)
        namespace = secrets.token_hex(8)
        fleet_base = 3 * n * n_groups
        fleet = [
            SidecarSpec(
                sidecar_id=f"sc-{k}",
                host=host,
                port=ports[fleet_base + 2 * k],
                control_port=ports[fleet_base + 2 * k + 1],
            )
            for k in range(n_sidecars)
        ]
        specs: Dict[str, ClusterSpec] = {}
        for gi, gid in enumerate(group_ids(n_groups)):
            group_dir = os.path.join(base_dir, gid)
            os.makedirs(group_dir, exist_ok=True)
            spec = ClusterSpec(
                n=n,
                base_dir=group_dir,
                auth_secret_hex=auth_secret_hex,
                key_namespace=f"{namespace}-g{gi}",
                clients=clients,
                config_overrides=dict(config_overrides or {}),
            )
            offset = 3 * n * gi
            for i in range(n):
                node_id = i + 1
                spec.replicas.append(
                    ReplicaSpec(
                        node_id=node_id,
                        host=host,
                        port=ports[offset + 3 * i],
                        sync_port=ports[offset + 3 * i + 1],
                        control_port=ports[offset + 3 * i + 2],
                        wal_dir=os.path.join(
                            group_dir, f"node-{node_id}", "wal"
                        ),
                    )
                )
            # Every group's cluster.json lists the SAME fleet addresses:
            # dataclass copies, so a later autoscale in one group's spec
            # cannot silently mutate another's.
            spec.sidecars = [
                SidecarSpec(**vars(sc)) for sc in fleet
            ]
            spec.attach_reservation(reservation)
            specs[gid] = spec
        return cls(specs, reservation=reservation)

    def group_ids(self) -> list:
        return sorted(self.specs)

    def release_ports(self) -> None:
        if self._reservation is not None:
            self._reservation.release()


class ShardedClusterLauncher:
    """Boots and operates one launcher per group over the shared fleet.

    Group 0 owns the sidecars; all launchers share the one reservation,
    released exactly once right before the first spawn."""

    def __init__(self, sharded: ShardedDeploySpec, **launcher_kwargs) -> None:
        self.sharded = sharded
        self.launchers: Dict[str, ClusterLauncher] = {}
        for gi, gid in enumerate(sharded.group_ids()):
            self.launchers[gid] = ClusterLauncher(
                sharded.specs[gid],
                spawn_sidecars=(gi == 0),
                **launcher_kwargs,
            )

    @property
    def fleet_owner(self) -> ClusterLauncher:
        return self.launchers[self.sharded.group_ids()[0]]

    def start(self, timeout: float = 120.0) -> None:
        self.sharded.release_ports()
        # Fleet owner first: its sidecars must listen before the other
        # groups' replicas dial them at verify time.
        for gid in self.sharded.group_ids():
            self.launchers[gid].start(timeout=timeout)

    def heights(self) -> dict:
        return {gid: l.heights() for gid, l in sorted(self.launchers.items())}

    def wait_heights(self, height: int, timeout: float) -> bool:
        deadline_each = max(timeout / max(len(self.launchers), 1), 1.0)
        return all(
            self.launchers[gid].wait_height(height, deadline_each)
            for gid in self.sharded.group_ids()
        )

    def observe_invariants(self) -> None:
        for launcher in self.launchers.values():
            launcher.observe_invariants()

    def stop(self) -> dict:
        """Tear down non-owning groups first, the fleet owner last (its
        stop kills the shared sidecars and audits their ports).  Every
        launcher's zero-orphan assertion runs; summaries are per group."""
        summaries = {}
        errors = []
        for gid in reversed(self.sharded.group_ids()):
            try:
                summaries[gid] = self.launchers[gid].stop()
            except BaseException as exc:  # audit all groups, then raise
                errors.append((gid, exc))
        if errors:
            raise errors[0][1]
        return summaries


__all__ = ["ShardedClusterLauncher", "ShardedDeploySpec"]
