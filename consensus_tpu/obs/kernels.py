"""Device/kernel accounting: compiles, retraces, launches, and cost
estimates per jit entry point.

:func:`instrumented_jit` replaces the bare ``jax.jit(fn)`` at the module
level of the signature models (ed25519 verify / batch-verify, ecdsa-p256
verify).  The wrapper is transparent — same signature, same outputs — and
on every call records into the process-wide :data:`KERNELS` registry:

* ``launches``   — calls into the jitted function;
* ``compiles``   — jit cache growth observed across calls (via the private
  but long-stable ``_cache_size`` probe; gracefully 0 if it disappears);
* ``retraces``   — compiles beyond the first, i.e. shape/dtype churn;
* ``flops`` / ``bytes_accessed`` — XLA cost-analysis estimates captured at
  first compile per kernel (``lower(...).cost_analysis()``; ``lower`` does
  not populate the jit call cache, so the probe never double-compiles).

The registry is surfaced as a ``kernels`` column family in bench.py on both
the live and structured-skip paths.

jax is imported lazily inside the wrapper so importing consensus_tpu.obs
never drags in the accelerator stack (the sim plane must stay importable
on boxes without jax).
"""

from __future__ import annotations

from typing import Optional


class KernelStats:
    """Mutable per-kernel counters."""

    __slots__ = ("name", "launches", "compiles", "flops", "bytes_accessed")

    def __init__(self, name: str) -> None:
        self.name = name
        self.launches = 0
        self.compiles = 0
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None

    @property
    def retraces(self) -> int:
        return max(0, self.compiles - 1)

    def as_dict(self) -> dict:
        return {
            "launches": self.launches,
            "compiles": self.compiles,
            "retraces": self.retraces,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
        }


class KernelRegistry:
    """Process-wide map of kernel name -> :class:`KernelStats`."""

    def __init__(self) -> None:
        self._stats: dict[str, KernelStats] = {}

    def stats(self, name: str) -> KernelStats:
        st = self._stats.get(name)
        if st is None:
            st = self._stats[name] = KernelStats(name)
        return st

    def snapshot(self) -> dict:
        """``{kernel: {launches, compiles, retraces, flops, bytes_accessed}}``,
        sorted, JSON-ready.  Empty dict when nothing has launched."""
        return {
            name: self._stats[name].as_dict() for name in sorted(self._stats)
        }

    def totals(self) -> dict:
        snap = self.snapshot()
        return {
            "launches": sum(s["launches"] for s in snap.values()),
            "compiles": sum(s["compiles"] for s in snap.values()),
            "retraces": sum(s["retraces"] for s in snap.values()),
        }

    def reset(self) -> None:
        self._stats.clear()


#: The process-wide registry bench.py snapshots.
KERNELS = KernelRegistry()


class TenantAccounting:
    """Per-tenant slice of the sidecar's kernel work: which tenant's
    signatures rode which share of the engine launches.

    The multi-tenant sidecar coalesces many tenants' submissions into one
    wave, so :data:`KERNELS` alone can no longer attribute device time to a
    tenant; the wave former reports each launch here instead.  ``waves``
    counts launches the tenant participated in (a shared wave counts once
    per PARTICIPANT, so summing waves over tenants exceeds engine launches
    exactly when coalescing is winning)."""

    def __init__(self) -> None:
        self._tenants: dict[str, dict] = {}

    def record_wave(self, tenant: str, signatures: int) -> None:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = {"waves": 0, "signatures": 0}
        t["waves"] += 1
        t["signatures"] += signatures

    def snapshot(self) -> dict:
        """``{tenant: {waves, signatures}}``, sorted, JSON-ready."""
        return {
            tenant: dict(self._tenants[tenant])
            for tenant in sorted(self._tenants)
        }

    def reset(self) -> None:
        self._tenants.clear()


#: Process-wide tenant accounting fed by the sidecar wave former.
TENANT_KERNELS = TenantAccounting()


class CompileCacheStats:
    """Hit/miss ledger for the in-process compiled-kernel memo
    (parallel/sharding.py ``compiled_kernel``).

    A *hit* means an engine construction reused an already-traced jit
    wrapper — the retrace storm a fleet restart or tenant churn would have
    paid; a *miss* is a fresh build (first construction of that
    ``(kernel, topology[, shape])`` key, or the memo disabled via
    ``CompileCacheConfig.enabled=False``).  Surfaced through the node
    metrics bundle as ``engine_compile_cache_{hits,misses}_total``.
    """

    def __init__(self) -> None:
        self.hits = 0
        self.misses = 0

    def record(self, *, hit: bool) -> None:
        if hit:
            self.hits += 1
        else:
            self.misses += 1

    def snapshot(self) -> dict:
        return {"hits": self.hits, "misses": self.misses}

    def reset(self) -> None:
        self.hits = 0
        self.misses = 0


#: Process-wide compiled-kernel memo ledger (fed by parallel/sharding.py).
COMPILE_CACHE = CompileCacheStats()


def _cache_size(jitted) -> int:
    try:
        return int(jitted._cache_size())
    except Exception:
        return 0


def _cost_number(analysis, key: str) -> Optional[float]:
    # cost_analysis() is a flat dict on current jax; older versions returned
    # a one-element list of dicts.
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    v = analysis.get(key)
    return float(v) if v is not None else None


def kernel_lane_suffix() -> str:
    """``"_mxu"`` when the process runs the MXU field lane
    (``CTPU_MXU_LIMBS=1``), else ``""``.

    Engine modules append this to their ``instrumented_jit`` names at
    import time, so an MXU-lane run's launches/compiles/cost_analysis land
    under ``ed25519.verify_mxu`` etc. instead of overwriting the headline
    VPU ledger keys — the device A/B reads both side by side."""
    import os

    return "_mxu" if os.environ.get("CTPU_MXU_LIMBS", "") == "1" else ""


def instrumented_jit(
    fn, name: str, *, registry: Optional[KernelRegistry] = None, **jit_kwargs
):
    """``jax.jit(fn, **jit_kwargs)`` plus accounting under ``name``.  Behaves
    exactly like the jitted function; every failure inside the accounting is
    swallowed so instrumentation can never break a verify path.  Extra
    keyword arguments pass straight to ``jax.jit`` (the fused engines donate
    their input buffers).  Wrappers may share a ``name`` — stats accumulate
    into one bucket, which is how the shape-specialized fused aggregate
    graphs report as a single kernel."""
    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    reg = registry if registry is not None else KERNELS

    def wrapper(*args, **kwargs):
        st = reg.stats(name)
        st.launches += 1
        before = _cache_size(jitted)
        out = jitted(*args, **kwargs)
        grew = _cache_size(jitted) - before
        if grew > 0:
            st.compiles += grew
            if st.flops is None:
                try:
                    analysis = jitted.lower(*args, **kwargs).cost_analysis()
                    st.flops = _cost_number(analysis, "flops")
                    st.bytes_accessed = _cost_number(analysis, "bytes accessed")
                except Exception:
                    pass
        return out

    wrapper.__name__ = f"instrumented_{name}"
    wrapper.__wrapped__ = jitted
    return wrapper


__all__ = [
    "COMPILE_CACHE",
    "CompileCacheStats",
    "KERNELS",
    "KernelRegistry",
    "KernelStats",
    "TENANT_KERNELS",
    "TenantAccounting",
    "instrumented_jit",
    "kernel_lane_suffix",
]
