"""Flight recorder: one atomic failure bundle per run, plus its loader.

On any of the three trigger seams —

* **invariant violation** (``InvariantMonitor.on_violation``),
* **node crash-point fire** (``FaultPlan.recorder``: the armed hit calls
  :meth:`FlightRecorder.on_fault_fired` before the node tears down, so the
  bundle captures the pre-crash state),
* **unhandled controller exception** (``SimScheduler.on_unhandled_error``)

— the recorder dumps the last-N sampler records, the trace ring, a live
per-node metrics snapshot, and the active chaos schedule into one
``flightrec_<seed>.json``, written atomically (tmp + ``os.replace``, so a
crash mid-dump never leaves a torn bundle).  Every subsequent trigger
re-dumps with the full trigger list; the bundle's ``reason`` stays the
FIRST cause.

The loader (:func:`load_flight_record`) reconstructs a failing node's last
known view / leader / in-flight state from the bundle alone — no re-run of
the schedule required (proved by tests/test_obs.py against the PR-5
sentinel-bug schedule).

No wall clock anywhere: timestamps come from the injected ``clock``
callable (the scheduler), so bundles of a fixed-seed run are deterministic
modulo the trigger that produced them.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Optional

FLIGHTREC_VERSION = 1


def _schedule_doc(schedule) -> Optional[dict]:
    if schedule is None:
        return None
    return {
        "seed": schedule.seed,
        "n": schedule.n,
        "durability_window": schedule.durability_window,
        "actions": [dataclasses.asdict(a) for a in schedule.actions],
    }


class FlightRecorder:
    """Collects trigger seams and dumps bundles.  Construct one per run and
    attach the seams you have; every attach is optional."""

    def __init__(
        self,
        *,
        seed: int,
        out_dir: str = ".",
        clock: Optional[Callable[[], float]] = None,
        sampler=None,
        tracer=None,
        schedule=None,
        last_n: int = 64,
    ) -> None:
        if last_n < 1:
            raise ValueError("last_n must be >= 1")
        self.seed = seed
        self.out_dir = out_dir
        self.clock = clock
        self.sampler = sampler
        self.tracer = tracer
        self.schedule = schedule
        self.last_n = last_n
        #: Every trigger seen, in order: {"reason", "t", "node", "detail"}.
        self.triggers: list[dict] = []
        #: Path of the written bundle (None until the first trigger).
        self.path: Optional[str] = None

    # --- seam wiring --------------------------------------------------------

    def attach_scheduler(self, scheduler) -> None:
        """Observe unhandled event-handler exceptions."""
        scheduler.on_unhandled_error = self._on_unhandled_error

    def attach_monitor(self, monitor) -> None:
        """Observe invariant violations the moment they are recorded."""
        monitor.on_violation.append(self._on_violation)

    def watch_plan(self, plan) -> None:
        """Observe a FaultPlan's armed firing (pre-teardown)."""
        plan.recorder = self

    # --- the seams ----------------------------------------------------------

    def _on_violation(self, violation) -> None:
        self.trigger(
            "invariant",
            node=violation.node,
            detail=f"{violation.invariant}: {violation.detail}",
        )

    def on_fault_fired(self, point: str, hit: int) -> None:
        self.trigger("crash-point", detail=f"{point} (hit {hit})")

    def _on_unhandled_error(self, name: str, err: BaseException) -> None:
        self.trigger(
            "unhandled-exception", detail=f"event {name!r}: {err!r}"
        )

    # --- dumping ------------------------------------------------------------

    def _now(self) -> float:
        return self.clock() if self.clock is not None else 0.0

    def trigger(self, reason: str, *, node=None, detail: str = "") -> str:
        """Record one trigger and (re)write the bundle.  Returns the path."""
        self.triggers.append({
            "reason": reason,
            "t": round(self._now(), 6),
            "node": node,
            "detail": detail,
        })
        return self._dump()

    def _metrics_snapshot(self) -> dict:
        sampler = self.sampler
        if sampler is None:
            return {}
        out = {}
        for nid in sorted(sampler.cluster.nodes):
            node = sampler.cluster.nodes[nid]
            provider = getattr(node.metrics, "provider", None)
            dump = getattr(provider, "dump", None)
            if dump is not None:
                out[str(nid)] = dump()
        return out

    def _dump(self) -> str:
        first = self.triggers[0]
        samples = self.sampler.samples()[-self.last_n:] if self.sampler else []
        trace = (
            [list(ev) for ev in self.tracer.events()]
            if self.tracer is not None
            else []
        )
        doc = {
            "flightrec_version": FLIGHTREC_VERSION,
            "seed": self.seed,
            "reason": first["reason"],
            "t": first["t"],
            "node": first["node"],
            "detail": first["detail"],
            "triggers": self.triggers,
            "samples": samples,
            "anomalies": (
                [a.as_dict() for a in self.sampler.anomalies]
                if self.sampler else []
            ),
            "trace": trace,
            "metrics": self._metrics_snapshot(),
            "schedule": _schedule_doc(self.schedule),
        }
        path = os.path.join(self.out_dir, f"flightrec_{self.seed}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(json.dumps(doc, sort_keys=True, separators=(",", ":")))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self.path = path
        return path


# --- loader -----------------------------------------------------------------


class FlightRecord:
    """A loaded bundle with reconstruction helpers — diagnosis without a
    re-run."""

    def __init__(self, doc: dict) -> None:
        if doc.get("flightrec_version") != FLIGHTREC_VERSION:
            raise ValueError(
                f"unsupported flightrec version {doc.get('flightrec_version')!r}"
            )
        self.doc = doc

    @property
    def seed(self) -> int:
        return self.doc["seed"]

    @property
    def reason(self) -> str:
        return self.doc["reason"]

    @property
    def detail(self) -> str:
        return self.doc["detail"]

    @property
    def triggers(self) -> list:
        return self.doc["triggers"]

    @property
    def samples(self) -> list:
        return self.doc["samples"]

    @property
    def anomalies(self) -> list:
        return self.doc.get("anomalies", [])

    @property
    def trace(self) -> list:
        return self.doc["trace"]

    @property
    def schedule_doc(self) -> Optional[dict]:
        return self.doc["schedule"]

    def last_sample(self) -> Optional[dict]:
        return self.samples[-1] if self.samples else None

    def last_health(self, node) -> Optional[dict]:
        """The failing node's last recorded health dict (view, leader,
        in-flight depth, ...), scanning the sample tail backwards."""
        key = str(node)
        for sample in reversed(self.samples):
            record = sample["nodes"].get(key)
            if record is not None:
                return record["health"]
        return None

    def metrics_of(self, node) -> Optional[dict]:
        return self.doc["metrics"].get(str(node))


def load_flight_record(path: str) -> FlightRecord:
    with open(path) as fh:
        return FlightRecord(json.load(fh))


__all__ = [
    "FLIGHTREC_VERSION",
    "FlightRecord",
    "FlightRecorder",
    "load_flight_record",
]
