"""Cluster observability plane: sim-clock time-series sampling, health
snapshots, anomaly detectors, exporters, a crash/invariant flight recorder,
and device/kernel accounting.

Always compiled, default off (like consensus_tpu/trace/).  See sampler.py
(the scheduler-driven ring sampler + derived health fields), detectors.py
(commit-stall / view-change storm / leader flap / sync-lag / verify-collapse),
export.py (Prometheus text format v0.0.4, sorted-key JSONL, terminal
sparklines), flightrec.py (atomic failure bundles + loader), kernels.py
(jit compile/retrace/launch/cost accounting).
"""

from consensus_tpu.obs.detectors import Anomaly, DetectorThresholds
from consensus_tpu.obs.export import (
    sample_to_prometheus,
    series_to_jsonl,
    sparkline,
    write_series_jsonl,
)
from consensus_tpu.obs.flightrec import (
    FlightRecord,
    FlightRecorder,
    load_flight_record,
)
from consensus_tpu.obs.kernels import (
    KERNELS,
    TENANT_KERNELS,
    KernelRegistry,
    TenantAccounting,
    instrumented_jit,
)
from consensus_tpu.obs.sampler import ClusterSampler

__all__ = [
    "Anomaly",
    "ClusterSampler",
    "DetectorThresholds",
    "FlightRecord",
    "FlightRecorder",
    "KERNELS",
    "KernelRegistry",
    "TENANT_KERNELS",
    "TenantAccounting",
    "instrumented_jit",
    "load_flight_record",
    "sample_to_prometheus",
    "series_to_jsonl",
    "sparkline",
    "write_series_jsonl",
]
