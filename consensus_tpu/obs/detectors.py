"""Anomaly detectors evaluated at sample time.

Each detector is a pure function of the health/metrics series the sampler
accumulates — no wall clock, no randomness — so the anomaly stream of a
fixed-seed run is byte-identical across replays.  Detectors are
EDGE-TRIGGERED: a condition fires once at onset and re-arms only after the
condition clears, so a 300-second stall is one anomaly, not 300.

The thirteen kinds (pinned metric names: metrics.OBS_ANOMALY_KEYS):

``commit_stall``        a running node has pending pool work but its ledger
                        has not grown for ``stall_window`` sim-seconds
``view_change_storm``   the node's view number advanced ``storm_views``+
                        times within ``storm_window``
``leader_flap``         the node's leader identity changed ``flap_changes``+
                        times within ``flap_window``
``sync_lag``            the node's ledger is ``lag_decisions``+ behind the
                        tallest RUNNING peer
``verify_collapse``     the ledger grew ``collapse_decisions``+ while the
                        node's ``consensus_verify_launches`` counter stayed
                        flat — decisions are appearing without commit-path
                        verification work (e.g. a sync catch-up burst, or a
                        verifier wedge)
``membership_churn``    the node's membership epoch advanced
                        ``churn_epochs``+ times within ``churn_window`` —
                        reconfigurations landing faster than a healthy
                        administrative cadence (an elastic-membership run
                        gone thrashy, or an adversary replaying admin
                        traffic)
``admission_overload`` between two samples, the ingress admission layer
                        rate-limited ``overload_reject_fraction``+ of at
                        least ``overload_min_offered`` offered requests —
                        sustained demand past the per-client budgets
``dedup_storm``         between two samples, ``dedup_hit_fraction``+ of at
                        least ``dedup_min_offered`` offered requests were
                        duplicates — a retry storm landing on the dedup
                        cache
``engine_degraded``     the node's supervised verify engine is serving
                        below its configured ladder rung (a fault-classed
                        breaker opened — models/supervisor.py); clears when
                        the supervisor re-promotes to rung 0
``wal_corruption``      the node quarantined a corrupt WAL suffix (boot or
                        scrub detection) and is fenced as a non-voting
                        learner until verified sync carries it past the
                        damage (wal/scrub.py, core/controller.py); clears
                        when the fence releases
``wal_stall``           the node's WAL refuses appends — the fsync retry
                        cap was hit or a write failed (ENOSPC) — so the
                        node stopped proposing/voting while still serving
                        sync and reads; clears when an append/probe fsync
                        succeeds
``cross_group_stall``   a cross-group atomic (2PC) transaction has been
                        unresolved — prepared but neither committed nor
                        aborted everywhere — for
                        ``cross_group_stall_window`` sim-seconds (fed by
                        the groups harness via the optional
                        ``groups_twopc_oldest_age`` health field); clears
                        when the oldest in-flight transaction resolves
``wire_abuse``          the node's listener guard (net/framing.py) booked
                        ``wire_abuse_events``+ NEW defense events —
                        malformed-frame strikes, handshake timeouts, bans,
                        quota rejects — since the last sample (fed via the
                        optional ``net_malformed`` / ``net_handshake_timeouts``
                        / ``net_peer_bans`` / ``net_conn_rejected`` health
                        fields a ``wire_guard``-carrying node reports);
                        clears on the first sample with no new events

The two ingress detectors read OPTIONAL health fields
(``ingress_offered`` / ``ingress_rate_limited`` / ``ingress_dedup_hits``,
fed by ingress/driver.py), ``engine_degraded`` reads the optional
``engine_degraded`` / ``engine_rung`` fields (fed only when a node carries
an ``engine_supervisor``), and the two wal detectors read the optional
``wal_fenced`` / ``wal_degraded`` fields (fed only for file-backed WALs);
samples without them, so every pre-existing fixed-seed anomaly stream, are
untouched.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

ANOMALY_KINDS = (
    "commit_stall",
    "view_change_storm",
    "leader_flap",
    "sync_lag",
    "verify_collapse",
    "membership_churn",
    "admission_overload",
    "dedup_storm",
    "engine_degraded",
    "wal_corruption",
    "wal_stall",
    "cross_group_stall",
    "wire_abuse",
)


@dataclasses.dataclass(frozen=True)
class DetectorThresholds:
    """Tuning knobs, all in sim-seconds / decision counts."""

    stall_window: float = 30.0
    storm_views: int = 3
    storm_window: float = 60.0
    flap_changes: int = 3
    flap_window: float = 60.0
    lag_decisions: int = 5
    collapse_decisions: int = 3
    churn_epochs: int = 2
    churn_window: float = 120.0
    overload_min_offered: int = 20
    overload_reject_fraction: float = 0.5
    dedup_min_offered: int = 20
    dedup_hit_fraction: float = 0.5
    cross_group_stall_window: float = 60.0
    wire_abuse_events: int = 1

    def validate(self) -> None:
        if self.stall_window <= 0 or self.storm_window <= 0 or self.flap_window <= 0:
            raise ValueError("detector windows must be positive")
        if self.churn_window <= 0 or self.cross_group_stall_window <= 0:
            raise ValueError("detector windows must be positive")
        if min(self.storm_views, self.flap_changes,
               self.lag_decisions, self.collapse_decisions,
               self.churn_epochs) < 1:
            raise ValueError("detector counts must be >= 1")
        if min(self.overload_min_offered, self.dedup_min_offered,
               self.wire_abuse_events) < 1:
            raise ValueError("detector counts must be >= 1")
        if not (0.0 < self.overload_reject_fraction <= 1.0
                and 0.0 < self.dedup_hit_fraction <= 1.0):
            raise ValueError("detector fractions must be in (0, 1]")


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One detector firing, pinned to the sim clock."""

    kind: str
    node: int
    sim_time: float
    detail: str

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "node": self.node,
            "t": self.sim_time,
            "detail": self.detail,
        }


class _NodeState:
    """Per-node detector memory between samples."""

    __slots__ = (
        "stall_since", "last_ledger", "view_changes", "leader_changes",
        "last_view", "last_leader", "collapse_base",
        "epoch_changes", "last_epoch", "ingress_base", "wire_abuse_base",
    )

    def __init__(self) -> None:
        self.stall_since: Optional[float] = None
        self.last_ledger = 0
        self.view_changes: deque = deque()     # (t, view)
        self.leader_changes: deque = deque()   # (t, leader)
        self.last_view: Optional[int] = None
        self.last_leader: Optional[int] = None
        self.collapse_base: Optional[tuple[int, float]] = None  # (ledger, launches)
        self.epoch_changes: deque = deque()    # (t, epoch)
        self.last_epoch: Optional[int] = None
        #: Previous sample's cumulative (offered, rate_limited, dedup_hits)
        #: — the ingress detectors fire on PER-SAMPLE deltas.
        self.ingress_base: Optional[tuple[int, int, int]] = None
        #: Previous sample's cumulative listener-guard event total — the
        #: wire_abuse detector fires on PER-SAMPLE deltas.
        self.wire_abuse_base: Optional[int] = None


class DetectorBank:
    """Stateful evaluator: feed it one ``(t, {node: health}, {node: launches})``
    tuple per sample, get the anomalies that FIRED at that sample."""

    def __init__(self, thresholds: Optional[DetectorThresholds] = None) -> None:
        self.thresholds = thresholds or DetectorThresholds()
        self.thresholds.validate()
        self._nodes: dict[int, _NodeState] = {}
        #: (kind, node) pairs whose condition currently holds — the
        #: edge-trigger latch.
        self._active: set[tuple[str, int]] = set()

    def _state(self, nid: int) -> _NodeState:
        st = self._nodes.get(nid)
        if st is None:
            st = self._nodes[nid] = _NodeState()
        return st

    def _edge(self, fired: list, kind: str, nid: int, t: float,
              condition: bool, detail: str) -> None:
        key = (kind, nid)
        if condition:
            if key not in self._active:
                self._active.add(key)
                fired.append(Anomaly(kind=kind, node=nid, sim_time=t,
                                     detail=detail))
        else:
            self._active.discard(key)

    def evaluate(
        self,
        t: float,
        health: dict,
        launches: Optional[dict] = None,
    ) -> list[Anomaly]:
        """``health``: node id -> the sampler's health dict;
        ``launches``: node id -> cumulative ``consensus_verify_launches``
        (None / missing node skips the collapse detector)."""
        th = self.thresholds
        fired: list[Anomaly] = []
        for nid in sorted(health):
            h = health[nid]
            st = self._state(nid)
            running = h.get("running", False)
            ledger = h.get("ledger", 0)

            # --- commit stall ------------------------------------------
            if not running or h.get("pool", 0) <= 0 or ledger > st.last_ledger:
                st.stall_since = None
            elif st.stall_since is None:
                st.stall_since = t
            stalled = (
                st.stall_since is not None
                and t - st.stall_since >= th.stall_window
            )
            self._edge(
                fired, "commit_stall", nid, t, stalled,
                f"ledger stuck at {ledger} with pending pool work for "
                f">= {th.stall_window:g}s",
            )
            st.last_ledger = max(st.last_ledger, ledger)

            # --- view-change storm -------------------------------------
            view = h.get("view", -1)
            if running and view >= 0:
                if st.last_view is not None and view != st.last_view:
                    st.view_changes.append((t, view))
                st.last_view = view
            while st.view_changes and t - st.view_changes[0][0] > th.storm_window:
                st.view_changes.popleft()
            self._edge(
                fired, "view_change_storm", nid, t,
                len(st.view_changes) >= th.storm_views,
                f"{len(st.view_changes)} view changes within "
                f"{th.storm_window:g}s (now at view {view})",
            )

            # --- leader flap -------------------------------------------
            leader = h.get("leader", -1)
            if running and leader >= 0:
                if st.last_leader is not None and leader != st.last_leader:
                    st.leader_changes.append((t, leader))
                st.last_leader = leader
            while st.leader_changes and t - st.leader_changes[0][0] > th.flap_window:
                st.leader_changes.popleft()
            self._edge(
                fired, "leader_flap", nid, t,
                len(st.leader_changes) >= th.flap_changes,
                f"{len(st.leader_changes)} leader changes within "
                f"{th.flap_window:g}s (now following {leader})",
            )

            # --- sync-lag divergence -----------------------------------
            lag = h.get("sync_lag", 0)
            self._edge(
                fired, "sync_lag", nid, t, lag >= th.lag_decisions,
                f"{lag} decisions behind the tallest running peer",
            )

            # --- membership churn --------------------------------------
            epoch = h.get("epoch", -1)
            if running and epoch >= 0:
                if st.last_epoch is not None and epoch != st.last_epoch:
                    st.epoch_changes.append((t, epoch))
                st.last_epoch = epoch
            while st.epoch_changes and t - st.epoch_changes[0][0] > th.churn_window:
                st.epoch_changes.popleft()
            self._edge(
                fired, "membership_churn", nid, t,
                len(st.epoch_changes) >= th.churn_epochs,
                f"{len(st.epoch_changes)} membership epoch changes within "
                f"{th.churn_window:g}s (now serving epoch {epoch})",
            )

            # --- ingress: admission overload / dedup storm -------------
            offered = h.get("ingress_offered")
            if offered is None:
                # Not an ingress-plane sample: clear state + latches so
                # cluster health dicts keep their pre-ingress streams.
                st.ingress_base = None
                self._active.discard(("admission_overload", nid))
                self._active.discard(("dedup_storm", nid))
            else:
                limited = h.get("ingress_rate_limited", 0)
                dedup = h.get("ingress_dedup_hits", 0)
                if st.ingress_base is None:
                    st.ingress_base = (0, 0, 0)
                d_off = offered - st.ingress_base[0]
                d_lim = limited - st.ingress_base[1]
                d_dup = dedup - st.ingress_base[2]
                st.ingress_base = (offered, limited, dedup)
                overloaded = (
                    d_off >= th.overload_min_offered
                    and d_lim >= th.overload_reject_fraction * d_off
                )
                self._edge(
                    fired, "admission_overload", nid, t, overloaded,
                    f"rate-limited {d_lim}/{d_off} offered requests since "
                    "the last sample",
                )
                storming = (
                    d_off >= th.dedup_min_offered
                    and d_dup >= th.dedup_hit_fraction * d_off
                )
                self._edge(
                    fired, "dedup_storm", nid, t, storming,
                    f"dedup absorbed {d_dup}/{d_off} offered requests since "
                    "the last sample",
                )

            # --- engine degraded ---------------------------------------
            degraded = h.get("engine_degraded")
            if degraded is None:
                # No supervised engine on this node: discard the latch so
                # pre-supervision health streams stay byte-identical.
                self._active.discard(("engine_degraded", nid))
            else:
                self._edge(
                    fired, "engine_degraded", nid, t, bool(degraded),
                    f"supervised verify engine serving at rung "
                    f"{h.get('engine_rung', -1)} (below configured)",
                )

            # --- wal corruption (fenced learner) -----------------------
            fenced = h.get("wal_fenced")
            if fenced is None:
                # No file-backed WAL on this node: discard the latch so
                # pre-storage health streams stay byte-identical.
                self._active.discard(("wal_corruption", nid))
            else:
                self._edge(
                    fired, "wal_corruption", nid, t, bool(fenced),
                    "durable-state corruption quarantined; fenced as a "
                    "non-voting learner pending verified sync",
                )

            # --- wal stall (degraded append path) ----------------------
            wal_deg = h.get("wal_degraded")
            if wal_deg is None:
                self._active.discard(("wal_stall", nid))
            else:
                self._edge(
                    fired, "wal_stall", nid, t, bool(wal_deg),
                    "WAL refusing appends (fsync/append failures); node "
                    "stopped proposing and voting until the disk heals",
                )

            # --- cross-group 2PC stall ---------------------------------
            twopc_age = h.get("groups_twopc_oldest_age")
            if twopc_age is None:
                # Not a sharded-deployment sample: discard the latch so
                # pre-groups health streams stay byte-identical.
                self._active.discard(("cross_group_stall", nid))
            else:
                self._edge(
                    fired, "cross_group_stall", nid, t,
                    twopc_age >= th.cross_group_stall_window,
                    f"oldest cross-group transaction unresolved for "
                    f"{twopc_age:g}s (window {th.cross_group_stall_window:g}s)",
                )

            # --- wire abuse (listener guard deltas) --------------------
            malformed = h.get("net_malformed")
            if malformed is None:
                # No listener guard on this node: discard the latch so
                # pre-hardening health streams stay byte-identical.
                st.wire_abuse_base = None
                self._active.discard(("wire_abuse", nid))
            else:
                total = (
                    malformed
                    + h.get("net_handshake_timeouts", 0)
                    + h.get("net_peer_bans", 0)
                    + h.get("net_conn_rejected", 0)
                )
                if st.wire_abuse_base is None:
                    st.wire_abuse_base = 0
                delta = total - st.wire_abuse_base
                st.wire_abuse_base = total
                self._edge(
                    fired, "wire_abuse", nid, t,
                    delta >= th.wire_abuse_events,
                    f"listener guard booked {delta} abuse events since the "
                    f"last sample ({total} cumulative: {malformed} malformed, "
                    f"{h.get('net_peer_bans', 0)} bans)",
                )

            # --- verify-launch-rate collapse ---------------------------
            nl = (launches or {}).get(nid)
            if nl is None:
                st.collapse_base = None
                self._active.discard(("verify_collapse", nid))
            else:
                if st.collapse_base is None or nl > st.collapse_base[1]:
                    st.collapse_base = (ledger, nl)
                grown = ledger - st.collapse_base[0]
                self._edge(
                    fired, "verify_collapse", nid, t,
                    grown >= th.collapse_decisions,
                    f"ledger grew {grown} decisions with zero verify "
                    f"launches (counter flat at {nl:g})",
                )
        return fired


__all__ = [
    "ANOMALY_KINDS",
    "Anomaly",
    "DetectorBank",
    "DetectorThresholds",
]
