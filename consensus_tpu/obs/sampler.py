"""Sim-clock time-series sampler: the heart of the observability plane.

A :class:`ClusterSampler` re-arms itself on the cluster's scheduler
(``call_later`` every ``interval`` sim-seconds) and, at each tick, snapshots
every node into one sample record:

* ``metrics`` — the node's full ``Metrics.dump()`` (the sampler installs an
  ``InMemoryProvider``-backed bundle on any node that has none, so every
  node dumps);
* ``health`` — derived fields read straight off the live objects: current
  view, leader, in-progress sequence, in-flight pipeline depth, pool
  occupancy, WAL size and fsync count, ledger height, and sync lag versus
  the tallest running peer.

Samples land in a bounded ring (oldest overwritten) and are evaluated by the
anomaly :class:`~consensus_tpu.obs.detectors.DetectorBank`; a firing bumps
the affected node's pinned ``obs_anomaly_*`` counter, emits an
``obs.anomaly`` trace instant, and is appended to :attr:`anomalies` (the
entry chaos runs assert on).

Everything reads — nothing writes protocol state — so sampling is
observationally transparent: a fixed-seed run produces byte-identical
ledgers and event logs with the plane on or off, and byte-identical sample
series across replays (enforced by tests/test_obs.py).

Hot-path contract (mirrors trace/tracer.py): the plane is DEFAULT OFF.  A
disabled cluster never constructs a sampler, never installs an in-memory
provider, and never takes a ring append — ``ClusterSampler.total_samples``
(class-level) is the guard counter the overhead test asserts stays flat.
"""

from __future__ import annotations

from typing import Callable, Optional

from consensus_tpu.metrics import InMemoryProvider, Metrics
from consensus_tpu.obs.detectors import Anomaly, DetectorBank, DetectorThresholds
from consensus_tpu.trace.tracer import NOOP_TRACER


class ClusterSampler:
    """Samples every node of a ``testing.app.Cluster`` (or anything
    duck-typed like one: ``scheduler``, ``nodes: {id: node}``) on a fixed
    sim-clock interval into a bounded ring."""

    #: Class-level count of ring appends across every sampler instance —
    #: the disabled-overhead guard snapshots this around a run.
    total_samples = 0

    def __init__(
        self,
        cluster,
        *,
        interval: float = 1.0,
        capacity: int = 4096,
        thresholds: Optional[DetectorThresholds] = None,
        tracer=None,
        install_metrics: bool = True,
    ) -> None:
        if interval <= 0:
            raise ValueError(f"sample interval must be positive, got {interval}")
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.cluster = cluster
        self.interval = interval
        self._capacity = capacity
        self._ring: list = [None] * capacity
        self._count = 0  # samples ever taken
        self._timer = None
        self._stopped = False
        self.detectors = DetectorBank(thresholds)
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        #: Every detector firing, in order.  Chaos runs assert on this.
        self.anomalies: list[Anomaly] = []
        #: ``fn(Anomaly)`` hooks called at fire time (the chaos engine logs
        #: through here so anomalies land in the deterministic event log).
        self.on_anomaly: list[Callable[[Anomaly], None]] = []
        if install_metrics:
            # Before cluster.start(): Node.start hands node.metrics to the
            # Consensus build, so every node must have a dumpable provider
            # by then.  Nodes that already carry a bundle keep it.
            for node in cluster.nodes.values():
                if getattr(node, "metrics", None) is None:
                    node.metrics = Metrics(InMemoryProvider())

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Arm the first tick (one full ``interval`` from now)."""
        self._stopped = False
        if self._timer is None:
            self._timer = self.cluster.scheduler.call_later(
                self.interval, self._tick, name="obs-sample"
            )

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # --- sampling ----------------------------------------------------------

    def _tick(self) -> None:
        self._timer = None
        if self._stopped:
            return
        self.sample_now()
        self._timer = self.cluster.scheduler.call_later(
            self.interval, self._tick, name="obs-sample"
        )

    def sample_now(self) -> dict:
        """Take one sample immediately (ticks call this; tests may too)."""
        t = self.cluster.scheduler.now()
        nodes = self.cluster.nodes
        max_height = max(
            (len(n.app.ledger) for n in nodes.values() if n.running),
            default=0,
        )
        health: dict[int, dict] = {}
        launches: dict[int, float] = {}
        node_records: dict[str, dict] = {}
        for nid in sorted(nodes):
            node = nodes[nid]
            h = self._node_health(node, max_height)
            health[nid] = h
            record: dict = {"health": h}
            provider = getattr(getattr(node, "metrics", None), "provider", None)
            if isinstance(provider, InMemoryProvider):
                record["metrics"] = provider.dump()
                inst = provider.instruments.get("consensus_verify_launches")
                if inst is not None:
                    launches[nid] = inst.value
                node.metrics.obs.count_samples.add(1)
            node_records[str(nid)] = record

        fired = self.detectors.evaluate(t, health, launches)
        for anomaly in fired:
            node = nodes.get(anomaly.node)
            metrics = getattr(node, "metrics", None)
            if metrics is not None:
                metrics.obs.anomaly_counter(anomaly.kind).add(1)
            if self.tracer.enabled:
                self.tracer.instant(
                    "obs", "obs.anomaly",
                    kind=anomaly.kind, node=anomaly.node,
                )
            self.anomalies.append(anomaly)
            for hook in self.on_anomaly:
                hook(anomaly)

        sample = {
            "t": round(t, 6),
            "i": self._count,
            "nodes": node_records,
            "anomalies": [a.as_dict() for a in fired],
        }
        self._ring[self._count % self._capacity] = sample
        self._count += 1
        ClusterSampler.total_samples += 1
        return sample

    def _node_health(self, node, max_height: int) -> dict:
        ledger = len(node.app.ledger)
        h = {
            "running": bool(node.running),
            "view": -1,
            "leader": -1,
            "seq": -1,
            "in_flight": 0,
            "syncing": False,
            "pool": 0,
            "wal_entries": -1,
            "wal_fsyncs": -1,
            "ledger": ledger,
            "sync_lag": max(0, max_height - ledger),
            "epoch": -1,
        }
        wal = getattr(node, "wal", None)
        entries = getattr(wal, "entries", None)
        if entries is not None:
            h["wal_entries"] = len(entries)
        fsyncs = getattr(wal, "fsync_count", None)
        if fsyncs is not None:
            h["wal_fsyncs"] = int(fsyncs)
        cons = getattr(node, "consensus", None)
        if node.running and cons is not None and cons.controller is not None:
            ch = cons.controller.health()
            h["view"] = int(ch["view"])
            h["leader"] = int(ch["leader"])
            h["seq"] = int(ch["seq"])
            h["in_flight"] = int(ch["in_flight"])
            h["syncing"] = bool(ch["syncing"])
            h["epoch"] = int(ch["epoch"])
            pool = getattr(cons, "pool", None)
            if pool is not None:
                h["pool"] = int(pool.count)
        # Optional supervision surface: only nodes carrying a supervised
        # engine report it, so pre-supervision samples stay byte-identical.
        sup = getattr(node, "engine_supervisor", None)
        if sup is not None:
            h["engine_degraded"] = bool(sup.degraded)
            h["engine_rung"] = int(sup.rung)
        # Optional durable-storage surface: only file-backed WALs carry a
        # degraded flag (MemWAL does not), so pre-storage samples stay
        # byte-identical.
        # Optional listener-guard surface: only nodes carrying a wire_guard
        # (hardened listeners, or the chaos net_abuse arm) report it, so
        # pre-hardening samples stay byte-identical.
        guard = getattr(node, "wire_guard", None)
        if guard is not None:
            stats = guard.stats
            h["net_malformed"] = int(stats.malformed)
            h["net_handshake_timeouts"] = int(stats.handshake_timeouts)
            h["net_peer_bans"] = int(stats.bans)
            h["net_conn_rejected"] = int(stats.rejected)
        wal_deg = getattr(wal, "degraded", None)
        if wal_deg is not None:
            h["wal_degraded"] = bool(wal_deg)
            fenced = False
            if node.running and cons is not None and cons.controller is not None:
                fenced = bool(cons.controller.health().get("fenced", False))
            h["wal_fenced"] = fenced
        return h

    # --- reads -------------------------------------------------------------

    def samples(self) -> list:
        """Surviving samples, oldest first (at most ``capacity``)."""
        n, cap = self._count, self._capacity
        if n <= cap:
            return [s for s in self._ring[:n]]
        cut = n % cap
        return self._ring[cut:] + self._ring[:cut]

    @property
    def taken(self) -> int:
        """Samples ever taken by this sampler."""
        return self._count

    def last_sample(self) -> Optional[dict]:
        if self._count == 0:
            return None
        return self._ring[(self._count - 1) % self._capacity]

    def latest_health(self) -> dict:
        """``{node id (str): health dict}`` from the most recent sample."""
        last = self.last_sample()
        if last is None:
            return {}
        return {nid: rec["health"] for nid, rec in last["nodes"].items()}

    def anomaly_counts(self) -> dict:
        """``{kind: total firings}``, only kinds that fired (sorted)."""
        counts: dict[str, int] = {}
        for a in self.anomalies:
            counts[a.kind] = counts.get(a.kind, 0) + 1
        return dict(sorted(counts.items()))


__all__ = ["ClusterSampler"]
