"""Observability exporters: Prometheus text format (v0.0.4), sorted-key
JSONL, and terminal sparklines.

Byte-determinism contract (same as trace/export.py): dict keys sorted,
separators fixed, timestamps from the scheduler clock, float formatting
canonical — two identically seeded runs export identical bytes, and the
golden-file test (tests/test_obs.py) pins the Prometheus export of a
fixed-seed 3-node run byte-for-byte.
"""

from __future__ import annotations

import json
import re
from typing import Iterable, Optional

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: Health fields a node reports only when the corresponding surface
#: exists — the listener guard's counters appear once a ``wire_guard``
#: is attached (hardened listeners, the chaos ``net_abuse`` arm) and
#: never before, so pre-hardening scrapes stay byte-identical.
OPTIONAL_HEALTH_FIELDS = (
    "net_malformed", "net_handshake_timeouts", "net_peer_bans",
    "net_conn_rejected",
)

#: Health fields exported as ``obs_health_<field>{node="..."}`` gauges.
#: The :data:`OPTIONAL_HEALTH_FIELDS` tail is emitted only when present.
HEALTH_FIELDS = (
    "running", "view", "leader", "seq", "in_flight", "syncing",
    "pool", "wal_entries", "wal_fsyncs", "ledger", "sync_lag", "epoch",
) + OPTIONAL_HEALTH_FIELDS


def _fmt_value(v) -> str:
    """Canonical Prometheus sample value: integers without a trailing
    ``.0``, floats via repr (shortest round-trip, stable across runs)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _split_series(name: str) -> tuple[str, Optional[str]]:
    """An ``InMemoryProvider`` label-vector child is keyed
    ``name{v1,v2}`` — map it to the parent family plus a ``labels`` label
    so the export stays inside the Prometheus grammar."""
    if name.endswith("}") and "{" in name:
        base, _, rest = name.partition("{")
        return base, rest[:-1]
    return name, None


def sample_to_prometheus(sample: dict, *, prefix: str = "") -> str:
    """Render ONE sampler record as a Prometheus text-format (v0.0.4)
    scrape body: the sample clock, every health field, and every metrics
    instrument, each labeled ``node="<id>"``."""
    families: dict[str, list[tuple[str, str]]] = {}

    def emit(name: str, labels: list[tuple[str, str]], value) -> None:
        name = prefix + name
        if not _NAME_OK.match(name):
            return  # unexportable name: skip rather than corrupt the scrape
        label_str = ",".join(f'{k}="{v}"' for k, v in labels)
        families.setdefault(name, []).append((label_str, _fmt_value(value)))

    emit("obs_sample_time", [], sample.get("t", 0.0))
    emit("obs_sample_index", [], sample.get("i", 0))
    for nid in sorted(sample.get("nodes", {})):
        record = sample["nodes"][nid]
        health = record.get("health", {})
        for field in HEALTH_FIELDS:
            if field in health:
                emit(f"obs_health_{field}", [("node", nid)], health[field])
        for name in sorted(record.get("metrics", {})):
            data = record["metrics"][name]
            base, extra = _split_series(name)
            labels: list[tuple[str, str]] = []
            if extra is not None:
                labels.append(("labels", extra))
            labels.append(("node", nid))
            emit(base, labels, data.get("value", 0.0))
            obs = data.get("observations") or ()
            if obs:
                emit(base + "_count", labels, len(obs))
                emit(base + "_sum", labels, sum(obs))

    lines: list[str] = []
    for name in sorted(families):
        lines.append(f"# TYPE {name} untyped")
        for label_str, value in sorted(families[name]):
            if label_str:
                lines.append(f"{name}{{{label_str}}} {value}")
            else:
                lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, sample: dict, *, prefix: str = "") -> str:
    body = sample_to_prometheus(sample, prefix=prefix)
    with open(path, "w") as fh:
        fh.write(body)
    return path


# --- JSONL ------------------------------------------------------------------


def series_to_jsonl(samples: Iterable[dict]) -> str:
    """One sorted-key compact JSON object per sample, trailing newline."""
    return "".join(
        json.dumps(s, sort_keys=True, separators=(",", ":")) + "\n"
        for s in samples
    )


def write_series_jsonl(path: str, samples: Iterable[dict]) -> str:
    with open(path, "w") as fh:
        fh.write(series_to_jsonl(samples))
    return path


# --- sparklines -------------------------------------------------------------

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, *, width: int = 60) -> str:
    """A tiny unicode sparkline of ``values`` (most recent ``width``)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _BLOCKS[0] * len(vals)
    return "".join(
        _BLOCKS[min(len(_BLOCKS) - 1, int((v - lo) / span * len(_BLOCKS)))]
        for v in vals
    )


def render_watch(samples, *, fields=("ledger", "pool", "in_flight"),
                 width: int = 60) -> str:
    """Terminal panel for ``chain_tps.py --watch``: one sparkline per
    health field, aggregated across nodes (max per sample), annotated with
    the latest value."""
    lines = []
    for field in fields:
        series = [
            max(
                (rec["health"].get(field, 0) for rec in s["nodes"].values()),
                default=0,
            )
            for s in samples
        ]
        spark = sparkline(series, width=width)
        latest = series[-1] if series else 0
        lines.append(f"{field:>10} {spark} {_fmt_value(latest)}")
    return "\n".join(lines)


__all__ = [
    "HEALTH_FIELDS",
    "render_watch",
    "sample_to_prometheus",
    "series_to_jsonl",
    "sparkline",
    "write_prometheus",
    "write_series_jsonl",
]
