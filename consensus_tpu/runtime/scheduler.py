"""Deterministic event scheduler — the concurrency model of the framework.

The reference runs one goroutine per component (View, Controller, ViewChanger,
HeartbeatMonitor, per-commit verification, per-request timers — reference
internal/bft/view.go:139-141, controller.go:808-811, viewchanger.go:154-158,
heartbeatmonitor.go:101-104, requestpool.go:250-252) and then needs locks to
serialize delivery against sync (reference internal/bft/controller.go:928-965,
``MutuallyExclusiveDeliver``).  Here the design is inverted: **every replica is
a single-threaded state machine driven by an event queue with an injectable
clock**.  Consequences:

* No locks anywhere in the protocol core — delivery, sync, timers, and message
  handling are serialized by construction.
* Multi-replica tests share one :class:`SimScheduler`, interleave replicas
  deterministically, and jump virtual time over heartbeat/complaint timeouts
  instantly (the reference's tests hand-feed ticker channels to get the same
  effect — reference test/basic_test.go:108-115).
* Production uses :class:`RealtimeScheduler`: the same queue pumped by one
  thread against the wall clock, with thread-safe ``post`` for ingress from
  transport/application threads.

This adopts — and completes — the reference's own intended direction: its
heap-based logical-time ``Scheduler``/``TaskQueue`` exists but is dead code
(reference internal/bft/sched.go:15-248, TODO at internal/bft/batcher.go:46).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time as _time
from typing import Callable, Optional, Protocol

logger = logging.getLogger("consensus_tpu.runtime")


class TimerHandle:
    """Cancelable handle for a scheduled callback."""

    __slots__ = ("when", "seq", "fn", "name", "_cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None], name: str):
        self.when = when
        self.seq = seq
        self.fn: Optional[Callable[[], None]] = fn
        self.name = name
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True
        self.fn = None  # break reference cycles for long-lived queues

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def __lt__(self, other: "TimerHandle") -> bool:
        # Total deterministic order: fire time, then scheduling order.
        return (self.when, self.seq) < (other.when, other.seq)

    def __repr__(self) -> str:
        state = "cancelled" if self._cancelled else f"@{self.when:.6f}"
        return f"<Timer {self.name or 'anon'} {state}>"


class Clock(Protocol):
    """Minimal time source components read; injected, never ``time.time``."""

    def now(self) -> float: ...


class Scheduler(Protocol):
    """What protocol components see: a clock plus callback scheduling.

    Implementations must execute callbacks one at a time (run-to-completion);
    callbacks may schedule further callbacks, including at zero delay.
    """

    def now(self) -> float: ...

    def call_later(
        self, delay: float, fn: Callable[[], None], *, name: str = ""
    ) -> TimerHandle: ...

    def post(self, fn: Callable[[], None], *, name: str = "") -> None: ...


class SimScheduler:
    """Virtual-time scheduler for tests and simulation.

    Time only moves when :meth:`advance` / :meth:`run` consume the queue; an
    idle queue costs nothing, so scenarios can leap over 20-second complaint
    timeouts instantly and stay fully deterministic (same seed of events →
    same interleaving, always).
    """

    def __init__(self, start: float = 0.0) -> None:
        self._now = start
        self._heap: list[TimerHandle] = []
        self._seq = itertools.count()
        #: Optional observer called as ``hook(event_name, exception)`` when
        #: an event handler raises — the flight recorder's trigger seam for
        #: unhandled controller exceptions (consensus_tpu/obs/flightrec.py).
        #: The exception is still swallowed (components must stay isolated
        #: from each other's failures); the hook only *observes* it.
        self.on_unhandled_error: Optional[Callable[[str, BaseException], None]] = None

    # --- Scheduler protocol ------------------------------------------------

    def now(self) -> float:
        return self._now

    def call_later(
        self, delay: float, fn: Callable[[], None], *, name: str = ""
    ) -> TimerHandle:
        if delay < 0:
            raise ValueError(f"negative delay {delay}")
        h = TimerHandle(self._now + delay, next(self._seq), fn, name)
        heapq.heappush(self._heap, h)
        return h

    def post(self, fn: Callable[[], None], *, name: str = "") -> None:
        self.call_later(0.0, fn, name=name)

    # --- test-driver surface ----------------------------------------------

    def _fire(self, h: TimerHandle) -> None:
        fn = h.fn
        if h.cancelled or fn is None:
            return
        try:
            fn()
        except Exception as err:
            # A crashing handler must not wedge the whole simulation; real
            # components are expected to catch their own errors.
            logger.exception("unhandled error in event %r", h.name)
            hook = self.on_unhandled_error
            if hook is not None:
                try:
                    hook(h.name, err)
                except Exception:
                    logger.exception("on_unhandled_error hook failed")

    def _drain(
        self,
        *,
        deadline: Optional[float],
        stop: Optional[Callable[[], bool]],
        max_events: int,
        label: str,
    ) -> int:
        """Shared event-loop body: pop due events in order, skip cancelled
        ones, fire the rest; stop at ``deadline`` (virtual time), when
        ``stop()`` turns true, or after ``max_events`` (livelock guard)."""
        executed = 0
        while self._heap:
            if deadline is not None and self._heap[0].when > deadline:
                break
            h = heapq.heappop(self._heap)
            if h.cancelled:
                continue
            if executed >= max_events:
                raise RuntimeError(f"{label} exceeded {max_events} events")
            self._now = max(self._now, h.when)
            self._fire(h)
            executed += 1
            if stop is not None and stop():
                break
        return executed

    def run_until_idle(self, *, max_events: int = 1_000_000) -> int:
        """Run events (advancing virtual time as needed) until none remain.

        Returns the number of events executed.  ``max_events`` guards against
        livelock from self-rescheduling handlers.
        """
        return self._drain(
            deadline=None, stop=None, max_events=max_events, label="run_until_idle"
        )

    def advance(self, dt: float, *, max_events: int = 1_000_000) -> int:
        """Run all events due within the next ``dt`` seconds, then set the
        clock to exactly ``now + dt``.  Returns events executed."""
        if dt < 0:
            raise ValueError(f"negative dt {dt}")
        deadline = self._now + dt
        executed = self._drain(
            deadline=deadline, stop=None, max_events=max_events, label="advance"
        )
        self._now = deadline
        return executed

    def run_until(
        self,
        predicate: Callable[[], bool],
        *,
        max_time: float = 3600.0,
        max_events: int = 1_000_000,
    ) -> bool:
        """Run events until ``predicate()`` holds or the virtual-time budget
        is exhausted.  Returns whether the predicate was met."""
        if predicate():
            return True
        self._drain(
            deadline=self._now + max_time,
            stop=predicate,
            max_events=max_events,
            label="run_until",
        )
        return predicate()

    @property
    def pending(self) -> int:
        """Live (non-cancelled) queued events."""
        return sum(1 for h in self._heap if not h.cancelled)


class RealtimeScheduler:
    """Wall-clock scheduler: one worker thread pumps the same event queue.

    Transport and application threads hand work in via the thread-safe
    ``post`` / ``call_later``; everything executes on the single worker
    thread, preserving the run-to-completion model the protocol core assumes.
    """

    def __init__(self) -> None:
        self._heap: list[TimerHandle] = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        #: Same contract as ``SimScheduler.on_unhandled_error``.
        self.on_unhandled_error: Optional[Callable[[str, BaseException], None]] = None

    def now(self) -> float:
        return _time.monotonic()

    def call_later(
        self, delay: float, fn: Callable[[], None], *, name: str = ""
    ) -> TimerHandle:
        if delay < 0:
            raise ValueError(f"negative delay {delay}")
        with self._cond:
            h = TimerHandle(self.now() + delay, next(self._seq), fn, name)
            heapq.heappush(self._heap, h)
            self._cond.notify()
            return h

    def post(self, fn: Callable[[], None], *, name: str = "") -> None:
        self.call_later(0.0, fn, name=name)

    def start(self, *, thread_name: str = "consensus-runtime") -> None:
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name=thread_name, daemon=True
        )
        self._thread.start()

    def stop(self, *, timeout: float = 5.0) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # A wedged callback outlived the join budget: keep the handle
                # so a later start() can't spawn a second worker over the
                # same heap (which would break run-to-completion).
                raise RuntimeError(
                    "runtime worker did not stop within "
                    f"{timeout}s; a callback is blocking it"
                )
            self._thread = None

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._stopped:
                        return
                    now = self.now()
                    if self._heap and self._heap[0].cancelled:
                        heapq.heappop(self._heap)
                        continue
                    if self._heap and self._heap[0].when <= now:
                        h = heapq.heappop(self._heap)
                        break
                    wait = (self._heap[0].when - now) if self._heap else None
                    self._cond.wait(timeout=wait)
            fn = h.fn
            if h.cancelled or fn is None:
                continue
            try:
                fn()
            except Exception as err:
                logger.exception("unhandled error in event %r", h.name)
                hook = self.on_unhandled_error
                if hook is not None:
                    try:
                        hook(h.name, err)
                    except Exception:
                        logger.exception("on_unhandled_error hook failed")


__all__ = [
    "Clock",
    "Scheduler",
    "SimScheduler",
    "RealtimeScheduler",
    "TimerHandle",
]
