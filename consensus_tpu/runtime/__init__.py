"""Deterministic clock + event scheduler (the replica concurrency model)."""

from consensus_tpu.runtime.scheduler import (
    Clock,
    RealtimeScheduler,
    Scheduler,
    SimScheduler,
    TimerHandle,
)

__all__ = [
    "Clock",
    "Scheduler",
    "SimScheduler",
    "RealtimeScheduler",
    "TimerHandle",
]
