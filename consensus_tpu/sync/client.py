"""Catch-up client: the production implementation of the Synchronizer port.

Replaces the test harness's shared-memory shortcut (``TestApp.sync`` reading
``cluster.longest_ledger``) with a real wire protocol: probe peers for their
chain height, fetch ranged decision chunks from the best-scored peer, verify
every fetched decision's commit-signature quorum, and apply.  Parity model:
the reference leaves ``Synchronizer`` to the application and Fabric fills it
with the block puller (pulls blocks from orderers, verifies each block's
signature set, round-robins away from failing endpoints) — this module is
that component for consensus_tpu.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Sequence, Set, Union

from consensus_tpu.api.deps import Synchronizer, Verifier
from consensus_tpu.sync.store import DecisionStore
from consensus_tpu.sync.transport import SyncTransport
from consensus_tpu.types import Decision, QuorumCert, Reconfig, SyncResponse, as_cert
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire.codec import CodecError, decode_view_metadata, encoded_cert_size
from consensus_tpu.wire.messages import SyncChunk, SyncRequest, SyncSnapshotMeta

logger = logging.getLogger("consensus_tpu.sync")

#: Score deltas: a failed fetch is routine (peer down, partition); serving
#: data that fails verification is byzantine evidence and effectively
#: disqualifies the peer until everyone else has failed many times over.
_DEMOTE_FETCH = 1.0
_DEMOTE_FORGED = 100.0


def honest_endorsement_threshold(n: int) -> int:
    """Default per-decision acceptance threshold: ``f + 1`` distinct valid
    consenter signatures.

    Commit certs are written with a full ``2f + 1`` quorum, and every
    signature in a fetched cert is batch-verified — but a decision committed
    before a membership change carries the quorum of ITS era, whose size is
    not reconstructible from the current configuration alone (a cluster
    grown from 4 to 5 nodes has 3-signature certs in its history that are
    perfectly valid).  ``f + 1`` valid signatures under the current fault
    assumption guarantee at least one HONEST replica signed the commit, and
    honest replicas only sign prepared proposals — the standard PBFT
    state-transfer acceptance rule.  Forging it needs ``f + 1`` colluding
    consenters, which is outside the fault model.  See SAFETY.md §4.
    """
    _q, f = compute_quorum(n)
    return f + 1


class LedgerSynchronizer(Synchronizer):
    """Verified, chunked catch-up over a :class:`SyncTransport`.

    Every fetched chunk is accepted only if (1) it starts exactly at our
    next chain position, (2) each decision's ``ViewMetadata.latest_sequence``
    equals its chain position exactly, and (3) each decision's commit cert contains at
    least ``threshold(n)`` distinct VALID consenter signatures (default
    ``f + 1`` — :func:`honest_endorsement_threshold` explains why that is
    the sound bar under reconfiguration) — every signature in every cert in
    the chunk drained through ONE
    ``Verifier.verify_consenter_sigs_multi_batch`` call, so a TPU-backed
    verifier validates catch-up at kernel throughput.  See SAFETY.md §4
    ("Byzantine sync servers") for why an unverified sync channel would let
    a single faulty peer fork a recovering replica.

    Peers that fail fetches are scored down and retried later; peers that
    serve data failing verification are scored down hard and skipped for the
    rest of the call — the sync completes from the remaining honest peers
    (there are at least ``n - f`` of them).
    """

    def __init__(
        self,
        *,
        node_id: int,
        store: DecisionStore,
        transport: SyncTransport,
        verifier: Verifier,
        nodes: Union[Sequence[int], Callable[[], Sequence[int]]],
        reconfig_of: Optional[Callable[[object], Reconfig]] = None,
        metrics=None,
        fault_plan=None,
        now: Callable[[], float] = time.monotonic,
        chunk_window: int = 32,
        max_fetch_failures: int = 3,
        threshold: Callable[[int], int] = honest_endorsement_threshold,
    ) -> None:
        self.node_id = node_id
        self.store = store
        self.transport = transport
        self.verifier = verifier
        self._nodes = nodes
        self._reconfig_of = reconfig_of
        if metrics is None:
            from consensus_tpu.metrics import MetricsSync, NoopProvider

            metrics = MetricsSync(NoopProvider())
        self.metrics = metrics
        self.fault_plan = fault_plan
        #: Optional decision-lifecycle tracer (trace.Tracer); None when off.
        self._tracer = None
        self._now = now
        self.chunk_window = chunk_window
        self.max_fetch_failures = max_fetch_failures
        #: n -> required distinct valid signers per decision.
        self.threshold = threshold
        #: Peer scores persist across sync() calls (higher is better).
        self.scores: Dict[int, float] = {}
        #: Height of the tallest probed peer on the most recent sync() call
        #: — the obs plane's sync-lag source (0 until a sync runs).
        self.last_target_height = 0

    def attach_tracer(self, tracer) -> None:
        """Emit chunk fetch/verify spans into a decision tracer."""
        self._tracer = tracer

    # --- peer scoring ------------------------------------------------------

    def _demote(self, peer: int, delta: float) -> None:
        self.scores[peer] = self.scores.get(peer, 0.0) - delta
        self.metrics.count_peer_demotions.add(1)

    def _ranked(self, candidates: Sequence[int]) -> list[int]:
        """Best-scored first; peer id breaks ties deterministically."""
        return sorted(candidates, key=lambda p: (-self.scores.get(p, 0.0), p))

    def _membership(self) -> Sequence[int]:
        nodes = self._nodes
        return list(nodes()) if callable(nodes) else list(nodes)

    # --- the port ----------------------------------------------------------

    def sync(self) -> SyncResponse:
        begin = self._now()
        reconfig = Reconfig()
        banned: Set[int] = set()  # served-forged-data, this call
        failures: Dict[int, int] = {}

        # Phase 1: probe reachable peers for their heights.
        heights: Dict[int, int] = {}
        for peer in self._ranked(self.transport.peers()):
            reply = self.transport.fetch(peer, SyncRequest(from_seq=1, to_seq=0))
            if reply is None:
                self._demote(peer, _DEMOTE_FETCH)
                continue
            if isinstance(reply, SyncSnapshotMeta):
                heights[peer] = reply.height
            elif isinstance(reply, SyncChunk):
                heights[peer] = reply.height
        target = max(heights.values(), default=0)
        self.last_target_height = target

        # Phase 2: chunk-fetch loop.  The target is pinned to the probed
        # maximum — a byzantine peer inflating `height` in later chunks
        # cannot extend the loop, and `max_rounds` bounds it even against
        # an inflated probe (each productive round advances >= 1 decision;
        # unproductive rounds consume the peer's failure budget).
        deficit = max(0, target - self.store.height())
        max_rounds = deficit + len(heights) * (self.max_fetch_failures + 1) + 4
        rounds = 0
        while self.store.height() < target and rounds < max_rounds:
            rounds += 1
            mine = self.store.height()
            candidates = [
                p
                for p, h in heights.items()
                if h > mine
                and p not in banned
                and failures.get(p, 0) < self.max_fetch_failures
            ]
            if not candidates:
                break
            peer = self._ranked(candidates)[0]
            request = SyncRequest(
                from_seq=mine + 1, to_seq=min(target, mine + self.chunk_window)
            )
            tracer = self._tracer
            tracing = tracer is not None and tracer.enabled
            if tracing:
                tracer.begin(
                    "sync",
                    "sync.fetch",
                    peer=peer,
                    from_seq=request.from_seq,
                    to_seq=request.to_seq,
                )
            reply = self.transport.fetch(peer, request)
            if tracing:
                tracer.end("sync", "sync.fetch", ok=reply is not None)
            if reply is None:
                failures[peer] = failures.get(peer, 0) + 1
                self._demote(peer, _DEMOTE_FETCH)
                continue
            if isinstance(reply, SyncSnapshotMeta):
                # Peer is shorter than it claimed at probe time.
                heights[peer] = min(heights[peer], reply.height)
                continue
            if tracing:
                tracer.begin("sync", "sync.apply", from_seq=mine + 1)
            applied = self._verify_and_apply(reply, expected_from=mine + 1)
            if tracing:
                tracer.end("sync", "sync.apply", ok=applied is not None)
            if applied is None:
                logger.warning(
                    "%d: peer %d served a chunk that failed verification; "
                    "routing around it", self.node_id, peer,
                )
                self._demote(peer, _DEMOTE_FORGED)
                banned.add(peer)
                continue
            if applied.in_latest_decision:
                reconfig = applied
            # sync.client.chunk_boundary: the canonical mid-transfer death —
            # a chunk durably applied, the next not yet requested.
            plan = self.fault_plan
            if plan is not None:
                plan.crash("sync.client.chunk_boundary")

        self.metrics.latency_catchup.observe(self._now() - begin)
        latest = self.store.last()
        return SyncResponse(latest=latest, reconfig=reconfig)

    # --- verification ------------------------------------------------------

    def _verify_and_apply(
        self, chunk: SyncChunk, *, expected_from: int
    ) -> Optional[Reconfig]:
        """Verify a whole chunk (position, metadata continuity, quorum
        certs), then apply it.  Returns the last reconfig seen (possibly the
        empty one) on success, None on any verification failure — a chunk
        is all-or-nothing so a crash mid-call never leaves half a chunk."""
        if chunk.from_seq != expected_from or not chunk.decisions:
            return None
        if len(chunk.decisions) != len(chunk.quorum_certs):
            return None

        required = self.threshold(len(self._membership()))

        # One batched verifier call per cert FORMAT in the chunk.  A ledger
        # whose cert_mode flipped mid-history (e.g. at a membership epoch
        # boundary) serves chunks mixing full signature tuples with
        # half-aggregated QuorumCerts; verify_consenter_sigs_multi_batch
        # rejects mixed groups by contract, so partition into homogeneous
        # sub-calls and merge the verdicts back in chunk order.
        groups = list(zip(chunk.decisions, chunk.quorum_certs))
        full_idx = [i for i, (_, c) in enumerate(groups) if not isinstance(c, QuorumCert)]
        agg_idx = [i for i, (_, c) in enumerate(groups) if isinstance(c, QuorumCert)]
        results: list = [None] * len(groups)
        for idx_list in (full_idx, agg_idx):
            if not idx_list:
                continue
            sub = self.verifier.verify_consenter_sigs_multi_batch(
                [groups[i] for i in idx_list]
            )
            for i, r in zip(idx_list, sub):
                results[i] = r
        total_sigs = sum(len(cert) for cert in chunk.quorum_certs)
        self.metrics.count_sig_verifications.add(total_sigs)
        self.metrics.sigs_per_chunk.observe(total_sigs)
        for i in agg_idx:
            self.metrics.sync_cert_bytes.add(encoded_cert_size(groups[i][1]))

        for i, (proposal, cert) in enumerate(groups):
            valid_signers = {
                cert[j].id for j in range(len(cert)) if results[i][j] is not None
            }
            if len(valid_signers) < required:
                return None
            # Chain position == committed sequence, exactly: a server that
            # omits, reorders, or offsets decisions (e.g. dropping the first
            # one against an empty store) produces a mismatch here and the
            # whole chunk is rejected.
            if _metadata_sequence(proposal) != chunk.from_seq + i:
                return None

        reconfig = Reconfig()
        for proposal, cert in groups:
            self.store.append(Decision(proposal=proposal, signatures=as_cert(cert)))
            if self._reconfig_of is not None:
                r = self._reconfig_of(proposal)
                if r.in_latest_decision:
                    reconfig = r
        self.metrics.count_chunks_fetched.add(1)
        self.metrics.count_decisions_fetched.add(len(groups))
        return reconfig


def _metadata_sequence(proposal) -> Optional[int]:
    if not proposal.metadata:
        return None
    try:
        return decode_view_metadata(proposal.metadata).latest_sequence
    except CodecError:
        return None


__all__ = ["LedgerSynchronizer"]
