"""Decision storage behind the catch-up subsystem.

The sync server reads ranges out of a :class:`DecisionStore` and the sync
client appends verified decisions into one — neither side knows whether the
store is the test harness's in-memory ledger, the example orderer's hash
chain, or a real database.  Positions are 1-based chain heights (position
``i`` is the ``i``-th decision ever committed), matching how the reference's
block puller addresses Fabric blocks by number.
"""

from __future__ import annotations

import abc
from typing import List, Optional, Sequence

from consensus_tpu.types import Decision


class DecisionStore(abc.ABC):
    """Ranged, position-addressed access to the committed decision chain."""

    @abc.abstractmethod
    def height(self) -> int:
        """Number of decisions in the chain (0 when empty)."""

    @abc.abstractmethod
    def read(self, from_seq: int, to_seq: int) -> Sequence[Decision]:
        """Decisions at positions ``[from_seq, to_seq]`` (1-based,
        inclusive), clamped to the available range; empty when the range is
        entirely above the current height."""

    @abc.abstractmethod
    def append(self, decision: Decision) -> None:
        """Extend the chain by one decision (the next position)."""

    def last(self) -> Optional[Decision]:
        h = self.height()
        if h == 0:
            return None
        return self.read(h, h)[0]


class LedgerDecisionStore(DecisionStore):
    """Adapter over a mutable ``list[Decision]`` ledger — the harness's
    ``TestApp.ledger`` and the example orderer's chain both plug in directly
    (the list object is shared, not copied, so consensus deliveries and sync
    appends land in the same chain)."""

    def __init__(self, ledger: List[Decision]) -> None:
        self._ledger = ledger

    def height(self) -> int:
        return len(self._ledger)

    def read(self, from_seq: int, to_seq: int) -> Sequence[Decision]:
        if from_seq < 1 or to_seq < from_seq:
            return []
        return list(self._ledger[from_seq - 1 : to_seq])

    def append(self, decision: Decision) -> None:
        self._ledger.append(decision)


__all__ = ["DecisionStore", "LedgerDecisionStore"]
