"""Sync transports: the request/response channel catch-up runs over.

The consensus ``Comm`` port is fire-and-forget by contract, and
``Synchronizer.sync()`` is called *synchronously* from inside the protocol
(controller ``_do_sync``, the view changer) — so catch-up gets its own
blocking fetch channel, exactly like the reference's deployment: Fabric's
block puller opens its own gRPC connections to peers, it does not ride the
consensus message stream.

Two implementations:

* :class:`InProcessSyncTransport` — for the simulated cluster.  Requests and
  replies make a full codec round-trip through bytes and honor the
  ``SimNetwork`` partition state in BOTH directions, so a partitioned
  replica cannot tunnel state through a side channel, and every byte a test
  syncs has survived encode→decode.
* :class:`TcpSyncTransport` + :class:`SyncListener` — real sockets with
  u32-length framing, for realtime deployments (benchmarks, the example
  orderer).

Both honor an armed :class:`~consensus_tpu.testing.faults.FaultPlan` through
the ``sync.fetch.io_error`` (survivable fetch failure) and
``sync.chunk.corrupt`` (reply bytes damaged in flight) seams — one ``is
None`` check each when no plan is armed.
"""

from __future__ import annotations

import abc
import socket
import struct
import threading
from typing import Dict, Optional, Sequence, Union

from consensus_tpu.net.framing import FrameStall, ListenerGuard, recv_exact
from consensus_tpu.sync.server import SyncServer
from consensus_tpu.wire.codec import CodecError, decode_message, encode_message
from consensus_tpu.wire.messages import SyncChunk, SyncRequest, SyncSnapshotMeta

SyncReply = Union[SyncChunk, SyncSnapshotMeta]

_FRAME = struct.Struct(">I")
_MAX_FRAME_BYTES = 64 * 1024 * 1024


class SyncTransport(abc.ABC):
    """Blocking fetch channel to peers' sync servers."""

    #: Armed testing FaultPlan; None in production (one attr check per fetch).
    fault_plan = None

    @abc.abstractmethod
    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        """Send ``request`` to ``peer_id``; return its decoded reply, or
        None when the peer is unreachable / errored / sent garbage."""

    @abc.abstractmethod
    def peers(self) -> Sequence[int]:
        """Candidate peers (never includes self)."""


def _maybe_corrupt(plan, reply_bytes: bytes) -> bytes:
    """sync.chunk.corrupt seam: flip one byte mid-payload when armed —
    decode must then fail closed (CodecError), never yield a wrong chunk."""
    if plan is not None and plan.trip("sync.chunk.corrupt"):
        pos = len(reply_bytes) // 2
        return (
            reply_bytes[:pos]
            + bytes([reply_bytes[pos] ^ 0xFF])
            + reply_bytes[pos + 1 :]
        )
    return reply_bytes


class InProcessSyncTransport(SyncTransport):
    """Sim-cluster transport: full wire round-trip against the shared
    ``sync_servers`` registry, gated on network reachability both ways."""

    def __init__(
        self,
        node_id: int,
        network,
        servers: Dict[int, SyncServer],
        *,
        fault_plan=None,
    ) -> None:
        self.node_id = node_id
        self._network = network
        self._servers = servers
        self.fault_plan = fault_plan

    def peers(self) -> Sequence[int]:
        return [n for n in self._network.node_ids() if n != self.node_id]

    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        # A fetch is a request AND a reply: both directions must be up.
        if not self._network.reachable(self.node_id, peer_id):
            return None
        if not self._network.reachable(peer_id, self.node_id):
            return None
        server = self._servers.get(peer_id)
        if server is None:
            return None  # peer process is down
        plan = self.fault_plan
        try:
            if plan is not None:
                plan.io_error("sync.fetch.io_error")
            reply_bytes = server.handle_bytes(encode_message(request))
            reply_bytes = _maybe_corrupt(plan, reply_bytes)
            reply = decode_message(reply_bytes)
        except (OSError, CodecError):
            return None
        if not isinstance(reply, (SyncChunk, SyncSnapshotMeta)):
            return None
        return reply


class SyncListener:
    """Serves a :class:`SyncServer` over TCP: one framed request, one framed
    reply per connection (catch-up is bursty and rare; connection reuse is
    not worth the state).  Daemon accept thread; ``close()`` stops it.

    Hardened DEFAULT-ON via a :class:`~consensus_tpu.net.framing
    .ListenerGuard`: connections are admitted against per-peer/global
    quotas before a byte is read, each is served on its own daemon thread
    (one slow-loris peer no longer blocks honest catch-up behind it), the
    first frame must start within the guard's handshake deadline, started
    frames must keep making progress, and malformed frames (oversized
    claim, stall, undecodable request) accrue strikes toward a temporary
    ban.  Pass a configured guard to tune, or ``guard=False`` for the
    pre-hardening serial listener behavior."""

    def __init__(
        self,
        server: SyncServer,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        guard=None,
    ) -> None:
        self.server = server
        if guard is None:
            guard = ListenerGuard(name="sync")
        self.guard = guard or None
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name=f"sync-listener-{self.address[1]}",
            daemon=True,
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            addr = "?"
            try:
                addr = conn.getpeername()[0]
            except OSError:
                pass
            guard = self.guard
            if guard is not None and not guard.admit(addr):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._serve_conn, args=(conn, addr),
                name=f"sync-serve-{self.address[1]}", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket, addr: str) -> None:
        guard = self.guard
        first_deadline = (
            guard.handshake_timeout if guard is not None else 5.0
        )
        progress = guard.progress_timeout if guard is not None else 5.0
        try:
            with conn:
                try:
                    header = recv_exact(
                        conn, _FRAME.size, progress_timeout=first_deadline
                    )
                except FrameStall as stall:
                    if guard is not None:
                        if stall.received == 0:
                            # Connect-and-idle: never started a frame.
                            guard.handshake_timed_out(addr)
                        else:
                            guard.strike(addr, "stall")
                    return
                if header is None:
                    return
                (length,) = _FRAME.unpack(header)
                if length > _MAX_FRAME_BYTES:
                    if guard is not None:
                        guard.strike(addr, "oversized")
                    return
                try:
                    raw = recv_exact(conn, length, progress_timeout=progress)
                except FrameStall:
                    if guard is not None:
                        guard.strike(addr, "stall")
                    return
                if raw is None:
                    return
                try:
                    reply = self.server.handle_bytes(raw)
                except CodecError:
                    if guard is not None:
                        guard.strike(addr, "garbage")
                    return
                conn.settimeout(5.0)
                conn.sendall(_FRAME.pack(len(reply)) + reply)
        except OSError:
            pass  # bad client; keep serving others
        finally:
            if guard is not None:
                guard.release(addr)

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def _read_frame(conn: socket.socket) -> Optional[bytes]:
    """Client-side framed read (the fetch reply path): cap check BEFORE
    any payload buffering, then the shared chunked
    :func:`~consensus_tpu.net.framing.recv_exact` — allocation tracks
    bytes actually received, never the peer's claimed length.  EOF,
    ECONNRESET, and timeouts all collapse to None (the fetch yielded
    nothing; the connection is dropped)."""
    header = recv_exact(conn, _FRAME.size)
    if header is None:
        return None
    (length,) = _FRAME.unpack(header)
    if length > _MAX_FRAME_BYTES:
        raise CodecError(f"sync frame of {length} bytes exceeds cap")
    return recv_exact(conn, length)


class TcpSyncTransport(SyncTransport):
    """Real-socket fetch channel: ``addresses`` maps peer id -> (host, port)
    of that peer's :class:`SyncListener`."""

    def __init__(
        self,
        node_id: int,
        addresses: Dict[int, tuple],
        *,
        timeout: float = 5.0,
        fault_plan=None,
    ) -> None:
        self.node_id = node_id
        self.addresses = addresses
        self.timeout = timeout
        self.fault_plan = fault_plan

    def peers(self) -> Sequence[int]:
        return [n for n in sorted(self.addresses) if n != self.node_id]

    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        address = self.addresses.get(peer_id)
        if address is None:
            return None
        plan = self.fault_plan
        try:
            if plan is not None:
                plan.io_error("sync.fetch.io_error")
            with socket.create_connection(address, timeout=self.timeout) as conn:
                payload = encode_message(request)
                conn.sendall(_FRAME.pack(len(payload)) + payload)
                reply_bytes = _read_frame(conn)
            if reply_bytes is None:
                return None
            reply_bytes = _maybe_corrupt(plan, reply_bytes)
            reply = decode_message(reply_bytes)
        except (OSError, CodecError):
            return None
        if not isinstance(reply, (SyncChunk, SyncSnapshotMeta)):
            return None
        return reply


__all__ = [
    "SyncTransport",
    "SyncReply",
    "InProcessSyncTransport",
    "SyncListener",
    "TcpSyncTransport",
]
