"""Sync transports: the request/response channel catch-up runs over.

The consensus ``Comm`` port is fire-and-forget by contract, and
``Synchronizer.sync()`` is called *synchronously* from inside the protocol
(controller ``_do_sync``, the view changer) — so catch-up gets its own
blocking fetch channel, exactly like the reference's deployment: Fabric's
block puller opens its own gRPC connections to peers, it does not ride the
consensus message stream.

Two implementations:

* :class:`InProcessSyncTransport` — for the simulated cluster.  Requests and
  replies make a full codec round-trip through bytes and honor the
  ``SimNetwork`` partition state in BOTH directions, so a partitioned
  replica cannot tunnel state through a side channel, and every byte a test
  syncs has survived encode→decode.
* :class:`TcpSyncTransport` + :class:`SyncListener` — real sockets with
  u32-length framing, for realtime deployments (benchmarks, the example
  orderer).

Both honor an armed :class:`~consensus_tpu.testing.faults.FaultPlan` through
the ``sync.fetch.io_error`` (survivable fetch failure) and
``sync.chunk.corrupt`` (reply bytes damaged in flight) seams — one ``is
None`` check each when no plan is armed.
"""

from __future__ import annotations

import abc
import socket
import struct
import threading
from typing import Dict, Optional, Sequence, Union

from consensus_tpu.sync.server import SyncServer
from consensus_tpu.wire.codec import CodecError, decode_message, encode_message
from consensus_tpu.wire.messages import SyncChunk, SyncRequest, SyncSnapshotMeta

SyncReply = Union[SyncChunk, SyncSnapshotMeta]

_FRAME = struct.Struct(">I")
_MAX_FRAME_BYTES = 64 * 1024 * 1024


class SyncTransport(abc.ABC):
    """Blocking fetch channel to peers' sync servers."""

    #: Armed testing FaultPlan; None in production (one attr check per fetch).
    fault_plan = None

    @abc.abstractmethod
    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        """Send ``request`` to ``peer_id``; return its decoded reply, or
        None when the peer is unreachable / errored / sent garbage."""

    @abc.abstractmethod
    def peers(self) -> Sequence[int]:
        """Candidate peers (never includes self)."""


def _maybe_corrupt(plan, reply_bytes: bytes) -> bytes:
    """sync.chunk.corrupt seam: flip one byte mid-payload when armed —
    decode must then fail closed (CodecError), never yield a wrong chunk."""
    if plan is not None and plan.trip("sync.chunk.corrupt"):
        pos = len(reply_bytes) // 2
        return (
            reply_bytes[:pos]
            + bytes([reply_bytes[pos] ^ 0xFF])
            + reply_bytes[pos + 1 :]
        )
    return reply_bytes


class InProcessSyncTransport(SyncTransport):
    """Sim-cluster transport: full wire round-trip against the shared
    ``sync_servers`` registry, gated on network reachability both ways."""

    def __init__(
        self,
        node_id: int,
        network,
        servers: Dict[int, SyncServer],
        *,
        fault_plan=None,
    ) -> None:
        self.node_id = node_id
        self._network = network
        self._servers = servers
        self.fault_plan = fault_plan

    def peers(self) -> Sequence[int]:
        return [n for n in self._network.node_ids() if n != self.node_id]

    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        # A fetch is a request AND a reply: both directions must be up.
        if not self._network.reachable(self.node_id, peer_id):
            return None
        if not self._network.reachable(peer_id, self.node_id):
            return None
        server = self._servers.get(peer_id)
        if server is None:
            return None  # peer process is down
        plan = self.fault_plan
        try:
            if plan is not None:
                plan.io_error("sync.fetch.io_error")
            reply_bytes = server.handle_bytes(encode_message(request))
            reply_bytes = _maybe_corrupt(plan, reply_bytes)
            reply = decode_message(reply_bytes)
        except (OSError, CodecError):
            return None
        if not isinstance(reply, (SyncChunk, SyncSnapshotMeta)):
            return None
        return reply


class SyncListener:
    """Serves a :class:`SyncServer` over TCP: one framed request, one framed
    reply per connection (catch-up is bursty and rare; connection reuse is
    not worth the state).  Daemon accept thread; ``close()`` stops it."""

    def __init__(self, server: SyncServer, *, host: str = "127.0.0.1", port: int = 0) -> None:
        self.server = server
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name=f"sync-listener-{self.address[1]}",
            daemon=True,
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                with conn:
                    conn.settimeout(5.0)
                    raw = _read_frame(conn)
                    if raw is None:
                        continue
                    reply = self.server.handle_bytes(raw)
                    conn.sendall(_FRAME.pack(len(reply)) + reply)
            except (OSError, CodecError):
                continue  # bad client; keep serving others

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def _read_frame(conn: socket.socket) -> Optional[bytes]:
    header = _read_exact(conn, _FRAME.size)
    if header is None:
        return None
    (length,) = _FRAME.unpack(header)
    if length > _MAX_FRAME_BYTES:
        raise CodecError(f"sync frame of {length} bytes exceeds cap")
    return _read_exact(conn, length)


def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes or fail CLEANLY with None.

    A peer killed mid-frame (kill -9, RST, or a stall past the socket
    timeout) must never hang the listener thread or hand a truncated
    buffer to the codec: EOF, ECONNRESET, and timeouts all collapse to
    None here, and every caller treats None as "this fetch yielded
    nothing" — the chunk is not applied, the connection is dropped, and
    the listener keeps serving other peers."""
    buf = b""
    while len(buf) < n:
        try:
            part = conn.recv(n - len(buf))
        except OSError:  # includes socket.timeout: bounded, never a hang
            return None
        if not part:
            return None
        buf += part
    return buf


class TcpSyncTransport(SyncTransport):
    """Real-socket fetch channel: ``addresses`` maps peer id -> (host, port)
    of that peer's :class:`SyncListener`."""

    def __init__(
        self,
        node_id: int,
        addresses: Dict[int, tuple],
        *,
        timeout: float = 5.0,
        fault_plan=None,
    ) -> None:
        self.node_id = node_id
        self.addresses = addresses
        self.timeout = timeout
        self.fault_plan = fault_plan

    def peers(self) -> Sequence[int]:
        return [n for n in sorted(self.addresses) if n != self.node_id]

    def fetch(self, peer_id: int, request: SyncRequest) -> Optional[SyncReply]:
        address = self.addresses.get(peer_id)
        if address is None:
            return None
        plan = self.fault_plan
        try:
            if plan is not None:
                plan.io_error("sync.fetch.io_error")
            with socket.create_connection(address, timeout=self.timeout) as conn:
                payload = encode_message(request)
                conn.sendall(_FRAME.pack(len(payload)) + payload)
                reply_bytes = _read_frame(conn)
            if reply_bytes is None:
                return None
            reply_bytes = _maybe_corrupt(plan, reply_bytes)
            reply = decode_message(reply_bytes)
        except (OSError, CodecError):
            return None
        if not isinstance(reply, (SyncChunk, SyncSnapshotMeta)):
            return None
        return reply


__all__ = [
    "SyncTransport",
    "SyncReply",
    "InProcessSyncTransport",
    "SyncListener",
    "TcpSyncTransport",
]
