"""Wire-native state transfer: verified, chunked catch-up behind the
Synchronizer port.

* :mod:`consensus_tpu.sync.store` — position-addressed decision storage.
* :mod:`consensus_tpu.sync.server` — serves ranged chunks with size caps.
* :mod:`consensus_tpu.sync.transport` — blocking fetch channels (sim + TCP).
* :mod:`consensus_tpu.sync.client` — the verifying Synchronizer.
"""

from consensus_tpu.sync.client import (
    LedgerSynchronizer,
    honest_endorsement_threshold,
)
from consensus_tpu.sync.server import SyncServer
from consensus_tpu.sync.store import DecisionStore, LedgerDecisionStore
from consensus_tpu.sync.transport import (
    InProcessSyncTransport,
    SyncListener,
    SyncTransport,
    TcpSyncTransport,
)

__all__ = [
    "DecisionStore",
    "LedgerDecisionStore",
    "SyncServer",
    "SyncTransport",
    "InProcessSyncTransport",
    "SyncListener",
    "TcpSyncTransport",
    "LedgerSynchronizer",
    "honest_endorsement_threshold",
]
