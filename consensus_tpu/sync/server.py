"""Catch-up server: serves ranged decision chunks out of a DecisionStore.

Stateless per request (any replica can answer any range it holds), with two
flow-control caps so one lagging peer cannot make the server materialize an
unbounded reply: a decision-count cap and an encoded-bytes cap per chunk.
The client keeps asking for the next range until it reaches its target — the
``height`` echoed in every chunk tells it how far the server's chain extends
without a second metadata probe.
"""

from __future__ import annotations

from consensus_tpu.sync.store import DecisionStore
from consensus_tpu.types import Decision, as_cert
from consensus_tpu.wire.codec import decode_message, encode_message
from consensus_tpu.wire.messages import SyncChunk, SyncRequest, SyncSnapshotMeta

#: Per-signature framing overhead in the wire encoding (id + 2 length
#: prefixes); used by the cheap size estimate below.
_SIG_OVERHEAD = 8 + 4 + 4
_PROPOSAL_OVERHEAD = 4 * 3 + 8


def _decision_wire_size(d: Decision) -> int:
    """Close upper-bound estimate of a decision's encoded size — cheap
    (no serialization) and monotone, which is all flow control needs."""
    p = d.proposal
    size = (
        _PROPOSAL_OVERHEAD
        + len(p.header)
        + len(p.payload)
        + len(p.metadata)
        + 4  # cert count prefix
    )
    for sig in d.signatures:
        size += _SIG_OVERHEAD + len(sig.value) + len(sig.msg)
    return size


class SyncServer:
    """Answers :class:`SyncRequest` with :class:`SyncChunk` /
    :class:`SyncSnapshotMeta` over whatever byte transport the caller runs.
    """

    def __init__(
        self,
        store: DecisionStore,
        *,
        max_chunk_decisions: int = 32,
        max_chunk_bytes: int = 1 << 20,
    ) -> None:
        if max_chunk_decisions < 1:
            raise ValueError("max_chunk_decisions must be >= 1")
        self.store = store
        self.max_chunk_decisions = max_chunk_decisions
        self.max_chunk_bytes = max_chunk_bytes
        #: Served-chunk counter (observability / tests).
        self.chunks_served = 0

    def handle(self, request: SyncRequest):
        """One request, one reply.  ``to_seq == 0`` or a range starting
        above our height is a metadata probe."""
        height = self.store.height()
        if request.to_seq == 0 or request.from_seq > height:
            tip = self.store.last()
            return SyncSnapshotMeta(
                height=height,
                last_digest=tip.proposal.digest() if tip is not None else "",
            )
        from_seq = max(1, request.from_seq)
        to_seq = min(request.to_seq, height, from_seq + self.max_chunk_decisions - 1)
        decisions: list = []
        certs: list = []
        budget = self.max_chunk_bytes
        for d in self.store.read(from_seq, to_seq):
            size = _decision_wire_size(d)
            # Always serve at least one decision, or a pathologically large
            # single decision could never be transferred at all.
            if decisions and size > budget:
                break
            budget -= size
            decisions.append(d.proposal)
            # Serve the cert in its stored format: a half-aggregated
            # QuorumCert passes through intact, a signature list as a tuple.
            certs.append(as_cert(d.signatures))
        self.chunks_served += 1
        return SyncChunk(
            from_seq=from_seq,
            height=height,
            decisions=tuple(decisions),
            quorum_certs=tuple(certs),
        )

    def handle_bytes(self, raw: bytes) -> bytes:
        """Wire entry point: decode the request, encode the reply.  Raises
        :class:`consensus_tpu.wire.codec.CodecError` on malformed input —
        transports surface that as a failed fetch."""
        request = decode_message(raw)
        if not isinstance(request, SyncRequest):
            from consensus_tpu.wire.codec import CodecError

            raise CodecError(
                f"sync server got {type(request).__name__}, want SyncRequest"
            )
        return encode_message(self.handle(request))


__all__ = ["SyncServer"]
