"""consensus_tpu — a TPU-native Byzantine fault-tolerant SMR framework.

A library-form PBFT-style consensus core (pre-prepare / prepare / commit with
depth-1 pipelining, view changes with in-flight agreement, leader rotation and
blacklisting, heartbeats, state transfer, CRC-chained WAL crash recovery, and
dynamic reconfiguration), with the signature-heavy protocol paths drained into
a batched JAX/XLA Ed25519 verification kernel that runs on TPU (f32 limb
field arithmetic on the VPU, windowed double-scalar multiplication, batch
axis shardable across a device mesh).

Capability parity target: hyperledger-labs/SmartBFT (see SURVEY.md).  The
architecture is deliberately *not* a port:

* The reference is goroutine-per-component with channel synchronization.  Here
  each replica is a single-threaded, deterministic event-driven state machine
  scheduled by ``consensus_tpu.runtime`` — which removes the reference's
  deliver-vs-sync lock dance (reference: internal/bft/controller.go:928-965)
  by construction, and makes every multi-replica test reproducible.
* The reference verifies each commit signature on its own goroutine with
  sequential CPU ECDSA (reference: internal/bft/view.go:537-541).  Here quorum
  signature sets and request batches are *deferred and verified as one batch*
  on the TPU (``consensus_tpu.models``), which is where the throughput
  headroom of the MXU/VPU actually is.

Layout:
    api/       dependency-injection ports (the seam applications implement)
    wire/      message schema + deterministic binary codec
    wal/       segmented CRC-chained write-ahead log
    runtime/   deterministic clock + event scheduler
    core/      the consensus protocol state machines
    ops/       GF(2^255-19) limb arithmetic + edwards25519 group ops (JAX)
    models/    batched signature verification + signer/verifier adapters
    parallel/  device-mesh sharding of the crypto batch path
    net/       production TCP transport (Comm over the datacenter network)
    metrics    provider abstraction + the 5 instrument bundles
    utils/     quorum math, leader selection, blacklist, digests
    testing/   in-process simulated network + all-ports test application
"""

__version__ = "0.1.0"

from consensus_tpu.types import (  # noqa: F401
    Checkpoint,
    Decision,
    Proposal,
    Reconfig,
    RequestInfo,
    Signature,
    SyncResponse,
    ViewSequence,
)
from consensus_tpu.config import Configuration, default_config  # noqa: F401
