"""consensus_tpu — a TPU-native Byzantine fault-tolerant SMR framework.

A library-form PBFT-style consensus core (pre-prepare / prepare / commit with
depth-1 pipelining, view changes with in-flight agreement, leader rotation and
blacklisting, heartbeats, state transfer, CRC-chained WAL crash recovery, and
dynamic reconfiguration), with the signature-heavy protocol paths drained into
batched JAX/XLA verification kernels (ECDSA-P256 / Ed25519) that run on TPU.

Capability parity target: hyperledger-labs/SmartBFT (see SURVEY.md).  The
architecture is deliberately *not* a port:

* The reference is goroutine-per-component with channel synchronization.  Here
  each replica is a single-threaded, deterministic event-driven state machine
  scheduled by ``consensus_tpu.runtime`` — which removes the reference's
  deliver-vs-sync lock dance (reference: internal/bft/controller.go:928-965)
  by construction, and makes every multi-replica test reproducible.
* The reference verifies each commit signature on its own goroutine with
  sequential CPU ECDSA (reference: internal/bft/view.go:537-541).  Here quorum
  signature sets and request batches are *deferred and verified as one batch*
  on the TPU (``consensus_tpu.models``), which is where the throughput
  headroom of the MXU/VPU actually is.

Layout:
    api/       dependency-injection ports (the seam applications implement)
    wire/      protobuf wire format + WAL record schema
    wal/       segmented CRC-chained write-ahead log
    runtime/   deterministic clock + event scheduler
    core/      the consensus protocol state machines
    ops/       TPU big-integer / modular-field kernels (jnp, vmap, pallas)
    models/    batched signature-verification models built on ops/
    parallel/  device-mesh sharding of the crypto batch path
    utils/     quorum math, leader selection, blacklist, codecs
    testing/   in-process simulated network + all-ports test application
"""

__version__ = "0.1.0"

from consensus_tpu.types import (  # noqa: F401
    Checkpoint,
    Decision,
    Proposal,
    Reconfig,
    RequestInfo,
    Signature,
    SyncResponse,
    ViewSequence,
)
from consensus_tpu.config import Configuration, default_config  # noqa: F401
