"""Device topologies for the sharded batch engines.

:class:`MeshTopology` generalizes ``Configuration.mesh_shards`` from a 1-D
device count to a named N-D device mesh: ``MeshTopology((2, 4))`` lays the
first 8 visible devices out as a ``("slice", "batch")`` mesh, while
``MeshTopology((8,))`` — and the ``mesh_shards=8`` sugar that normalizes to
it — builds today's 1-D ``("batch",)`` mesh bit-for-bit.

The verification workload is pure data parallelism, so every kernel shards
its batch dimension over ALL mesh axes (``PartitionSpec`` with the full
axis-name tuple) and reduces with one ``psum`` over the same tuple; a 2-D
topology therefore changes only the device layout the runtime maps onto the
physical interconnect (which ICI links the reduction tree rides), never the
per-lane math or the verdict.  Multi-host awareness: ``jax.devices()``
enumerates the whole slice across processes, so the same spec builds the
same GLOBAL mesh on every host of a multi-host slice — partial meshes that
exclude another process's devices are rejected loudly rather than silently
degrading to a single-host layout.

This module is deliberately jax-free at import time (jax loads lazily inside
:meth:`MeshTopology.build_mesh` / :func:`apply_compile_cache`) so the config
plane and the engine registry can reason about topologies on boxes without
the accelerator stack.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Union

#: The trailing mesh axis every kernel shards its batch dimension over (the
#: leading axes of an N-D topology join it via the full axis-name tuple).
BATCH_AXIS = "batch"

TopologySpec = Union["MeshTopology", int, str, Sequence[int], None]


def mesh_padded_size(n: int, n_shards: int, minimum: int = 8) -> int:
    """Pow-2 growth for compile-shape reuse, then rounded UP to a multiple
    of the mesh size — terminates for any shard count (a pure doubling loop
    never exits for non-power-of-two meshes)."""
    size = minimum
    while size < n:
        size *= 2
    size += (-size) % n_shards
    return size


def engine_padded_size(
    n: int,
    n_shards: int,
    *,
    pad_to: int = 0,
    pad_pow2: bool = True,
    minimum: int = 8,
) -> int:
    """Mesh-aligned padded batch size honouring the engine's padding knobs
    (``pad_to`` pins one compiled shape, ``pad_pow2`` grows by doubling),
    then rounded UP to a multiple of the mesh size so every shard gets an
    equal slice."""
    if pad_to >= n:
        size = pad_to
    elif pad_pow2:
        size = minimum
        while size < n:
            size *= 2
    else:
        size = max(n, 1)
    size += (-size) % n_shards
    return size


def _default_axis_names(ndim: int) -> tuple:
    if ndim == 1:
        return (BATCH_AXIS,)
    if ndim == 2:
        return ("slice", BATCH_AXIS)
    return tuple(f"slice{i}" for i in range(ndim - 1)) + (BATCH_AXIS,)


@dataclass(frozen=True)
class MeshTopology:
    """A named device-mesh layout for the sharded engines.

    ``axes`` are per-axis device counts (product = total shard count);
    ``axis_names`` name them, defaulting to ``("batch",)`` for 1-D and
    ``("slice", "batch")`` for 2-D, so ``MeshTopology((n,))`` is exactly
    the mesh ``mesh_shards=n`` always built.
    """

    axes: tuple = (1,)
    axis_names: Optional[tuple] = None

    def __post_init__(self) -> None:
        axes = tuple(int(a) for a in self.axes)
        if not axes or any(a < 1 for a in axes):
            raise ValueError(
                f"topology axes must be a non-empty tuple of positive device "
                f"counts, got {self.axes!r}"
            )
        names = self.axis_names
        names = _default_axis_names(len(axes)) if names is None else tuple(names)
        if len(names) != len(axes) or len(set(names)) != len(names):
            raise ValueError(
                f"axis_names {names!r} must be distinct and match axes {axes!r}"
            )
        object.__setattr__(self, "axes", axes)
        object.__setattr__(self, "axis_names", names)

    # -- identity ----------------------------------------------------------

    @property
    def shard_count(self) -> int:
        """Total devices the topology spans (the batch is sharded this many
        ways regardless of how the axes factor it)."""
        count = 1
        for a in self.axes:
            count *= a
        return count

    @property
    def ndim(self) -> int:
        return len(self.axes)

    @property
    def label(self) -> str:
        """Canonical spelling — ``"8"`` for 1-D, ``"2x4"`` for 2-D — used in
        bench sweep keys, ``last_good`` JSON, and registry errors."""
        return "x".join(str(a) for a in self.axes)

    # -- construction ------------------------------------------------------

    @classmethod
    def parse(cls, text: str) -> "MeshTopology":
        """``"8"`` -> ``(8,)``; ``"2x4"`` -> ``(2, 4)`` (the CLI seam)."""
        try:
            axes = tuple(int(part) for part in str(text).split("x"))
        except ValueError:
            raise ValueError(
                f"cannot parse topology {text!r} (want e.g. '8' or '2x4')"
            ) from None
        return cls(axes)

    @classmethod
    def normalize(cls, spec: TopologySpec) -> "MeshTopology":
        """Coerce every accepted spelling to a :class:`MeshTopology`:
        ``None`` -> single device, int ``n`` (the ``mesh_shards`` sugar) ->
        ``(n,)``, a string via :meth:`parse`, a sequence of axis sizes
        verbatim."""
        if isinstance(spec, cls):
            return spec
        if spec is None:
            return cls((1,))
        if isinstance(spec, int):
            if spec < 1:
                raise ValueError(f"mesh_shards must be >= 1, got {spec}")
            return cls((spec,))
        if isinstance(spec, str):
            return cls.parse(spec)
        return cls(tuple(spec))

    def build_mesh(self, devices: Optional[Sequence] = None):
        """A ``jax.sharding.Mesh`` laying the first ``shard_count`` visible
        devices out as ``axes``.  1-D topologies build byte-identical meshes
        to the historical ``mesh_for_shards`` (same device order, same
        ``("batch",)`` axis name).  Fails loudly when the host exposes fewer
        devices than the spec demands — silently shrinking the mesh would
        make the compiled kernel shape depend on deploy-time topology — and
        when a multi-host slice would be partially covered (every process
        must participate in the same global mesh)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = list(devices if devices is not None else jax.devices())
        count = self.shard_count
        if len(devices) < count:
            raise ValueError(
                f"topology {self.label} needs {count} devices but only "
                f"{len(devices)} device(s) visible (set XLA_FLAGS="
                "--xla_force_host_platform_device_count for a host mesh, "
                "or shrink the topology)"
            )
        if jax.process_count() > 1 and count != len(devices):
            raise ValueError(
                f"topology {self.label} covers {count} of "
                f"{len(devices)} global devices on a "
                f"{jax.process_count()}-process slice; multi-host meshes "
                "must span the whole slice (every process participates)"
            )
        arr = np.array(devices[:count])
        if self.ndim > 1:
            arr = arr.reshape(self.axes)
        return Mesh(arr, self.axis_names)


def topology_for_config(config) -> MeshTopology:
    """The topology a ``Configuration`` selects: ``mesh_topology`` when set,
    else the ``mesh_shards`` 1-D sugar."""
    axes = tuple(getattr(config, "mesh_topology", ()) or ())
    if axes:
        return MeshTopology(axes)
    return MeshTopology.normalize(int(getattr(config, "mesh_shards", 1) or 1))


def apply_compile_cache(cache) -> None:
    """Wire a ``CompileCacheConfig``'s persistent-cache knobs into
    ``jax.config`` (idempotent; repeated calls with the same values are
    no-ops inside jax).  ``persistent_dir=""`` leaves the runtime default
    untouched — the in-process memo works either way."""
    if cache is None or not getattr(cache, "persistent_dir", ""):
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache.persistent_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(getattr(cache, "min_compile_time_secs", 1.0)),
    )
    # Cache every entry regardless of serialized size: correctness work like
    # this repo's is dominated by many small-but-slow-to-trace kernels.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


__all__ = [
    "BATCH_AXIS",
    "MeshTopology",
    "apply_compile_cache",
    "engine_padded_size",
    "mesh_padded_size",
    "topology_for_config",
]
