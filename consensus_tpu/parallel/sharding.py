"""Device-mesh sharding of the crypto batch path.

The verification workload is pure data parallelism: every signature's
double-scalar multiplication is independent, so the natural multi-chip
layout is a 1-D mesh with the batch axis sharded across it.  Collectives
only appear at the reduction edge (the validity count / all-valid bit),
where a ``psum`` rides the ICI.

Two entry points:

* :func:`sharded_verify` — ``shard_map`` of the kernel body over the mesh:
  each device verifies its batch shard; outputs stay sharded (gathered
  lazily by the host when read).
* :class:`ShardedEd25519Verifier` — drop-in
  :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier` that pads the
  batch to a multiple of the mesh size and runs the sharded kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    to_kernel_layout,
    verify_impl,
)

BATCH_AXIS = "batch"

#: Device-layout partition specs: limb/bit arrays are (20|256, batch) —
#: batch is the trailing axis; per-element vectors are (batch,).
_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # s_bits
    P(None, BATCH_AXIS),  # k_bits
    P(BATCH_AXIS),        # host_ok
)


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all visible devices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def sharded_verify_fn(mesh: Mesh):
    """A jitted verify over ``mesh``: inputs sharded on the batch axis, plus
    a ``psum``-reduced valid count so the collective path is exercised."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
        ok = verify_impl(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return jax.jit(_shard)


class ShardedEd25519Verifier(Ed25519BatchVerifier):
    """Batch verifier that spreads the batch across a device mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def _pad_to(self, n: int) -> int:
        # Pow-2 padding AND divisibility by the mesh size.
        size = max(self._n_shards, 8)
        while size < n or size % self._n_shards:
            size *= 2
        return size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if n == 0:
            return np.zeros(0, dtype=bool)
        # Reuse the host-side preparation from the base class by padding to
        # the mesh-aligned size before the kernel call.
        prepped = self._prepare(messages, signatures, public_keys)
        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = prepped
        padded = self._pad_to(n)
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok = np.pad(host_ok, (0, pad))
        device_args = to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok
        )
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


__all__ = ["make_mesh", "sharded_verify_fn", "ShardedEd25519Verifier", "BATCH_AXIS"]
