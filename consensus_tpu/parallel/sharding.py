"""Device-mesh sharding of the crypto batch path.

The verification workload is pure data parallelism: every signature's
double-scalar multiplication is independent, so the natural multi-chip
layout is a 1-D mesh with the batch axis sharded across it.  Collectives
only appear at the reduction edge (the validity count / all-valid bit),
where a ``psum`` rides the ICI.

Two entry points:

* :func:`sharded_verify` — ``shard_map`` of the kernel body over the mesh:
  each device verifies its batch shard; outputs stay sharded (gathered
  lazily by the host when read).
* :class:`ShardedEd25519Verifier` — drop-in
  :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier` that pads the
  batch to a multiple of the mesh size and runs the sharded kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    to_kernel_layout,
    verify_impl,
)
from consensus_tpu.models.fused import (
    FusedEd25519BatchVerifier,
    FusedEd25519RandomizedBatchVerifier,
)
from consensus_tpu.obs.kernels import instrumented_jit

BATCH_AXIS = "batch"

# jax.shard_map was promoted to the top level after 0.4.x; older releases
# ship it under jax.experimental only.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 installs
    from jax.experimental.shard_map import shard_map as _shard_map

#: Device-layout partition specs: limb/bit arrays are (20|256, batch) —
#: batch is the trailing axis; per-element vectors are (batch,).
_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # s_bits
    P(None, BATCH_AXIS),  # k_bits
    P(BATCH_AXIS),        # host_ok
)


def mesh_padded_size(n: int, n_shards: int, minimum: int = 8) -> int:
    """Pow-2 growth for compile-shape reuse, then rounded UP to a multiple
    of the mesh size — terminates for any shard count (a pure doubling loop
    never exits for non-power-of-two meshes)."""
    size = minimum
    while size < n:
        size *= 2
    size += (-size) % n_shards
    return size


def engine_padded_size(
    n: int,
    n_shards: int,
    *,
    pad_to: int = 0,
    pad_pow2: bool = True,
    minimum: int = 8,
) -> int:
    """Mesh-aligned padded batch size honouring the engine's padding knobs
    (``pad_to`` pins one compiled shape, ``pad_pow2`` grows by doubling),
    then rounded UP to a multiple of the mesh size so every shard gets an
    equal slice."""
    if pad_to >= n:
        size = pad_to
    elif pad_pow2:
        size = minimum
        while size < n:
            size *= 2
    else:
        size = max(n, 1)
    size += (-size) % n_shards
    return size


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all visible devices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def mesh_for_shards(n_shards: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_shards`` visible devices — the
    ``Configuration.mesh_shards`` -> engine seam.  Fails loudly when the
    host exposes fewer devices than the config demands: silently shrinking
    the mesh would make the one compiled kernel shape depend on deploy-time
    topology."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards < 1:
        raise ValueError(f"mesh_shards must be >= 1, got {n_shards}")
    if len(devices) < n_shards:
        raise ValueError(
            f"mesh_shards={n_shards} but only {len(devices)} device(s) "
            "visible (set XLA_FLAGS=--xla_force_host_platform_device_count "
            "for a host mesh, or lower mesh_shards)"
        )
    return Mesh(np.array(devices[:n_shards]), (BATCH_AXIS,))


def sharded_verify_fn(mesh: Mesh):
    """A jitted verify over ``mesh``: inputs sharded on the batch axis, plus
    a ``psum``-reduced valid count so the collective path is exercised."""

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # pallas_call-under-shard_map is unvalidated (and per-shard batch
        # sizes would change the tiling decision): the multi-chip path
        # always traces the XLA scan, opt-in flag or not.
        with suppress_pallas_scan():
            ok = verify_impl(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ed25519.sharded_verify")


class ShardedEd25519Verifier(Ed25519BatchVerifier):
    """Batch verifier that spreads the batch across a device mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    @property
    def shard_count(self) -> int:
        """Devices this engine spreads a batch across.  The engine
        supervisor's degrade ladder labels mesh rungs with it (an
        ``N-shard`` rung degrading to a ``1-shard`` rung reads as exactly
        that in logs/traces rather than two identical class names)."""
        return self._n_shards

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        # Reuse the host-side preparation from the base class by padding to
        # the mesh-aligned size before the kernel call.
        prepped = self._prepare(messages, signatures, public_keys)
        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = prepped
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok = np.pad(host_ok, (0, pad))
        device_args = to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok
        )
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


# --- ECDSA-P256 sharding ---------------------------------------------------

#: Device-layout specs for the P-256 kernel (see models/ecdsa_p256.py):
#: limb/digit arrays lead with their vector axis, batch trails.
_P256_IN_SPECS = (
    P(None, BATCH_AXIS),  # qx
    P(None, BATCH_AXIS),  # qy
    P(None, BATCH_AXIS),  # u1 digits
    P(None, BATCH_AXIS),  # u2 digits
    P(None, BATCH_AXIS),  # r1
    P(None, BATCH_AXIS),  # r2
    P(BATCH_AXIS),        # has_r2
    P(BATCH_AXIS),        # host_ok
)


def sharded_p256_verify_fn(mesh: Mesh):
    """jitted ECDSA-P256 verify over ``mesh`` with a psum valid count."""
    from consensus_tpu.models.ecdsa_p256 import verify_impl as p256_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_P256_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok):
        from consensus_tpu.ops.pallas_scan import suppress_pallas_scan

        # Same rule as the Ed25519 shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = p256_verify_impl(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ecdsa_p256.sharded_verify")


class ShardedEcdsaP256Verifier(EcdsaP256BatchVerifier):
    """ECDSA-P256 batch verifier spread across a device mesh (reuses the
    base class's preparation/validation; only the launch path differs)."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_p256_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    @property
    def shard_count(self) -> int:
        """Devices this engine spreads a batch across (ladder labeling)."""
        return self._n_shards

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.ecdsa_p256 import pad_prepared, to_kernel_layout

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        prepped = self._prepare(messages, signatures, public_keys)
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        device_args = to_kernel_layout(*pad_prepared(prepped, padded))
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _P256_IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


# --- randomized Ed25519 batch verification over the mesh --------------------

#: Specs for the randomized-aggregate kernel (models/ed25519.py
#: batch_verify_impl): per-lane arrays shard on the batch axis, and the
#: fixed-base comb digits carry ONE (32, 1) column per shard — each shard
#: checks its own aggregate [u_s]B + Σ[zkᵢ](−Aᵢ) + Σ[zᵢ](−Rᵢ) = 0 against
#: its lanes' base-point scalar u_s.
_RAND_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # zs_digits8: (32, n_shards), one column per shard
    P(None, BATCH_AXIS),  # zk_digits
    P(None, BATCH_AXIS),  # z_digits
    P(BATCH_AXIS),        # host_ok
)


def sharded_batch_verify_fn(mesh: Mesh):
    """jitted randomized-aggregate verify over ``mesh``.

    Point addition is not componentwise, so the per-shard accumulators can
    NOT be psum'd as coordinates; instead every shard runs an independent
    aggregate check over its own lane subset (each sound to 2^-128 —
    the conjunction is at least as strong as one whole-batch check), and
    the single ``psum`` tree-reduces the per-shard not-identity counts
    into the global verdict.  A padding-only shard contributes u_s = 0 and
    all-masked digits, so its accumulator is the identity and it votes ok.
    """
    from consensus_tpu.models.ed25519 import batch_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_RAND_IN_SPECS,
        out_specs=(P(), P(BATCH_AXIS)),
    )
    def _shard(y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # Same rule as the strict shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            eq_ok, valid = batch_verify_impl(
                y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok
            )
        bad = jax.lax.psum(1 - eq_ok.astype(jnp.int32), BATCH_AXIS)
        return bad == 0, valid

    return instrumented_jit(_shard, "ed25519.sharded_batch_verify")


class ShardedEd25519RandomizedVerifier(Ed25519RandomizedBatchVerifier):
    """Randomized batch verifier whose aggregate check rides the mesh.

    Only the device aggregate changes: the bisection driver, transcript
    coefficients, host fallback, and strict-verifier floor are all
    inherited, so verdict semantics (including the SAFETY.md §7 torsion
    caveat) are exactly the single-device engine's.
    """

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_batch_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    @property
    def shard_count(self) -> int:
        """Devices this engine spreads a batch across (ladder labeling)."""
        return self._n_shards

    def _aggregate_device(self, idx, signatures, public_keys, scalars, zs):
        from consensus_tpu.models.ed25519 import (
            _bits_to_comb_digits8,
            _bytes_rows_to_bits,
            _prep_compressed,
            _signed_digits_int,
            _WINDOWS,
            _Z_WINDOWS,
            L,
        )

        m = len(idx)
        zk = [(z * scalars[i][1]) % L for z, i in zip(zs, idx)]
        y_r, sign_r, _ = _prep_compressed([bytes(signatures[i])[:32] for i in idx])
        y_a, sign_a, _ = _prep_compressed([bytes(public_keys[i]) for i in idx])
        zk_digits = np.array(
            [_signed_digits_int(v, _WINDOWS) for v in zk], dtype=np.int16
        ).T
        z_digits = np.array(
            [_signed_digits_int(z, _Z_WINDOWS) for z in zs], dtype=np.int16
        ).T
        zk_digits = (zk_digits + 8).astype(np.uint8)
        z_digits = (z_digits + 8).astype(np.uint8)
        host_ok = np.ones(m, dtype=bool)

        padded = engine_padded_size(
            m, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != m:
            pad = padded - m
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            zk_digits = np.pad(zk_digits, ((0, 0), (0, pad)), constant_values=8)
            z_digits = np.pad(z_digits, ((0, 0), (0, pad)), constant_values=8)
            host_ok = np.pad(host_ok, (0, pad))

        # Per-shard fixed-base scalars: lane j lives on shard j // per, so
        # u_s sums z·s over exactly that shard's live lanes.  Pad-only
        # shards get u_s = 0 (identity comb contribution).
        per = padded // self._n_shards
        u_rows = np.zeros((self._n_shards, 32), dtype=np.uint8)
        for s in range(self._n_shards):
            u_s = 0
            for j in range(s * per, min((s + 1) * per, m)):
                u_s += zs[j] * scalars[idx[j]][0]
            u_rows[s] = np.frombuffer(
                (u_s % L).to_bytes(32, "little"), dtype=np.uint8
            )
        zs_digits8 = _bits_to_comb_digits8(_bytes_rows_to_bits(u_rows))

        device_args = (
            np.ascontiguousarray(y_r.T),
            sign_r,
            np.ascontiguousarray(y_a.T),
            sign_a,
            zs_digits8,
            zk_digits,
            z_digits,
            host_ok,
        )
        args = [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _RAND_IN_SPECS)
        ]
        eq_ok, valid = self._fn(*args)
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])


# --- fused bytes-in -> verdict-out engines over the mesh ---------------------

#: Specs for the fused strict kernel (models/fused.py fused_verify_impl):
#: byte rows and SHA-512 block arrays all trail with the batch axis.
_FUSED_IN_SPECS = (
    P(None, BATCH_AXIS),              # sig_rows (64, batch)
    P(None, BATCH_AXIS),              # key_rows (32, batch)
    P(None, None, None, BATCH_AXIS),  # blocks (B, 16, 2, batch)
    P(BATCH_AXIS),                    # n_blocks
    P(BATCH_AXIS),                    # host_ok
)


def sharded_fused_verify_fn(mesh: Mesh):
    """jitted fused strict verify over ``mesh``: every shard runs the whole
    bytes-in → verdict-out front-end (SHA-512, mod-L reduction, canonical
    checks, digit recoding) on its own batch slice — the pipeline is pure
    data parallelism end to end, so the only collective is still the psum
    at the validity-count edge."""
    from consensus_tpu.models.fused import fused_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_FUSED_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(sig_rows, key_rows, blocks, n_blocks, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # Same rule as the host-prep shards: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = fused_verify_impl(sig_rows, key_rows, blocks, n_blocks, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ed25519.sharded_fused_verify")


class ShardedFusedEd25519Verifier(FusedEd25519BatchVerifier):
    """Fused strict verifier that spreads the batch across a device mesh —
    ``Configuration.device_prep`` + ``mesh_shards > 1``.  Verdicts are
    bit-identical to every other strict engine."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_fused_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.fused import _pad_wave

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        sig_rows, key_rows, blocks, n_blocks, host_ok = self._prepare_fused(
            messages, signatures, public_keys
        )
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        sig_rows, key_rows, n_blocks, host_ok = _pad_wave(
            [sig_rows, key_rows, n_blocks, host_ok], n, padded
        )
        if padded != n:
            blocks = np.pad(blocks, ((0, 0),) * 3 + ((0, padded - n),))
        device_args = (
            np.ascontiguousarray(sig_rows.T),
            np.ascontiguousarray(key_rows.T),
            blocks,
            n_blocks,
            host_ok,
        )
        args = [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _FUSED_IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


#: Specs for the sharded fused aggregate: byte rows and block arrays shard
#: on the trailing batch axis; the transcript's cross-shard edge (every
#: shard needs every lane's leaf digest to assemble the root) is an
#: all_gather INSIDE the shard body, not an input spec.
_FUSED_AGG_IN_SPECS = (
    P(None, BATCH_AXIS),              # r_rows
    P(None, BATCH_AXIS),              # s_rows
    P(None, BATCH_AXIS),              # key_rows
    P(None, None, None, BATCH_AXIS),  # k_blocks
    P(BATCH_AXIS),                    # k_nblocks
    P(None, None, None, BATCH_AXIS),  # leaf_blocks
    P(BATCH_AXIS),                    # leaf_nblocks
    P(BATCH_AXIS),                    # host_ok
)


def sharded_fused_aggregate_fn(mesh: Mesh, tag: bytes, n: int, padded: int):
    """jitted fused randomized-aggregate check over ``mesh``.

    Device Fiat–Shamir with one collective: each shard hashes its own
    lanes' transcript leaves, an ``all_gather`` assembles the full leaf
    digest table on every shard, and each shard then derives the IDENTICAL
    root and its own lanes' coefficients ``zᵢ = H(root ‖ i)`` — the same
    transcript bytes as the host twin, so coefficients match bit-for-bit.
    As in :func:`sharded_batch_verify_fn`, every shard checks an
    independent aggregate over its lane subset with its own base scalar
    ``u_s = Σ zᵢsᵢ`` (pad lanes carry s = 0 and masked digits, so a
    padding-only shard votes ok), and one psum tree-reduces the verdict.
    Specialized per (n, padded) like the single-device aggregate graphs —
    stats accumulate under one kernel-accounting name."""
    from consensus_tpu.models.ed25519 import (
        _WINDOWS,
        _Z_WINDOWS,
        batch_verify_impl,
    )
    from consensus_tpu.models.fused import _aggregate_constants
    from consensus_tpu.ops import scalar25519 as sc
    from consensus_tpu.ops import sha512 as sh

    n_shards = mesh.devices.size
    if padded % n_shards:
        raise ValueError("padded batch must be a multiple of the mesh size")
    per = padded // n_shards
    (
        root_prefix, root_trailer, root_blocks, z_trailer, idx_rows
    ) = _aggregate_constants(tag, n, padded)
    one_z = np.zeros((16, 1), dtype=np.int32)
    one_z[0, 0] = 1

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_FUSED_AGG_IN_SPECS,
        out_specs=(P(), P(BATCH_AXIS)),
    )
    def _shard(
        r_rows, s_rows, key_rows, k_blocks, k_nblocks,
        leaf_blocks, leaf_nblocks, host_ok,
    ):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        shard = jax.lax.axis_index(BATCH_AXIS)
        r = r_rows.astype(jnp.int32)
        key = key_rows.astype(jnp.int32)
        with suppress_pallas_scan():
            k_digest = sh.digest_bytes(sh.sha512_blocks(k_blocks, k_nblocks))
            k_bytes = sc.reduce_bytes_mod_l(k_digest)

            leaves = sh.digest_bytes(
                sh.sha512_blocks(leaf_blocks, leaf_nblocks)
            )  # (64, per)
            gathered = jax.lax.all_gather(
                leaves, BATCH_AXIS, axis=1, tiled=True
            )  # (64, padded), global lane order
            root_rows = jnp.concatenate(
                [
                    jnp.asarray(root_prefix, jnp.int32),
                    gathered[:, :n].T.reshape(64 * n, 1),
                    jnp.asarray(root_trailer, jnp.int32),
                ],
                axis=0,
            )
            root = sh.digest_bytes(
                sh.sha512_blocks(
                    sh.pack_bytes_device(root_rows),
                    jnp.full((1,), root_blocks, jnp.int32),
                )
            )

            local_idx = jax.lax.dynamic_slice_in_dim(
                jnp.asarray(idx_rows, jnp.int32), shard * per, per, axis=1
            )
            z_rows = jnp.concatenate(
                [
                    jnp.broadcast_to(root, (64, per)),
                    local_idx,
                    jnp.asarray(z_trailer[:, :per], jnp.int32),
                ],
                axis=0,
            )
            z_digest = sh.digest_bytes(
                sh.sha512_blocks(
                    sh.pack_bytes_device(z_rows), jnp.ones((per,), jnp.int32)
                )
            )
            z = z_digest[:16]
            z = jnp.where((z == 0).all(axis=0)[None], jnp.asarray(one_z), z)

            zk = sc.mul_mod_l(z, k_bytes)
            zk_digits = sc.signed_window_digits(zk, _WINDOWS)
            z_digits = sc.signed_window_digits(z, _Z_WINDOWS)
            u = sc.sum_mod_l(sc.mul_mod_l(z, s_rows.astype(jnp.int32)))

            y_r = jnp.concatenate([r[:31], (r[31] & 0x7F)[None]], axis=0)
            y_a = jnp.concatenate([key[:31], (key[31] & 0x7F)[None]], axis=0)
            eq_ok, valid = batch_verify_impl(
                y_r, r[31] >> 7, y_a, key[31] >> 7, u, zk_digits, z_digits,
                host_ok,
            )
        bad = jax.lax.psum(1 - eq_ok.astype(jnp.int32), BATCH_AXIS)
        return bad == 0, valid

    return instrumented_jit(_shard, "ed25519.sharded_fused_batch_verify")


class ShardedFusedEd25519RandomizedVerifier(
    FusedEd25519RandomizedBatchVerifier, ShardedFusedEd25519Verifier
):
    """Randomized fused verifier whose aggregate check (and strict floor)
    ride the mesh.  The bisection driver, host fallback, and canonical
    pre-filter are inherited from the single-device fused engine; only the
    two launch seams are re-routed."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        # The randomized base consumes min_randomized before the strict
        # chain; with the diamond MRO here the strict chain would skip it,
        # so pop + set it explicitly (same clamp as the base).
        min_randomized = kw.pop("min_randomized", 2)
        ShardedFusedEd25519Verifier.__init__(self, mesh, **kw)
        self._min_randomized = max(2, int(min_randomized))
        self._agg_fns: dict = {}

    def _strict_floor(self, messages, signatures, public_keys) -> np.ndarray:
        return ShardedFusedEd25519Verifier.verify_batch(
            self, messages, signatures, public_keys
        )

    def _fused_aggregate(self, idx, messages, signatures, public_keys):
        from consensus_tpu.models.ed25519 import _Z_TAG
        from consensus_tpu.models.fused import (
            _byte_rows,
            _frame,
            _pack_blocks,
            _pad_wave,
        )

        m = len(idx)
        rs = [bytes(signatures[i])[:32] for i in idx]
        keys = [bytes(public_keys[i]) for i in idx]
        msgs = [bytes(messages[i]) for i in idx]
        r_rows = _byte_rows(rs, 32)
        key_rows = _byte_rows(keys, 32)
        s_rows = _byte_rows([bytes(signatures[i])[32:] for i in idx], 32)
        k_blocks, k_nblocks = _pack_blocks(
            [r + a + mm for r, a, mm in zip(rs, keys, msgs)]
        )
        leaf_blocks, leaf_nblocks = _pack_blocks(
            [
                _frame(mm) + _frame(bytes(signatures[i])) + _frame(a)
                for mm, i, a in zip(msgs, idx, keys)
            ]
        )
        host_ok = np.ones(m, dtype=bool)

        padded = engine_padded_size(
            m, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok = _pad_wave(
            [r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok],
            m, padded,
        )
        if padded != m:
            batch_pad = ((0, 0),) * 3 + ((0, padded - m),)
            k_blocks = np.pad(k_blocks, batch_pad)
            leaf_blocks = np.pad(leaf_blocks, batch_pad)

        fn = self._agg_fns.get((m, padded))
        if fn is None:
            fn = self._agg_fns[(m, padded)] = sharded_fused_aggregate_fn(
                self.mesh, _Z_TAG, m, padded
            )
        device_args = (
            np.ascontiguousarray(r_rows.T),
            np.ascontiguousarray(s_rows.T),
            np.ascontiguousarray(key_rows.T),
            k_blocks,
            k_nblocks,
            leaf_blocks,
            leaf_nblocks,
            host_ok,
        )
        args = [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _FUSED_AGG_IN_SPECS)
        ]
        eq_ok, valid = fn(*args)
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])


__all__ = [
    "make_mesh",
    "mesh_for_shards",
    "sharded_verify_fn",
    "sharded_batch_verify_fn",
    "sharded_p256_verify_fn",
    "sharded_fused_verify_fn",
    "sharded_fused_aggregate_fn",
    "ShardedEd25519Verifier",
    "ShardedEd25519RandomizedVerifier",
    "ShardedEcdsaP256Verifier",
    "ShardedFusedEd25519Verifier",
    "ShardedFusedEd25519RandomizedVerifier",
    "mesh_padded_size",
    "engine_padded_size",
    "BATCH_AXIS",
]
