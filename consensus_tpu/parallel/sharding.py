"""Device-mesh sharding of the crypto batch path.

The verification workload is pure data parallelism: every signature's
double-scalar multiplication is independent, so the natural multi-chip
layout is a 1-D mesh with the batch axis sharded across it.  Collectives
only appear at the reduction edge (the validity count / all-valid bit),
where a ``psum`` rides the ICI.

Two entry points:

* :func:`sharded_verify` — ``shard_map`` of the kernel body over the mesh:
  each device verifies its batch shard; outputs stay sharded (gathered
  lazily by the host when read).
* :class:`ShardedEd25519Verifier` — drop-in
  :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier` that pads the
  batch to a multiple of the mesh size and runs the sharded kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    to_kernel_layout,
    verify_impl,
)
from consensus_tpu.obs.kernels import instrumented_jit

BATCH_AXIS = "batch"

# jax.shard_map was promoted to the top level after 0.4.x; older releases
# ship it under jax.experimental only.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 installs
    from jax.experimental.shard_map import shard_map as _shard_map

#: Device-layout partition specs: limb/bit arrays are (20|256, batch) —
#: batch is the trailing axis; per-element vectors are (batch,).
_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # s_bits
    P(None, BATCH_AXIS),  # k_bits
    P(BATCH_AXIS),        # host_ok
)


def mesh_padded_size(n: int, n_shards: int, minimum: int = 8) -> int:
    """Pow-2 growth for compile-shape reuse, then rounded UP to a multiple
    of the mesh size — terminates for any shard count (a pure doubling loop
    never exits for non-power-of-two meshes)."""
    size = minimum
    while size < n:
        size *= 2
    size += (-size) % n_shards
    return size


def engine_padded_size(
    n: int,
    n_shards: int,
    *,
    pad_to: int = 0,
    pad_pow2: bool = True,
    minimum: int = 8,
) -> int:
    """Mesh-aligned padded batch size honouring the engine's padding knobs
    (``pad_to`` pins one compiled shape, ``pad_pow2`` grows by doubling),
    then rounded UP to a multiple of the mesh size so every shard gets an
    equal slice."""
    if pad_to >= n:
        size = pad_to
    elif pad_pow2:
        size = minimum
        while size < n:
            size *= 2
    else:
        size = max(n, 1)
    size += (-size) % n_shards
    return size


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all visible devices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def mesh_for_shards(n_shards: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_shards`` visible devices — the
    ``Configuration.mesh_shards`` -> engine seam.  Fails loudly when the
    host exposes fewer devices than the config demands: silently shrinking
    the mesh would make the one compiled kernel shape depend on deploy-time
    topology."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards < 1:
        raise ValueError(f"mesh_shards must be >= 1, got {n_shards}")
    if len(devices) < n_shards:
        raise ValueError(
            f"mesh_shards={n_shards} but only {len(devices)} device(s) "
            "visible (set XLA_FLAGS=--xla_force_host_platform_device_count "
            "for a host mesh, or lower mesh_shards)"
        )
    return Mesh(np.array(devices[:n_shards]), (BATCH_AXIS,))


def sharded_verify_fn(mesh: Mesh):
    """A jitted verify over ``mesh``: inputs sharded on the batch axis, plus
    a ``psum``-reduced valid count so the collective path is exercised."""

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # pallas_call-under-shard_map is unvalidated (and per-shard batch
        # sizes would change the tiling decision): the multi-chip path
        # always traces the XLA scan, opt-in flag or not.
        with suppress_pallas_scan():
            ok = verify_impl(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ed25519.sharded_verify")


class ShardedEd25519Verifier(Ed25519BatchVerifier):
    """Batch verifier that spreads the batch across a device mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        # Reuse the host-side preparation from the base class by padding to
        # the mesh-aligned size before the kernel call.
        prepped = self._prepare(messages, signatures, public_keys)
        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = prepped
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok = np.pad(host_ok, (0, pad))
        device_args = to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok
        )
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


# --- ECDSA-P256 sharding ---------------------------------------------------

#: Device-layout specs for the P-256 kernel (see models/ecdsa_p256.py):
#: limb/digit arrays lead with their vector axis, batch trails.
_P256_IN_SPECS = (
    P(None, BATCH_AXIS),  # qx
    P(None, BATCH_AXIS),  # qy
    P(None, BATCH_AXIS),  # u1 digits
    P(None, BATCH_AXIS),  # u2 digits
    P(None, BATCH_AXIS),  # r1
    P(None, BATCH_AXIS),  # r2
    P(BATCH_AXIS),        # has_r2
    P(BATCH_AXIS),        # host_ok
)


def sharded_p256_verify_fn(mesh: Mesh):
    """jitted ECDSA-P256 verify over ``mesh`` with a psum valid count."""
    from consensus_tpu.models.ecdsa_p256 import verify_impl as p256_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_P256_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok):
        from consensus_tpu.ops.pallas_scan import suppress_pallas_scan

        # Same rule as the Ed25519 shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = p256_verify_impl(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ecdsa_p256.sharded_verify")


class ShardedEcdsaP256Verifier(EcdsaP256BatchVerifier):
    """ECDSA-P256 batch verifier spread across a device mesh (reuses the
    base class's preparation/validation; only the launch path differs)."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_p256_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.ecdsa_p256 import pad_prepared, to_kernel_layout

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        prepped = self._prepare(messages, signatures, public_keys)
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        device_args = to_kernel_layout(*pad_prepared(prepped, padded))
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _P256_IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


# --- randomized Ed25519 batch verification over the mesh --------------------

#: Specs for the randomized-aggregate kernel (models/ed25519.py
#: batch_verify_impl): per-lane arrays shard on the batch axis, and the
#: fixed-base comb digits carry ONE (32, 1) column per shard — each shard
#: checks its own aggregate [u_s]B + Σ[zkᵢ](−Aᵢ) + Σ[zᵢ](−Rᵢ) = 0 against
#: its lanes' base-point scalar u_s.
_RAND_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # zs_digits8: (32, n_shards), one column per shard
    P(None, BATCH_AXIS),  # zk_digits
    P(None, BATCH_AXIS),  # z_digits
    P(BATCH_AXIS),        # host_ok
)


def sharded_batch_verify_fn(mesh: Mesh):
    """jitted randomized-aggregate verify over ``mesh``.

    Point addition is not componentwise, so the per-shard accumulators can
    NOT be psum'd as coordinates; instead every shard runs an independent
    aggregate check over its own lane subset (each sound to 2^-128 —
    the conjunction is at least as strong as one whole-batch check), and
    the single ``psum`` tree-reduces the per-shard not-identity counts
    into the global verdict.  A padding-only shard contributes u_s = 0 and
    all-masked digits, so its accumulator is the identity and it votes ok.
    """
    from consensus_tpu.models.ed25519 import batch_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_RAND_IN_SPECS,
        out_specs=(P(), P(BATCH_AXIS)),
    )
    def _shard(y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # Same rule as the strict shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            eq_ok, valid = batch_verify_impl(
                y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok
            )
        bad = jax.lax.psum(1 - eq_ok.astype(jnp.int32), BATCH_AXIS)
        return bad == 0, valid

    return instrumented_jit(_shard, "ed25519.sharded_batch_verify")


class ShardedEd25519RandomizedVerifier(Ed25519RandomizedBatchVerifier):
    """Randomized batch verifier whose aggregate check rides the mesh.

    Only the device aggregate changes: the bisection driver, transcript
    coefficients, host fallback, and strict-verifier floor are all
    inherited, so verdict semantics (including the SAFETY.md §7 torsion
    caveat) are exactly the single-device engine's.
    """

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_batch_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def _aggregate_device(self, idx, signatures, public_keys, scalars, zs):
        from consensus_tpu.models.ed25519 import (
            _bits_to_comb_digits8,
            _bytes_rows_to_bits,
            _prep_compressed,
            _signed_digits_int,
            _WINDOWS,
            _Z_WINDOWS,
            L,
        )

        m = len(idx)
        zk = [(z * scalars[i][1]) % L for z, i in zip(zs, idx)]
        y_r, sign_r, _ = _prep_compressed([bytes(signatures[i])[:32] for i in idx])
        y_a, sign_a, _ = _prep_compressed([bytes(public_keys[i]) for i in idx])
        zk_digits = np.array(
            [_signed_digits_int(v, _WINDOWS) for v in zk], dtype=np.int16
        ).T
        z_digits = np.array(
            [_signed_digits_int(z, _Z_WINDOWS) for z in zs], dtype=np.int16
        ).T
        zk_digits = (zk_digits + 8).astype(np.uint8)
        z_digits = (z_digits + 8).astype(np.uint8)
        host_ok = np.ones(m, dtype=bool)

        padded = engine_padded_size(
            m, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != m:
            pad = padded - m
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            zk_digits = np.pad(zk_digits, ((0, 0), (0, pad)), constant_values=8)
            z_digits = np.pad(z_digits, ((0, 0), (0, pad)), constant_values=8)
            host_ok = np.pad(host_ok, (0, pad))

        # Per-shard fixed-base scalars: lane j lives on shard j // per, so
        # u_s sums z·s over exactly that shard's live lanes.  Pad-only
        # shards get u_s = 0 (identity comb contribution).
        per = padded // self._n_shards
        u_rows = np.zeros((self._n_shards, 32), dtype=np.uint8)
        for s in range(self._n_shards):
            u_s = 0
            for j in range(s * per, min((s + 1) * per, m)):
                u_s += zs[j] * scalars[idx[j]][0]
            u_rows[s] = np.frombuffer(
                (u_s % L).to_bytes(32, "little"), dtype=np.uint8
            )
        zs_digits8 = _bits_to_comb_digits8(_bytes_rows_to_bits(u_rows))

        device_args = (
            np.ascontiguousarray(y_r.T),
            sign_r,
            np.ascontiguousarray(y_a.T),
            sign_a,
            zs_digits8,
            zk_digits,
            z_digits,
            host_ok,
        )
        args = [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _RAND_IN_SPECS)
        ]
        eq_ok, valid = self._fn(*args)
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])


__all__ = [
    "make_mesh",
    "mesh_for_shards",
    "sharded_verify_fn",
    "sharded_batch_verify_fn",
    "sharded_p256_verify_fn",
    "ShardedEd25519Verifier",
    "ShardedEd25519RandomizedVerifier",
    "ShardedEcdsaP256Verifier",
    "mesh_padded_size",
    "engine_padded_size",
    "BATCH_AXIS",
]
