"""Device-mesh sharding of the crypto batch path.

The verification workload is pure data parallelism: every signature's
double-scalar multiplication is independent, so the natural multi-chip
layout is a 1-D mesh with the batch axis sharded across it.  Collectives
only appear at the reduction edge (the validity count / all-valid bit),
where a ``psum`` rides the ICI.

Two entry points:

* :func:`sharded_verify` — ``shard_map`` of the kernel body over the mesh:
  each device verifies its batch shard; outputs stay sharded (gathered
  lazily by the host when read).
* :class:`ShardedEd25519Verifier` — drop-in
  :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier` that pads the
  batch to a multiple of the mesh size and runs the sharded kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    to_kernel_layout,
    verify_impl,
)
from consensus_tpu.obs.kernels import instrumented_jit

BATCH_AXIS = "batch"

# jax.shard_map was promoted to the top level after 0.4.x; older releases
# ship it under jax.experimental only.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 installs
    from jax.experimental.shard_map import shard_map as _shard_map

#: Device-layout partition specs: limb/bit arrays are (20|256, batch) —
#: batch is the trailing axis; per-element vectors are (batch,).
_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # s_bits
    P(None, BATCH_AXIS),  # k_bits
    P(BATCH_AXIS),        # host_ok
)


def mesh_padded_size(n: int, n_shards: int, minimum: int = 8) -> int:
    """Pow-2 growth for compile-shape reuse, then rounded UP to a multiple
    of the mesh size — terminates for any shard count (a pure doubling loop
    never exits for non-power-of-two meshes)."""
    size = minimum
    while size < n:
        size *= 2
    size += (-size) % n_shards
    return size


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all visible devices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def sharded_verify_fn(mesh: Mesh):
    """A jitted verify over ``mesh``: inputs sharded on the batch axis, plus
    a ``psum``-reduced valid count so the collective path is exercised."""

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # pallas_call-under-shard_map is unvalidated (and per-shard batch
        # sizes would change the tiling decision): the multi-chip path
        # always traces the XLA scan, opt-in flag or not.
        with suppress_pallas_scan():
            ok = verify_impl(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ed25519.sharded_verify")


class ShardedEd25519Verifier(Ed25519BatchVerifier):
    """Batch verifier that spreads the batch across a device mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        # Reuse the host-side preparation from the base class by padding to
        # the mesh-aligned size before the kernel call.
        prepped = self._prepare(messages, signatures, public_keys)
        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = prepped
        padded = mesh_padded_size(n, self._n_shards)
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok = np.pad(host_ok, (0, pad))
        device_args = to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok
        )
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


# --- ECDSA-P256 sharding ---------------------------------------------------

#: Device-layout specs for the P-256 kernel (see models/ecdsa_p256.py):
#: limb/digit arrays lead with their vector axis, batch trails.
_P256_IN_SPECS = (
    P(None, BATCH_AXIS),  # qx
    P(None, BATCH_AXIS),  # qy
    P(None, BATCH_AXIS),  # u1 digits
    P(None, BATCH_AXIS),  # u2 digits
    P(None, BATCH_AXIS),  # r1
    P(None, BATCH_AXIS),  # r2
    P(BATCH_AXIS),        # has_r2
    P(BATCH_AXIS),        # host_ok
)


def sharded_p256_verify_fn(mesh: Mesh):
    """jitted ECDSA-P256 verify over ``mesh`` with a psum valid count."""
    from consensus_tpu.models.ecdsa_p256 import verify_impl as p256_verify_impl

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_P256_IN_SPECS,
        out_specs=(P(BATCH_AXIS), P()),
    )
    def _shard(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok):
        from consensus_tpu.ops.pallas_scan import suppress_pallas_scan

        # Same rule as the Ed25519 shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = p256_verify_impl(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, total

    return instrumented_jit(_shard, "ecdsa_p256.sharded_verify")


class ShardedEcdsaP256Verifier(EcdsaP256BatchVerifier):
    """ECDSA-P256 batch verifier spread across a device mesh (reuses the
    base class's preparation/validation; only the launch path differs)."""

    def __init__(self, mesh: Optional[Mesh] = None, **kw) -> None:
        super().__init__(**kw)
        self.mesh = mesh if mesh is not None else make_mesh()
        self._fn = sharded_p256_verify_fn(self.mesh)
        self._n_shards = self.mesh.devices.size

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.ecdsa_p256 import pad_prepared, to_kernel_layout

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        prepped = self._prepare(messages, signatures, public_keys)
        padded = mesh_padded_size(n, self._n_shards)
        device_args = to_kernel_layout(*pad_prepared(prepped, padded))
        args = [
            jax.device_put(a, NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _P256_IN_SPECS)
        ]
        ok, _total = self._fn(*args)
        return np.asarray(ok)[:n]


__all__ = [
    "make_mesh",
    "sharded_verify_fn",
    "sharded_p256_verify_fn",
    "ShardedEd25519Verifier",
    "ShardedEcdsaP256Verifier",
    "mesh_padded_size",
    "BATCH_AXIS",
]
