"""Device-mesh sharding of the crypto batch path.

The verification workload is pure data parallelism: every signature's
double-scalar multiplication is independent, so the natural multi-chip
layout is a mesh with the batch axis sharded across it.  Collectives only
appear at the reduction edge (the validity count / all-valid bit), where a
``psum`` rides the ICI.

Topologies come from :class:`~consensus_tpu.parallel.topology.MeshTopology`:
a 1-D ``(n,)`` spec (the ``mesh_shards=n`` sugar) builds the historical
``("batch",)`` mesh bit-for-bit, while an N-D spec such as ``(2, 4)`` names
its leading axes (``("slice", "batch")``) and shards the batch dimension
over the FULL axis tuple — the per-lane math, padding, and verdicts are
identical at equal device counts; only the device layout the runtime maps
onto the physical interconnect changes.

Two entry points:

* :func:`sharded_verify_fn` — ``shard_map`` of the kernel body over the
  mesh: each device verifies its batch shard; outputs stay sharded
  (gathered lazily by the host when read).
* :class:`ShardedEd25519Verifier` — drop-in
  :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier` that pads the
  batch to a multiple of the mesh size and runs the sharded kernel.

Kernel construction rides an in-process ``(kernel, topology[, shape])`` ->
compiled-fn memo (:func:`compiled_kernel`): rebuilding an engine — fleet
restart, tenant churn, supervisor ladder reconstruction — reuses the
already-traced jit wrapper instead of paying a retrace storm, which the obs
kernel ledger's compile counter proves (tests/test_mesh.py).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    to_kernel_layout,
    verify_impl,
)
from consensus_tpu.models.fused import (
    FusedEd25519BatchVerifier,
    FusedEd25519RandomizedBatchVerifier,
)
from consensus_tpu.obs.kernels import (
    COMPILE_CACHE,
    instrumented_jit,
    kernel_lane_suffix,
)
from consensus_tpu.parallel.topology import (
    BATCH_AXIS,
    MeshTopology,
    engine_padded_size,
    mesh_padded_size,
)

# jax.shard_map was promoted to the top level after 0.4.x; older releases
# ship it under jax.experimental only.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 installs
    from jax.experimental.shard_map import shard_map as _shard_map

#: Device-layout partition specs: limb/bit arrays are (20|256, batch) —
#: batch is the trailing axis; per-element vectors are (batch,).  These are
#: the 1-D templates; :func:`_mesh_specs` widens the batch entry to the full
#: axis-name tuple for N-D topologies.
_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # s_bits
    P(None, BATCH_AXIS),  # k_bits
    P(BATCH_AXIS),        # host_ok
)


def _reduce_axes(mesh: Mesh):
    """The axis-name argument collectives reduce/gather over: the bare
    ``BATCH_AXIS`` on a 1-D mesh (bit-for-bit the historical graphs), the
    full name tuple on N-D topologies."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def _mesh_specs(mesh: Mesh, specs):
    """Widen 1-D spec templates to ``mesh``: every ``BATCH_AXIS`` entry
    becomes the full axis-name tuple, so the batch dimension is sharded
    across ALL mesh axes (row-major — matching tiled ``all_gather`` order
    and the linear :func:`_shard_index`)."""
    names = tuple(mesh.axis_names)
    if names == (BATCH_AXIS,):
        return tuple(specs)
    return tuple(
        P(*[names if part == BATCH_AXIS else part for part in spec])
        for spec in specs
    )


def _shard_index(mesh: Mesh):
    """This shard's linear index in global (row-major) lane order — inside a
    shard body only.  Reduces to the historical ``axis_index(BATCH_AXIS)``
    on 1-D meshes."""
    names = tuple(mesh.axis_names)
    idx = jax.lax.axis_index(names[0])
    for name in names[1:]:
        idx = idx * mesh.shape[name] + jax.lax.axis_index(name)
    return idx


# --- in-process compiled-kernel memo ----------------------------------------

_COMPILED_KERNELS: dict = {}


def _kernel_key(name: str, mesh: Mesh, extra: tuple) -> tuple:
    return (
        name,
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
        extra,
    )


def compiled_kernel(
    name: str,
    mesh: Mesh,
    builder: Callable[[], Callable],
    *,
    memo: bool = True,
    extra: tuple = (),
) -> Callable:
    """The in-process ``(kernel, topology[, shape])`` -> compiled-fn memo.

    A jit wrapper's trace cache lives on the wrapper object, so an engine
    that builds a fresh wrapper per construction re-traces every compiled
    shape on rebuild even when XLA's persistent cache skips the backend
    compile.  Two engines over the same mesh run the same computation, so
    the wrapper itself is shared here instead — a rebuilt engine's warmup
    books ZERO new compiles in the kernel ledger.  ``extra`` extends the key
    for shape-specialized graphs (the fused aggregate's ``(n, padded)``).
    Hits/misses book into :data:`consensus_tpu.obs.kernels.COMPILE_CACHE`;
    ``memo=False`` (``CompileCacheConfig.enabled=False``) always builds
    fresh and books a miss.
    """
    if not memo:
        COMPILE_CACHE.record(hit=False)
        return builder()
    key = _kernel_key(name, mesh, extra)
    fn = _COMPILED_KERNELS.get(key)
    if fn is None:
        COMPILE_CACHE.record(hit=False)
        fn = _COMPILED_KERNELS[key] = builder()
    else:
        COMPILE_CACHE.record(hit=True)
    return fn


def clear_compiled_kernels() -> None:
    """Drop every memoized kernel (tests; never needed in production)."""
    _COMPILED_KERNELS.clear()


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all visible devices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def mesh_for_shards(n_shards: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first ``n_shards`` visible devices — the
    ``Configuration.mesh_shards`` -> engine seam, now the 1-D special case
    of :meth:`MeshTopology.build_mesh`.  Fails loudly when the host exposes
    fewer devices than the config demands: silently shrinking the mesh
    would make the one compiled kernel shape depend on deploy-time
    topology."""
    if n_shards < 1:
        raise ValueError(f"mesh_shards must be >= 1, got {n_shards}")
    return MeshTopology((n_shards,)).build_mesh(devices)


class _MeshEngine:
    """Shared mesh plumbing for the sharded engines: topology coercion, the
    memoized kernel seam, and the wave-sizing surface the coalescers read.

    ``mesh`` may be a ``jax.sharding.Mesh`` or a :class:`MeshTopology`
    (built over the visible devices); ``compile_cache=False`` opts this
    engine out of the process-wide compiled-kernel memo."""

    def _init_mesh(
        self,
        mesh: Union[Mesh, MeshTopology, None],
        kernel_name: str,
        builder: Callable[[Mesh], Callable],
        in_specs,
        compile_cache: bool = True,
    ) -> None:
        if isinstance(mesh, MeshTopology):
            mesh = mesh.build_mesh()
        self.mesh = mesh if mesh is not None else make_mesh()
        self._compile_cache = bool(compile_cache)
        self._in_specs = _mesh_specs(self.mesh, in_specs)
        self._fn = compiled_kernel(
            kernel_name,
            self.mesh,
            lambda: builder(self.mesh),
            memo=self._compile_cache,
        )
        self._n_shards = int(self.mesh.devices.size)

    @property
    def shard_count(self) -> int:
        """Devices this engine spreads a batch across.  The engine
        supervisor's degrade ladder labels mesh rungs with it (an
        ``N-shard`` rung degrading to a ``1-shard`` rung reads as exactly
        that in logs/traces rather than two identical class names)."""
        return self._n_shards

    @property
    def preferred_wave_size(self) -> int:
        """The smallest padded wave that saturates the whole topology —
        every shard receives at least ``min_device_batch`` lanes, rounded
        through the engine's padding knobs.  The wave formers
        (models/engine.py) flush early once this many signatures are
        aboard: waiting longer adds latency without adding devices."""
        return engine_padded_size(
            self._n_shards * max(1, self._min_device_batch),
            self._n_shards,
            pad_to=self._pad_to,
            pad_pow2=self._pad_pow2,
        )

    def _put_sharded(self, device_args):
        return [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, self._in_specs)
        ]


def sharded_verify_fn(mesh: Mesh):
    """A jitted verify over ``mesh``: inputs sharded on the batch axis, plus
    a ``psum``-reduced valid count so the collective path is exercised."""
    axes = _reduce_axes(mesh)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_mesh_specs(mesh, _IN_SPECS),
        out_specs=_mesh_specs(mesh, (P(BATCH_AXIS), P())),
    )
    def _shard(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # pallas_call-under-shard_map is unvalidated (and per-shard batch
        # sizes would change the tiling decision): the multi-chip path
        # always traces the XLA scan, opt-in flag or not.
        with suppress_pallas_scan():
            ok = verify_impl(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axes)
        return ok, total

    return instrumented_jit(_shard, "ed25519.sharded_verify" + kernel_lane_suffix())


class ShardedEd25519Verifier(_MeshEngine, Ed25519BatchVerifier):
    """Batch verifier that spreads the batch across a device mesh."""

    def __init__(
        self,
        mesh: Union[Mesh, MeshTopology, None] = None,
        *,
        compile_cache: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self._init_mesh(
            mesh, "ed25519.sharded_verify", sharded_verify_fn, _IN_SPECS,
            compile_cache,
        )

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        # Reuse the host-side preparation from the base class by padding to
        # the mesh-aligned size before the kernel call.
        prepped = self._prepare(messages, signatures, public_keys)
        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = prepped
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok = np.pad(host_ok, (0, pad))
        device_args = to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok
        )
        ok, _total = self._fn(*self._put_sharded(device_args))
        return np.asarray(ok)[:n]


# --- ECDSA-P256 sharding ---------------------------------------------------

#: Device-layout specs for the P-256 kernel (see models/ecdsa_p256.py):
#: limb/digit arrays lead with their vector axis, batch trails.
_P256_IN_SPECS = (
    P(None, BATCH_AXIS),  # qx
    P(None, BATCH_AXIS),  # qy
    P(None, BATCH_AXIS),  # u1 digits
    P(None, BATCH_AXIS),  # u2 digits
    P(None, BATCH_AXIS),  # r1
    P(None, BATCH_AXIS),  # r2
    P(BATCH_AXIS),        # has_r2
    P(BATCH_AXIS),        # host_ok
)


def sharded_p256_verify_fn(mesh: Mesh):
    """jitted ECDSA-P256 verify over ``mesh`` with a psum valid count."""
    from consensus_tpu.models.ecdsa_p256 import verify_impl as p256_verify_impl

    axes = _reduce_axes(mesh)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_mesh_specs(mesh, _P256_IN_SPECS),
        out_specs=_mesh_specs(mesh, (P(BATCH_AXIS), P())),
    )
    def _shard(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok):
        from consensus_tpu.ops.pallas_scan import suppress_pallas_scan

        # Same rule as the Ed25519 shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = p256_verify_impl(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axes)
        return ok, total

    return instrumented_jit(_shard, "ecdsa_p256.sharded_verify" + kernel_lane_suffix())


class ShardedEcdsaP256Verifier(_MeshEngine, EcdsaP256BatchVerifier):
    """ECDSA-P256 batch verifier spread across a device mesh (reuses the
    base class's preparation/validation; only the launch path differs)."""

    def __init__(
        self,
        mesh: Union[Mesh, MeshTopology, None] = None,
        *,
        compile_cache: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self._init_mesh(
            mesh, "ecdsa_p256.sharded_verify", sharded_p256_verify_fn,
            _P256_IN_SPECS, compile_cache,
        )

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.ecdsa_p256 import pad_prepared, to_kernel_layout

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        prepped = self._prepare(messages, signatures, public_keys)
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        device_args = to_kernel_layout(*pad_prepared(prepped, padded))
        ok, _total = self._fn(*self._put_sharded(device_args))
        return np.asarray(ok)[:n]


# --- randomized Ed25519 batch verification over the mesh --------------------

#: Specs for the randomized-aggregate kernel (models/ed25519.py
#: batch_verify_impl): per-lane arrays shard on the batch axis, and the
#: fixed-base comb digits carry ONE (32, 1) column per shard — each shard
#: checks its own aggregate [u_s]B + Σ[zkᵢ](−Aᵢ) + Σ[zᵢ](−Rᵢ) = 0 against
#: its lanes' base-point scalar u_s.
_RAND_IN_SPECS = (
    P(None, BATCH_AXIS),  # y_r
    P(BATCH_AXIS),        # sign_r
    P(None, BATCH_AXIS),  # y_a
    P(BATCH_AXIS),        # sign_a
    P(None, BATCH_AXIS),  # zs_digits8: (32, n_shards), one column per shard
    P(None, BATCH_AXIS),  # zk_digits
    P(None, BATCH_AXIS),  # z_digits
    P(BATCH_AXIS),        # host_ok
)


def sharded_batch_verify_fn(mesh: Mesh):
    """jitted randomized-aggregate verify over ``mesh``.

    Point addition is not componentwise, so the per-shard accumulators can
    NOT be psum'd as coordinates; instead every shard runs an independent
    aggregate check over its own lane subset (each sound to 2^-128 —
    the conjunction is at least as strong as one whole-batch check), and
    the single ``psum`` tree-reduces the per-shard not-identity counts
    into the global verdict.  A padding-only shard contributes u_s = 0 and
    all-masked digits, so its accumulator is the identity and it votes ok.
    """
    from consensus_tpu.models.ed25519 import batch_verify_impl

    axes = _reduce_axes(mesh)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_mesh_specs(mesh, _RAND_IN_SPECS),
        out_specs=_mesh_specs(mesh, (P(), P(BATCH_AXIS))),
    )
    def _shard(y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # Same rule as the strict shard: no pallas_call under shard_map.
        with suppress_pallas_scan():
            eq_ok, valid = batch_verify_impl(
                y_r, sign_r, y_a, sign_a, zs_digits8, zk_digits, z_digits, host_ok
            )
        bad = jax.lax.psum(1 - eq_ok.astype(jnp.int32), axes)
        return bad == 0, valid

    return instrumented_jit(
        _shard, "ed25519.sharded_batch_verify" + kernel_lane_suffix()
    )


class ShardedEd25519RandomizedVerifier(_MeshEngine, Ed25519RandomizedBatchVerifier):
    """Randomized batch verifier whose aggregate check rides the mesh.

    Only the device aggregate changes: the bisection driver, transcript
    coefficients, host fallback, and strict-verifier floor are all
    inherited, so verdict semantics (including the SAFETY.md §7 torsion
    caveat) are exactly the single-device engine's.
    """

    def __init__(
        self,
        mesh: Union[Mesh, MeshTopology, None] = None,
        *,
        compile_cache: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self._init_mesh(
            mesh, "ed25519.sharded_batch_verify", sharded_batch_verify_fn,
            _RAND_IN_SPECS, compile_cache,
        )

    def _aggregate_device(self, idx, signatures, public_keys, scalars, zs):
        from consensus_tpu.models.ed25519 import (
            _bits_to_comb_digits8,
            _bytes_rows_to_bits,
            _prep_compressed,
            _signed_digits_int,
            _WINDOWS,
            _Z_WINDOWS,
            L,
        )

        m = len(idx)
        zk = [(z * scalars[i][1]) % L for z, i in zip(zs, idx)]
        y_r, sign_r, _ = _prep_compressed([bytes(signatures[i])[:32] for i in idx])
        y_a, sign_a, _ = _prep_compressed([bytes(public_keys[i]) for i in idx])
        zk_digits = np.array(
            [_signed_digits_int(v, _WINDOWS) for v in zk], dtype=np.int16
        ).T
        z_digits = np.array(
            [_signed_digits_int(z, _Z_WINDOWS) for z in zs], dtype=np.int16
        ).T
        zk_digits = (zk_digits + 8).astype(np.uint8)
        z_digits = (z_digits + 8).astype(np.uint8)
        host_ok = np.ones(m, dtype=bool)

        padded = engine_padded_size(
            m, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        if padded != m:
            pad = padded - m
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            zk_digits = np.pad(zk_digits, ((0, 0), (0, pad)), constant_values=8)
            z_digits = np.pad(z_digits, ((0, 0), (0, pad)), constant_values=8)
            host_ok = np.pad(host_ok, (0, pad))

        # Per-shard fixed-base scalars: lane j lives on shard j // per, so
        # u_s sums z·s over exactly that shard's live lanes.  Pad-only
        # shards get u_s = 0 (identity comb contribution).  Shard order is
        # the linear row-major device order on every topology, so the same
        # slicing covers 1-D and N-D meshes.
        per = padded // self._n_shards
        u_rows = np.zeros((self._n_shards, 32), dtype=np.uint8)
        for s in range(self._n_shards):
            u_s = 0
            for j in range(s * per, min((s + 1) * per, m)):
                u_s += zs[j] * scalars[idx[j]][0]
            u_rows[s] = np.frombuffer(
                (u_s % L).to_bytes(32, "little"), dtype=np.uint8
            )
        zs_digits8 = _bits_to_comb_digits8(_bytes_rows_to_bits(u_rows))

        device_args = (
            np.ascontiguousarray(y_r.T),
            sign_r,
            np.ascontiguousarray(y_a.T),
            sign_a,
            zs_digits8,
            zk_digits,
            z_digits,
            host_ok,
        )
        eq_ok, valid = self._fn(*self._put_sharded(device_args))
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])


# --- fused bytes-in -> verdict-out engines over the mesh ---------------------

#: Specs for the fused strict kernel (models/fused.py fused_verify_impl):
#: byte rows and SHA-512 block arrays all trail with the batch axis.
_FUSED_IN_SPECS = (
    P(None, BATCH_AXIS),              # sig_rows (64, batch)
    P(None, BATCH_AXIS),              # key_rows (32, batch)
    P(None, None, None, BATCH_AXIS),  # blocks (B, 16, 2, batch)
    P(BATCH_AXIS),                    # n_blocks
    P(BATCH_AXIS),                    # host_ok
)


def sharded_fused_verify_fn(mesh: Mesh):
    """jitted fused strict verify over ``mesh``: every shard runs the whole
    bytes-in → verdict-out front-end (SHA-512, mod-L reduction, canonical
    checks, digit recoding) on its own batch slice — the pipeline is pure
    data parallelism end to end, so the only collective is still the psum
    at the validity-count edge."""
    from consensus_tpu.models.fused import fused_verify_impl

    axes = _reduce_axes(mesh)

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_mesh_specs(mesh, _FUSED_IN_SPECS),
        out_specs=_mesh_specs(mesh, (P(BATCH_AXIS), P())),
    )
    def _shard(sig_rows, key_rows, blocks, n_blocks, host_ok):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        # Same rule as the host-prep shards: no pallas_call under shard_map.
        with suppress_pallas_scan():
            ok = fused_verify_impl(sig_rows, key_rows, blocks, n_blocks, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axes)
        return ok, total

    return instrumented_jit(
        _shard, "ed25519.sharded_fused_verify" + kernel_lane_suffix()
    )


class ShardedFusedEd25519Verifier(_MeshEngine, FusedEd25519BatchVerifier):
    """Fused strict verifier that spreads the batch across a device mesh —
    ``Configuration.device_prep`` + a multi-device topology.  Verdicts are
    bit-identical to every other strict engine."""

    def __init__(
        self,
        mesh: Union[Mesh, MeshTopology, None] = None,
        *,
        compile_cache: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self._init_mesh(
            mesh, "ed25519.sharded_fused_verify", sharded_fused_verify_fn,
            _FUSED_IN_SPECS, compile_cache,
        )

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        from consensus_tpu.models.fused import _pad_wave

        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        sig_rows, key_rows, blocks, n_blocks, host_ok = self._prepare_fused(
            messages, signatures, public_keys
        )
        padded = engine_padded_size(
            n, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        sig_rows, key_rows, n_blocks, host_ok = _pad_wave(
            [sig_rows, key_rows, n_blocks, host_ok], n, padded
        )
        if padded != n:
            blocks = np.pad(blocks, ((0, 0),) * 3 + ((0, padded - n),))
        device_args = (
            np.ascontiguousarray(sig_rows.T),
            np.ascontiguousarray(key_rows.T),
            blocks,
            n_blocks,
            host_ok,
        )
        ok, _total = self._fn(*self._put_sharded(device_args))
        return np.asarray(ok)[:n]


#: Specs for the sharded fused aggregate: byte rows and block arrays shard
#: on the trailing batch axis; the transcript's cross-shard edge (every
#: shard needs every lane's leaf digest to assemble the root) is an
#: all_gather INSIDE the shard body, not an input spec.
_FUSED_AGG_IN_SPECS = (
    P(None, BATCH_AXIS),              # r_rows
    P(None, BATCH_AXIS),              # s_rows
    P(None, BATCH_AXIS),              # key_rows
    P(None, None, None, BATCH_AXIS),  # k_blocks
    P(BATCH_AXIS),                    # k_nblocks
    P(None, None, None, BATCH_AXIS),  # leaf_blocks
    P(BATCH_AXIS),                    # leaf_nblocks
    P(BATCH_AXIS),                    # host_ok
)


def sharded_fused_aggregate_fn(mesh: Mesh, tag: bytes, n: int, padded: int):
    """jitted fused randomized-aggregate check over ``mesh``.

    Device Fiat–Shamir with one collective: each shard hashes its own
    lanes' transcript leaves, an ``all_gather`` assembles the full leaf
    digest table on every shard, and each shard then derives the IDENTICAL
    root and its own lanes' coefficients ``zᵢ = H(root ‖ i)`` — the same
    transcript bytes as the host twin, so coefficients match bit-for-bit.
    (On an N-D topology the gather runs over the full axis tuple in
    row-major order — the same global lane order the input sharding uses,
    so the assembled table is identical to the 1-D mesh's.)
    As in :func:`sharded_batch_verify_fn`, every shard checks an
    independent aggregate over its lane subset with its own base scalar
    ``u_s = Σ zᵢsᵢ`` (pad lanes carry s = 0 and masked digits, so a
    padding-only shard votes ok), and one psum tree-reduces the verdict.
    Specialized per (n, padded) like the single-device aggregate graphs —
    stats accumulate under one kernel-accounting name."""
    from consensus_tpu.models.ed25519 import (
        _WINDOWS,
        _Z_WINDOWS,
        batch_verify_impl,
    )
    from consensus_tpu.models.fused import _aggregate_constants
    from consensus_tpu.ops import scalar25519 as sc
    from consensus_tpu.ops import sha512 as sh

    n_shards = mesh.devices.size
    if padded % n_shards:
        raise ValueError("padded batch must be a multiple of the mesh size")
    per = padded // n_shards
    axes = _reduce_axes(mesh)
    (
        root_prefix, root_trailer, root_blocks, z_trailer, idx_rows
    ) = _aggregate_constants(tag, n, padded)
    one_z = np.zeros((16, 1), dtype=np.int32)
    one_z[0, 0] = 1

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=_mesh_specs(mesh, _FUSED_AGG_IN_SPECS),
        out_specs=_mesh_specs(mesh, (P(), P(BATCH_AXIS))),
    )
    def _shard(
        r_rows, s_rows, key_rows, k_blocks, k_nblocks,
        leaf_blocks, leaf_nblocks, host_ok,
    ):
        from consensus_tpu.models.ed25519 import suppress_pallas_scan

        shard = _shard_index(mesh)
        r = r_rows.astype(jnp.int32)
        key = key_rows.astype(jnp.int32)
        with suppress_pallas_scan():
            k_digest = sh.digest_bytes(sh.sha512_blocks(k_blocks, k_nblocks))
            k_bytes = sc.reduce_bytes_mod_l(k_digest)

            leaves = sh.digest_bytes(
                sh.sha512_blocks(leaf_blocks, leaf_nblocks)
            )  # (64, per)
            gathered = jax.lax.all_gather(
                leaves, axes, axis=1, tiled=True
            )  # (64, padded), global lane order
            root_rows = jnp.concatenate(
                [
                    jnp.asarray(root_prefix, jnp.int32),
                    gathered[:, :n].T.reshape(64 * n, 1),
                    jnp.asarray(root_trailer, jnp.int32),
                ],
                axis=0,
            )
            root = sh.digest_bytes(
                sh.sha512_blocks(
                    sh.pack_bytes_device(root_rows),
                    jnp.full((1,), root_blocks, jnp.int32),
                )
            )

            local_idx = jax.lax.dynamic_slice_in_dim(
                jnp.asarray(idx_rows, jnp.int32), shard * per, per, axis=1
            )
            z_rows = jnp.concatenate(
                [
                    jnp.broadcast_to(root, (64, per)),
                    local_idx,
                    jnp.asarray(z_trailer[:, :per], jnp.int32),
                ],
                axis=0,
            )
            z_digest = sh.digest_bytes(
                sh.sha512_blocks(
                    sh.pack_bytes_device(z_rows), jnp.ones((per,), jnp.int32)
                )
            )
            z = z_digest[:16]
            z = jnp.where((z == 0).all(axis=0)[None], jnp.asarray(one_z), z)

            zk = sc.mul_mod_l(z, k_bytes)
            zk_digits = sc.signed_window_digits(zk, _WINDOWS)
            z_digits = sc.signed_window_digits(z, _Z_WINDOWS)
            u = sc.sum_mod_l(sc.mul_mod_l(z, s_rows.astype(jnp.int32)))

            y_r = jnp.concatenate([r[:31], (r[31] & 0x7F)[None]], axis=0)
            y_a = jnp.concatenate([key[:31], (key[31] & 0x7F)[None]], axis=0)
            eq_ok, valid = batch_verify_impl(
                y_r, r[31] >> 7, y_a, key[31] >> 7, u, zk_digits, z_digits,
                host_ok,
            )
        bad = jax.lax.psum(1 - eq_ok.astype(jnp.int32), axes)
        return bad == 0, valid

    return instrumented_jit(
        _shard, "ed25519.sharded_fused_batch_verify" + kernel_lane_suffix()
    )


class ShardedFusedEd25519RandomizedVerifier(
    FusedEd25519RandomizedBatchVerifier, ShardedFusedEd25519Verifier
):
    """Randomized fused verifier whose aggregate check (and strict floor)
    ride the mesh.  The bisection driver, host fallback, and canonical
    pre-filter are inherited from the single-device fused engine; only the
    two launch seams are re-routed."""

    def __init__(
        self,
        mesh: Union[Mesh, MeshTopology, None] = None,
        *,
        compile_cache: bool = True,
        **kw,
    ) -> None:
        # The randomized base consumes min_randomized before the strict
        # chain; with the diamond MRO here the strict chain would skip it,
        # so pop + set it explicitly (same clamp as the base).
        min_randomized = kw.pop("min_randomized", 2)
        ShardedFusedEd25519Verifier.__init__(
            self, mesh, compile_cache=compile_cache, **kw
        )
        self._min_randomized = max(2, int(min_randomized))
        self._agg_fns: dict = {}

    def _strict_floor(self, messages, signatures, public_keys) -> np.ndarray:
        return ShardedFusedEd25519Verifier.verify_batch(
            self, messages, signatures, public_keys
        )

    def _fused_aggregate(self, idx, messages, signatures, public_keys):
        from consensus_tpu.models.ed25519 import _Z_TAG
        from consensus_tpu.models.fused import (
            _byte_rows,
            _frame,
            _pack_blocks,
            _pad_wave,
        )

        m = len(idx)
        rs = [bytes(signatures[i])[:32] for i in idx]
        keys = [bytes(public_keys[i]) for i in idx]
        msgs = [bytes(messages[i]) for i in idx]
        r_rows = _byte_rows(rs, 32)
        key_rows = _byte_rows(keys, 32)
        s_rows = _byte_rows([bytes(signatures[i])[32:] for i in idx], 32)
        k_blocks, k_nblocks = _pack_blocks(
            [r + a + mm for r, a, mm in zip(rs, keys, msgs)]
        )
        leaf_blocks, leaf_nblocks = _pack_blocks(
            [
                _frame(mm) + _frame(bytes(signatures[i])) + _frame(a)
                for mm, i, a in zip(msgs, idx, keys)
            ]
        )
        host_ok = np.ones(m, dtype=bool)

        padded = engine_padded_size(
            m, self._n_shards, pad_to=self._pad_to, pad_pow2=self._pad_pow2
        )
        r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok = _pad_wave(
            [r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok],
            m, padded,
        )
        if padded != m:
            batch_pad = ((0, 0),) * 3 + ((0, padded - m),)
            k_blocks = np.pad(k_blocks, batch_pad)
            leaf_blocks = np.pad(leaf_blocks, batch_pad)

        # Instance memo first (the historical per-engine shape cache), then
        # the process-wide memo so a REBUILT engine reuses the traced graph.
        fn = self._agg_fns.get((m, padded))
        if fn is None:
            fn = self._agg_fns[(m, padded)] = compiled_kernel(
                "ed25519.sharded_fused_batch_verify",
                self.mesh,
                lambda: sharded_fused_aggregate_fn(self.mesh, _Z_TAG, m, padded),
                memo=self._compile_cache,
                extra=(_Z_TAG, m, padded),
            )
        device_args = (
            np.ascontiguousarray(r_rows.T),
            np.ascontiguousarray(s_rows.T),
            np.ascontiguousarray(key_rows.T),
            k_blocks,
            k_nblocks,
            leaf_blocks,
            leaf_nblocks,
            host_ok,
        )
        args = [
            jax.device_put(np.asarray(a), NamedSharding(self.mesh, spec))
            for a, spec in zip(device_args, _mesh_specs(self.mesh, _FUSED_AGG_IN_SPECS))
        ]
        eq_ok, valid = fn(*args)
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])


__all__ = [
    "make_mesh",
    "mesh_for_shards",
    "compiled_kernel",
    "clear_compiled_kernels",
    "sharded_verify_fn",
    "sharded_batch_verify_fn",
    "sharded_p256_verify_fn",
    "sharded_fused_verify_fn",
    "sharded_fused_aggregate_fn",
    "ShardedEd25519Verifier",
    "ShardedEd25519RandomizedVerifier",
    "ShardedEcdsaP256Verifier",
    "ShardedFusedEd25519Verifier",
    "ShardedFusedEd25519RandomizedVerifier",
    "MeshTopology",
    "mesh_padded_size",
    "engine_padded_size",
    "BATCH_AXIS",
]
