"""Device-mesh sharding of the crypto batch path (data parallel over ICI)."""

from consensus_tpu.parallel.sharding import (
    BATCH_AXIS,
    ShardedEcdsaP256Verifier,
    ShardedEd25519RandomizedVerifier,
    ShardedEd25519Verifier,
    ShardedFusedEd25519RandomizedVerifier,
    ShardedFusedEd25519Verifier,
    engine_padded_size,
    make_mesh,
    mesh_for_shards,
    sharded_batch_verify_fn,
    sharded_fused_aggregate_fn,
    sharded_fused_verify_fn,
    sharded_p256_verify_fn,
    sharded_verify_fn,
)

__all__ = [
    "BATCH_AXIS",
    "make_mesh",
    "mesh_for_shards",
    "engine_padded_size",
    "sharded_verify_fn",
    "sharded_batch_verify_fn",
    "sharded_p256_verify_fn",
    "sharded_fused_verify_fn",
    "sharded_fused_aggregate_fn",
    "ShardedEd25519Verifier",
    "ShardedEd25519RandomizedVerifier",
    "ShardedEcdsaP256Verifier",
    "ShardedFusedEd25519Verifier",
    "ShardedFusedEd25519RandomizedVerifier",
]
