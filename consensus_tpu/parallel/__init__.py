"""Device-mesh sharding of the crypto batch path (data parallel over ICI)."""

from consensus_tpu.parallel.sharding import (
    BATCH_AXIS,
    ShardedEcdsaP256Verifier,
    ShardedEd25519Verifier,
    make_mesh,
    sharded_p256_verify_fn,
    sharded_verify_fn,
)

__all__ = [
    "BATCH_AXIS",
    "make_mesh",
    "sharded_verify_fn",
    "sharded_p256_verify_fn",
    "ShardedEd25519Verifier",
    "ShardedEcdsaP256Verifier",
]
