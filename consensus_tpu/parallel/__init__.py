"""Device-mesh sharding of the crypto batch path (data parallel over ICI)."""

from consensus_tpu.parallel.sharding import (
    BATCH_AXIS,
    ShardedEd25519Verifier,
    make_mesh,
    sharded_verify_fn,
)

__all__ = [
    "BATCH_AXIS",
    "make_mesh",
    "sharded_verify_fn",
    "ShardedEd25519Verifier",
]
