"""Device-mesh sharding of the crypto batch path (data parallel over ICI).

Re-exports resolve lazily (PEP 562): the topology/compile-cache surface
(``MeshTopology``, ``topology_for_config``, ``apply_compile_cache``, the
padding helpers) is jax-free and always importable, while the sharded
engines in :mod:`consensus_tpu.parallel.sharding` drag in jax only when
first touched — the config plane and the engine registry can reason about
topologies on boxes without the accelerator stack.
"""

_TOPOLOGY_NAMES = frozenset(
    {
        "BATCH_AXIS",
        "MeshTopology",
        "apply_compile_cache",
        "engine_padded_size",
        "mesh_padded_size",
        "topology_for_config",
    }
)

_SHARDING_NAMES = frozenset(
    {
        "ShardedEcdsaP256Verifier",
        "ShardedEd25519RandomizedVerifier",
        "ShardedEd25519Verifier",
        "ShardedFusedEd25519RandomizedVerifier",
        "ShardedFusedEd25519Verifier",
        "clear_compiled_kernels",
        "compiled_kernel",
        "make_mesh",
        "mesh_for_shards",
        "sharded_batch_verify_fn",
        "sharded_fused_aggregate_fn",
        "sharded_fused_verify_fn",
        "sharded_p256_verify_fn",
        "sharded_verify_fn",
    }
)

__all__ = sorted(_TOPOLOGY_NAMES | _SHARDING_NAMES)


def __getattr__(name: str):
    if name in _TOPOLOGY_NAMES:
        from consensus_tpu.parallel import topology

        return getattr(topology, name)
    if name in _SHARDING_NAMES:
        from consensus_tpu.parallel import sharding

        return getattr(sharding, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
