"""In-process simulated network for multi-replica tests.

Parity: reference test/network.go:34-253.  Every replica shares one
SimScheduler, so message delivery interleaves deterministically with timers;
fault-injection knobs mirror the reference:

* per-node and per-link disconnection (``disconnect`` / ``disconnect_pair``)
* probabilistic loss with a seeded RNG (``set_loss``)
* message mutation hooks for byzantine-sender simulation (``mutate_send``,
  reference test/test_app.go:180-191)
* receiver-side selective filters (``lose_messages``)
* per-link latency (``set_delay``)
"""

from __future__ import annotations

import random
from typing import Callable, Optional, Sequence

from consensus_tpu.api.deps import Comm
from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.wire import ConsensusMessage


class SimNetwork:
    """Routes messages between registered replicas over the shared clock."""

    def __init__(self, scheduler: SimScheduler, *, seed: int = 0, default_delay: float = 0.001) -> None:
        self.scheduler = scheduler
        self.rng = random.Random(seed)
        self.default_delay = default_delay
        self._handlers: dict[int, Callable[[int, object, bool], None]] = {}
        #: Configured cluster membership (stable across crashes); falls back
        #: to the live registration set when unset.
        self.membership: Optional[list[int]] = None
        self._disconnected: set[int] = set()
        self._cut_links: set[tuple[int, int]] = set()
        self._loss: dict[tuple[int, int], float] = {}
        self._delay: dict[tuple[int, int], float] = {}
        #: fn(sender, target, msg) -> msg | None (None drops the message).
        self.mutate_send: Optional[Callable[[int, int, object], Optional[object]]] = None
        #: fn(target, sender, msg) -> bool; True drops at the receiver.
        self.lose_messages: Optional[Callable[[int, int, object], bool]] = None

    # --- membership --------------------------------------------------------

    def register(
        self, node_id: int, on_message: Callable[[int, object, bool], None]
    ) -> "NodeComm":
        """``on_message(sender, payload, is_request)`` is the replica ingress."""
        self._handlers[node_id] = on_message
        return NodeComm(self, node_id)

    def unregister(self, node_id: int) -> None:
        self._handlers.pop(node_id, None)

    def node_ids(self) -> list[int]:
        if self.membership is not None:
            return sorted(self.membership)
        return sorted(self._handlers)

    # --- fault injection ---------------------------------------------------

    def disconnect(self, node_id: int) -> None:
        self._disconnected.add(node_id)

    def connect(self, node_id: int) -> None:
        self._disconnected.discard(node_id)

    def disconnect_pair(self, a: int, b: int) -> None:
        self._cut_links.add((a, b))
        self._cut_links.add((b, a))

    def connect_pair(self, a: int, b: int) -> None:
        self._cut_links.discard((a, b))
        self._cut_links.discard((b, a))

    def partition(self, group: Sequence[int]) -> None:
        """Cut every link crossing the boundary of ``group``."""
        inside = set(group)
        for a in self.node_ids():
            for b in self.node_ids():
                if (a in inside) != (b in inside):
                    self._cut_links.add((a, b))

    def heal(self) -> None:
        self._cut_links.clear()
        self._disconnected.clear()
        self._loss.clear()

    def set_loss(self, a: int, b: int, probability: float) -> None:
        """Drop a fraction of messages on the directed link a->b."""
        self._loss[(a, b)] = probability

    def reachable(self, a: int, b: int) -> bool:
        """Whether a message from ``a`` could currently reach ``b`` —
        used by the test harness to keep OUT-OF-BAND paths (application
        state transfer in ``TestApp.sync``) honest about partitions: a
        partitioned replica must not be able to fetch peer state through a
        side channel the network would not carry."""
        if a in self._disconnected or b in self._disconnected:
            return False
        if self._loss.get((a, b), 0.0) >= 1.0:
            return False  # a total-loss link is a cut, not a lossy link
        return (a, b) not in self._cut_links

    def set_delay(self, a: int, b: int, delay: float) -> None:
        self._delay[(a, b)] = delay

    # --- transport ---------------------------------------------------------

    def send(self, sender: int, target: int, payload, *, is_request: bool) -> None:
        if sender not in self._handlers:
            return  # a crashed (unregistered) process cannot transmit:
            # scheduler events queued by its zombie frames must not leak
            # messages a dead replica never actually sent.
        if sender in self._disconnected or target in self._disconnected:
            return
        if (sender, target) in self._cut_links:
            return
        loss = self._loss.get((sender, target), 0.0)
        if loss and self.rng.random() < loss:
            return
        if self.mutate_send is not None:
            payload = self.mutate_send(sender, target, payload)
            if payload is None:
                return
        delay = self._delay.get((sender, target), self.default_delay)

        def deliver() -> None:
            handler = self._handlers.get(target)
            if handler is None:
                return  # crashed / removed meanwhile
            if self.lose_messages is not None and self.lose_messages(
                target, sender, payload
            ):
                return
            handler(sender, payload, is_request)

        self.scheduler.call_later(delay, deliver, name=f"net {sender}->{target}")


class NodeComm(Comm):
    """The api.Comm a replica plugs in: fire-and-forget over the network."""

    def __init__(self, network: SimNetwork, node_id: int) -> None:
        self._network = network
        self.node_id = node_id

    def send_consensus(self, target_id: int, message: ConsensusMessage) -> None:
        self._network.send(self.node_id, target_id, message, is_request=False)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self._network.send(self.node_id, target_id, request, is_request=True)

    def nodes(self) -> Sequence[int]:
        return self._network.node_ids()


__all__ = ["SimNetwork", "NodeComm"]
