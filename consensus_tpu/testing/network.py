"""In-process simulated network for multi-replica tests.

Parity: reference test/network.go:34-253.  Every replica shares one
SimScheduler, so message delivery interleaves deterministically with timers;
fault-injection knobs mirror the reference:

* per-node and per-link disconnection (``disconnect`` / ``disconnect_pair``)
* probabilistic loss with a seeded RNG (``set_loss``)
* message mutation hooks for byzantine-sender simulation (``mutate_send``,
  reference test/test_app.go:180-191)
* receiver-side selective filters (``lose_messages``)
* per-link latency (``set_delay``)

plus the byzantine-NETWORK primitives the reference harness lacks (the
chaos engine's adversary vocabulary, consensus_tpu/testing/chaos.py):

* probabilistic duplication (``set_duplicate``) — the same signed message
  delivered twice,
* probabilistic reordering (``set_reorder``) — a message overtaken by
  later sends on the same link,
* stale replay (``set_replay``) — an OLD captured message re-delivered
  long after it was first sent (the baseline adversary for signed-message
  protocols; arXiv:2302.00418 §2).

Every injected event (a loss-roll drop, a mutate/filter drop, a duplicate,
a reorder, a replay) is counted in :attr:`SimNetwork.injected`, mirrored
into an attached ``MetricsNetwork`` bundle (``attach_metrics``) and, when a
tracer is attached, emitted as ``net.<event>`` instants on the shared sim
clock — so a chaos run's adversary activity is attributable in the same
trace as the protocol's phase spans.
"""

from __future__ import annotations

import random
from collections import Counter, deque
from typing import Callable, Optional, Sequence

from consensus_tpu.api.deps import Comm
from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.wire import ConsensusMessage

#: The injected-event kinds :attr:`SimNetwork.injected` counts, in the
#: order the metrics bundle pins them (metrics.py NET_INJECTED_KEYS).
INJECTED_EVENT_KINDS = ("dropped", "duplicated", "reordered", "replayed")


class SimNetwork:
    """Routes messages between registered replicas over the shared clock."""

    #: Captured messages kept per replay-armed link (oldest evicted first).
    REPLAY_BUFFER_DEPTH = 32

    def __init__(self, scheduler: SimScheduler, *, seed: int = 0, default_delay: float = 0.001) -> None:
        self.scheduler = scheduler
        self.rng = random.Random(seed)
        self.default_delay = default_delay
        self._handlers: dict[int, Callable[[int, object, bool], None]] = {}
        #: Configured cluster membership (stable across crashes); falls back
        #: to the live registration set when unset.  Mutate ONLY through
        #: :meth:`set_membership` — the setter keeps the removed-node
        #: accounting and the epoch counter consistent.
        self.membership: Optional[list[int]] = None
        #: Bumped by every :meth:`set_membership` call (or pinned to the
        #: caller's epoch): lets assertions tie network-level membership to
        #: the protocol's membership epoch.
        self.membership_epoch = 0
        #: Ids removed from membership whose in-flight / future deliveries
        #: are dropped-and-counted rather than silently lost.
        self._removed: set[int] = set()
        #: In-flight deliveries to removed nodes that were accounted for
        #: (the membership analogue of :attr:`injected`, but NOT an
        #: injected-adversary event — removal is topology, so it gets its
        #: own counter instead of a new INJECTED_EVENT_KINDS entry).
        self.removed_drops = 0
        self._disconnected: set[int] = set()
        self._cut_links: set[tuple[int, int]] = set()
        self._loss: dict[tuple[int, int], float] = {}
        self._delay: dict[tuple[int, int], float] = {}
        #: (a, b) -> (base, spread): per-link latency DISTRIBUTION — each
        #: send draws uniform(base, base + spread) from the seeded RNG.
        #: The WAN scenario bank's geography knob; unarmed links consume
        #: no RNG, so non-WAN schedules replay byte-identically.
        self._jitter: dict[tuple[int, int], tuple[float, float]] = {}
        self._duplicate: dict[tuple[int, int], float] = {}
        self._reorder: dict[tuple[int, int], float] = {}
        self._replay: dict[tuple[int, int], float] = {}
        #: (a, b) -> deque of stale (payload, is_request) captures for links
        #: with replay armed.
        self._replay_buffers: dict[tuple[int, int], deque] = {}
        #: fn(sender, target, msg) -> msg | None (None drops the message).
        self.mutate_send: Optional[Callable[[int, int, object], Optional[object]]] = None
        #: fn(target, sender, msg) -> bool; True drops at the receiver.
        self.lose_messages: Optional[Callable[[int, int, object], bool]] = None
        #: Injected adversary events: dropped / duplicated / reordered /
        #: replayed.  "dropped" counts only *injected* drops (loss rolls,
        #: mutate_send returning None, lose_messages filtering) — cuts,
        #: partitions, and dead endpoints are topology, not per-message
        #: injection.
        self.injected: Counter = Counter()
        #: Optional MetricsNetwork bundle mirroring :attr:`injected`.
        self.metrics = None
        #: Optional trace.Tracer: injected events become ``net.<kind>``
        #: instants on the shared sim clock.
        self.tracer = None

    # --- membership --------------------------------------------------------

    def register(
        self, node_id: int, on_message: Callable[[int, object, bool], None]
    ) -> "NodeComm":
        """``on_message(sender, payload, is_request)`` is the replica ingress."""
        self._handlers[node_id] = on_message
        return NodeComm(self, node_id)

    def unregister(self, node_id: int) -> None:
        self._handlers.pop(node_id, None)

    def node_ids(self) -> list[int]:
        if self.membership is not None:
            return sorted(self.membership)
        return sorted(self._handlers)

    def set_membership(
        self, ids: Sequence[int], *, epoch: Optional[int] = None
    ) -> None:
        """The one supported way to change :attr:`membership`.

        Ids leaving the member set are tracked in ``_removed`` so their
        in-flight deliveries (already scheduled on the sim clock) are
        DROPPED AND COUNTED at delivery time instead of vanishing; a
        re-added id is un-tracked.  ``epoch`` pins the epoch counter (the
        harness passes the directory's epoch); omitted, it increments.
        """
        new = set(ids)
        old = set(self.membership) if self.membership is not None else set(
            self._handlers
        )
        self._removed |= old - new
        self._removed -= new
        self.membership = sorted(new)
        if epoch is not None:
            self.membership_epoch = epoch
        else:
            self.membership_epoch += 1

    # --- fault injection ---------------------------------------------------

    def disconnect(self, node_id: int) -> None:
        self._disconnected.add(node_id)

    def connect(self, node_id: int) -> None:
        self._disconnected.discard(node_id)

    def disconnect_pair(self, a: int, b: int) -> None:
        self._cut_links.add((a, b))
        self._cut_links.add((b, a))

    def connect_pair(self, a: int, b: int) -> None:
        self._cut_links.discard((a, b))
        self._cut_links.discard((b, a))

    def partition(self, group: Sequence[int]) -> None:
        """Cut every link crossing the boundary of ``group``.

        NOTE for direct users (Cluster sets this up for you): the boundary
        is computed over :meth:`node_ids`, which without ``membership``
        falls back to the *live registration set* — a node that is crashed
        (unregistered) when ``partition`` is called gets NO cut links, so
        the partition silently leaks around it once it restarts.  Set
        ``membership`` to the full configured id set before partitioning
        around crashes (pinned by
        tests/test_network_adversary.py::test_partition_leaks_around_crashed_node_without_membership).
        """
        inside = set(group)
        for a in self.node_ids():
            for b in self.node_ids():
                if (a in inside) != (b in inside):
                    self._cut_links.add((a, b))

    def heal(self) -> None:
        """Clear every fault knob: cuts, disconnections, loss, per-link
        delay overrides, duplication, reordering, and replay (stale capture
        buffers included — a healed network holds no adversary state)."""
        self._cut_links.clear()
        self._disconnected.clear()
        self._loss.clear()
        self._delay.clear()
        self._jitter.clear()
        self._duplicate.clear()
        self._reorder.clear()
        self._replay.clear()
        self._replay_buffers.clear()

    def set_loss(self, a: int, b: int, probability: float) -> None:
        """Drop a fraction of messages on the directed link a->b."""
        self._loss[(a, b)] = probability

    def set_duplicate(self, a: int, b: int, probability: float) -> None:
        """Deliver a fraction of messages on a->b TWICE (second copy lands
        one extra delay later — a retransmitting/duplicating network)."""
        self._duplicate[(a, b)] = probability

    def set_reorder(self, a: int, b: int, probability: float) -> None:
        """Hold back a fraction of messages on a->b so messages sent after
        them arrive first (delivery delay inflated 2-5x, seeded RNG)."""
        self._reorder[(a, b)] = probability

    def set_replay(self, a: int, b: int, probability: float) -> None:
        """Capture messages crossing a->b and, per send, with the given
        probability ALSO re-deliver one stale captured message — the
        signed-message replay adversary.  Captures are bounded
        (:attr:`REPLAY_BUFFER_DEPTH`) and cleared by :meth:`heal`."""
        self._replay[(a, b)] = probability
        self._replay_buffers.setdefault((a, b), deque(maxlen=self.REPLAY_BUFFER_DEPTH))

    def reachable(self, a: int, b: int) -> bool:
        """Whether a message from ``a`` could currently reach ``b`` —
        used by the test harness to keep OUT-OF-BAND paths (application
        state transfer in ``TestApp.sync``) honest about partitions: a
        partitioned replica must not be able to fetch peer state through a
        side channel the network would not carry."""
        if a in self._disconnected or b in self._disconnected:
            return False
        if self._loss.get((a, b), 0.0) >= 1.0:
            return False  # a total-loss link is a cut, not a lossy link
        return (a, b) not in self._cut_links

    def set_delay(self, a: int, b: int, delay: float) -> None:
        self._delay[(a, b)] = delay

    def set_jitter(
        self, a: int, b: int, base: float, spread: float = 0.0
    ) -> None:
        """Give the directed link a->b a latency DISTRIBUTION: each send is
        delayed uniform(base, base + spread), drawn from the network's
        seeded RNG.  This is the WAN geography primitive (chaos WAN
        profiles arm it per region pair); it composes with ``set_delay`` by
        taking whichever is larger, so a chaos ``delay`` degradation still
        bites on a WAN link.  Cleared by :meth:`heal` like every knob — the
        chaos engine re-arms geography after heals."""
        if base < 0 or spread < 0:
            raise ValueError("jitter base and spread must be >= 0")
        self._jitter[(a, b)] = (base, spread)

    # --- transport ---------------------------------------------------------

    def _record_injected(self, kind: str, sender: int, target: int) -> None:
        self.injected[kind] += 1
        if self.metrics is not None:
            getattr(self.metrics, f"count_{kind}").add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant("net", f"net.{kind}", sender=sender, target=target)

    def send(self, sender: int, target: int, payload, *, is_request: bool) -> None:
        if sender not in self._handlers:
            return  # a crashed (unregistered) process cannot transmit:
            # scheduler events queued by its zombie frames must not leak
            # messages a dead replica never actually sent.
        if sender in self._disconnected or target in self._disconnected:
            return
        if (sender, target) in self._cut_links:
            return
        loss = self._loss.get((sender, target), 0.0)
        if loss and self.rng.random() < loss:
            self._record_injected("dropped", sender, target)
            return
        if self.mutate_send is not None:
            payload = self.mutate_send(sender, target, payload)
            if payload is None:
                self._record_injected("dropped", sender, target)
                return
        link = (sender, target)
        jitter = self._jitter.get(link)
        if jitter is not None:
            base, spread = jitter
            drawn = base + (self.rng.random() * spread if spread else 0.0)
            override = self._delay.get(link)
            delay = drawn if override is None else max(drawn, override)
        else:
            delay = self._delay.get(link, self.default_delay)

        replay_p = self._replay.get(link, 0.0)
        if replay_p:
            buf = self._replay_buffers[link]
            if buf and self.rng.random() < replay_p:
                stale_payload, stale_is_request = buf[0]  # the STALEST capture
                self._record_injected("replayed", sender, target)
                self._schedule_delivery(
                    sender, target, stale_payload, stale_is_request,
                    delay + self.default_delay,
                )
            buf.append((payload, is_request))

        reorder_p = self._reorder.get(link, 0.0)
        if reorder_p and self.rng.random() < reorder_p:
            # Held back past 1-4 subsequently-sent messages' delivery times.
            self._record_injected("reordered", sender, target)
            delay = delay * (2 + 3 * self.rng.random())

        self._schedule_delivery(sender, target, payload, is_request, delay)

        dup_p = self._duplicate.get(link, 0.0)
        if dup_p and self.rng.random() < dup_p:
            self._record_injected("duplicated", sender, target)
            self._schedule_delivery(
                sender, target, payload, is_request, delay + self.default_delay
            )

    def _schedule_delivery(
        self, sender: int, target: int, payload, is_request: bool, delay: float
    ) -> None:
        def deliver() -> None:
            handler = self._handlers.get(target)
            if handler is None:
                if target in self._removed:
                    # The target left the membership AND unregistered while
                    # this delivery was in flight: account for the drop
                    # instead of silently losing it.  (A removed-but-live
                    # node still receives — it must be able to deliver the
                    # very decision that evicts it.)
                    self.removed_drops += 1
                return  # crashed / removed meanwhile
            if self.lose_messages is not None and self.lose_messages(
                target, sender, payload
            ):
                self._record_injected("dropped", sender, target)
                return
            handler(sender, payload, is_request)

        self.scheduler.call_later(delay, deliver, name=f"net {sender}->{target}")


class NodeComm(Comm):
    """The api.Comm a replica plugs in: fire-and-forget over the network."""

    def __init__(self, network: SimNetwork, node_id: int) -> None:
        self._network = network
        self.node_id = node_id

    def send_consensus(self, target_id: int, message: ConsensusMessage) -> None:
        self._network.send(self.node_id, target_id, message, is_request=False)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self._network.send(self.node_id, target_id, request, is_request=True)

    def nodes(self) -> Sequence[int]:
        return self._network.node_ids()


__all__ = ["SimNetwork", "NodeComm", "INJECTED_EVENT_KINDS"]
