"""Continuously-checked protocol invariants for chaos runs.

The soak loops used to spot-check safety BETWEEN schedule steps
(``cluster.assert_ledgers_consistent()`` after each ``advance``); a fork
that appears and is "healed" by a later sync inside one step, or a
decision delivered on an undersized certificate, could slip through.  The
:class:`InvariantMonitor` instead hangs off the cluster's COMMIT-PATH
delivery hook (``Cluster.delivery_hooks``) and judges every delivery the
moment it happens, recording the exact sim-time and the adversary-action
history that led there.

Monitored invariants (formal statements: SAFETY.md §6):

* **prefix-agreement** — at every delivery, each pair of replica ledgers
  agrees on its common prefix of proposal digests.
* **quorum-cert** — every delivered decision carries ``>= 2f + 1``
  commit signatures from distinct consenters, each verifying against the
  delivered proposal.  With a membership directory installed
  (``install_reconfig_hook``) the quorum bar is the one of the EPOCH THE
  DECISION BELONGS TO, and only that epoch's members count toward it.
* **epoch-cert** — with a directory installed: no valid signer of a
  delivered decision lies outside the membership of the decision's epoch —
  in particular, a removed node never appears in a later quorum cert
  (SAFETY.md §8).  Without a directory the ledgers carry no epoch
  structure and this check is vacuous.
* **durable-before-visible** — at the moment a replica delivers sequence
  ``s`` through the commit path, its own WAL already holds a protocol
  record binding it to that proposal at ``s`` (the persist-before-sign
  spine made visible).  Checked against the union of durable + pending
  appends: under group commit the durability of the *send* is what the
  protocol defers, and the append always precedes visibility (see
  SAFETY.md §6 for why this is the strongest true statement).

Violations are RECORDED, not raised: delivery runs inside a scheduler
event and ``SimScheduler._fire`` swallows exceptions, so raising would
hide the failure.  The chaos engine polls :attr:`InvariantMonitor.violations`
between schedule steps and aborts the run on the first one;
:meth:`InvariantMonitor.assert_clean` re-raises for plain pytest use.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import (
    ProposedRecord,
    SavedCommit,
    decode_saved,
    decode_view_metadata,
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant failure, pinned to the sim clock and the adversary
    actions executed before it."""

    invariant: str  # "prefix-agreement" | "quorum-cert" | "epoch-cert" | "durable-before-visible" | "cross-group-atomicity" | "liveness"
    sim_time: float
    node: Optional[int]
    detail: str
    history: tuple[str, ...]

    def __str__(self) -> str:  # pragma: no cover — formatting aid
        lines = [
            f"invariant {self.invariant} violated at sim t={self.sim_time:.6f}"
            + (f" on replica {self.node}" if self.node is not None else ""),
            f"  {self.detail}",
        ]
        if self.history:
            lines.append("  adversary actions so far:")
            lines.extend(f"    {h}" for h in self.history)
        return "\n".join(lines)


class InvariantViolation(AssertionError):
    """Raised by :meth:`InvariantMonitor.assert_clean`."""

    def __init__(self, violation: Violation) -> None:
        super().__init__(str(violation))
        self.violation = violation


def _wal_appended_entries(node) -> Optional[list[bytes]]:
    """Every record the node's WAL has ACCEPTED (durable backing plus any
    group-commit pending buffer), or None when the WAL is not inspectable
    in memory (real file-backed WALs)."""
    wal = node.wal
    entries = getattr(wal, "entries", None)
    if entries is None:
        return None
    out = list(entries)
    pending = getattr(wal, "_pending", None)
    if pending:
        out.extend(entry for entry, _, _ in pending)
    return out


def _seq_of(proposal) -> Optional[int]:
    if not proposal.metadata:
        return None
    try:
        return decode_view_metadata(proposal.metadata).latest_sequence
    except Exception:
        return None


class InvariantMonitor:
    """Wired into ``Cluster.delivery_hooks``; judges every commit-path
    delivery and records the first failure of each kind."""

    def __init__(self, cluster, *, check_durability: bool = True) -> None:
        self.cluster = cluster
        n = len(cluster.nodes)
        self.quorum, self.f = compute_quorum(n)
        self.check_durability = check_durability
        self.violations: list[Violation] = []
        #: Adversary-action lines the chaos engine appends as it executes
        #: the schedule; snapshotted into each violation.
        self.history: list[str] = []
        #: ``fn(Violation)`` called the moment a violation is recorded —
        #: the flight recorder hangs here to dump while the failing state
        #: is still live.  Hook failures must not mask the violation.
        self.on_violation: list = []
        self.deliveries = 0
        #: Cross-group atomicity wiring (consensus sharding): a shared
        #: CrossGroupRegistry + this monitor's group id, installed via
        #: :meth:`attach_cross_group`.  None on single-group clusters.
        self.cross_group_registry = None
        self.cross_group_id: Optional[str] = None
        self._cross_group_seen = 0
        cluster.delivery_hooks.append(self._on_deliver)

    def attach_cross_group(self, registry, group_id: str) -> None:
        """Mirror the shared registry's cross-group atomicity verdicts
        into THIS monitor at every delivery (SAFETY.md §15): a violation
        involving this group surfaces with the group's own sim-time and
        adversary history attached.  Install the registry-feeding
        participant hook BEFORE this monitor was constructed so the
        delivery that completes a one-sided commit is judged immediately."""
        self.cross_group_registry = registry
        self.cross_group_id = group_id
        self._cross_group_seen = len(registry.violations)

    # --- recording ---------------------------------------------------------

    @property
    def first(self) -> Optional[Violation]:
        return self.violations[0] if self.violations else None

    def record(self, invariant: str, node: Optional[int], detail: str) -> None:
        violation = Violation(
            invariant=invariant,
            sim_time=self.cluster.scheduler.now(),
            node=node,
            detail=detail,
            history=tuple(self.history),
        )
        self.violations.append(violation)
        for hook in self.on_violation:
            try:
                hook(violation)
            except Exception:
                pass

    def assert_clean(self) -> None:
        if self.violations:
            raise InvariantViolation(self.violations[0])

    # --- the delivery-time checks -----------------------------------------

    def _on_deliver(self, node_id: int, decision) -> None:
        self.deliveries += 1
        self._check_prefix_agreement(node_id)
        self._check_quorum_cert(node_id, decision)
        if self.check_durability:
            self._check_durable_before_visible(node_id, decision)
        self._check_cross_group_atomicity(node_id)

    def _check_cross_group_atomicity(self, node_id: int) -> None:
        """Mirror any NEW cross-group atomicity violations the shared
        registry recorded (the participant hook runs before this monitor,
        so the registry is up to date for this very delivery)."""
        registry = self.cross_group_registry
        if registry is None:
            return
        fresh = registry.violations[self._cross_group_seen:]
        self._cross_group_seen = len(registry.violations)
        for violation in fresh:
            self.record(
                "cross-group-atomicity",
                node_id,
                f"[{self.cross_group_id}] {violation.detail} "
                f"(txid {violation.txid})",
            )

    def _check_prefix_agreement(self, node_id: Optional[int] = None) -> None:
        """Every pair of ledgers agrees on its common digest prefix."""
        ledgers = {
            nid: [d.proposal.digest() for d in node.app.ledger]
            for nid, node in self.cluster.nodes.items()
        }
        ids = sorted(ledgers)
        for i, a in enumerate(ids):
            for b in ids[i + 1:]:
                la, lb = ledgers[a], ledgers[b]
                common = min(len(la), len(lb))
                if la[:common] != lb[:common]:
                    at = next(
                        k for k in range(common) if la[k] != lb[k]
                    )
                    self.record(
                        "prefix-agreement",
                        node_id,
                        f"replicas {a} and {b} fork at height {at}: "
                        f"{la[at]} != {lb[at]}",
                    )
                    return

    def _check_quorum_cert(self, node_id: int, decision) -> None:
        """>= quorum distinct consenters, each signature verifying against
        the delivered proposal.  Epoch-aware when the cluster carries a
        membership directory: the quorum bar and the eligible signer set
        are the ones of the epoch the decision's sequence falls in, and a
        valid signer OUTSIDE that membership is its own violation
        (``epoch-cert``) — the cert a node built from a retired committee,
        or padded with an evicted member, is caught here even if it is
        numerically big enough."""
        app = self.cluster.nodes[node_id].app
        valid: set[int] = set()
        bad: list[str] = []
        if getattr(decision.signatures, "s_agg", None) is not None:
            # Half-aggregated QuorumCert: the proof is all-or-nothing — one
            # aggregate verification vouches for every listed signer at once.
            cert = decision.signatures
            vac = getattr(app, "verify_aggregate_cert", None)
            aux = vac(cert, decision.proposal) if vac is not None else None
            if aux is not None:
                valid = set(cert.signer_ids)
            else:
                bad.append(
                    f"half-agg cert with signers {sorted(set(cert.signer_ids))} "
                    "failed aggregate verification"
                )
        else:
            for sig in decision.signatures:
                try:
                    app.verify_consenter_sig(sig, decision.proposal)
                except Exception as err:
                    bad.append(f"id={sig.id}: {err}")
                    continue
                valid.add(sig.id)
        seq = _seq_of(decision.proposal)
        quorum = self.quorum
        directory = getattr(self.cluster, "membership_directory", None)
        if directory is not None:
            cfg = directory.membership_at(seq)
            quorum = cfg.quorum
            members = set(cfg.nodes)
            foreign = sorted(valid - members)
            if foreign:
                evicted = sorted(set(foreign) & directory.ever_removed())
                self.record(
                    "epoch-cert",
                    node_id,
                    f"decision at seq {seq} (epoch {cfg.epoch}, members "
                    f"{list(cfg.nodes)}) carries valid signature(s) from "
                    f"non-member(s) {foreign}"
                    + (f", previously removed: {evicted}" if evicted else ""),
                )
            valid &= members
        if len(valid) < quorum:
            self.record(
                "quorum-cert",
                node_id,
                f"decision at seq {seq} delivered with "
                f"{len(valid)} distinct valid commit signature(s) "
                f"(quorum is {quorum}"
                + (f"; invalid: {'; '.join(bad)}" if bad else "")
                + ")",
            )

    def _check_durable_before_visible(self, node_id: int, decision) -> None:
        """The delivering replica's own WAL already holds a record binding
        it to this proposal at this sequence.

        Scoped to deliveries the replica itself ATTESTED: the certificate
        contains its own commit signature (the 3-phase commit path always
        does — ``_try_process_commits`` asserts it).  A decision ADOPTED
        from a peer's verified quorum cert during a view change
        (``viewchanger._deliver_decision``) carries no local-durability
        claim — the signers' WALs back it, not ours — and is exempt, same
        as the sync path (which bypasses ``deliver`` entirely).  Persist-
        before-sign (SAFETY.md §1) is what makes the scoped form airtight:
        an own signature cannot exist in any cert before the backing
        record was appended (and, at durability window 0, fsynced)."""
        if not any(sig.id == node_id for sig in decision.signatures):
            return  # adopted foreign cert: no local-durability claim
        node = self.cluster.nodes[node_id]
        entries = _wal_appended_entries(node)
        if entries is None:
            return  # file-backed WAL: not inspectable without re-opening
        digest = decision.proposal.digest()
        seq = _seq_of(decision.proposal)
        for raw in entries:
            try:
                rec = decode_saved(raw)
            except Exception:
                continue
            if (
                isinstance(rec, ProposedRecord)
                and rec.pre_prepare.proposal.digest() == digest
            ):
                return
            if (
                isinstance(rec, SavedCommit)
                and seq is not None
                and rec.commit.seq == seq
                and rec.commit.digest == digest
            ):
                return
        self.record(
            "durable-before-visible",
            node_id,
            f"delivered seq {seq} (digest {digest}) with no WAL record "
            f"binding this replica to it ({len(entries)} entries searched)",
        )


def is_known_unresolvable_split(cluster, n: int) -> bool:
    """True iff the cluster's CURRENT attestations form a PREPARED-SPLIT
    stall that is unresolvable BY DESIGN (``check_in_flight`` docstring,
    SAFETY.md §2): prepared attestations exist at the next sequence, no
    candidate is adoptable (condition A), and a fresh proposal is not
    justified (condition B) — covering both the sub-f+1 split and opposed
    f+1-corroborated camps, where a hidden commit cannot be ruled out on
    either side.  The arithmetic is recomputed here INDEPENDENTLY of
    ``check_in_flight`` so a resolvability regression in the production
    code cannot self-excuse a wedge.  The liveness invariant's one excuse:
    stalling here is the safe outcome."""
    from consensus_tpu.wire import decode_view_data

    msgs = []
    for node in cluster.nodes.values():
        if not node.running or node.consensus is None:
            continue  # a retired (evicted) replica argues no camp
        vc = node.consensus.view_changer
        svd = vc._prepare_view_data()
        msgs.append(decode_view_data(svd.raw_view_data))
    quorum, f = compute_quorum(n)

    expected_seq = max(
        (
            decode_view_metadata(m.last_decision.metadata).latest_sequence
            for m in msgs
            if m.last_decision is not None and m.last_decision.metadata
        ),
        default=0,
    ) + 1
    prepared_groups: dict = {}
    quiet = 0  # none / unprepared / wrong-seq — the B-side count
    for m in msgs:
        p = m.in_flight_proposal
        if p is None or not p.metadata:
            quiet += 1
            continue
        md = decode_view_metadata(p.metadata)
        if md.latest_sequence != expected_seq or not m.in_flight_prepared:
            quiet += 1
            continue
        prepared_groups[p.digest()] = prepared_groups.get(p.digest(), 0) + 1

    if not prepared_groups:
        return False  # nothing prepared: a stall here is a real bug
    if quiet >= quorum:
        return False  # condition B should have fired: real bug
    prepared_total = sum(prepared_groups.values())
    for count in prepared_groups.values():
        arguing = prepared_total - count
        if count >= f + 1 and len(msgs) - arguing >= quorum:
            return False  # condition A should have adopted it: real bug
    return True


__all__ = [
    "InvariantMonitor",
    "InvariantViolation",
    "Violation",
    "is_known_unresolvable_split",
]
