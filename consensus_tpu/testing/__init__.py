"""In-process simulated network + all-ports test application.

Parity: reference test/ (network.go, test_app.go).
"""

from consensus_tpu.testing.app import (
    ByteInspector,
    Cluster,
    MemWAL,
    Node,
    TestApp,
    make_request,
    pack_batch,
    unpack_batch,
)
from consensus_tpu.testing.network import NodeComm, SimNetwork

__all__ = [
    "Cluster",
    "Node",
    "TestApp",
    "ByteInspector",
    "MemWAL",
    "make_request",
    "pack_batch",
    "unpack_batch",
    "SimNetwork",
    "NodeComm",
]
