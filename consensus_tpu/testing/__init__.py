"""In-process simulated network + all-ports test application.

Parity: reference test/ (network.go, test_app.go).
"""

from consensus_tpu.testing.app import (
    ByteInspector,
    Cluster,
    DeferredMemWAL,
    MemWAL,
    Node,
    TestApp,
    make_request,
    pack_batch,
    unpack_batch,
)
from consensus_tpu.testing.chaos import (
    ChaosAction,
    ChaosEngine,
    ChaosResult,
    ChaosSchedule,
    format_repro,
    shrink,
)
from consensus_tpu.testing.crypto_app import ClientKeyring, CryptoApp, SignedRequestApp
from consensus_tpu.testing.faults import (
    CRASH_POINTS,
    FaultPlan,
    InjectedIOError,
    SimulatedCrash,
    registered_crash_points,
)
from consensus_tpu.testing.invariants import (
    InvariantMonitor,
    InvariantViolation,
    Violation,
    is_known_unresolvable_split,
)
from consensus_tpu.testing.membership import (
    boot_node,
    install_reconfig_hook,
    reconfig_request,
)
from consensus_tpu.testing.network import INJECTED_EVENT_KINDS, NodeComm, SimNetwork
from consensus_tpu.testing.storage import (
    STORAGE_FAULT_CLASSES,
    FaultyDecisionStore,
    StorageFaultInjector,
)

__all__ = [
    "ChaosAction",
    "ChaosEngine",
    "ChaosResult",
    "ChaosSchedule",
    "format_repro",
    "shrink",
    "InvariantMonitor",
    "InvariantViolation",
    "Violation",
    "is_known_unresolvable_split",
    "INJECTED_EVENT_KINDS",
    "CRASH_POINTS",
    "FaultPlan",
    "InjectedIOError",
    "SimulatedCrash",
    "registered_crash_points",
    "ClientKeyring",
    "CryptoApp",
    "SignedRequestApp",
    "Cluster",
    "Node",
    "TestApp",
    "ByteInspector",
    "DeferredMemWAL",
    "MemWAL",
    "make_request",
    "pack_batch",
    "unpack_batch",
    "SimNetwork",
    "NodeComm",
    "boot_node",
    "install_reconfig_hook",
    "reconfig_request",
    "STORAGE_FAULT_CLASSES",
    "FaultyDecisionStore",
    "StorageFaultInjector",
]
