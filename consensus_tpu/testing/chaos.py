"""Deterministic chaos engine: schedule DSL, executor, and shrinker.

The randomized soak loops (tests/test_soak.py) interleave their adversary
decisions WITH the run — the ``random.Random`` stream decides each step as
the cluster evolves, so a failure reproduces only by re-running the whole
loop, and no part of it can be removed without perturbing everything after
it.  The chaos engine splits those concerns:

* :class:`ChaosSchedule` — a seed-derived, **sim-clock-anchored** sequence
  of named adversary actions (:class:`ChaosAction`), generated up front.
  The schedule IS the adversary: executing the same schedule yields a
  byte-identical event log and identical final ledgers, and individual
  actions can be deleted without changing when the survivors fire.
* :class:`ChaosEngine` — executes a schedule on a fresh
  :class:`~consensus_tpu.testing.app.Cluster` with an
  :class:`~consensus_tpu.testing.invariants.InvariantMonitor` wired into
  the delivery hooks, checking safety AT EVERY DELIVERY and bounded
  time-to-progress after the last disruptive action.  Violations carry the
  exact sim-time and the action history that led there.
* :func:`shrink` — delta-debugging (ddmin) over the action list: given a
  failing schedule, converge to a minimal action subset that still fails
  with the SAME invariant, and :func:`format_repro` renders it as a
  paste-able snippet.

Adversary vocabulary (``ChaosAction.kind``):

``crash`` / ``restart``         process death and recovery (WAL survives)
``partition`` / ``heal``        link cuts around a group / clear ALL knobs
``loss`` / ``delay``            per-link probabilistic drop / latency
``duplicate`` / ``reorder`` / ``replay``
                                the byzantine-network primitives
                                (testing/network.py)
``byzantine`` / ``byzantine_stop``
                                per-SENDER message mutation (≤ f senders)
``arm_fault``                   arm a WAL/state/sync crash point from the
                                FaultPlan catalog (testing/faults.py)
``add_node`` / ``remove_node``  elastic membership (``generate(churn=True)``
                                only): order a reconfiguration through the
                                protocol itself, then boot the joiner /
                                retire the evictee.  A schedule containing
                                churn actions makes the engine install the
                                membership harness
                                (``install_reconfig_hook``) and turn on
                                ``epoch_tagging``; ``generate(churn=False)``
                                draws a byte-identical schedule to before
                                the vocabulary existed.
``region_partition`` / ``leader_shift``
                                WAN vocabulary (``generate(wan=<profile>)``
                                only).  A WAN schedule pins every node to a
                                region of the named :data:`WAN_PROFILES`
                                entry (round-robin over sorted ids) and the
                                engine arms per-link latency distributions
                                (``set_jitter``: intra-region base+spread vs
                                the profile's inter-region matrix), re-armed
                                after every heal since ``heal()`` clears all
                                knobs.  ``region_partition`` cuts one whole
                                region off; ``leader_shift`` multiplies the
                                base latency of every link INTO one region —
                                the leader-placement sensitivity probe (a
                                leader in the slowed region must hand over
                                or drag commit latency, never violate
                                safety).  ``generate(wan=None)`` draws a
                                byte-identical schedule to before the
                                vocabulary existed.
``device_fault``                device-fault vocabulary
                                (``generate(device_faults=True)`` only): arm
                                the shared verify engine's launch-fault
                                injector so its Kth next launch hangs
                                (:class:`~consensus_tpu.models.supervisor.LaunchTimeout`),
                                raises (an injected XLA launch failure), or
                                flips its verdict bits.  A schedule carrying
                                device-fault actions makes the engine wrap
                                the shared crypto engine in a
                                :class:`FaultInjectingEngine` under an
                                :class:`~consensus_tpu.models.supervisor.EngineSupervisor`
                                (host-twin cross-check every launch), so
                                every injected fault is masked: ledgers and
                                event logs stay byte-identical to the
                                fault-free run.  ``generate(device_faults=
                                False)`` consumes no extra RNG, so pinned
                                schedules replay byte-identically.
``storage_fault``               storage-fault vocabulary
                                (``generate(storage_faults=True)`` only):
                                arm one node's seeded disk-fault injector
                                (testing/storage.py) — a bit flip in a
                                committed WAL region, a torn write at an
                                arbitrary frame offset, a lying fsync
                                (acked bytes dropped at the next crash), an
                                ENOSPC byte budget, read EIO, or transient
                                fsync stalls.  A schedule carrying storage
                                faults runs the cluster on REAL file-backed
                                WALs under a temp dir with the background
                                scrubber (wal/scrub.py) on: a detection
                                quarantines the corrupt suffix and fences
                                the node as a non-voting learner until
                                verified sync carries it past its
                                checkpoint fence (a commit-path delivery
                                while fenced is the ``learner-fence``
                                invariant violation).
                                ``generate(storage_faults=False)`` consumes
                                no extra RNG, so pinned schedules replay
                                byte-identically.
``net_abuse``                   adversarial-network vocabulary
                                (``generate(adversarial_net=True)`` only):
                                a byzantine wire peer abuses one node's
                                listener guard (net/framing.py) — a
                                slow-loris stall flood, a malformed-frame
                                flood, or a connect flood past the
                                per-peer quota.  The sim arm drives a
                                :class:`~consensus_tpu.net.framing
                                .ListenerGuard` directly on the sim clock
                                (scripted, zero sockets, byte-
                                deterministic); the REAL-socket
                                equivalent of the same vocabulary is
                                ``testing/adversary.py``, run tier-1
                                against live listeners and by the deploy
                                rig.  The guard surfaces on the node as
                                ``wire_guard`` so the obs sampler exports
                                its counters and the ``wire_abuse``
                                detector fires; bans land in the event
                                log and trip the flight recorder.
                                ``generate(adversarial_net=False)``
                                consumes no extra RNG, so pinned
                                schedules replay byte-identically.

Everything runs on the SimScheduler's virtual clock — no wall-clock reads
anywhere (scripts/check_no_wallclock.py lints this module too).
"""

from __future__ import annotations

import dataclasses
import random
import shutil
import tempfile
from typing import Optional

from consensus_tpu.testing.app import Cluster, make_request
from consensus_tpu.testing.faults import FaultPlan
from consensus_tpu.testing.invariants import (
    InvariantMonitor,
    Violation,
    is_known_unresolvable_split,
)
from consensus_tpu.testing.membership import install_reconfig_hook, reconfig_request
from consensus_tpu.testing.storage import (
    STORAGE_FAULT_CLASSES,
    StorageFaultInjector,
)
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import EpochTagged

#: The churn vocabulary: actions that change the member set through an
#: ordered reconfiguration (not a topology knob).
CHURN_KINDS = ("add_node", "remove_node")

#: The WAN vocabulary: region-shaped topology actions, only drawn when a
#: schedule names a geography profile.
WAN_KINDS = ("region_partition", "leader_shift")

#: The device-fault vocabulary: launch-level faults against the shared
#: verify engine (not a node or a link), only drawn when a schedule opts in.
DEVICE_FAULT_KINDS = ("device_fault",)

#: The three injectable launch-fault classes, matching the supervisor's
#: fault taxonomy: ``hang`` -> LaunchTimeout, ``raise`` -> launch raise,
#: ``flip`` -> verdict corruption (caught by the host cross-check).
DEVICE_FAULT_CLASSES = ("hang", "raise", "flip")

#: The storage-fault vocabulary: disk-level faults against one node's
#: file-backed WAL, only drawn when a schedule opts in.  The ``fault`` arg
#: is one of testing/storage.py's :data:`STORAGE_FAULT_CLASSES`.
STORAGE_FAULT_KINDS = ("storage_fault",)

#: The adversarial-network vocabulary: scripted listener-guard abuse
#: against one node's wire edge, only drawn when a schedule opts in.
ADVERSARIAL_NET_KINDS = ("net_abuse",)

#: The scripted abuse batteries a ``net_abuse`` action may run (sim-clock
#: mirrors of the real-socket batteries in testing/adversary.py).
NET_ABUSE_BATTERIES = ("stall_flood", "garbage_flood", "connect_flood")

#: Geography bank: per-profile region names, intra-region link latency
#: ``(base, jitter)`` in sim-seconds, and the inter-region latency matrix
#: keyed on the SORTED region pair.  Values are loosely shaped on public
#: cloud RTT tables — what matters for the harness is the ORDER between
#: them (intra << transatlantic << transpacific), not the digits.
WAN_PROFILES = {
    "3region": {
        "regions": ("us-east", "eu-west", "ap-south"),
        "intra": (0.002, 0.001),
        "inter": {
            ("ap-south", "eu-west"): (0.075, 0.020),
            ("ap-south", "us-east"): (0.110, 0.025),
            ("eu-west", "us-east"): (0.040, 0.010),
        },
    },
    "2region-lopsided": {
        "regions": ("us-east", "ap-south"),
        "intra": (0.002, 0.001),
        "inter": {
            ("ap-south", "us-east"): (0.140, 0.040),
        },
    },
    "global5": {
        "regions": ("us-east", "us-west", "eu-west", "ap-south", "sa-east"),
        "intra": (0.002, 0.001),
        "inter": {
            ("ap-south", "eu-west"): (0.075, 0.020),
            ("ap-south", "sa-east"): (0.160, 0.040),
            ("ap-south", "us-east"): (0.110, 0.025),
            ("ap-south", "us-west"): (0.090, 0.020),
            ("eu-west", "sa-east"): (0.095, 0.025),
            ("eu-west", "us-east"): (0.040, 0.010),
            ("eu-west", "us-west"): (0.065, 0.015),
            ("sa-east", "us-east"): (0.060, 0.015),
            ("sa-east", "us-west"): (0.085, 0.020),
            ("us-east", "us-west"): (0.030, 0.008),
        },
    },
}


def region_map(profile: str, ids) -> dict:
    """node id -> region name: round-robin over SORTED ids, so placement is
    a pure function of (profile, member set) and survives churn."""
    regions = WAN_PROFILES[profile]["regions"]
    return {
        nid: regions[i % len(regions)]
        for i, nid in enumerate(sorted(ids))
    }


def wan_links(profile: str, ids) -> tuple:
    """Every ordered link ``(a, b, base, jitter)`` for the member set under
    ``profile`` — the engine feeds these straight into ``set_jitter``."""
    prof = WAN_PROFILES[profile]
    rmap = region_map(profile, ids)
    intra_base, intra_jitter = prof["intra"]
    links = []
    ordered = sorted(ids)
    for a in ordered:
        for b in ordered:
            if a == b:
                continue
            ra, rb = rmap[a], rmap[b]
            if ra == rb:
                base, jitter = intra_base, intra_jitter
            else:
                base, jitter = prof["inter"][tuple(sorted((ra, rb)))]
            links.append((a, b, base, jitter))
    return tuple(links)

#: The soak suite's fast-timeout profile; chaos runs use the same one so a
#: 25-action schedule finishes in well under a sim-hour.
DEFAULT_TWEAKS = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}

#: Crash points the generator arms (all reachable on the in-memory WAL
#: path; the wal.* points need a file-backed cluster and stay out of the
#: default vocabulary).
ARMABLE_POINTS = (
    "state.save.proposed.pre",
    "state.save.proposed.post",
    "state.save.commit.pre",
    "state.save.commit.post",
    "state.save.viewchange.post",
    "state.save.newview.pre",
)


@dataclasses.dataclass(frozen=True)
class ChaosAction:
    """One named adversary action at an absolute sim-time.  The default
    dataclass repr is deliberately paste-able Python (``args`` is a plain
    dict literal) — :func:`format_repro` leans on that."""

    at: float
    kind: str
    args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A complete adversary: cluster shape + ordered actions.  Frozen so a
    schedule can be replayed or shrunk without aliasing surprises."""

    seed: int
    n: int = 4
    durability_window: float = 0.0
    actions: tuple = ()
    #: WAN geography profile name (a :data:`WAN_PROFILES` key) or None.
    #: Carried on the schedule so shrunk subsets keep their geography.
    wan: Optional[str] = None
    #: True when the schedule was drawn with the device-fault vocabulary.
    #: Carried so shrunk subsets keep arming the launch-fault injector even
    #: after every ``device_fault`` action was deleted.
    device_faults: bool = False
    #: True when the schedule was drawn with the storage-fault vocabulary.
    #: Carried so shrunk subsets keep the file-backed cluster + scrubber
    #: even after every ``storage_fault`` action was deleted.
    storage_faults: bool = False
    #: True when the schedule was drawn with the adversarial-network
    #: vocabulary.  Carried so shrunk subsets stay recognizable even after
    #: every ``net_abuse`` action was deleted.
    adversarial_net: bool = False

    @classmethod
    def generate(
        cls,
        seed: int,
        *,
        n: int = 4,
        steps: int = 25,
        durability_window: float = 0.0,
        start: float = 30.0,
        churn: bool = False,
        wan: Optional[str] = None,
        device_faults: bool = False,
        storage_faults: bool = False,
        adversarial_net: bool = False,
    ) -> "ChaosSchedule":
        """Derive a feasible schedule from ``seed``: action times are
        cumulative uniform(5, 40) gaps from ``start``, kinds are weighted
        draws constrained so the adversary stays inside the fault model
        (≤ f replicas down or doomed at once, ≤ f byzantine senders).

        ``churn=True`` adds ``add_node`` / ``remove_node`` to the
        vocabulary (bounded: member set never below 4 or more than two
        above ``n``, removes only target live non-byzantine members);
        ``churn=False`` leaves every RNG draw byte-identical to the
        pre-churn generator, so pinned schedules replay unchanged.

        ``wan=<profile>`` (a :data:`WAN_PROFILES` key) pins the geography
        and adds ``region_partition`` / ``leader_shift`` to the vocabulary;
        ``wan=None`` consumes no extra RNG, so pre-WAN schedules replay
        byte-identically.

        ``device_faults=True`` adds ``device_fault`` to the vocabulary:
        launch-level hang/raise/verdict-flip faults against the shared
        verify engine, masked at run time by the engine supervisor;
        ``device_faults=False`` consumes no extra RNG, so pre-device-fault
        schedules replay byte-identically.

        ``storage_faults=True`` adds ``storage_fault`` to the vocabulary:
        seeded disk faults against one node's file-backed WAL
        (testing/storage.py).  A faulted node may fence itself as a
        non-voting learner until verified sync clears it, so storage
        targets share the crash budget (at most ``f`` replicas down or
        suspect at once) and each node is faulted at most once per
        schedule; ``storage_faults=False`` consumes no extra RNG, so
        pre-storage schedules replay byte-identically.

        ``adversarial_net=True`` adds ``net_abuse`` to the vocabulary: a
        byzantine wire peer runs one scripted abuse battery
        (:data:`NET_ABUSE_BATTERIES`) against one node's listener guard.
        Abuse targets the wire EDGE, not the protocol, so it needs no
        feasibility budget — a guarded listener sheds it by design;
        ``adversarial_net=False`` consumes no extra RNG, so pre-hardening
        schedules replay byte-identically."""
        if wan is not None and wan not in WAN_PROFILES:
            raise ValueError(
                f"unknown WAN profile {wan!r}; "
                f"choose from {sorted(WAN_PROFILES)}"
            )
        rng = random.Random(seed)
        ids = list(range(1, n + 1))
        _, f = compute_quorum(n)
        kinds = ["crash", "restart", "partition", "heal", "loss", "delay",
                 "duplicate", "reorder", "replay", "byzantine",
                 "byzantine_stop", "arm_fault"]
        weights = [2.0, 2.0, 1.5, 2.0, 2.0, 1.5, 1.5, 1.5, 1.5, 1.0, 1.0, 1.0]
        if churn:
            kinds += list(CHURN_KINDS)
            weights += [1.2, 1.2]
        if wan is not None:
            kinds += list(WAN_KINDS)
            weights += [1.5, 1.0]
        if device_faults:
            kinds += list(DEVICE_FAULT_KINDS)
            weights += [1.5]
        if storage_faults:
            kinds += list(STORAGE_FAULT_KINDS)
            weights += [1.5]
        if adversarial_net:
            kinds += list(ADVERSARIAL_NET_KINDS)
            weights += [1.5]
        members = set(ids)
        next_id = n + 1
        t = start
        down: set[int] = set()  # crashed or armed-to-crash
        #: Storage-faulted nodes: they may spend sim-time fenced as
        #: non-voting learners, so they count against the crash budget and
        #: are never faulted twice (conservative — most faults heal).
        suspect: set[int] = set()
        byzantine: set[int] = set()
        actions = []
        for _ in range(steps):
            t += rng.uniform(5.0, 40.0)
            if churn:
                # Feasibility tracks the CURRENT member set, not the seed
                # shape: targets are drawn from live members and the fault
                # budget follows the shrunken/grown committee.
                ids = sorted(members)
                _, f = compute_quorum(len(ids))
            kind = rng.choices(kinds, weights)[0]
            if kind == "add_node" and len(members) - n >= 2:
                kind = "remove_node"
            if kind == "remove_node":
                evictable = [i for i in sorted(members)
                             if i not in down and i not in byzantine]
                if len(members) <= 4 or not evictable:
                    kind = "heal"
            # Feasibility downgrades keep every generated action applicable
            # (the engine re-checks at run time anyway — shrunk subsets may
            # still strand a restart whose crash was deleted).
            if kind == "storage_fault":
                targets = [i for i in ids
                           if i not in down and i not in suspect]
                if not targets or len(down) + len(suspect) >= f:
                    kind = "heal"
            if kind in ("crash", "arm_fault") and len(down) + len(suspect) >= f:
                kind = "restart" if down else "heal"
            if kind == "restart" and not down:
                kind = "heal"
            if kind == "byzantine" and len(byzantine) >= max(f, 1):
                kind = "byzantine_stop"
            if kind == "byzantine_stop" and not byzantine:
                kind = "loss"

            if kind == "crash":
                node = rng.choice([i for i in ids if i not in down])
                down.add(node)
                actions.append(ChaosAction(at=t, kind="crash",
                                           args={"node": node}))
            elif kind == "restart":
                node = rng.choice(sorted(down))
                down.discard(node)
                actions.append(ChaosAction(at=t, kind="restart",
                                           args={"node": node}))
            elif kind == "partition":
                group = sorted(rng.sample(ids, rng.choice([1, 1, 2])))
                actions.append(ChaosAction(at=t, kind="partition",
                                           args={"group": tuple(group)}))
            elif kind == "heal":
                actions.append(ChaosAction(at=t, kind="heal"))
            elif kind in ("loss", "duplicate", "reorder", "replay"):
                a, b = rng.sample(ids, 2)
                p = rng.choice([0.1, 0.3, 0.5])
                actions.append(ChaosAction(at=t, kind=kind,
                                           args={"a": a, "b": b, "p": p}))
            elif kind == "delay":
                a, b = rng.sample(ids, 2)
                d = round(rng.uniform(0.05, 0.5), 3)
                actions.append(ChaosAction(at=t, kind="delay",
                                           args={"a": a, "b": b, "d": d}))
            elif kind == "byzantine":
                node = rng.choice([i for i in ids if i not in byzantine])
                byzantine.add(node)
                actions.append(ChaosAction(
                    at=t, kind="byzantine",
                    args={"node": node, "rate": rng.choice([0.3, 0.7])},
                ))
            elif kind == "byzantine_stop":
                byzantine.clear()
                actions.append(ChaosAction(at=t, kind="byzantine_stop"))
            elif kind == "add_node":
                node = next_id
                next_id += 1
                members.add(node)
                actions.append(ChaosAction(at=t, kind="add_node",
                                           args={"node": node}))
            elif kind == "remove_node":
                node = rng.choice(evictable)
                members.discard(node)
                down.discard(node)
                actions.append(ChaosAction(at=t, kind="remove_node",
                                           args={"node": node}))
            elif kind == "region_partition":
                # The concrete group is baked in at generate time so the
                # action repros stand alone (no geography lookup needed).
                rmap = region_map(wan, ids)
                region = rng.choice(sorted(set(rmap.values())))
                group = tuple(sorted(i for i in ids if rmap[i] == region))
                actions.append(ChaosAction(
                    at=t, kind="region_partition",
                    args={"region": region, "group": group},
                ))
            elif kind == "leader_shift":
                rmap = region_map(wan, ids)
                region = rng.choice(sorted(set(rmap.values())))
                actions.append(ChaosAction(
                    at=t, kind="leader_shift",
                    args={"region": region,
                          "factor": rng.choice([2.0, 4.0])},
                ))
            elif kind == "storage_fault":
                node = rng.choice(targets)
                suspect.add(node)
                fault = rng.choice(STORAGE_FAULT_CLASSES)
                args = {"node": node, "fault": fault}
                if fault == "enospc":
                    # A zero budget refuses the very next append; positive
                    # budgets let a few records land first.
                    args["budget"] = rng.choice([0, 256, 1024])
                elif fault in ("eio_read", "slow_fsync"):
                    args["count"] = rng.randrange(1, 4)
                actions.append(ChaosAction(at=t, kind="storage_fault",
                                           args=args))
            elif kind == "device_fault":
                # ``launch`` is RELATIVE: the Kth verify launch after the
                # action applies faults, so the action stays meaningful in
                # shrunk subsets regardless of how many launches preceded it.
                actions.append(ChaosAction(
                    at=t, kind="device_fault",
                    args={"fault": rng.choice(DEVICE_FAULT_CLASSES),
                          "launch": rng.randrange(1, 4)},
                ))
            elif kind == "net_abuse":
                # Abuse hits the wire edge of one node; no feasibility
                # budget (a guarded listener sheds it without protocol
                # involvement, crashed targets are skipped at run time).
                actions.append(ChaosAction(
                    at=t, kind="net_abuse",
                    args={"node": rng.choice(ids),
                          "battery": rng.choice(NET_ABUSE_BATTERIES),
                          "events": rng.randrange(3, 8)},
                ))
            else:  # arm_fault: the armed replica dies at the seam firing
                node = rng.choice([i for i in ids if i not in down])
                down.add(node)
                actions.append(ChaosAction(
                    at=t, kind="arm_fault",
                    args={"node": node,
                          "point": rng.choice(ARMABLE_POINTS),
                          "hit": rng.randrange(1, 4)},
                ))
        return cls(seed=seed, n=n, durability_window=durability_window,
                   actions=tuple(actions), wan=wan,
                   device_faults=device_faults,
                   storage_faults=storage_faults,
                   adversarial_net=adversarial_net)


@dataclasses.dataclass
class ChaosResult:
    """Outcome of one engine run.  ``event_log`` is the full deterministic
    trace of applied actions, violations, and final ledger digests —
    byte-identical across replays of the same schedule."""

    ok: bool
    violation: Optional[Violation]
    event_log: bytes
    ledgers: dict
    schedule: ChaosSchedule
    deliveries: int
    #: Every obs-plane detector firing (obs.Anomaly), in sim-time order.
    #: Empty unless the engine ran with ``obs`` enabled.
    anomalies: tuple = ()
    #: Final per-node health snapshot ({node id (str): health dict}) from
    #: the sampler's last sample.  Empty without ``obs``.
    final_health: dict = dataclasses.field(default_factory=dict)
    #: Flight-recorder bundle path, when a recorder was armed AND triggered.
    flightrec_path: Optional[str] = None


class FaultInjectingEngine:
    """Deterministic launch-fault wrapper around a verify engine.

    Counts ``verify_batch`` launches and, when an armed index comes up,
    models one of the three supervisor fault classes:

    * ``hang``  — raises :class:`~consensus_tpu.models.supervisor.LaunchTimeout`
      (a real thread-hang would wedge the deterministic sim; the timeout
      exception IS how the production watchdog surfaces one).
    * ``raise`` — raises ``RuntimeError`` (an XLA launch failure / device
      loss as the runtime reports it).
    * ``flip``  — lets the launch complete, then inverts every verdict bit
      (silent wrong answers, catchable only by the host cross-check).

    ``verify_host`` passes through UNINJECTED — the host twin is ground
    truth, so a supervisor wrapping this injector masks every fault.  All
    other attributes forward to the wrapped engine.
    """

    def __init__(self, engine) -> None:
        self.engine = engine
        #: Cumulative ``verify_batch`` calls (faulted launches count too).
        self.launches = 0
        #: Faults actually fired, in order: ``(launch index, fault kind)``.
        self.fired: list[tuple[int, str]] = []
        self._armed: dict[int, str] = {}

    def arm(self, launch_offset: int, fault: str) -> None:
        """Arm ``fault`` on the ``launch_offset``-th launch from now."""
        if fault not in DEVICE_FAULT_CLASSES:
            raise ValueError(
                f"unknown device fault {fault!r}; "
                f"choose from {DEVICE_FAULT_CLASSES}"
            )
        self._armed[self.launches + max(1, int(launch_offset))] = fault

    @property
    def pending(self) -> int:
        """Armed faults that have not fired yet."""
        return len(self._armed)

    def verify_batch(self, *args, **kwargs):
        from consensus_tpu.models.supervisor import LaunchTimeout

        self.launches += 1
        fault = self._armed.pop(self.launches, None)
        if fault is not None:
            self.fired.append((self.launches, fault))
        if fault == "hang":
            raise LaunchTimeout(
                f"injected device hang at launch {self.launches}"
            )
        if fault == "raise":
            raise RuntimeError(
                f"injected XLA launch failure at launch {self.launches}"
            )
        out = self.engine.verify_batch(*args, **kwargs)
        if fault == "flip":
            import numpy as np

            return np.logical_not(np.asarray(out))
        return out

    def verify_host(self, *args, **kwargs):
        return self.engine.verify_host(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.engine, name)


class ChaosEngine:
    """Executes one :class:`ChaosSchedule` to a :class:`ChaosResult`."""

    #: Requests submitted alongside each applied action / at warmup / at
    #: the final progress probe.
    REQUESTS_PER_ACTION = 2
    WARMUP_REQUESTS = 4
    PROBE_REQUESTS = 5
    WARMUP_BUDGET = 300.0
    SETTLE_TIME = 60.0
    #: Sim-time allowed for one churn action's reconfiguration to ORDER
    #: (epoch advance observed) and, for removes, for the evictee to
    #: deliver its own eviction and shut down.
    RECONFIG_BUDGET = 300.0
    #: Bounded time-to-progress after the last disruptive action: n - f
    #: replicas must extend the ledger within this much sim-time of the
    #: post-schedule heal (the liveness invariant's budget).
    LIVENESS_BUDGET = 900.0
    #: Scrub cadence on storage-fault runs: short relative to the ≥5s
    #: action gaps, so a latent flip or tear is quarantined (and the node
    #: fenced) before the adversary can crash the node into the boot-time
    #: tail-repair path.
    SCRUB_INTERVAL = 2.0

    def __init__(
        self,
        schedule: ChaosSchedule,
        *,
        config_tweaks: Optional[dict] = None,
        check_durability: bool = True,
        metrics=None,
        tracer=None,
        crypto: Optional[str] = None,
        engine_factory=None,
        obs=None,
        flight_dir: Optional[str] = None,
        device_faults: tuple = (),
    ) -> None:
        """``crypto`` arms REAL Ed25519 on every replica signature path:
        ``"ed25519"`` uses the strict batch engine, ``"ed25519-batch"`` the
        randomized aggregate-check engine (Configuration.batch_verify_mode)
        — node keys are derived from the schedule seed, so two engines run
        on byte-identical schedules and must produce identical ledgers.
        Crypto mode also unlocks a signature-corruption byzantine arm,
        rolled on a dedicated RNG stream so non-crypto schedules replay
        byte-for-byte unchanged.

        ``engine_factory`` (requires ``crypto``) overrides the verification
        engine construction — a zero-arg callable returning any object with
        the ``verify_batch`` contract.  The mesh parity gates use it to run
        the SAME schedule through sharded engines and assert byte-identical
        ledgers/event logs against the single-device run.

        ``device_faults`` arms the launch-fault injector directly from the
        constructor: a tuple of ``(launch_offset, fault)`` pairs (fault in
        :data:`DEVICE_FAULT_CLASSES`), each firing on the given launch
        counted from run start.  Arming is SILENT — no schedule action, no
        event-log line, no RNG draw — so a run with constructor faults must
        stay byte-identical to the clean run (the supervisor masks every
        fault); the device-fault parity matrix is built on exactly that.
        Either form of device faults (constructor pairs or ``device_fault``
        schedule actions) implies a crypto run: ``crypto`` defaults up to
        ``"ed25519"`` when unset."""
        wants_faults = bool(device_faults) or any(
            a.kind in DEVICE_FAULT_KINDS for a in schedule.actions
        )
        #: Storage runs get a real file-backed cluster (temp WAL dir), the
        #: background scrubber, per-node disk-fault injectors, and the
        #: learner-fence invariant wired into the delivery hooks.
        self._wants_storage = schedule.storage_faults or any(
            a.kind in STORAGE_FAULT_KINDS for a in schedule.actions
        )
        self._wal_tmp: Optional[str] = None
        if wants_faults and crypto is None:
            crypto = "ed25519"
        if crypto not in (None, "ed25519", "ed25519-batch", "ed25519-halfagg"):
            raise ValueError(f"unknown chaos crypto mode {crypto!r}")
        if engine_factory is not None and crypto is None:
            raise ValueError("engine_factory requires a crypto mode")
        self.schedule = schedule
        self.config_tweaks = dict(config_tweaks or DEFAULT_TWEAKS)
        if crypto == "ed25519-halfagg":
            # Same strict engine as "ed25519", but every decided quorum is
            # compressed into a half-aggregated QuorumCert — the ledger/
            # event-log parity gate runs the SAME schedule under both modes.
            self.config_tweaks.setdefault("cert_mode", "half-agg")
        #: A schedule carrying churn actions runs with the membership
        #: harness installed and epoch tagging on — stale-epoch traffic
        #: from evictees must be dropped at ingress, not interpreted.
        self._churn = any(a.kind in CHURN_KINDS for a in schedule.actions)
        if self._churn:
            self.config_tweaks.setdefault("epoch_tagging", True)
        self.check_durability = check_durability
        self.metrics = metrics
        self.tracer = tracer
        self.crypto = crypto
        self.engine_factory = engine_factory
        #: Observability plane: an ``ObsConfig`` (enabled=True) samples the
        #: cluster during the run; detector firings land in the event log
        #: as ANOMALY lines and on ``ChaosResult.anomalies``.  Sampling is
        #: read-only, so ledgers are byte-identical with or without it.
        self.obs = obs
        #: Directory for flight-recorder bundles; None leaves the recorder
        #: unarmed.  Requires ``obs`` for sample/health capture but works
        #: without it (trace + schedule only).
        self.flight_dir = flight_dir
        self.recorder = None
        self.cluster: Optional[Cluster] = None
        self.monitor: Optional[InvariantMonitor] = None
        self._log: list[str] = []
        self._submitted = 0
        self._byz_rules: dict[int, float] = {}
        #: Engine-owned mutation stream, independent of the network's RNG
        #: so arming byzantine mid-run cannot shift loss/duplicate rolls.
        self._byz_rng = random.Random(schedule.seed ^ 0xB12A)
        #: Separate stream for the crypto-only signature-flip arm: never
        #: consulted without ``crypto``, so existing pinned schedules keep
        #: their exact mutation sequence.
        self._sig_rng = random.Random(schedule.seed ^ 0x516)
        #: Active leader_shift ``(region, factor)`` or None — heal clears
        #: it along with every other topology knob.
        self._wan_shift: Optional[tuple] = None
        #: Constructor-armed ``(launch_offset, fault)`` pairs, applied as
        #: soon as the injector exists (before the cluster starts).
        self.device_faults = tuple(device_faults)
        self._wants_faults = wants_faults
        #: The launch-fault injector, its supervisor, and the supervisor's
        #: pinned-metrics bundle (``engine_degrade_total{reason}`` etc.) —
        #: built by ``_install_crypto`` only on device-fault runs.
        self.fault_injector: Optional[FaultInjectingEngine] = None
        self.supervisor = None
        self.engine_metrics = None

    # --- bookkeeping --------------------------------------------------------

    def _emit(self, line: str) -> None:
        self._log.append(line)
        self.monitor.history.append(line)

    def _now(self) -> float:
        return self.cluster.scheduler.now()

    def _submit(self, k: int) -> None:
        for _ in range(k):
            self.cluster.submit_to_all(make_request("chaos", self._submitted))
            self._submitted += 1

    def _fmt_args(self, action: ChaosAction) -> str:
        return " ".join(f"{k}={v!r}" for k, v in sorted(action.args.items()))

    def _apply_wan_links(self) -> None:
        """(Re-)arm the geography: one ``set_jitter`` per ordered member
        link, with an active leader_shift multiplying the base of every
        link INTO the shifted region.  Idempotent; called at start and
        after every heal, since ``heal()`` clears all jitter knobs."""
        if self.schedule.wan is None:
            return
        net = self.cluster.network
        ids = sorted(net.node_ids())
        rmap = region_map(self.schedule.wan, ids)
        shift = self._wan_shift
        for a, b, base, jitter in wan_links(self.schedule.wan, ids):
            if shift is not None and rmap[b] == shift[0]:
                base *= shift[1]
            net.set_jitter(a, b, base, jitter)

    # --- the adversary actions ---------------------------------------------

    def _apply(self, action: ChaosAction) -> bool:
        """Apply one action if currently feasible; False means skipped
        (shrunk subsets legitimately strand restarts, byzantine_stops, and
        churn actions whose prerequisite add/remove was deleted)."""
        net = self.cluster.network
        nodes = self.cluster.nodes
        members = set(net.node_ids())
        _, f = compute_quorum(len(members))
        # The fault budget covers MEMBERS only: an evicted node kept around
        # for its ledger is not a crash the protocol must tolerate.
        dead = sum(
            1 for nid, nd in nodes.items()
            if nid in members and not nd.running
        )
        kind, args = action.kind, action.args
        if kind == "crash":
            node = nodes.get(args["node"])
            if node is None or args["node"] not in members:
                return False
            if not node.running or dead >= f:
                return False
            node.crash()
            return True
        if kind == "restart":
            node = nodes.get(args["node"])
            if node is None or args["node"] not in members or node.running:
                return False
            node.restart()
            return True
        if kind == "partition":
            net.partition(list(args["group"]))
            return True
        if kind == "heal":
            net.heal()
            self._wan_shift = None
            self._apply_wan_links()
            return True
        if kind == "region_partition":
            if self.schedule.wan is None:
                return False
            net.partition(list(args["group"]))
            return True
        if kind == "leader_shift":
            if self.schedule.wan is None:
                return False
            self._wan_shift = (args["region"], args["factor"])
            self._apply_wan_links()
            return True
        if kind == "loss":
            net.set_loss(args["a"], args["b"], args["p"])
            return True
        if kind == "delay":
            net.set_delay(args["a"], args["b"], args["d"])
            return True
        if kind == "duplicate":
            net.set_duplicate(args["a"], args["b"], args["p"])
            return True
        if kind == "reorder":
            net.set_reorder(args["a"], args["b"], args["p"])
            return True
        if kind == "replay":
            net.set_replay(args["a"], args["b"], args["p"])
            return True
        if kind == "byzantine":
            if (args["node"] not in self._byz_rules
                    and len(self._byz_rules) >= max(f, 1)):
                return False
            self._byz_rules[args["node"]] = args["rate"]
            net.mutate_send = self._mutate
            return True
        if kind == "byzantine_stop":
            if not self._byz_rules:
                return False
            self._byz_rules.clear()
            return True
        if kind == "add_node":
            node_id = args["node"]
            if node_id in nodes or node_id in members:
                return False
            if not self._order_reconfig(sorted(members | {node_id})):
                return False
            self.cluster.add_node(node_id)
            self._apply_wan_links()  # geography follows the member set
            return True
        if kind == "remove_node":
            node_id = args["node"]
            node = nodes.get(node_id)
            if node is None or node_id not in members or len(members) <= 4:
                return False
            if not node.running:
                return False  # eviction must be DELIVERED, not assumed
            if not self._order_reconfig(sorted(members - {node_id})):
                return False
            # The evictee delivers its own eviction decision and shuts
            # itself down; only then is retiring the process legitimate.
            self.cluster.scheduler.run_until(
                lambda: node.consensus is None or not node.consensus._running,
                max_time=self.RECONFIG_BUDGET,
            )
            if node.consensus is not None and node.consensus._running:
                return False  # stranded (e.g. partitioned evictee): leave it
            self.cluster.remove_node(node_id)
            self._apply_wan_links()  # geography follows the member set
            return True
        if kind == "arm_fault":
            node = nodes.get(args["node"])
            if node is None or args["node"] not in members:
                return False
            if not node.running or node.fault_plan is not None or dead >= f:
                return False
            plan = FaultPlan(args["point"], on_hit=args["hit"],
                             label=f"chaos@{action.at:.4f}")
            node.arm_fault_plan(plan)
            if self.recorder is not None:
                self.recorder.watch_plan(plan)
            return True
        if kind == "device_fault":
            # Targets the SHARED verify engine, not a member — feasible
            # whenever the injector exists (i.e. any crypto device-fault
            # run; shrunk subsets keep it via schedule.device_faults).
            if self.fault_injector is None:
                return False
            self.fault_injector.arm(args["launch"], args["fault"])
            return True
        if kind == "storage_fault":
            node = nodes.get(args["node"])
            if node is None or args["node"] not in members or not node.running:
                return False
            inj = getattr(node, "storage_injector", None)
            if inj is None:
                return False
            inj.arm(
                args["fault"],
                **{k: v for k, v in args.items() if k in ("budget", "count")},
            )
            return True
        if kind == "net_abuse":
            node = nodes.get(args["node"])
            if node is None or args["node"] not in members or not node.running:
                return False
            self._run_net_abuse(args["node"], node,
                                args["battery"], args["events"])
            return True
        raise ValueError(f"unknown chaos action kind {kind!r}")

    def _run_net_abuse(self, nid, node, battery: str, events: int) -> None:
        """Scripted abuse against ``nid``'s listener guard, on the SIM
        clock (zero sockets, zero RNG, byte-deterministic).  The guard is
        attached lazily as ``node.wire_guard`` so the obs sampler exports
        its counters and the ``wire_abuse`` detector fires; a ban trips
        the flight recorder and lands in the event log."""
        from consensus_tpu.net.framing import ListenerGuard

        guard = getattr(node, "wire_guard", None)
        if guard is None:
            guard = ListenerGuard(
                name=f"sim-{nid}",
                max_conns_per_peer=4,
                clock=self.cluster.scheduler.now,
                on_ban=lambda addr, kind, _nid=nid: self._on_wire_ban(
                    _nid, addr, kind
                ),
            )
            node.wire_guard = guard
        addr = f"10.66.0.{nid}"  # the (simulated) byzantine peer's address
        if battery == "stall_flood":
            for _ in range(events):
                guard.strike(addr, "stall")
        elif battery == "garbage_flood":
            for _ in range(events):
                guard.strike(addr, "garbage")
        elif battery == "connect_flood":
            held = 0
            for _ in range(guard.max_conns_per_peer):
                if guard.admit(addr):
                    held += 1
            for _ in range(events):
                guard.admit(addr)  # over quota (or banned): rejected
            for _ in range(held):
                guard.release(addr)
        else:
            raise ValueError(f"unknown net_abuse battery {battery!r}")

    def _on_wire_ban(self, nid, addr: str, kind: str) -> None:
        self._emit(
            f"{self._now():10.4f} wire-ban node={nid} peer={addr} "
            f"kind={kind}"
        )
        if self.recorder is not None:
            self.recorder.trigger(
                "wire-abuse-ban", node=nid,
                detail=f"peer {addr} banned after {kind}",
            )

    def _order_reconfig(self, target_nodes) -> bool:
        """Submit a membership-change request and run until SOME replica
        surfaces the decision (directory epoch advance).  False means the
        change did not order within the budget — the action is reported
        skipped, though the request stays pooled and may still order later
        (the final probe re-reads the member set, so a late reconfig is
        picked up there)."""
        directory = self.cluster.membership_directory
        before = directory.current_epoch
        self.cluster.submit_to_all(
            reconfig_request(f"chaos-{self._submitted}", target_nodes)
        )
        self._submitted += 1
        return self.cluster.scheduler.run_until(
            lambda: directory.current_epoch > before,
            max_time=self.RECONFIG_BUDGET,
        )

    def _mutate(self, sender: int, target: int, msg):
        """Byzantine-SENDER mutation: messages from an armed sender are
        corrupted at its configured rate.  Validation must shed all of it;
        ≤ f armed senders keeps this inside the threat model.  An
        epoch-tagged envelope is mutated THROUGH: the inner message is
        corrupted and re-wrapped under the sender's original epoch, so the
        byzantine arm keeps attacking the protocol rather than tripping on
        the envelope."""
        rate = self._byz_rules.get(sender)
        if not rate:
            return msg
        if isinstance(msg, EpochTagged):
            inner = self._mutate_body(msg.msg, rate)
            if inner is msg.msg:
                return msg
            return dataclasses.replace(msg, msg=inner)
        return self._mutate_body(msg, rate)

    def _mutate_body(self, msg, rate: float):
        if self.crypto is not None:
            # Crypto-only arm: flip a signature byte — real verification
            # (strict or randomized-batch) must shed it.  Dedicated RNG so
            # the shared _byz_rng stream (and every pinned non-crypto
            # schedule) is untouched.
            sig = getattr(msg, "signature", None)
            value = getattr(sig, "value", None)
            if value and self._sig_rng.random() < rate * 0.5:
                flipped = bytearray(value)
                i = self._sig_rng.randrange(len(flipped))
                flipped[i] ^= 0xFF
                return dataclasses.replace(
                    msg,
                    signature=dataclasses.replace(sig, value=bytes(flipped)),
                )
        if self._byz_rng.random() >= rate:
            return msg
        roll = self._byz_rng.random()
        digest = getattr(msg, "digest", None)
        if isinstance(digest, str) and roll < 0.4:
            return dataclasses.replace(msg, digest="byz-" + digest[:8])
        view = getattr(msg, "view", None)
        if isinstance(view, int) and roll < 0.7:
            return dataclasses.replace(
                msg, view=view + 1 + self._byz_rng.randrange(3)
            )
        seq = getattr(msg, "seq", None)
        if isinstance(seq, int):
            return dataclasses.replace(
                msg, seq=max(0, seq + self._byz_rng.choice([-1, 1, 5]))
            )
        return msg

    def _disarm_faults(self) -> None:
        for node in self.cluster.nodes.values():
            node.fault_plan = None
            if node.wal is not None:
                node.wal.fault_plan = None
            sync = node.synchronizer
            if sync is not None and hasattr(sync, "fault_plan"):
                sync.fault_plan = None
                sync.transport.fault_plan = None

    def _install_crypto(self) -> None:
        """Swap every node's app for a CryptoApp with REAL Ed25519 keys.

        Keys are sha512-derived from the schedule seed (no ambient RNG), so
        a strict-engine run and a randomized-batch run of the SAME schedule
        sign and verify the exact same bytes — ledger divergence between
        them can only come from the verifier, which is what the parity
        gate is hunting.  Node.app survives crash()/restart(), so one
        install covers the whole schedule."""
        import hashlib

        from consensus_tpu.models import Ed25519Signer
        from consensus_tpu.models.ed25519 import (
            Ed25519BatchVerifier,
            Ed25519RandomizedBatchVerifier,
        )
        from consensus_tpu.testing.crypto_app import CryptoApp, SigOnlyVerifier

        if self.engine_factory is not None:
            engine = self.engine_factory()
        elif self.crypto == "ed25519-batch":
            # min_randomized=2 keeps quorum-sized batches on the randomized
            # aggregate path even at chaos scale (n=4 certs).
            engine = Ed25519RandomizedBatchVerifier(
                min_device_batch=10**9, min_randomized=2
            )
        else:
            engine = Ed25519BatchVerifier(min_device_batch=10**9)
        if self._wants_faults:
            # Device-fault arm: injector under a supervisor whose host twin
            # (the injector's UNINJECTED verify_host) is ground truth.
            # crosscheck_interval=1 cross-checks EVERY launch, so verdict
            # flips are caught on the launch they corrupt and never reach a
            # quorum decision — that is what keeps faulted runs
            # byte-identical to clean ones.  The supervisor's clock is the
            # sim scheduler, so breaker backoff/re-probe run on sim time.
            from consensus_tpu.metrics import InMemoryProvider, Metrics
            from consensus_tpu.models.supervisor import EngineSupervisor

            self.fault_injector = FaultInjectingEngine(engine)
            for launch, fault in self.device_faults:
                self.fault_injector.arm(launch, fault)
            self.engine_metrics = Metrics(InMemoryProvider())
            self.supervisor = EngineSupervisor(
                [self.fault_injector],
                clock=self.cluster.scheduler.now,
                crosscheck_interval=1,
                metrics=self.engine_metrics,
                name="chaos-engine",
            )
            engine = self.supervisor
        signers = {
            nid: Ed25519Signer(
                nid,
                hashlib.sha512(
                    b"ctpu/chaos-key/%d/%d" % (self.schedule.seed, nid)
                ).digest()[:32],
            )
            for nid in self.cluster.nodes
        }
        keys = {nid: s.public_bytes for nid, s in signers.items()}
        for nid, node in self.cluster.nodes.items():
            node.app = CryptoApp(
                nid, self.cluster, signers[nid],
                SigOnlyVerifier(keys, engine=engine),
            )
            if self.supervisor is not None:
                # Obs surface: the sampler reads node.engine_supervisor to
                # export engine_degraded / engine_rung health fields.
                node.engine_supervisor = self.supervisor
            if self.crypto == "ed25519-halfagg":
                self._arm_halfagg_byz(nid, node.app)

    def _arm_halfagg_byz(self, nid: int, app) -> None:
        """Half-agg byzantine arm: when this node has a byzantine rule armed,
        occasionally corrupt ONE component signature inside an otherwise
        valid quorum right before aggregation.  The aggregator's self-check
        must catch it, the bisection fallback must localize the bad index,
        and the view degrades to the full signature tuple — ledgers stay
        clean.  Rolls ride the crypto-only ``_sig_rng`` stream; honest runs
        (no byzantine rule) consume NO rolls, keeping honest half-agg
        schedules replayable against other crypto modes."""
        inner = app.aggregate_cert

        def aggregate_cert(proposal, signatures, _inner=inner, _nid=nid):
            rate = self._byz_rules.get(_nid)
            if rate and signatures and self._sig_rng.random() < rate * 0.5:
                sigs = list(signatures)
                i = self._sig_rng.randrange(len(sigs))
                flipped = bytearray(sigs[i].value)
                flipped[self._sig_rng.randrange(len(flipped))] ^= 0xFF
                sigs[i] = dataclasses.replace(sigs[i], value=bytes(flipped))
                return _inner(proposal, tuple(sigs))
            return _inner(proposal, signatures)

        app.aggregate_cert = aggregate_cert

    def _on_corruption(self, node_id: int, recovery) -> None:
        """Cluster corruption hook: a scrub detection quarantined a corrupt
        suffix and the node fenced itself.  Log it deterministically (counts
        only — never temp paths), heal that node's injector (the fault is
        consumed), and snapshot a flight record when one is armed."""
        self._emit(
            f"{self._now():10.4f} QUARANTINE node={node_id} "
            f"files={len(recovery.quarantined)} "
            f"intact={recovery.intact_entries}"
        )
        node = self.cluster.nodes.get(node_id)
        inj = getattr(node, "storage_injector", None)
        if inj is not None:
            inj.heal()
        if self.recorder is not None:
            self.recorder.trigger(
                "wal-corruption", node=node_id, detail=recovery.reason
            )

    def _check_learner_fence(self, node_id: int, decision) -> None:
        """Delivery-hook invariant: a replica whose WAL lost durable records
        must not commit (= must not have voted) until verified sync carried
        it past its fence.  Sync appends bypass deliver(), so any commit-path
        delivery while ``fence_required()`` means the fence leaked a vote."""
        node = self.cluster.nodes.get(node_id)
        cons = node.consensus if node is not None else None
        if (
            cons is not None
            and cons.controller is not None
            and cons.controller.fence_required()
        ):
            self.monitor.record(
                "learner-fence", node_id,
                "commit-path delivery while fenced as a non-voting learner "
                "(voted before verified sync passed the last intact record)",
            )

    # --- the run ------------------------------------------------------------

    def run(self) -> ChaosResult:
        if self._wants_storage:
            self._wal_tmp = tempfile.mkdtemp(prefix="chaos-wal-")
        try:
            return self._run()
        finally:
            if self._wal_tmp is not None:
                shutil.rmtree(self._wal_tmp, ignore_errors=True)
                self._wal_tmp = None

    def _run(self) -> ChaosResult:
        sched = self.schedule
        self.cluster = Cluster(
            sched.n,
            seed=sched.seed ^ 0xCA05,
            config_tweaks=self.config_tweaks,
            durability_window=sched.durability_window,
            wal_dir=self._wal_tmp,
            scrub_interval=(
                self.SCRUB_INTERVAL if self._wants_storage else None
            ),
            obs=self.obs,
        )
        if self._churn:
            install_reconfig_hook(self.cluster)
        if self.metrics is not None:
            self.cluster.network.metrics = self.metrics.network
        if self.tracer is not None:
            self.cluster.network.tracer = self.tracer
        if self.crypto is not None:
            self._install_crypto()
        self.monitor = InvariantMonitor(
            self.cluster, check_durability=self.check_durability
        )
        if self._wants_storage:
            for nid, node in self.cluster.nodes.items():
                # One private RNG stream per node, derived from the schedule
                # seed: fault targeting replays byte-identically.
                node.storage_injector = StorageFaultInjector(
                    seed=sched.seed ^ 0x570A ^ (nid * 7919)
                )
            self.cluster.corruption_hooks.append(self._on_corruption)
            self.cluster.delivery_hooks.append(self._check_learner_fence)
        sampler = self.cluster.sampler
        if sampler is not None:
            if self.tracer is not None:
                sampler.tracer = self.tracer
            # Detector firings land in the deterministic event log with the
            # same sim-time stamp format as adversary actions.
            sampler.on_anomaly.append(
                lambda a: self._emit(
                    f"{a.sim_time:10.4f} ANOMALY {a.kind} node={a.node} "
                    f"{a.detail}"
                )
            )
        if self.flight_dir is not None:
            from consensus_tpu.obs.flightrec import FlightRecorder

            self.recorder = FlightRecorder(
                seed=sched.seed,
                out_dir=self.flight_dir,
                clock=self.cluster.scheduler.now,
                sampler=sampler,
                tracer=self.tracer,
                schedule=sched,
                last_n=(
                    self.obs.flight_samples if self.obs is not None else 64
                ),
            )
            self.recorder.attach_scheduler(self.cluster.scheduler)
            self.recorder.attach_monitor(self.monitor)
        self.cluster.start()
        self._apply_wan_links()
        self._emit(f"{self._now():10.4f} start n={sched.n} seed={sched.seed} "
                   f"window={sched.durability_window!r}"
                   + (f" wan={sched.wan}" if sched.wan else ""))

        # Warm up: the cluster must order a block before the adversary acts.
        self._submit(self.WARMUP_REQUESTS)
        if not self.cluster.run_until_ledger(1, max_time=self.WARMUP_BUDGET):
            self.monitor.record(
                "liveness", None,
                f"no block ordered within {self.WARMUP_BUDGET}s sim-time "
                "BEFORE any adversary action",
            )
        self._emit(f"{self._now():10.4f} warmup done")

        for action in sched.actions:
            if self.monitor.violations:
                break
            gap = action.at - self._now()
            if gap > 0:
                self.cluster.scheduler.advance(gap)
            if self.monitor.violations:
                break
            applied = self._apply(action)
            self._emit(
                f"{self._now():10.4f} "
                f"{'apply' if applied else 'skip '} "
                f"{action.kind} {self._fmt_args(action)}".rstrip()
            )
            self._submit(self.REQUESTS_PER_ACTION)

        if not self.monitor.violations:
            # Quiesce: adversary off, every MEMBER back, then LIVENESS —
            # m - f member replicas must make progress within the budget
            # (m follows the final member set under churn; a retired
            # evictee is neither restarted nor counted).
            self.cluster.network.heal()
            self._wan_shift = None
            self._apply_wan_links()
            self.cluster.network.mutate_send = None
            self._byz_rules.clear()
            self._disarm_faults()
            if self._wants_storage:
                # The disks heal (pending arms cleared; the suspect latch
                # survives so a lie-truncated node still boots fenced) and
                # degraded WALs recover on their probe during the settle.
                for node in self.cluster.nodes.values():
                    inj = getattr(node, "storage_injector", None)
                    if inj is not None:
                        inj.heal()
            members = set(self.cluster.network.node_ids())
            for nid, node in self.cluster.nodes.items():
                if nid in members and not node.running:
                    node.restart()
            self._emit(f"{self._now():10.4f} quiesce: healed + restarted")
            self.cluster.scheduler.advance(self.SETTLE_TIME)
            members = set(self.cluster.network.node_ids())
            m = len(members)
            member_nodes = [
                nd for nid, nd in self.cluster.nodes.items() if nid in members
            ]
            _, f = compute_quorum(m)
            floor = max(len(nd.app.ledger) for nd in member_nodes)
            self._submit(self.PROBE_REQUESTS)
            target = floor + 1
            progressed = self.cluster.scheduler.run_until(
                lambda: sum(
                    1 for nd in member_nodes
                    if len(nd.app.ledger) >= target
                ) >= m - f,
                max_time=self.LIVENESS_BUDGET,
            )
            if not progressed and not is_known_unresolvable_split(
                self.cluster, m
            ):
                self.monitor.record(
                    "liveness", None,
                    f"{m - f} replicas failed to reach height {target} "
                    f"within {self.LIVENESS_BUDGET}s sim-time of the final "
                    "heal (and the stall is not a known-unresolvable "
                    "prepared split)",
                )
            self.monitor._check_prefix_agreement()

        violation = self.monitor.first
        if violation is not None:
            self._emit(
                f"{violation.sim_time:10.4f} VIOLATION {violation.invariant}: "
                f"{violation.detail}"
            )
        ledgers = {
            nid: tuple(d.proposal.digest() for d in node.app.ledger)
            for nid, node in sorted(self.cluster.nodes.items())
        }
        for nid, digests in ledgers.items():
            tail = ",".join(digests[-3:])
            self._emit(f"{self._now():10.4f} ledger {nid} "
                       f"height={len(digests)} tail={tail}")
        sampler = self.cluster.sampler
        if sampler is not None:
            # One closing sample so the final health snapshot reflects the
            # post-quiesce state (deterministic: always exactly here).
            sampler.sample_now()
        return ChaosResult(
            ok=violation is None,
            violation=violation,
            event_log="\n".join(self._log).encode() + b"\n",
            ledgers=ledgers,
            schedule=sched,
            deliveries=self.monitor.deliveries,
            anomalies=tuple(sampler.anomalies) if sampler is not None else (),
            final_health=sampler.latest_health() if sampler is not None else {},
            flightrec_path=(
                self.recorder.path if self.recorder is not None else None
            ),
        )


# --- shrinking -------------------------------------------------------------


def _run_subset(schedule: ChaosSchedule, actions, engine_kwargs) -> ChaosResult:
    sub = dataclasses.replace(schedule, actions=tuple(actions))
    return ChaosEngine(sub, **engine_kwargs).run()


def shrink(
    schedule: ChaosSchedule,
    *,
    invariant: Optional[str] = None,
    engine_kwargs: Optional[dict] = None,
    max_runs: int = 200,
) -> tuple[ChaosSchedule, ChaosResult]:
    """Delta-debug (ddmin) a failing schedule down to a minimal action
    subset that still violates the SAME invariant.

    ``invariant`` defaults to whatever the full schedule violates (the
    full run happens first either way, to anchor the target); shrinking a
    passing schedule raises.  ``max_runs`` bounds the engine executions —
    each is a full deterministic sim, so this is a time cap, not a
    correctness knob.  Returns ``(shrunk_schedule, failing_result)``."""
    kwargs = dict(engine_kwargs or {})
    runs = [0]

    def failing(actions) -> Optional[ChaosResult]:
        if runs[0] >= max_runs:
            return None
        runs[0] += 1
        res = _run_subset(schedule, actions, kwargs)
        if res.violation is not None and (
            invariant is None or res.violation.invariant == invariant
        ):
            return res
        return None

    best_res = failing(schedule.actions)
    if best_res is None:
        raise ValueError(
            "schedule does not fail"
            + (f" with invariant {invariant!r}" if invariant else "")
            + " — nothing to shrink"
        )
    if invariant is None:
        invariant = best_res.violation.invariant
    best = list(schedule.actions)

    granularity = 2
    while len(best) >= 2:
        chunk = max(1, len(best) // granularity)
        reduced = False
        i = 0
        while i < len(best):
            candidate = best[:i] + best[i + chunk:]  # drop one chunk
            res = failing(candidate)
            if res is not None:
                best, best_res = candidate, res
                granularity = max(granularity - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if granularity >= len(best):
                break
            granularity = min(len(best), granularity * 2)
        if runs[0] >= max_runs:
            break
    return dataclasses.replace(schedule, actions=tuple(best)), best_res


def format_repro(result: ChaosResult) -> str:
    """A paste-able snippet reproducing ``result``'s schedule byte-for-byte
    (drop it in a test or a REPL; the engine is fully deterministic)."""
    s = result.schedule
    lines = [
        "from consensus_tpu.testing.chaos import (",
        "    ChaosAction, ChaosEngine, ChaosSchedule,",
        ")",
        "",
        "schedule = ChaosSchedule(",
        f"    seed={s.seed!r},",
        f"    n={s.n!r},",
        f"    durability_window={s.durability_window!r},",
        f"    wan={s.wan!r},",
        f"    device_faults={s.device_faults!r},",
        f"    storage_faults={s.storage_faults!r},",
        f"    adversarial_net={s.adversarial_net!r},",
        "    actions=(",
    ]
    for a in s.actions:
        lines.append(f"        {a!r},")
    lines += [
        "    ),",
        ")",
        "result = ChaosEngine(schedule).run()",
        "print(result.violation or 'run is clean')",
    ]
    return "\n".join(lines)


__all__ = [
    "ADVERSARIAL_NET_KINDS",
    "ARMABLE_POINTS",
    "CHURN_KINDS",
    "ChaosAction",
    "ChaosEngine",
    "ChaosResult",
    "ChaosSchedule",
    "DEFAULT_TWEAKS",
    "DEVICE_FAULT_CLASSES",
    "DEVICE_FAULT_KINDS",
    "FaultInjectingEngine",
    "NET_ABUSE_BATTERIES",
    "STORAGE_FAULT_CLASSES",
    "STORAGE_FAULT_KINDS",
    "WAN_KINDS",
    "WAN_PROFILES",
    "format_repro",
    "region_map",
    "shrink",
    "wan_links",
]
