"""All-ports test application + cluster builder.

Parity: reference test/test_app.go:49-494 — trivial crypto, a per-node
in-memory ledger that ``sync`` replays from peers, real (or in-memory) WALs,
and ``restart`` realism: tearing a replica down and rebuilding the whole
Consensus over the same WAL content.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Optional, Sequence

from consensus_tpu.api.deps import (
    Application,
    Assembler,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
    WriteAheadLog,
)
from consensus_tpu.config import Configuration
from consensus_tpu.consensus import Consensus
from consensus_tpu.core.view import Phase  # noqa: F401  (re-export convenience)
from consensus_tpu.membership import JoinBootstrap
from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.sync import (
    InProcessSyncTransport,
    LedgerDecisionStore,
    LedgerSynchronizer,
    SyncServer,
)
from consensus_tpu.testing.network import NodeComm, SimNetwork
from consensus_tpu.types import (
    Decision,
    Proposal,
    Reconfig,
    RequestInfo,
    Signature,
    SyncResponse,
    as_cert,
)

# --- request / batch encoding --------------------------------------------
# A request is b"client:reqid|payload".  A proposal payload is a packed
# sequence of requests.


def make_request(client: str, rid, payload: bytes = b"") -> bytes:
    return f"{client}:{rid}|".encode() + payload


def pack_batch(requests: Sequence[bytes]) -> bytes:
    out = [struct.pack(">I", len(requests))]
    for r in requests:
        out.append(struct.pack(">I", len(r)))
        out.append(r)
    return b"".join(out)


def unpack_batch(payload: bytes) -> list[bytes]:
    (count,) = struct.unpack_from(">I", payload, 0)
    off = 4
    out = []
    for _ in range(count):
        (n,) = struct.unpack_from(">I", payload, off)
        off += 4
        out.append(payload[off : off + n])
        off += n
    return out


class ByteInspector(RequestInspector):
    def request_id(self, raw_request: bytes) -> RequestInfo:
        head = raw_request.split(b"|", 1)[0].decode()
        client, _, rid = head.partition(":")
        if not client or not rid:
            raise ValueError(f"malformed request {raw_request!r}")
        return RequestInfo(client_id=client, request_id=rid)


def _toy_digest(data: bytes) -> bytes:
    """Short content digest for the toy signature scheme."""
    return hashlib.sha256(data).hexdigest()[:12].encode()


class MemWAL(WriteAheadLog):
    """In-memory WAL whose entries survive a simulated crash (the backing
    list lives in the cluster, not the node object)."""

    def __init__(self, backing: list[bytes]) -> None:
        self._backing = backing
        #: Simulated fsyncs — per append here (no group window), so the
        #: pipelining coalescing guards can count them like the real WAL's.
        self.fsync_count = 0
        self._tracer = None

    def attach_tracer(self, tracer) -> None:
        self._tracer = tracer

    def append(self, entry: bytes, truncate_to: bool = False, on_durable=None) -> None:
        if truncate_to:
            self._backing.clear()
        self._backing.append(entry)
        self.fsync_count += 1
        if self._tracer is not None and self._tracer.enabled:
            # Per-append fsync semantics: same instants the real WAL emits.
            self._tracer.instant(
                "wal", "wal.append", bytes=len(entry), truncate=truncate_to
            )
            self._tracer.instant("wal", "wal.fsync", records=1)
        if on_durable is not None:
            on_durable()  # memory-backed: "durable" immediately

    @property
    def entries(self) -> list[bytes]:
        return list(self._backing)


class DeferredMemWAL(WriteAheadLog):
    """MemWAL with GROUP-COMMIT durability semantics on the sim clock:
    appends land in a pending buffer, and only a flush (after ``window``
    sim-seconds) moves them into the crash-surviving backing list and
    fires their durability callbacks.  A simulated crash with unflushed
    records LOSES them — exactly the torn-tail realism a real group-commit
    window adds (and the regime that exposed the late-flush liveness
    wedge; see view.py::maybe_send_prepare)."""

    def __init__(self, backing: list[bytes], scheduler, window: float) -> None:
        self._backing = backing
        self._sched = scheduler
        self._window = window
        self._pending: list[tuple[bytes, bool, object]] = []
        self._timer = None
        self._dead = False
        #: Simulated fsyncs — one per group flush, however many records it
        #: covers (what the pipelining coalescing guards assert on).
        self.fsync_count = 0
        #: MetricsConsensus bundle for the coalescing-ratio gauge (the
        #: facade wires this like the real WAL's attach_consensus_metrics).
        self._consensus_metrics = None
        self._tracer = None

    def attach_consensus_metrics(self, metrics) -> None:
        self._consensus_metrics = metrics

    def attach_tracer(self, tracer) -> None:
        self._tracer = tracer

    def append(self, entry: bytes, truncate_to: bool = False, on_durable=None) -> None:
        if self._dead:
            return
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "wal", "wal.append", bytes=len(entry), truncate=truncate_to
            )
        self._pending.append((entry, truncate_to, on_durable))
        if self._timer is None:
            self._timer = self._sched.call_later(
                self._window, self._flush, name="sim-wal-group-flush"
            )

    def _flush(self) -> None:
        self._timer = None
        if self._dead:
            return
        pending, self._pending = self._pending, []
        for entry, truncate_to, _ in pending:
            if truncate_to:
                self._backing.clear()
            self._backing.append(entry)
        if pending:
            self.fsync_count += 1
            if self._consensus_metrics is not None:
                self._consensus_metrics.wal_records_per_fsync.set(len(pending))
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant("wal", "wal.fsync", records=len(pending))
        for _, _, on_durable in pending:
            if on_durable is not None:
                on_durable()

    def abandon(self) -> None:
        """Simulated process death: unflushed records are gone and the
        flush timer must never fire into a dead replica."""
        self._dead = True
        self._pending.clear()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    @property
    def entries(self) -> list[bytes]:
        return list(self._backing)


class TestApp(Application, Assembler, Signer, Verifier, Synchronizer):
    """Implements every application-side port with trivial crypto.

    Parity: reference test/test_app.go (SignProposal returns {ID, aux};
    VerifyConsenterSig echoes the aux back — node.go:90-110 does the same in
    naive_chain)."""

    def __init__(self, node_id: int, cluster: "Cluster") -> None:
        self.node_id = node_id
        self.cluster = cluster
        self.ledger: list[Decision] = []
        self.inspector = ByteInspector()
        self._vseq = 0

    # Application
    def deliver(self, proposal: Proposal, signatures: Sequence[Signature]) -> Reconfig:
        decision = Decision(proposal=proposal, signatures=as_cert(signatures))
        self.ledger.append(decision)
        # Commit-path delivery hooks (the chaos invariant monitor lives
        # here): called AFTER the append so a hook sees the ledger it is
        # judging.  Sync/catch-up appends bypass deliver() — hooks observe
        # only decisions this replica committed itself.  getattr: several
        # tests duck-type `cluster` with minimal stubs.
        for hook in getattr(self.cluster, "delivery_hooks", ()):
            hook(self.node_id, decision)
        return self.cluster.reconfig_of(proposal)

    # Assembler
    def assemble_proposal(self, metadata: bytes, requests: Sequence[bytes]) -> Proposal:
        return Proposal(
            payload=pack_batch(requests),
            header=struct.pack(">Q", len(self.ledger)),
            metadata=metadata,
            verification_sequence=self._vseq,
        )

    # Signer
    # Toy signatures BIND THE SIGNED CONTENT (id + a digest of the bytes):
    # content-free values (the old b"sig-<id>") let a byzantine network
    # tamper a carried last-decision payload undetectably — the round-5
    # mutation chaos forked the ledger through exactly that hole, which
    # real Ed25519 consenter signatures (models/verifier.py) never allow.
    def sign(self, data: bytes) -> bytes:
        return b"sig-%d:%s" % (self.node_id, _toy_digest(data))

    def sign_proposal(self, proposal: Proposal, aux: bytes = b"") -> Signature:
        # Binds BOTH the proposal content and the aux payload (the
        # PreparesFrom proof travels in Signature.msg), mirroring what the
        # real Ed25519 signer signs (models/verifier.py commit_message).
        return Signature(
            id=self.node_id,
            value=b"sig-%d:%s" % (
                self.node_id, _toy_digest(proposal.digest().encode() + aux)
            ),
            msg=aux,
        )

    # Verifier
    def verify_proposal(self, proposal: Proposal) -> Sequence[RequestInfo]:
        return [self.inspector.request_id(r) for r in unpack_batch(proposal.payload)]

    def verify_request(self, raw_request: bytes) -> RequestInfo:
        return self.inspector.request_id(raw_request)

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        expect = b"sig-%d:%s" % (
            signature.id,
            _toy_digest(proposal.digest().encode() + signature.msg),
        )
        if signature.value != expect:
            raise ValueError(f"bad signature from {signature.id}")
        return signature.msg

    def verify_signature(self, signature: Signature) -> None:
        expect = b"sig-%d:%s" % (signature.id, _toy_digest(signature.msg))
        if signature.value != expect:
            raise ValueError(f"bad signature from {signature.id}")

    def verification_sequence(self) -> int:
        return self._vseq

    def requests_from_proposal(self, proposal: Proposal) -> Sequence[RequestInfo]:
        return [self.inspector.request_id(r) for r in unpack_batch(proposal.payload)]

    def raw_requests_from_proposal(self, proposal: Proposal) -> Sequence[bytes]:
        return unpack_batch(proposal.payload)

    def auxiliary_data(self, msg: bytes) -> bytes:
        return msg

    # Synchronizer (TOY fallback, ``Cluster(sync_mode="toy")``): replay
    # missing decisions straight out of the most advanced peer's in-memory
    # ledger — no wire protocol, no verification.  Kept for unit tests that
    # don't start transports; clusters default to the real wire path
    # (consensus_tpu/sync/), built per node in :meth:`Node.start`.
    # Parity: reference test/test_app.go:327-371.
    def sync(self) -> SyncResponse:
        best = self.cluster.longest_ledger(exclude=self.node_id)
        mine = len(self.ledger)
        reconfig = Reconfig()
        for decision in best[mine:]:
            self.ledger.append(decision)
            r = self.cluster.reconfig_of(decision.proposal)
            if r.in_latest_decision:
                reconfig = r
        if not self.ledger:
            return SyncResponse(latest=None, reconfig=reconfig)
        return SyncResponse(latest=self.ledger[-1], reconfig=reconfig)


class Node:
    """A replica: app + consensus + WAL, restartable."""

    def __init__(self, node_id: int, cluster: "Cluster", config: Configuration) -> None:
        self.node_id = node_id
        self.cluster = cluster
        self.config = config
        self.app = TestApp(node_id, cluster)
        self.wal_backing: list[bytes] = []
        self.wal: Optional[WriteAheadLog] = None
        self.consensus: Optional[Consensus] = None
        self.running = False
        #: Optional Metrics bundle handed to the next (re)build.
        self.metrics = None
        #: Armed testing FaultPlan (consensus_tpu/testing/faults.py); attach
        #: via arm_fault_plan so a firing crash seam tears this node down.
        self.fault_plan = None
        #: Wire-sync components (sync_mode="wire"): rebuilt on every start
        #: over the surviving app ledger.
        self.sync_server: Optional[SyncServer] = None
        self.synchronizer = None
        #: membership.JoinBootstrap armed by Cluster.add_node(bootstrap=True).
        self.join_bootstrap = None
        #: Optional testing.storage.StorageFaultInjector: installed over the
        #: file-backed WAL's open seams at every (re)start.
        self.storage_injector = None
        #: Background wal.scrub.WalScrubber (file-backed WAL + a cluster
        #: ``scrub_interval`` only); torn down with the process on crash.
        self.scrubber = None

    def arm_fault_plan(self, plan) -> None:
        """Arm ``plan`` on this node: its crash seams will call
        :meth:`crash` (teardown BEFORE the SimulatedCrash unwinds, so a
        swallowed exception cannot resurrect the process), and the plan is
        cleared on firing so a later :meth:`restart` boots clean."""
        plan.on_crash = self._fault_crash
        self.fault_plan = plan
        if self.wal is not None:
            self.wal.fault_plan = plan
        if self.consensus is not None:
            plan.tracer = self.consensus.tracer
        if isinstance(self.synchronizer, LedgerSynchronizer):
            self.synchronizer.fault_plan = plan
            self.synchronizer.transport.fault_plan = plan

    def _fault_crash(self) -> None:
        self.fault_plan = None  # the restarted process is a fresh one
        self.crash()

    def start(self) -> None:
        comm = self.cluster.network.register(self.node_id, self._on_message)
        last = self.app.ledger[-1] if self.app.ledger else None
        window = self.cluster.durability_window
        if self.cluster.wal_dir is not None:
            # Real file-backed WAL (fsync per append, small segments so
            # rolls happen under test): restart re-opens the directory,
            # repairing a torn tail exactly as a production boot would.
            from consensus_tpu.wal.log import initialize_and_read_all

            self.wal, initial = initialize_and_read_all(
                os.path.join(self.cluster.wal_dir, f"wal-{self.node_id}"),
                segment_max_bytes=self.cluster.wal_segment_bytes,
                quarantine_corrupt=True,
                # Sim-clocked so the WAL's degraded-mode recovery probe can
                # arm (without a scheduler an ENOSPC episode never ends).
                scheduler=self.cluster.scheduler,
            )
            if self.storage_injector is not None:
                self.storage_injector.install(self.wal)
        else:
            self.wal = (
                DeferredMemWAL(self.wal_backing, self.cluster.scheduler, window)
                if window > 0
                else MemWAL(self.wal_backing)
            )
            initial = list(self.wal_backing)
        self.wal.fault_plan = self.fault_plan
        if self.cluster.sync_mode == "wire":
            # Real catch-up path: this node serves its ledger to peers and
            # fetches+verifies chunks over the (simulated) wire — no reads
            # of peer memory; every synced byte crossed the codec.
            store = LedgerDecisionStore(self.app.ledger)
            self.sync_server = SyncServer(store)
            self.cluster.sync_servers[self.node_id] = self.sync_server
            transport = InProcessSyncTransport(
                self.node_id,
                self.cluster.network,
                self.cluster.sync_servers,
                fault_plan=self.fault_plan,
            )
            self.synchronizer = LedgerSynchronizer(
                node_id=self.node_id,
                store=store,
                transport=transport,
                verifier=self.app,
                nodes=self.cluster.network.node_ids,
                reconfig_of=self.cluster.reconfig_of,
                metrics=self.metrics.sync if self.metrics is not None else None,
                fault_plan=self.fault_plan,
                now=self.cluster.scheduler.now,
            )
        else:
            self.synchronizer = self.app
        self.consensus = Consensus(
            config=self.config,
            scheduler=self.cluster.scheduler,
            comm=comm,
            application=self.app,
            assembler=self.app,
            wal=self.wal,
            signer=self.app,
            verifier=self.app,
            request_inspector=self.app.inspector,
            synchronizer=self.synchronizer,
            wal_initial_content=initial,
            last_proposal=last.proposal if last else None,
            last_signatures=last.signatures if last else (),
            metrics=self.metrics,
        )
        if self.fault_plan is not None:
            # A plan armed before (re)start binds to the fresh tracer so a
            # crash-matrix trace records exactly which seam fired.
            self.fault_plan.tracer = self.consensus.tracer
        self.consensus.start()
        inj = self.storage_injector
        if inj is not None and inj.consume_suspect_fence():
            # The injector knows this disk dropped or damaged durable bytes
            # in a way the boot scan could not prove (an fsync lie, an
            # unscrubbed flip chopped by tail repair): the incarnation
            # starts as a non-voting learner until verified sync clears it.
            self.consensus.controller.fence_as_learner(
                self.consensus.controller.latest_seq()
            )
        if (
            self.cluster.wal_dir is not None
            and self.cluster.scrub_interval is not None
        ):
            from consensus_tpu.wal.scrub import WalScrubber

            self.scrubber = WalScrubber(
                self.wal,
                self.cluster.scheduler,
                interval=self.cluster.scrub_interval,
                metrics=getattr(self.wal, "_metrics", None),
                tracer=self.consensus.tracer,
                on_corruption=self._on_scrub_corruption,
            )
            self.scrubber.start()
        self.running = True

    def _on_scrub_corruption(self, err) -> None:
        """Scrub detection → quarantine the corrupt suffix, fence this
        replica as a non-voting learner, notify the cluster's hooks (the
        chaos engine logs + flight-records through them)."""
        recovery = self.wal.quarantine_corrupt(err)
        cons = self.consensus
        if cons is not None and cons.controller is not None:
            cons.controller.fence_as_learner(cons.controller.latest_seq())
        for hook in getattr(self.cluster, "corruption_hooks", ()):
            hook(self.node_id, recovery)

    def crash(self) -> None:
        """Hard-stop: drop off the network and kill all components."""
        self.running = False
        self.cluster.network.unregister(self.node_id)
        self.cluster.sync_servers.pop(self.node_id, None)
        self.sync_server = None
        if self.scrubber is not None:
            self.scrubber.stop()
            self.scrubber = None
        abandon = getattr(self.wal, "abandon", None)
        if abandon is not None:
            abandon()  # unflushed records / open fds die with the process
        if self.storage_injector is not None:
            # A lying disk drops its unsynced suffix exactly at crash time.
            self.storage_injector.on_crash()
        if self.consensus is not None:
            self.consensus.stop()
            self.consensus = None

    def restart(self) -> None:
        """Parity: reference test/test_app.go:130-143 (Restart)."""
        if self.running:
            self.crash()
        self.start()

    def submit(self, raw: bytes, on_done=None) -> None:
        if self.consensus is not None:
            self.consensus.submit_request(raw, on_done)

    def _on_message(self, sender: int, payload, is_request: bool) -> None:
        if self.consensus is None:
            return
        if is_request:
            self.consensus.handle_request(sender, payload)
        else:
            self.consensus.handle_message(sender, payload)


class Cluster:
    """n replicas over a simulated network on one virtual clock."""

    def __init__(
        self,
        n: int = 4,
        *,
        seed: int = 0,
        config_tweaks: Optional[dict] = None,
        leader_rotation: bool = False,
        durability_window: float = 0.0,
        wal_dir: Optional[str] = None,
        wal_segment_bytes: int = 2048,
        scrub_interval: Optional[float] = None,
        sync_mode: str = "wire",
        obs=None,
        scheduler=None,
    ) -> None:
        #: > 0 gives every node group-commit durability semantics
        #: (DeferredMemWAL): appends become durable — and their deferred
        #: sends fire — only after this many sim-seconds.
        self.durability_window = durability_window
        #: Set to a directory to give every node a REAL file-backed WAL
        #: (wal/log.py) under <wal_dir>/wal-<id> instead of the in-memory
        #: one; segments deliberately tiny so rolls happen in short runs.
        self.wal_dir = wal_dir
        self.wal_segment_bytes = wal_segment_bytes
        #: Sim-seconds between background WAL scrub passes (file-backed
        #: clusters only); None leaves the scrubber off.
        self.scrub_interval = scrub_interval
        #: fn(node_id, WALRecovery) called whenever a scrub detection
        #: quarantines a corrupt suffix (after the node fenced itself).
        self.corruption_hooks: list = []
        #: "wire" (default) gives every node the real catch-up subsystem
        #: (consensus_tpu/sync/: LedgerSynchronizer over an in-process wire
        #: transport with full codec round-trips and quorum-cert
        #: verification); "toy" opts back into TestApp.sync's direct
        #: peer-memory replay for unit tests that bypass transports.
        if sync_mode not in ("wire", "toy"):
            raise ValueError(f"unknown sync_mode {sync_mode!r}")
        self.sync_mode = sync_mode
        #: node id -> live SyncServer (wire mode); a crashed node serves
        #: nothing, exactly like its consensus ingress.
        self.sync_servers: dict[int, SyncServer] = {}
        #: Injectable virtual clock: a ShardedCluster hands every group ONE
        #: shared SimScheduler so cross-group time is a single total order;
        #: None (the default) keeps the private-clock construction
        #: bit-for-bit as before.  Each cluster always owns its own
        #: SimNetwork (per-group partitions/heals stay per-group).
        self.scheduler = scheduler if scheduler is not None else SimScheduler()
        self.network = SimNetwork(self.scheduler, seed=seed)
        self.network.set_membership(list(range(1, n + 1)), epoch=0)
        self.nodes: dict[int, Node] = {}
        #: fn(node_id, Decision) called on every COMMIT-PATH delivery (not
        #: on sync appends) — the invariant monitor's wiring point.
        self.delivery_hooks: list = []
        #: proposal-digest -> Reconfig to report on delivery (reconfig tests).
        self._reconfigs: dict[str, Reconfig] = {}
        #: membership.MembershipDirectory once the reconfig harness
        #: (testing/membership.py install_reconfig_hook) is installed.
        self.membership_directory = None
        #: fn(Proposal) -> Reconfig; consulted by :meth:`reconfig_of` after
        #: the explicit-digest table (the harness's payload interpreter).
        self._membership_interpreter = None
        self._config_tweaks = dict(config_tweaks or {})
        self._leader_rotation = leader_rotation
        for node_id in range(1, n + 1):
            self.nodes[node_id] = Node(node_id, self, self._node_config(node_id))
        #: Observability plane — DEFAULT OFF.  Pass an ``ObsConfig`` with
        #: ``enabled=True`` to build a ClusterSampler here (pre-start, so
        #: the installed metrics providers reach the Consensus builds) and
        #: arm it in :meth:`start`.
        self.sampler = None
        if obs is not None and obs.enabled:
            obs.validate()
            from consensus_tpu.obs.sampler import ClusterSampler

            self.sampler = ClusterSampler(
                self,
                interval=obs.sample_interval,
                capacity=obs.ring_capacity,
                thresholds=obs.detector_thresholds,
            )

    def _node_config(self, node_id: int) -> Configuration:
        """Build a node's Configuration from the cluster-wide tweaks (the
        same recipe the constructor uses, so a node added later matches the
        boot-time ones)."""
        tweaks = dict(self._config_tweaks)
        return Configuration(
            self_id=node_id,
            leader_rotation=self._leader_rotation,
            decisions_per_leader=tweaks.pop("decisions_per_leader", 3)
            if self._leader_rotation
            else 0,
            **tweaks,
        )

    def start(self) -> None:
        for node in self.nodes.values():
            node.start()
        if self.sampler is not None:
            self.sampler.start()

    # --- dynamic membership ------------------------------------------------

    def add_node(self, node_id: int, *, bootstrap: bool = True) -> Node:
        """Boot a node admitted by an ordered grow decision.

        Always builds a FRESH Node (empty ledger, empty WAL) with the
        cluster-wide config recipe: a joiner — even a re-added id — is a
        new process that must sync the whole history over the wire.  With
        ``bootstrap=True`` and the reconfig harness installed, arms a
        :class:`~consensus_tpu.membership.JoinBootstrap` so the joiner
        drives wire sync with retry/backoff until it reaches the current
        membership epoch (surviving injected loss and epochs advancing
        mid-join).
        """
        node = Node(node_id, self, self._node_config(node_id))
        self.nodes[node_id] = node
        if self.sampler is not None and node.metrics is None:
            # Same pre-start install the sampler does for boot-time nodes.
            from consensus_tpu.metrics import InMemoryProvider, Metrics

            node.metrics = Metrics(InMemoryProvider())
        node.start()
        directory = self.membership_directory
        if bootstrap and directory is not None:
            bootstrapper = JoinBootstrap(
                self.scheduler,
                sync=lambda: (
                    node.consensus.controller.sync()
                    if node.consensus is not None and node.consensus._running
                    else None
                ),
                caught_up=lambda: (
                    node.consensus is None
                    or not node.consensus._running
                    or node.consensus.membership_epoch >= directory.current_epoch
                ),
                current_epoch=lambda: directory.current_epoch,
                metrics=node.metrics.membership if node.metrics is not None else None,
            )
            node.join_bootstrap = bootstrapper
            bootstrapper.start()
        return node

    def remove_node(self, node_id: int) -> None:
        """Retire a node evicted by an ordered shrink decision.

        The eviction must already have been ORDERED AND DELIVERED (the
        node's consensus self-shuts-down when it applies the Reconfig that
        drops it) — this method only retires the harness-level process.
        The node deliberately STAYS registered on the network: a removed-
        but-live process keeps transmitting, which is exactly the
        stale-epoch traffic the facade's epoch gate must drop-and-count.
        """
        node = self.nodes[node_id]
        assert node.consensus is None or not node.consensus._running, (
            f"node {node_id} is still running consensus — remove-node must be "
            f"ordered as a decision and delivered (self-eviction) first"
        )
        bootstrapper = getattr(node, "join_bootstrap", None)
        if bootstrapper is not None:
            bootstrapper.stop()
        node.running = False

    # --- app-level cluster state ------------------------------------------

    def longest_ledger(self, *, exclude: int) -> list[Decision]:
        """Longest ledger among peers REACHABLE from ``exclude`` — state
        transfer must not tunnel through a network partition."""
        best: list[Decision] = []
        for node_id, node in self.nodes.items():
            if node_id == exclude or not node.running:
                continue
            if not self.network.reachable(exclude, node_id):
                continue
            if len(node.app.ledger) > len(best):
                best = node.app.ledger
        return list(best)

    def reconfig_of(self, proposal: Proposal) -> Reconfig:
        # Stable METHOD (never replaced): LedgerSynchronizer captures it as
        # a bound method at Node.start, so the interpreter chain must live
        # inside it rather than in a swapped-out attribute.
        hit = self._reconfigs.get(proposal.digest())
        if hit is not None:
            return hit
        if self._membership_interpreter is not None:
            return self._membership_interpreter(proposal)
        return Reconfig()

    # --- driving -----------------------------------------------------------

    def submit_to_all(self, raw: bytes) -> None:
        for node in self.nodes.values():
            if node.running:
                node.submit(raw)

    def ledgers_equal_len(self, expected: int, node_ids: Optional[Sequence[int]] = None) -> bool:
        ids = node_ids or [i for i, nd in self.nodes.items() if nd.running]
        return all(len(self.nodes[i].app.ledger) >= expected for i in ids)

    def run_until_ledger(self, expected: int, *, max_time: float = 600.0, node_ids=None) -> bool:
        return self.scheduler.run_until(
            lambda: self.ledgers_equal_len(expected, node_ids), max_time=max_time
        )

    def assert_ledgers_consistent(self) -> None:
        """Every pair of ledgers must agree on their common prefix."""
        ledgers = [
            [d.proposal.digest() for d in node.app.ledger]
            for node in self.nodes.values()
        ]
        for i in range(len(ledgers)):
            for j in range(i + 1, len(ledgers)):
                common = min(len(ledgers[i]), len(ledgers[j]))
                assert ledgers[i][:common] == ledgers[j][:common], (
                    f"ledger fork between replicas {i + 1} and {j + 1}"
                )


__all__ = [
    "Cluster",
    "Node",
    "TestApp",
    "ByteInspector",
    "MemWAL",
    "make_request",
    "pack_batch",
    "unpack_batch",
]
