"""The one supported reconfiguration harness for Cluster-based tests.

Lifted out of the test files (PR 9): a membership change in a test cluster
is an ORDINARY ORDERED REQUEST whose payload names the new member set
(``nodes=1,2,3``); when any replica surfaces that decision —
commit-path delivery or wire-sync replay — :func:`install_reconfig_hook`'s
interpreter turns it into a ``Reconfig`` carrying the new epoch's
:class:`~consensus_tpu.membership.MembershipConfig`, records the change in
the cluster's :class:`~consensus_tpu.membership.MembershipDirectory`, and
routes the network-level membership through ``SimNetwork.set_membership``
(epoch-bumped, removed-node deliveries accounted).

Idempotence is keyed on the proposal digest: every replica delivers the
same decision, and a lagging replica re-surfaces it through sync — only the
first sighting assigns an epoch; later sightings (including stale replays)
return the already-recorded config and leave the network membership at the
directory's CURRENT epoch.
"""

from __future__ import annotations

from consensus_tpu.membership import MembershipDirectory
from consensus_tpu.testing.app import Cluster, Node, make_request, unpack_batch
from consensus_tpu.types import Proposal, Reconfig
from consensus_tpu.wire import decode_view_metadata


def reconfig_request(rid, nodes) -> bytes:
    """An admin request whose commit changes membership to ``nodes``."""
    payload = b"nodes=" + ",".join(str(n) for n in nodes).encode()
    return make_request("admin", rid, payload)


def install_reconfig_hook(cluster: Cluster) -> MembershipDirectory:
    """Install the membership interpreter on ``cluster``; returns the
    directory (also stored as ``cluster.membership_directory``).

    Installs via ``cluster._membership_interpreter`` — ``Cluster.reconfig_of``
    stays a stable bound method (the LedgerSynchronizer captures it at
    ``Node.start``), so install order relative to node starts is free.
    """
    directory = MembershipDirectory(cluster.network.node_ids())
    cluster.membership_directory = directory

    def interpret(proposal: Proposal) -> Reconfig:
        try:
            requests = unpack_batch(proposal.payload)
        except Exception:
            return Reconfig()
        for raw in requests:
            _, _, payload = raw.partition(b"|")
            if payload.startswith(b"nodes="):
                ids = tuple(int(x) for x in payload[6:].split(b","))
                try:
                    seq = decode_view_metadata(proposal.metadata).latest_sequence
                except Exception:
                    seq = 0
                cfg = directory.record_change(proposal.digest(), seq, ids)
                # Network membership follows the directory's CURRENT epoch
                # (a stale sync replay of an old change must not drag it
                # backwards).
                current = directory.current
                cluster.network.set_membership(
                    list(current.nodes), epoch=current.epoch
                )
                reconfig = Reconfig(
                    in_latest_decision=True,
                    current_nodes=cfg.nodes,
                    membership=cfg,
                )
                # Cache by digest: later sightings skip re-parsing and the
                # synchronizer's per-proposal reconfig_of stays cheap.
                cluster._reconfigs[proposal.digest()] = reconfig
                return reconfig
        return Reconfig()

    cluster._membership_interpreter = interpret
    return directory


def boot_node(cluster: Cluster, node_id: int) -> Node:
    """Boot a freshly-admitted node WITHOUT the JoinBootstrap driver: it
    catches up through heartbeat-gap detection + sync, exactly like the
    historical test-local ``_boot_node`` (kept for ledger parity on pinned
    seeds; new tests should prefer ``cluster.add_node(node_id)``)."""
    return cluster.add_node(node_id, bootstrap=False)


__all__ = ["boot_node", "install_reconfig_hook", "reconfig_request"]
