"""Deterministic storage-fault injection beneath the file-backed WAL.

The crash matrix (testing/faults.py) kills the *process* at instrumented
seams; this layer faults the *disk* under a live process instead.  A
:class:`StorageFaultInjector` swaps the log's injectable open seams
(``WriteAheadLog._open_for_append`` / ``_open_for_read``) for fault-wrapped
file objects, so every byte the WAL writes or reads can be corrupted,
refused, or silently dropped — deterministically, from an explicit seed,
with zero wall-clock reads (scripts/check_no_wallclock.py lints this module
too).

Fault classes (:data:`STORAGE_FAULT_CLASSES`):

``bit_flip``    flip one bit of a committed record on disk, chosen from the
                seeded RNG over record bytes (headers + payloads; never the
                inter-record zero padding, which the CRC chain does not
                cover).  Latent until the scrubber (wal/scrub.py) or the
                next boot re-walks the chain.
``torn_mid``    the next append writes only a prefix of its frame (torn at
                an RNG offset), fsyncs the partial bytes, then fails — and
                the device goes read-only (every later write refused) until
                :meth:`heal`.  Holding writes off keeps the torn frame the
                durable tail, so the fault is exactly the mid-file tear the
                scrubber must quarantine (a tear followed by more appends
                would instead be chopped by boot-time ``repair`` as if the
                suffix had never been durable).
``fsync_lie``   fsyncs keep reporting success but stop being real: at the
                next simulated crash every byte written after the arm is
                dropped (the file truncates back to its arm-time length).
                The classic lying-disk hazard — locally undetectable, so
                the harness boots the next incarnation fenced
                (:meth:`consume_suspect_fence`).
``enospc``      a byte budget, after which writes (and flushes, so the
                degraded-probe cannot lie its way out) fail with ENOSPC
                until :meth:`heal` — the WAL must degrade, stop minting
                unpersistable work, and auto-recover when space returns.
``eio_read``    the next ``count`` reads through the read seam raise EIO —
                the scrubber treats an unreadable segment as corruption at
                offset 0 and the quarantine/fence path takes over.
``slow_fsync``  the next ``count`` fsyncs fail transiently (injected-clock
                latency modeled as deferred durability): in group-commit
                mode each failure books ``wal_fsync_retry_total``; below
                the retry cap the log recovers on its own.

Every fired fault is recorded on :attr:`StorageFaultInjector.fired` as
``(kind, detail)``, mirroring the chaos engine's launch-fault injector.
"""

from __future__ import annotations

import errno
import os
import random
import struct
from typing import Optional

from consensus_tpu.wal.log import (
    _HEADER,
    _list_segments,
    _pad,
    _segment_name,
)

#: The injectable fault taxonomy (chaos draws ``storage_fault`` actions
#: with a ``fault`` arg from this tuple, mirroring DEVICE_FAULT_CLASSES).
STORAGE_FAULT_CLASSES = (
    "bit_flip",
    "torn_mid",
    "fsync_lie",
    "enospc",
    "eio_read",
    "slow_fsync",
)


class _FaultyAppendFile:
    """Write-side wrapper installed over the WAL's current segment file.

    Forwards to the real buffered writer unless the owning injector has a
    write-side fault armed.  ``fileno`` is the fsync seam: the log calls
    ``os.fsync(self._file.fileno())``, so raising here surfaces exactly
    where a real fsync failure would."""

    def __init__(self, real, injector: "StorageFaultInjector", path: str) -> None:
        self._real = real
        self._inj = injector
        self._path = path

    def write(self, data: bytes) -> int:
        inj = self._inj
        if inj._torn_armed:
            inj._torn_armed = False
            # Tear inside the frame: at least the header start, never the
            # full frame.  The partial bytes are made durable (that is the
            # point of a torn write), then the device goes read-only so the
            # tear stays the tail until scrub/quarantine or heal.
            tear = 1 + inj._rng.randrange(max(1, len(data) - 1))
            self._real.write(data[:tear])
            self._real.flush()
            os.fsync(self._real.fileno())
            inj._enospc_budget = 0
            inj._enospc_recorded = True  # hard-full: probes must not "heal" it
            inj._suspect = True
            inj._record("torn_mid", f"{os.path.basename(self._path)}+{tear}")
            raise OSError(errno.EIO, f"injected torn write ({tear} bytes landed)")
        budget = inj._enospc_budget
        if budget is not None:
            if budget < len(data):
                inj._record_enospc()
                raise OSError(errno.ENOSPC, "injected: no space left on device")
            inj._enospc_budget = budget - len(data)
        return self._real.write(data)

    def flush(self) -> None:
        # Once a write has been REFUSED the device is hard-full: flushes
        # fail too, so the WAL's degraded probe (flush + fsync, no payload)
        # cannot declare the disk healed while writes would still bounce —
        # without this the degraded gauge would flap once per append.
        # Before the first refusal flushes pass, so a budget that drains to
        # exactly zero still lands its final frame coherently.
        if self._inj._enospc_recorded:
            raise OSError(errno.ENOSPC, "injected: no space left on device")
        self._real.flush()

    def fileno(self) -> int:
        inj = self._inj
        if inj._slow_fsyncs > 0:
            inj._slow_fsyncs -= 1
            inj._record("slow_fsync", f"remaining={inj._slow_fsyncs}")
            raise OSError(errno.EIO, "injected fsync stall")
        return self._real.fileno()

    def tell(self) -> int:
        return self._real.tell()

    def close(self) -> None:
        if self._inj._current is self:
            self._inj._current = None
        self._real.close()

    @property
    def closed(self) -> bool:
        return self._real.closed


class _FaultyReadFile:
    """Read-side wrapper: raises EIO while the injector has reads armed."""

    def __init__(self, real, injector: "StorageFaultInjector") -> None:
        self._real = real
        self._inj = injector

    def read(self, *args):
        inj = self._inj
        if inj._eio_reads > 0:
            inj._eio_reads -= 1
            inj._record("eio_read", f"remaining={inj._eio_reads}")
            raise OSError(errno.EIO, "injected read failure")
        return self._real.read(*args)

    def close(self) -> None:
        self._real.close()

    def __enter__(self) -> "_FaultyReadFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StorageFaultInjector:
    """Seeded fault layer for one node's file-backed WAL.

    :meth:`install` swaps the log's open seams and wraps its current
    segment file; faults are then armed one at a time with :meth:`arm` and
    fire deterministically from the injector's private RNG stream — a run
    with no injector (or no armed fault) consumes zero RNG and touches no
    seam, so fault-free schedules replay byte-identically.
    """

    def __init__(self, seed: int = 0) -> None:
        self._rng = random.Random(seed)
        self._wal = None
        self._current: Optional[_FaultyAppendFile] = None
        #: Faults that actually fired, in order: ``(kind, detail)``.
        self.fired: list[tuple[str, str]] = []
        self._torn_armed = False
        self._enospc_budget: Optional[int] = None
        self._enospc_recorded = False
        self._eio_reads = 0
        self._slow_fsyncs = 0
        #: path -> durable length at fsync-lie arm time; applied (truncated
        #: back) at the next simulated crash.
        self._lie_lengths: dict[str, int] = {}
        self._lie_armed = False
        #: The disk is known-damaged in a way the next boot cannot prove
        #: from local bytes alone (a lie truncation or an unsrubbed flip):
        #: the harness boots that incarnation fenced as a learner.
        self._suspect = False

    # --- wiring ------------------------------------------------------------

    def install(self, wal) -> None:
        """Attach to a live :class:`WriteAheadLog`: swap the open seams and
        wrap the current segment file.  Called at every node (re)start —
        a remount heals transient write-side arms (budget, tear, stalls),
        while :attr:`_suspect` survives until consumed by the boot fence."""
        self._wal = wal
        self._torn_armed = False
        self._enospc_budget = None
        self._enospc_recorded = False
        self._slow_fsyncs = 0
        self._lie_armed = False
        self._lie_lengths.clear()
        wal._open_for_append = self._open_append
        wal._open_for_read = self._open_read
        if wal._file is not None:
            path = os.path.join(wal._dir, _segment_name(wal._segment_index))
            wal._file = _FaultyAppendFile(wal._file, self, path)
            self._current = wal._file

    def _open_append(self, path: str, mode: str):
        f = _FaultyAppendFile(open(path, mode), self, path)
        if self._lie_armed:
            # A segment born under a lying fsync is entirely volatile.
            self._lie_lengths.setdefault(path, os.path.getsize(path))
        self._current = f
        return f

    def _open_read(self, path: str, mode: str):
        return _FaultyReadFile(open(path, mode), self)

    # --- arming ------------------------------------------------------------

    def arm(self, fault: str, *, budget: Optional[int] = None,
            count: int = 1) -> None:
        """Arm one fault.  ``budget`` (bytes) applies to ``enospc``;
        ``count`` to ``eio_read`` / ``slow_fsync``.  ``bit_flip`` fires
        immediately (it targets bytes already on disk)."""
        if fault not in STORAGE_FAULT_CLASSES:
            raise ValueError(
                f"unknown storage fault {fault!r}; "
                f"choose from {STORAGE_FAULT_CLASSES}"
            )
        if fault == "bit_flip":
            self._flip_bit()
        elif fault == "torn_mid":
            self._torn_armed = True
        elif fault == "fsync_lie":
            self._arm_lie()
        elif fault == "enospc":
            self._enospc_budget = int(budget) if budget is not None else 0
            self._enospc_recorded = False
        elif fault == "eio_read":
            self._eio_reads = max(1, int(count))
        elif fault == "slow_fsync":
            self._slow_fsyncs = max(1, int(count))

    def heal(self) -> None:
        """The disk recovers: every pending write/read-side arm clears.
        The suspect latch deliberately SURVIVES healing — damage already
        done (a lie truncation, an unscrubbed flip) is not undone by space
        returning, so only the boot fence (:meth:`consume_suspect_fence`)
        consumes it."""
        self._torn_armed = False
        self._enospc_budget = None
        self._enospc_recorded = False
        self._eio_reads = 0
        self._slow_fsyncs = 0
        self._lie_armed = False
        self._lie_lengths.clear()

    @property
    def pending(self) -> int:
        """Armed faults that have not fired/cleared yet."""
        return (
            int(self._torn_armed)
            + int(self._enospc_budget is not None)
            + int(self._lie_armed)
            + self._eio_reads
            + self._slow_fsyncs
        )

    # --- the fault bodies ---------------------------------------------------

    def _record(self, kind: str, detail: str) -> None:
        self.fired.append((kind, detail))

    def _record_enospc(self) -> None:
        # One fault instance however many writes it refuses.
        if not self._enospc_recorded:
            self._enospc_recorded = True
            self._record("enospc", f"budget={self._enospc_budget}")

    def _arm_lie(self) -> None:
        self._lie_armed = True
        wal = self._wal
        if wal is None or wal._file is None:
            return
        # Record the truly-durable length: flush the buffered writer so
        # tell()/getsize agree, then pin the current byte count.  Everything
        # past it is what the lying disk will drop at crash time.
        try:
            wal._file.flush()
        except OSError:
            pass
        path = os.path.join(wal._dir, _segment_name(wal._segment_index))
        if os.path.exists(path):
            self._lie_lengths[path] = os.path.getsize(path)

    def on_crash(self) -> None:
        """Apply the fsync lie at simulated process death: truncate every
        tracked file back to its arm-time durable length.  Called by the
        harness AFTER the node's file handles are abandoned."""
        if not self._lie_lengths:
            return
        dropped = 0
        for path, length in sorted(self._lie_lengths.items()):
            if not os.path.exists(path):
                continue
            size = os.path.getsize(path)
            if size <= length:
                continue
            with open(path, "r+b") as f:
                f.truncate(length)
                f.flush()
                os.fsync(f.fileno())
            dropped += size - length
        self._lie_lengths.clear()
        self._lie_armed = False
        if dropped:
            self._suspect = True
            self._record("fsync_lie", f"dropped={dropped}")

    def consume_suspect_fence(self) -> bool:
        """True exactly once after a locally-undetectable damage event (a
        lie truncation, or a flip the scrubber has not yet caught when the
        node reboots): the harness fences that incarnation as a learner."""
        suspect = self._suspect
        self._suspect = False
        return suspect

    def _flip_bit(self) -> None:
        """Flip one RNG-chosen bit of a committed record byte on disk.

        Only header+payload bytes are candidates — the zero padding between
        frames is not covered by the CRC chain, so a flip there would be
        legitimately undetectable (and the scrub test would hang waiting
        for a detection that can never come)."""
        wal = self._wal
        if wal is None:
            raise ValueError("injector not installed on a WAL")
        if wal._file is not None:
            try:
                wal._file.flush()
            except OSError:
                pass
        candidates: list[tuple[str, int]] = []
        for _, name in _list_segments(wal._dir):
            path = os.path.join(wal._dir, name)
            with open(path, "rb") as f:
                buf = f.read()
            for start, end in self._frame_spans(buf):
                candidates.extend((path, off) for off in range(start, end))
        if not candidates:
            raise ValueError("no committed record bytes to flip")
        path, off = candidates[self._rng.randrange(len(candidates))]
        mask = 1 << self._rng.randrange(8)
        with open(path, "r+b") as f:
            f.seek(off)
            byte = f.read(1)[0]
            f.seek(off)
            f.write(bytes([byte ^ mask]))
            f.flush()
            os.fsync(f.fileno())
        self._suspect = True
        self._record(
            "bit_flip", f"{os.path.basename(path)}@{off} mask=0x{mask:02x}"
        )

    @staticmethod
    def _frame_spans(buf: bytes) -> list[tuple[int, int]]:
        """Frame extents (header start → payload end, excluding padding)
        walked WITHOUT CRC validation — the flip targets well-framed bytes
        whether or not an earlier flip already broke the chain."""
        spans = []
        off = 0
        while off + _HEADER.size <= len(buf):
            length = struct.unpack_from("<I", buf, off)[0]
            end = off + _HEADER.size + length
            if length < 2 or end + _pad(length) > len(buf):
                break
            spans.append((off, end))
            off = end + _pad(length)
        return spans


class FaultyDecisionStore:
    """EIO-on-read wrapper for a sync-plane DecisionStore: ``fail_reads``
    reads raise before delegation, modeling a replica whose ledger store
    (not its WAL) hits media errors mid-catch-up.  Unit-test convenience —
    the chaos vocabulary targets the WAL seams."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self.fail_reads = 0
        self.fired = 0

    def height(self) -> int:
        return self._inner.height()

    def read(self, from_seq: int, to_seq: int):
        if self.fail_reads > 0:
            self.fail_reads -= 1
            self.fired += 1
            raise OSError(errno.EIO, "injected decision-store read failure")
        return self._inner.read(from_seq, to_seq)

    def append(self, decision) -> None:
        self._inner.append(decision)

    def last(self):
        return self._inner.last()


__all__ = [
    "STORAGE_FAULT_CLASSES",
    "StorageFaultInjector",
    "FaultyDecisionStore",
]
