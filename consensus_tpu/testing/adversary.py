"""AdversarialPeer: a byzantine wire driver for the hardened listeners.

Speaks RAW TCP — the frame layouts below are written out from the wire
specs, never imported from the server modules, so a server refactor that
accidentally changes bytes on the wire breaks these batteries instead of
silently tracking it.  One driver covers all four listener families:

========  =====================================================section
style     wire format
========  =====================================================
comm      ``u32 len | u64 sender | u8 kind`` (kind 2 = HELLO); the
          acceptor sends a 16-byte challenge nonce first and the HELLO
          answers ``HMAC-SHA256(secret, context | nonce | sender)``
sync      ``u32 len | payload`` (one codec-framed request per connection)
control   one JSON object per line (newline-terminated)
sidecar   ``u32 len | u64 req_id`` frames behind a mutual nonce
          handshake (server nonce -> client nonce + proof -> server
          proof)
========  =====================================================

Each battery method provokes ONE family of listener-guard defense events
and returns ``{event_kind: provoked_count}`` so a test can assert the
guard booked each defense EXACTLY once per provoked event:

* ``never_hello``     — connect and go silent: ``handshake_timeout``
* ``connect_flood``   — hold many simultaneous connections:
  ``conn_rejected`` for every one past the quota (the count is measured,
  not assumed: a refused connection is observable as an immediate close
  before the server speaks)
* ``midframe_stall``  — start a frame, never finish it: ``stall`` strike
* ``oversized_length``— claim a 2 GiB frame in the length header:
  ``oversized`` strike (the hardened reader allocates NOTHING for it)
* ``wrong_hmac_flood``— comm/sidecar: flood failing auth proofs
  (``bad_hello``); sync/control have no handshake, so the nearest
  equivalent is structurally-invalid payloads (``garbage``)
* ``handshake_replay``— complete one real handshake, then replay its
  captured proof against a FRESH nonce: ``bad_hello`` (requires the
  secret — this is the insider-byzantine case)

:data:`STYLE_BATTERIES` maps each style to the batteries that apply to
it; :meth:`AdversarialPeer.run_battery` runs them all and merges the
counts.  Batteries are synchronous with the defense they provoke: each
poisoned connection is held until the server closes it, which happens
strictly AFTER the strike/timeout is booked — so when a battery returns,
the guard's counters are settled (no sleeps, no polling).

Real sockets mean real deadlines, but everything here blocks on socket
timeouts — no wallclock reads, so the no-wallclock lint pins this file
with zero escapes.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import select
import socket
import struct
from typing import Dict, Iterable, Optional, Tuple

# Wire constants (mirrors of the servers' specs — see module docstring).
_COMM_HEADER = struct.Struct(">IQB")
_COMM_KIND_CONSENSUS = 0
_COMM_KIND_HELLO = 2
_COMM_HELLO_CONTEXT = b"consensus-tpu/hello/v1"
_SYNC_FRAME = struct.Struct(">I")
_SIDECAR_FRAME = struct.Struct(">IQ")
_SIDECAR_NONCE_LEN = 32
_SIDECAR_CLIENT_PROOF = b"ctpu-sidecar-client-v1"
_SIDECAR_TENANT_PROOF = b"ctpu-sidecar-tenant-v1"

#: A length claim far beyond every listener's 64 MiB cap.
HUGE_LENGTH = 2**31

STYLES = ("comm", "sync", "control", "sidecar")

#: Batteries that apply per listener style (``run_battery`` default set).
STYLE_BATTERIES = {
    "comm": (
        "never_hello", "connect_flood", "midframe_stall",
        "oversized_length", "wrong_hmac_flood",
    ),
    "sync": (
        "never_hello", "connect_flood", "midframe_stall",
        "oversized_length", "wrong_hmac_flood",
    ),
    "control": (
        "never_hello", "connect_flood", "midframe_stall",
        "oversized_length", "wrong_hmac_flood",
    ),
    "sidecar": (
        "never_hello", "connect_flood", "wrong_hmac_flood",
        "handshake_replay",
    ),
}


def _merge(into: Dict[str, int], more: Dict[str, int]) -> Dict[str, int]:
    for k, v in more.items():
        into[k] = into.get(k, 0) + v
    return into


class AdversarialPeer:
    """Drives one abuse vocabulary against one listener address.

    ``secret`` arms the insider batteries (``handshake_replay``, and
    ``oversized_length`` against a sidecar) — a byzantine peer that HOLDS
    the cluster secret must still be bounded by the guard.  ``claim_id``
    is the replica id forged into comm frames.

    ``close_wait`` bounds how long a battery waits for the server to
    close a poisoned connection; it must exceed the guard's
    handshake/progress deadlines (tests shorten those, not this).
    """

    def __init__(
        self,
        address: Tuple[str, int],
        style: str = "comm",
        *,
        secret: Optional[bytes] = None,
        tenant: Optional[str] = None,
        claim_id: int = 999,
        connect_timeout: float = 5.0,
        close_wait: float = 30.0,
    ) -> None:
        if style not in STYLES:
            raise ValueError(f"unknown listener style {style!r}")
        self.address = tuple(address)
        self.style = style
        self.secret = secret
        self.tenant = tenant
        self.claim_id = claim_id
        self.connect_timeout = connect_timeout
        self.close_wait = close_wait

    # --- plumbing -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self.address, timeout=self.connect_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _await_close(self, sock: socket.socket) -> None:
        """Drain until the server closes — i.e. until the defense we just
        provoked has been booked (servers strike, THEN close)."""
        sock.settimeout(self.close_wait)
        try:
            while sock.recv(4096):
                pass
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _recv_n(self, sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed during read")
            buf += chunk
        return buf

    def _read_comm_challenge(self, sock: socket.socket) -> bytes:
        """The comm acceptor speaks first: header + 16-byte nonce."""
        sock.settimeout(self.connect_timeout)
        header = self._recv_n(sock, _COMM_HEADER.size)
        length, _, kind = _COMM_HEADER.unpack(header)
        if kind != _COMM_KIND_HELLO or length > 64:
            raise ConnectionError("unexpected comm challenge")
        return self._recv_n(sock, length)

    def _comm_hello_proof(self, nonce: bytes, sender: int) -> bytes:
        if not self.secret:
            return b""
        return hmac.new(
            self.secret,
            _COMM_HELLO_CONTEXT + nonce + struct.pack(">Q", sender),
            hashlib.sha256,
        ).digest()

    # --- batteries ----------------------------------------------------------

    def never_hello(self, events: int = 1) -> Dict[str, int]:
        """Connect and go silent; the listener must drop us at its
        handshake deadline and book exactly one ``handshake_timeout``."""
        for _ in range(events):
            sock = self._connect()
            try:
                if self.style == "comm":
                    self._read_comm_challenge(sock)
                elif self.style == "sidecar":
                    self._recv_n(sock, _SIDECAR_NONCE_LEN)
            except OSError:
                pass
            self._await_close(sock)
        return {"handshake_timeout": events}

    def connect_flood(
        self, count: int = 8, probe_timeout: float = 0.5
    ) -> Dict[str, int]:
        """Open ``count`` simultaneous connections and measure how many
        the listener refuses.  A refusal is an immediate close before the
        server speaks; an admitted comm/sidecar connection receives the
        challenge, an admitted sync/control connection just stays open
        (``probe_timeout`` must be well under the guard's handshake
        deadline so silence is unambiguous).  Admitted connections are
        closed BEFORE the handshake deadline, so the flood itself books
        nothing but ``conn_rejected``."""
        socks = []
        for _ in range(count):
            try:
                socks.append(self._connect())
            except OSError:
                # Kernel-level refusal (backlog overflow) counts too.
                socks.append(None)
        admitted = 0
        rejected = sum(1 for s in socks if s is None)
        pending = {s for s in socks if s is not None}
        while pending:
            readable, _, _ = select.select(list(pending), [], [], probe_timeout)
            if not readable:
                admitted += len(pending)  # silent and open = admitted
                break
            for sock in readable:
                try:
                    data = sock.recv(64)
                except OSError:
                    data = b""
                if data:
                    admitted += 1
                else:
                    rejected += 1
                pending.discard(sock)
        for sock in socks:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        return {"conn_rejected": rejected, "admitted": admitted}

    def midframe_stall(self, events: int = 1) -> Dict[str, int]:
        """Start a frame, never finish it: a ``stall`` strike per event
        (this is the slow-loris the progress deadline exists for)."""
        if self.style == "sidecar":
            raise ValueError("midframe_stall battery does not apply to sidecar")
        for _ in range(events):
            sock = self._connect()
            try:
                if self.style == "comm":
                    self._read_comm_challenge(sock)
                    sock.sendall(b"\x00\x00\x00\x10\x00")  # 5 of 13 header bytes
                elif self.style == "sync":
                    sock.sendall(b"\x00\x00")  # 2 of 4 header bytes
                else:  # control: an unterminated JSON prefix
                    sock.sendall(b'{"op": "pi')
            except OSError:
                pass
            self._await_close(sock)
        return {"stall": events}

    def oversized_length(self, events: int = 1) -> Dict[str, int]:
        """Claim a :data:`HUGE_LENGTH` frame: an ``oversized`` strike per
        event, with no allocation on the server (cap-check-before-
        allocate).  For control (no length header) this is a line that
        overruns the server's ``max_line`` without a newline — pass the
        server's cap + 1 as ``payload_bytes`` via a configured test
        server; the default floods 256 KiB chunks until struck."""
        for _ in range(events):
            sock = self._connect()
            try:
                if self.style == "comm":
                    self._read_comm_challenge(sock)
                    sock.sendall(
                        _COMM_HEADER.pack(
                            HUGE_LENGTH, self.claim_id, _COMM_KIND_CONSENSUS
                        )
                    )
                elif self.style == "sync":
                    sock.sendall(_SYNC_FRAME.pack(HUGE_LENGTH))
                elif self.style == "sidecar":
                    self._sidecar_handshake(sock)  # insider: needs the secret
                    sock.sendall(_SIDECAR_FRAME.pack(HUGE_LENGTH, 0))
                else:  # control
                    chunk = b"x" * 65536
                    try:
                        while True:
                            sock.sendall(chunk)
                    except OSError:
                        pass  # server struck and closed mid-flood
            except OSError:
                pass
            self._await_close(sock)
        return {"oversized": events}

    def wrong_hmac_flood(self, events: int = 1) -> Dict[str, int]:
        """Flood failing proofs.  comm/sidecar: a HELLO/handshake answer
        that cannot verify (``bad_hello``).  sync/control have no
        handshake; the nearest equivalent is a structurally-invalid
        payload (``garbage`` — and control still answers its structured
        error, which this battery verifies by reading the reply)."""
        kind = "bad_hello" if self.style in ("comm", "sidecar") else "garbage"
        for _ in range(events):
            sock = self._connect()
            try:
                if self.style == "comm":
                    self._read_comm_challenge(sock)
                    proof = b"\x00" * 32  # cannot be a valid HMAC answer
                    sock.sendall(
                        _COMM_HEADER.pack(
                            len(proof), self.claim_id, _COMM_KIND_HELLO
                        ) + proof
                    )
                elif self.style == "sidecar":
                    self._recv_n(sock, _SIDECAR_NONCE_LEN)
                    sock.settimeout(self.connect_timeout)
                    sock.sendall(b"\x00" * (_SIDECAR_NONCE_LEN + 32))
                elif self.style == "sync":
                    payload = b"\xff" * 8  # no codec tag starts with 0xff
                    sock.sendall(_SYNC_FRAME.pack(len(payload)) + payload)
                else:  # control
                    sock.sendall(b"this is not json\n")
                    sock.settimeout(self.connect_timeout)
                    try:
                        reply = sock.recv(4096)
                        if reply and b"error" not in reply:
                            raise AssertionError(
                                "control server lost its error contract "
                                f"under garbage: {reply!r}"
                            )
                    except OSError:
                        pass
            except OSError:
                pass
            self._await_close(sock)
        return {kind: events}

    def handshake_replay(self, events: int = 1) -> Dict[str, int]:
        """Complete ONE honest handshake, then replay its captured proof
        against fresh nonces: each replay must fail verification
        (``bad_hello``) — proofs are bound to the acceptor's nonce."""
        if not self.secret:
            raise ValueError(
                "handshake_replay needs the secret (insider-byzantine case)"
            )
        if self.style == "comm":
            sock = self._connect()
            nonce = self._read_comm_challenge(sock)
            proof = self._comm_hello_proof(nonce, self.claim_id)
            sock.sendall(
                _COMM_HEADER.pack(len(proof), self.claim_id, _COMM_KIND_HELLO)
                + proof
            )
            sock.close()  # honest handshake done; now replay its proof
            for _ in range(events):
                replay = self._connect()
                try:
                    self._read_comm_challenge(replay)  # FRESH nonce, ignored
                    replay.sendall(
                        _COMM_HEADER.pack(
                            len(proof), self.claim_id, _COMM_KIND_HELLO
                        ) + proof
                    )
                except OSError:
                    pass
                self._await_close(replay)
        elif self.style == "sidecar":
            sock = self._connect()
            transcript = self._sidecar_handshake(sock)
            sock.close()
            for _ in range(events):
                replay = self._connect()
                try:
                    self._recv_n(replay, _SIDECAR_NONCE_LEN)  # fresh nonce
                    replay.sendall(transcript)  # stale client_nonce + answer
                except OSError:
                    pass
                self._await_close(replay)
        else:
            raise ValueError(
                f"handshake_replay battery does not apply to {self.style!r}"
            )
        return {"bad_hello": events}

    def _sidecar_handshake(self, sock: socket.socket) -> bytes:
        """Complete the sidecar's mutual handshake (requires the secret);
        returns the ``client_nonce + answer`` transcript for replays."""
        if not self.secret:
            raise ValueError("sidecar insider batteries need the secret")
        server_nonce = self._recv_n(sock, _SIDECAR_NONCE_LEN)
        client_nonce = b"\x5a" * _SIDECAR_NONCE_LEN
        mac = hmac.new(self.secret, digestmod=hashlib.sha256)
        if self.tenant is None:
            for part in (_SIDECAR_CLIENT_PROOF, server_nonce, client_nonce):
                mac.update(part)
        else:
            for part in (
                _SIDECAR_TENANT_PROOF, self.tenant.encode(),
                server_nonce, client_nonce,
            ):
                mac.update(part)
        transcript = client_nonce + mac.digest()
        sock.settimeout(self.connect_timeout)
        sock.sendall(transcript)
        self._recv_n(sock, 32)  # server proof (unchecked: we're the liar here)
        return transcript

    # --- the full vocabulary ------------------------------------------------

    def run_battery(
        self, names: Optional[Iterable[str]] = None, *, events: int = 1
    ) -> Dict[str, int]:
        """Run ``names`` (default: every battery that applies to this
        style) and merge the provoked-event counts."""
        provoked: Dict[str, int] = {}
        for name in names if names is not None else STYLE_BATTERIES[self.style]:
            battery = getattr(self, name)
            if name == "connect_flood":
                _merge(provoked, battery())
            else:
                _merge(provoked, battery(events))
        return provoked


def control_probe_reply(address: Tuple[str, int], op: str = "ping") -> dict:
    """A minimal HONEST control request (used by tests to show the plane
    still answers while a battery runs)."""
    with socket.create_connection(address, timeout=5.0) as sock:
        sock.sendall(json.dumps({"op": op}).encode() + b"\n")
        sock.settimeout(5.0)
        buf = b""
        while b"\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0] or b"{}")


__all__ = [
    "AdversarialPeer",
    "HUGE_LENGTH",
    "STYLES",
    "STYLE_BATTERIES",
    "control_probe_reply",
]
