"""Test/bench application with REAL crypto on every signature path.

Two layers over :class:`consensus_tpu.testing.app.TestApp` (whose crypto is
trivial byte-compares):

* :class:`CryptoApp` — replica identity: proposals and consensus messages
  are signed by a per-replica key and verified through a batch-verify
  engine (the TPU seam).  The verifier half is injected so Ed25519 and
  ECDSA-P256 share one app class.
* :class:`SignedRequestApp` — additionally, CLIENT requests carry a
  signature; followers batch-verify every request in a proposal in ONE
  engine call (``verify_proposal``).  This is the integrated equivalent of
  the reference's per-request VerifyRequest loop inside proposal
  verification (reference internal/bft/view.go:602-647 verifies requests
  and prev-commit signatures sequentially per proposal).

Request wire format (SignedRequestApp):
``client_idx(4) || seq(8) || body || signature(64)`` — signed over
everything before the signature with the client's key.
"""

from __future__ import annotations

import struct
from typing import Mapping, Optional, Sequence

from consensus_tpu.models.verifier import Ed25519VerifierMixin
from consensus_tpu.testing.app import TestApp, pack_batch, unpack_batch
from consensus_tpu.types import QuorumCert, RequestInfo

_REQ_TAG = b"ctpu/request"


class SigOnlyVerifier(Ed25519VerifierMixin):
    """Signature-only half of the Verifier port: the application half
    (proposal/request semantics) lives in the app that wraps this —
    CryptoApp delegates only the four signature paths here."""

    def verify_proposal(self, proposal):
        raise NotImplementedError  # app half lives in CryptoApp

    def verify_request(self, raw):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []


class CryptoApp(TestApp):
    """TestApp with the trivial crypto swapped for a real signer/verifier."""

    def __init__(self, node_id, cluster, signer, verifier):
        super().__init__(node_id, cluster)
        self._signer = signer
        self._verifier = verifier
        # With a randomized batch engine behind the verifier, the Verifier
        # base class coalesces multi-batch calls through this delegate in
        # ONE launch (api/deps.py); strict engines keep the per-group loop
        # bit-for-bit.
        self.multi_batch_delegate = verifier
        self.batch_verify_enabled = getattr(verifier, "batch_verify_enabled", False)

    # Signer
    def sign(self, data):
        return self._signer.sign(data)

    def sign_proposal(self, proposal, aux=b""):
        return self._signer.sign_proposal(proposal, aux)

    # Verifier signature paths
    def verify_consenter_sig(self, signature, proposal):
        return self._verifier.verify_consenter_sig(signature, proposal)

    def verify_consenter_sigs_batch(self, signatures, proposal):
        return self._verifier.verify_consenter_sigs_batch(signatures, proposal)

    def verify_signature(self, signature):
        return self._verifier.verify_signature(signature)

    def auxiliary_data(self, msg):
        return self._verifier.auxiliary_data(msg)

    # Half-aggregated quorum certs: delegate straight to the crypto half.
    @property
    def supports_cert_aggregation(self):
        return getattr(self._verifier, "supports_cert_aggregation", False)

    def aggregate_cert(self, proposal, signatures):
        agg = getattr(self._verifier, "aggregate_cert", None)
        return agg(proposal, signatures) if agg is not None else None

    def verify_aggregate_cert(self, cert, proposal):
        vac = getattr(self._verifier, "verify_aggregate_cert", None)
        return vac(cert, proposal) if vac is not None else None


class ClientKeyring:
    """A set of client signing keys + the matching verification registry."""

    def __init__(self, signers: Sequence) -> None:
        self.signers = list(signers)
        self.public_keys = [s.public_bytes for s in self.signers]

    def make_request(self, client_idx: int, seq: int, body: bytes = b"x" * 64) -> bytes:
        head = struct.pack(">IQ", client_idx, seq) + body
        return head + self.signers[client_idx].sign_raw(_REQ_TAG + head)


class SignedRequestApp(CryptoApp):
    """CryptoApp whose client requests carry signatures, batch-verified per
    proposal through the engine — the TPU-thesis hot path."""

    def __init__(self, node_id, cluster, signer, verifier, *,
                 client_keys: Sequence[bytes], engine, sig_len: int = 64):
        super().__init__(node_id, cluster, signer, verifier)
        self._client_keys = list(client_keys)
        self._engine = engine
        self._sig_len = sig_len

    def _split(self, raw: bytes) -> tuple[int, int, bytes, bytes]:
        if len(raw) < 12 + self._sig_len:
            raise ValueError("request too short")
        client_idx, seq = struct.unpack(">IQ", raw[:12])
        if client_idx >= len(self._client_keys):
            raise ValueError(f"unknown client {client_idx}")
        return client_idx, seq, raw[: -self._sig_len], raw[-self._sig_len :]

    def _request_info(self, raw: bytes) -> RequestInfo:
        client_idx, seq, _, _ = self._split(raw)
        return RequestInfo(client_id=str(client_idx), request_id=str(seq))

    # RequestInspector-ish surface (pool ingress id computation). The pool
    # uses an inspector object; TestApp exposes self.inspector — override
    # with ourselves.
    def request_id(self, raw: bytes) -> RequestInfo:
        return self._request_info(raw)

    @property
    def inspector(self):
        return self

    @inspector.setter
    def inspector(self, value):  # TestApp.__init__ assigns; ignore
        pass

    def verify_request(self, raw: bytes) -> RequestInfo:
        client_idx, seq, signed, sig = self._split(raw)
        ok = self._engine.verify_batch(
            [_REQ_TAG + signed], [sig], [self._client_keys[client_idx]]
        )
        if not ok[0]:
            raise ValueError("bad request signature")
        return RequestInfo(client_id=str(client_idx), request_id=str(seq))

    def _collect(self, raws, *, tolerate_parse_errors: bool):
        """(messages, sigs, keys, infos, parsed) for a list of raw requests;
        ``parsed[i]`` is the batch index of ``raws[i]`` or None if it failed
        to parse (only when tolerated)."""
        messages, sigs, keys, infos = [], [], [], []
        parsed = []
        for raw in raws:
            try:
                client_idx, seq, signed, sig = self._split(raw)
            except ValueError:
                if not tolerate_parse_errors:
                    raise
                parsed.append(None)
                continue
            parsed.append(len(messages))
            messages.append(_REQ_TAG + signed)
            sigs.append(sig)
            keys.append(self._client_keys[client_idx])
            infos.append(RequestInfo(client_id=str(client_idx), request_id=str(seq)))
        return messages, sigs, keys, infos, parsed

    def verify_requests_batch(self, raw_requests) -> "list":
        """ONE engine call for a list of raw requests (the pool's
        re-validation burst path — controller.maybe_prune_revoked_requests)."""
        messages, sigs, keys, infos, parsed = self._collect(
            raw_requests, tolerate_parse_errors=True
        )
        if not messages:
            return [None] * len(raw_requests)
        ok = self._engine.verify_batch(messages, sigs, keys)
        return [
            infos[j] if (j is not None and ok[j]) else None for j in parsed
        ]

    def verify_proposal(self, proposal) -> Sequence[RequestInfo]:
        """Batch-verify EVERY request signature in the proposal in one
        engine call (vs the reference's sequential per-request loop)."""
        messages, sigs, keys, infos, _ = self._collect(
            unpack_batch(proposal.payload), tolerate_parse_errors=False
        )
        if messages:
            ok = self._engine.verify_batch(messages, sigs, keys)
            if not ok.all():
                raise ValueError("proposal carries an invalid request signature")
        return infos

    def verify_proposal_and_prev_commits(self, proposal, prev_commits, prev_proposal):
        """Fuse the proposal's request-signature wave and the previous
        decision's commit cert into ONE engine launch (ROADMAP item 3a tail:
        request waves coalesce like consenter certs).  Only when both waves
        run on the SAME engine — mixing engines inside one wave would break
        the SAFETY.md §7 no-mixed-engine rule — and errors keep the split
        path's order: request failures raise before any cert verdict is
        consumed."""
        if getattr(self._verifier, "engine", None) is not self._engine:
            return super().verify_proposal_and_prev_commits(
                proposal, prev_commits, prev_proposal
            )
        if isinstance(prev_commits, QuorumCert):
            # A half-aggregated cert verifies through its own MSM launch —
            # it has no per-signature triples to splice into the request
            # wave; the split path routes it via verify_aggregate_cert.
            return super().verify_proposal_and_prev_commits(
                proposal, prev_commits, prev_proposal
            )
        messages, sigs, keys, infos, _ = self._collect(
            unpack_batch(proposal.payload), tolerate_parse_errors=False
        )
        n_req = len(messages)
        c_msgs, c_sigs, c_keys, known = self._verifier.consenter_sig_triples(
            prev_commits, prev_proposal
        )
        messages += c_msgs
        sigs += c_sigs
        keys += c_keys
        if not messages:
            return infos, []
        ok = self._engine.verify_batch(messages, sigs, keys)
        if n_req and not ok[:n_req].all():
            raise ValueError("proposal carries an invalid request signature")
        cert_results = [
            prev_commits[i].msg if (known[i] and ok[n_req + i]) else None
            for i in range(len(prev_commits))
        ]
        return infos, cert_results


__all__ = ["CryptoApp", "SigOnlyVerifier", "SignedRequestApp", "ClientKeyring"]
