"""Deterministic crash-point fault injection for the durability spine.

The production modules (``wal/log.py``, ``core/state.py``,
``net/transport.py``, ``net/sidecar.py``) expose *named crash points*:
places where a real process can die (mid-frame, between fsyncs, between
the two halves of the view changer's endorsement append) or where real
I/O can fail (socket writes, short reads).  Each seam is a no-op unless a
:class:`FaultPlan` is armed — production code pays one ``is None``
attribute check per seam, no lock and no extra fsync.

The seam modules do NOT import this module (that would invert the
production→testing dependency); they only call methods on whatever plan
object the test attached.  The canonical catalog of point names therefore
lives HERE, and :meth:`FaultPlan.trip` validates every name it is handed
against it — a typo'd seam explodes the first time any plan is armed, and
the crash-matrix coverage gate (tests/test_crash_matrix.py) fails if a
cataloged point is never hit at all.

Determinism: a plan fires on the *Nth hit* of one named point.  Replaying
a matrix failure needs only the printed (crash point, hit, schedule seed)
triple — there is no wall clock and no unseeded randomness anywhere in
the injection path.
"""

from __future__ import annotations

import threading
from collections import Counter
from typing import Callable, Optional


class SimulatedCrash(Exception):
    """Injected process death.  Raised by a crash seam when its plan fires;
    the plan's ``on_crash`` hook (typically ``Node.crash``) has already torn
    the replica down by the time this propagates, so even an intermediate
    ``except Exception`` swallowing it cannot resurrect the process — every
    later seam touch by the zombie frame raises again."""


class InjectedIOError(OSError):
    """Injected transport-level I/O failure (a *fault*, not a death): the
    component's normal OSError handling must absorb it — drop the link,
    reconnect, fall back — exactly as for a real socket error."""


#: name -> one-line description.  The single source of truth for what crash
#: points exist; the seams reference these names as string literals and
#: ``FaultPlan.trip`` rejects anything not listed.
CRASH_POINTS: dict[str, str] = {}


def register_crash_point(name: str, description: str = "") -> str:
    CRASH_POINTS[name] = description
    return name


def registered_crash_points(domain: Optional[str] = None) -> tuple[str, ...]:
    """All cataloged point names, optionally filtered by the leading
    dot-separated component (``wal`` / ``state`` / ``net`` / ``sidecar``)."""
    names = sorted(CRASH_POINTS)
    if domain is not None:
        names = [n for n in names if n.split(".", 1)[0] == domain]
    return tuple(names)


# --- the catalog -----------------------------------------------------------

# wal/log.py (real file-backed WAL only; MemWAL runs exercise the state.*
# points instead).
register_crash_point(
    "wal.append.pre_write", "before any byte of the record frame is written"
)
register_crash_point(
    "wal.append.torn_write",
    "half the record frame written + flushed, then death (repair must chop)",
)
register_crash_point(
    "wal.fsync.pre",
    "record written + flushed but not fsynced (bytes may still survive: the"
    " OS page cache outlives a process crash)",
)
register_crash_point("wal.fsync.post", "record durable, append never returned")
register_crash_point(
    "wal.segment.roll", "record written, death before rolling to a new segment"
)

# core/state.py — one pre/post pair per save() record kind, plus the view
# changer's endorsement append (the _commit_in_flight [proposed, commit]
# tail) labeled distinctly so the buried-vote restore gap stays pinned.
for _kind in ("proposed", "commit", "viewchange", "newview",
              "endorsement_proposed", "endorsement_commit"):
    register_crash_point(
        f"state.save.{_kind}.pre",
        f"before persisting a {_kind} record (nothing happened)",
    )
    register_crash_point(
        f"state.save.{_kind}.post",
        f"after the {_kind} record is durable (deferred sends already fired"
        " in per-append-fsync mode)",
    )

# net/transport.py + net/sidecar.py — I/O faults, not process deaths.
register_crash_point("net.send.io_error", "peer socket write fails")
register_crash_point("net.recv.short_read", "inbound link dies mid-frame")
register_crash_point("sidecar.send.io_error", "sidecar request write fails")
register_crash_point("sidecar.recv.short_read", "sidecar response link dies")

# sync/ — the catch-up path (client + transport).  The client seam is a
# process death at the worst moment (a chunk applied, the next not yet
# fetched — resume must start from the store height, not refetch or skip);
# the transport seams are survivable fetch failures the peer-scoring loop
# must absorb.
register_crash_point(
    "sync.client.chunk_boundary",
    "death right after a verified chunk is applied to the store",
)
register_crash_point(
    "sync.fetch.io_error", "sync fetch fails mid-flight (socket-level error)"
)
register_crash_point(
    "sync.chunk.corrupt",
    "a fetched chunk's bytes arrive corrupted (decode must fail closed)",
)


class FaultPlan:
    """One replica's armed fault: fire at the ``on_hit``-th hit of
    ``crash_at``.

    ``crash()`` seams mark the plan dead, run ``on_crash`` (the harness
    wires this to the node teardown), and raise :class:`SimulatedCrash`;
    ``io_error()`` seams raise :class:`InjectedIOError` without killing the
    plan (an I/O fault is survivable).  ``trip()`` is the raw
    count-and-check for seams that need custom behavior (torn writes,
    short reads).

    ``hits`` counts every visit to every point — armed or not — so the
    matrix's coverage-of-injection gate can prove each registered point is
    actually reachable.  Thread-safe: transport/sidecar seams run on their
    own threads.
    """

    def __init__(
        self,
        crash_at: Optional[str] = None,
        *,
        on_hit: int = 1,
        on_crash: Optional[Callable[[], None]] = None,
        label: str = "",
    ) -> None:
        if crash_at is not None and crash_at not in CRASH_POINTS:
            raise ValueError(f"unknown crash point {crash_at!r}")
        if on_hit < 1:
            raise ValueError("on_hit is 1-based")
        self.crash_at = crash_at
        self.on_hit = on_hit
        self.on_crash = on_crash
        self.label = label
        self.hits: Counter = Counter()
        self.dead = False
        #: (point, hit_number) once the plan has fired.
        self.fired: Optional[tuple[str, int]] = None
        #: Optional decision-lifecycle tracer (trace.Tracer): the armed hit
        #: emits a ``fault.fired`` instant so a trace shows exactly which
        #: seam fired, on the same deterministic clock as the phase spans.
        self.tracer = None
        #: Optional flight recorder (obs.FlightRecorder): the armed hit calls
        #: ``recorder.on_fault_fired(point, hit)`` BEFORE on_crash tears the
        #: node down, so the bundle captures the pre-crash state.
        self.recorder = None
        self._lock = threading.Lock()

    def _count_hit(self, point: str, *, die: bool) -> tuple[bool, int]:
        """One atomic hit of ``point``: count it and, when this hit is the
        armed one, mark it fired (and dead, for crash seams) in the SAME
        critical section — transport/sidecar seams race the consensus
        thread, and a dead-check outside the lock lets two threads both
        observe the firing (or a zombie slip one last effect through).
        Returns ``(armed, hit_number)``; ``hit_number`` is 0 when the call
        was a zombie touch (``die`` and already dead)."""
        if point not in CRASH_POINTS:
            raise ValueError(f"seam reports unregistered crash point {point!r}")
        with self._lock:
            if die and self.dead:
                return False, 0
            self.hits[point] += 1
            n = self.hits[point]
            if self.dead or self.fired is not None:
                return False, n
            armed = point == self.crash_at and n == self.on_hit
            if armed:
                self.fired = (point, n)
                if die:
                    self.dead = True
        if armed:
            tracer = self.tracer
            if tracer is not None and tracer.enabled:
                tracer.instant("fault", "fault.fired", point=point, hit=n)
            recorder = self.recorder
            if recorder is not None:
                try:
                    recorder.on_fault_fired(point, n)
                except Exception:
                    pass
        return armed, n

    def trip(self, point: str) -> bool:
        """Count one hit of ``point``; True when this hit is the armed one."""
        armed, _ = self._count_hit(point, die=False)
        return armed

    def will_fire(self, point: str) -> bool:
        """Whether the NEXT hit of ``point`` would fire (peek, no count) —
        for seams that must do damage (write torn bytes) before dying."""
        with self._lock:
            return (
                not self.dead
                and self.fired is None
                and point == self.crash_at
                and self.hits[point] + 1 == self.on_hit
            )

    def crash(self, point: str) -> None:
        """Crash-type seam: die here when armed; zombie frames die again.
        The dead-check, hit count, and dead-set are one atomic step
        (:meth:`_count_hit`), so concurrent seam threads agree on exactly
        one firing and no post-death touch slips through."""
        armed, n = self._count_hit(point, die=True)
        if n == 0:
            raise SimulatedCrash(f"zombie process touched {point}")
        if armed:
            if self.on_crash is not None:
                self.on_crash()
            raise SimulatedCrash(
                f"injected crash at {point} (hit {self.on_hit})"
            )

    def io_error(self, point: str) -> None:
        """I/O-fault seam: raise a survivable OSError when armed."""
        if self.trip(point):
            raise InjectedIOError(f"injected I/O error at {point}")

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (
            f"FaultPlan({self.crash_at!r}, on_hit={self.on_hit}, "
            f"fired={self.fired}, dead={self.dead}, label={self.label!r})"
        )


__all__ = [
    "FaultPlan",
    "SimulatedCrash",
    "InjectedIOError",
    "CRASH_POINTS",
    "register_crash_point",
    "registered_crash_points",
]
