"""Deterministic structure-aware wire fuzzer.

A Byzantine peer controls every byte on the wire, so the codec's contract
is binary: ANY input either decodes to a message that re-encodes
canonically, or raises :class:`~consensus_tpu.wire.codec.CodecError` —
never another exception type, never a hang, never an allocation
proportional to a lied-about length field.

This module enforces that contract without a ``hypothesis`` dependency
(the old fuzz tests silently skipped wherever the package was absent —
which was every CI environment that mattered).  Everything is driven by
``random.Random(seed)``:

* the **seed corpus** is one real encoding per codec case — every wire
  tag 1–15 and every saved tag 1–5, including the version-dependent
  layouts (wire v2 cert-carrying PrePrepare/SyncChunk/QuorumCert, saved
  v2 unverified records, saved v3 cert-carrying records, saved v4 2PC
  records) — produced by the codec itself, so the fuzzer can never drift
  from the format it is attacking;
* **mutation operators** (:data:`MUTATION_OPERATORS`) are structure-aware:
  truncation, bit flips, length-field lies, tag swaps, envelope nesting,
  field repetition, and huge-length headers that probe the
  allocation-before-validation class of bug specifically;
* the run is **byte-identical per seed**: :class:`FuzzReport` carries a
  SHA-256 over the corpus and over every mutated frame in generation
  order, so two same-seed runs must produce equal digests (pinned by
  tests/test_fuzz.py).

No wall clock, no I/O — pure bytes in, verdicts out.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
import struct
from typing import Callable, Dict, List, Optional, Tuple

from consensus_tpu.types import Proposal, QuorumCert, Signature
from consensus_tpu.wire.codec import (
    CodecError,
    decode_message,
    decode_saved,
    encode_message,
    encode_saved,
)
from consensus_tpu.wire.messages import (
    Commit,
    EpochTagged,
    HeartBeat,
    HeartBeatResponse,
    NewView,
    PrePrepare,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SavedNewView,
    SavedTwoPC,
    SavedViewChange,
    SignedViewData,
    StateTransferRequest,
    StateTransferResponse,
    SyncChunk,
    SyncRequest,
    SyncSnapshotMeta,
    ViewChange,
    ViewMetadata,
)

MUTATION_OPERATORS = (
    "truncate",
    "bit_flip",
    "length_lie",
    "tag_swap",
    "envelope_nest",
    "field_repeat",
    "huge_length",
)

_PROPOSAL = Proposal(
    header=b"hdr", payload=b"batch-bytes", metadata=b"md",
    verification_sequence=7,
)
_SIG = Signature(id=3, value=b"\x01\x02", msg=b"aux")
_BIG_SIG = Signature(id=2**63 + 5, value=b"v" * 64, msg=b"")
_CERT = QuorumCert(
    signer_ids=(1, 2, 3),
    rs=(b"\x11" * 32, b"\x22" * 32, b"\x33" * 32),
    s_agg=b"\x44" * 32,
    aux_table=(b"aux-a", b"aux-b"),
    aux_index=(0, 1, 0),
)
_PRE_PREPARE_V1 = PrePrepare(
    view=1, seq=2, proposal=_PROPOSAL,
    prev_commit_signatures=(_SIG, _BIG_SIG),
)
_PRE_PREPARE_V2 = PrePrepare(
    view=1, seq=2, proposal=_PROPOSAL, prev_commit_signatures=_CERT
)
_COMMIT = Commit(view=9, seq=10, digest="ff00", signature=_SIG)
_VIEW_METADATA = ViewMetadata(
    view_id=4, latest_sequence=17, decisions_in_view=2, black_list=(3, 9),
    prev_commit_signature_digest=b"\xaa" * 32,
)

#: Every codec case the corpus seeds from: (key, encoder, message).  Keys
#: are stable identifiers — tests assert the tag coverage against the
#: codec's own tables, so a new message kind that forgets to register here
#: fails loudly.
_WIRE_CASES: Tuple[Tuple[str, object], ...] = (
    ("wire/tag01/v1/PrePrepare", _PRE_PREPARE_V1),
    ("wire/tag01/v2/PrePrepare", _PRE_PREPARE_V2),
    ("wire/tag02/v1/Prepare", Prepare(view=1, seq=2, digest="abcd", assist=True)),
    ("wire/tag03/v1/Commit", _COMMIT),
    ("wire/tag04/v1/ViewChange", ViewChange(next_view=4, reason="heartbeat timeout")),
    ("wire/tag05/v1/SignedViewData",
     SignedViewData(raw_view_data=b"vd-bytes", signer=2, signature=b"s")),
    ("wire/tag06/v1/NewView", NewView(signed_view_data=(
        SignedViewData(raw_view_data=b"a", signer=1, signature=b"x"),
        SignedViewData(raw_view_data=b"b", signer=2, signature=b"y"),
    ))),
    ("wire/tag07/v1/HeartBeat", HeartBeat(view=3, seq=11)),
    ("wire/tag08/v1/HeartBeatResponse", HeartBeatResponse(view=5)),
    ("wire/tag09/v1/StateTransferRequest", StateTransferRequest()),
    ("wire/tag10/v1/StateTransferResponse",
     StateTransferResponse(view_num=2, sequence=30)),
    ("wire/tag11/v1/SyncRequest", SyncRequest(from_seq=1, to_seq=9)),
    ("wire/tag12/v1/SyncChunk", SyncChunk(
        from_seq=1, height=2, decisions=(_PROPOSAL, _PROPOSAL),
        quorum_certs=((_SIG,), (_SIG, _BIG_SIG)),
    )),
    ("wire/tag12/v2/SyncChunk", SyncChunk(
        from_seq=1, height=2, decisions=(_PROPOSAL,), quorum_certs=(_CERT,),
    )),
    ("wire/tag13/v1/SyncSnapshotMeta",
     SyncSnapshotMeta(height=40, last_digest="deadbeef")),
    ("wire/tag14/v1/EpochTagged", EpochTagged(epoch=6, msg=HeartBeat(view=3, seq=11))),
    ("wire/tag15/v2/QuorumCert", _CERT),
)

_SAVED_CASES: Tuple[Tuple[str, object], ...] = (
    ("saved/tag01/v1/ProposedRecord", ProposedRecord(
        pre_prepare=PrePrepare(view=1, seq=2, proposal=_PROPOSAL),
        prepare=Prepare(view=1, seq=2, digest=_PROPOSAL.digest()),
    )),
    ("saved/tag01/v2/ProposedRecord", ProposedRecord(
        pre_prepare=PrePrepare(view=1, seq=2, proposal=_PROPOSAL),
        prepare=Prepare(view=1, seq=2, digest=_PROPOSAL.digest()),
        verified=False,
    )),
    ("saved/tag01/v3/ProposedRecord", ProposedRecord(
        pre_prepare=_PRE_PREPARE_V2,
        prepare=Prepare(view=1, seq=2, digest=_PROPOSAL.digest()),
    )),
    ("saved/tag02/v1/SavedCommit", SavedCommit(commit=_COMMIT)),
    ("saved/tag02/v3/SavedCommit", SavedCommit(commit=_COMMIT, cert=_CERT)),
    ("saved/tag03/v1/SavedNewView", SavedNewView(view_metadata=_VIEW_METADATA)),
    ("saved/tag04/v1/SavedViewChange",
     SavedViewChange(view_change=ViewChange(next_view=6, reason=""))),
    ("saved/tag05/v4/SavedTwoPC", SavedTwoPC(
        txid="tx-7", phase="prepared", groups=("g0", "g1"), coordinator="g0",
    )),
)


def seed_corpus() -> Dict[str, bytes]:
    """Real encodings of every codec case, keyed by the stable case id.
    Deterministic by construction — the codec is deterministic and the
    exemplar messages are module constants."""
    corpus: Dict[str, bytes] = {}
    for key, msg in _WIRE_CASES:
        corpus[key] = encode_message(msg)
    for key, msg in _SAVED_CASES:
        corpus[key] = encode_saved(msg)
    return corpus


# --- mutation operators ----------------------------------------------------


def _op_truncate(rng: random.Random, base: bytes) -> bytes:
    if not base:
        return base
    return base[: rng.randrange(len(base))]


def _op_bit_flip(rng: random.Random, base: bytes) -> bytes:
    if not base:
        return base
    raw = bytearray(base)
    for _ in range(rng.randint(1, 8)):
        raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
    return bytes(raw)


def _op_length_lie(rng: random.Random, base: bytes) -> bytes:
    """Overwrite 4 bytes somewhere with a lying u32 — the codec's length
    prefixes live at data-dependent offsets, so a random placement hits
    blob/seq counts often enough while also exercising misaligned lies."""
    if len(base) < 4:
        return base + b"\xff\xff\xff\xff"
    raw = bytearray(base)
    pos = rng.randrange(len(raw) - 3)
    lie = rng.choice(
        (0, 1, len(base), len(base) * 2, 0xFFFF, 0x7FFFFFFF, 0xFFFFFFFF)
    )
    raw[pos:pos + 4] = struct.pack(">I", lie)
    return bytes(raw)


def _op_tag_swap(rng: random.Random, base: bytes) -> bytes:
    """Rewrite an envelope byte (version, domain, or tag) — cross-domain
    and unknown-tag probes."""
    if len(base) < 3:
        return base
    raw = bytearray(base)
    raw[rng.randrange(3)] = rng.randrange(256)
    return bytes(raw)


def _op_envelope_nest(rng: random.Random, base: bytes) -> bytes:
    """Wrap the frame as the inner blob of a synthetic EpochTagged
    envelope (tag 14), or double the envelope header in place.  A valid
    wire frame nested this way must decode and round-trip; a nested
    EpochTagged must be rejected (the codec forbids two levels)."""
    if rng.random() < 0.5:
        epoch = rng.randrange(2**32)
        return (
            bytes((1, 0x57, 14))
            + struct.pack(">Q", epoch)
            + struct.pack(">I", len(base))
            + base
        )
    return base[:3] + base


def _op_field_repeat(rng: random.Random, base: bytes) -> bytes:
    if len(base) < 2:
        return base + base
    i = rng.randrange(len(base))
    j = rng.randrange(len(base))
    lo, hi = min(i, j), max(i, j) + 1
    return base[:hi] + base[lo:hi] + base[hi:]


def _op_huge_length(rng: random.Random, base: bytes) -> bytes:
    """Plant a 2^31..2^32-1 length header: the allocation-before-
    validation probe.  A codec that trusts it would try to materialize
    gigabytes; ours must raise CodecError from its have-vs-need check."""
    raw = bytearray(base + b"\x00" * 8)
    pos = rng.randrange(len(raw) - 7)
    raw[pos:pos + 4] = struct.pack(
        ">I", rng.choice((2**31, 2**31 + 1, 2**32 - 1))
    )
    return bytes(raw)


_OPERATOR_FNS: Dict[str, Callable[[random.Random, bytes], bytes]] = {
    "truncate": _op_truncate,
    "bit_flip": _op_bit_flip,
    "length_lie": _op_length_lie,
    "tag_swap": _op_tag_swap,
    "envelope_nest": _op_envelope_nest,
    "field_repeat": _op_field_repeat,
    "huge_length": _op_huge_length,
}


def mutate(rng: random.Random, base: bytes, op: str) -> bytes:
    """Apply one named operator; unknown names fail loudly."""
    fn = _OPERATOR_FNS.get(op)
    if fn is None:
        raise ValueError(f"unknown mutation operator {op!r}")
    return fn(rng, base)


# --- the oracle ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FuzzEscape:
    """One oracle violation: an input whose decode (or re-encode) raised
    something other than CodecError, or round-tripped non-canonically."""

    case: str
    op: str
    frame_hex: str
    error: str


@dataclasses.dataclass(frozen=True)
class FuzzReport:
    seed: int
    frames: int
    decoded: int
    rejected: int
    escapes: Tuple[FuzzEscape, ...]
    corpus_digest: str
    stream_digest: str
    frames_per_case: Dict[str, int]

    def ok(self) -> bool:
        return not self.escapes


def check_frame(buf: bytes, *, saved: bool = False) -> Optional[str]:
    """The oracle for one frame: None when the contract held (decoded
    canonically or rejected with CodecError), else a description of the
    escape."""
    decode = decode_saved if saved else decode_message
    encode = encode_saved if saved else encode_message
    try:
        msg = decode(buf)
    except CodecError:
        return None
    except Exception as exc:  # the contract: CodecError or nothing
        return f"decode escaped with {type(exc).__name__}: {exc}"
    try:
        again = decode(encode(msg))
    except Exception as exc:
        return f"re-encode of decoded message failed: {type(exc).__name__}: {exc}"
    if again != msg:
        return "non-canonical round-trip"
    return None


def run_fuzz(
    seed: int,
    *,
    frames_per_case: int = 10_000,
    operators: Tuple[str, ...] = MUTATION_OPERATORS,
) -> FuzzReport:
    """Fuzz every corpus case with ``frames_per_case`` mutated frames.

    Byte-identical per seed: the mutation stream is a pure function of
    ``(seed, frames_per_case, operators)``; ``stream_digest`` commits to
    every generated frame in order.
    """
    corpus = seed_corpus()
    corpus_hash = hashlib.sha256()
    for key in sorted(corpus):
        corpus_hash.update(key.encode())
        corpus_hash.update(struct.pack(">I", len(corpus[key])))
        corpus_hash.update(corpus[key])
    rng = random.Random(seed)
    stream_hash = hashlib.sha256()
    decoded = rejected = frames = 0
    escapes: List[FuzzEscape] = []
    per_case: Dict[str, int] = {}
    for key in sorted(corpus):
        base = corpus[key]
        saved = key.startswith("saved/")
        for _ in range(frames_per_case):
            op = operators[rng.randrange(len(operators))]
            frame = mutate(rng, base, op)
            if rng.random() < 0.25:  # stacked mutations find deeper paths
                op2 = operators[rng.randrange(len(operators))]
                frame = mutate(rng, frame, op2)
                op = f"{op}+{op2}"
            stream_hash.update(struct.pack(">I", len(frame)))
            stream_hash.update(frame)
            frames += 1
            per_case[key] = per_case.get(key, 0) + 1
            verdict = check_frame(frame, saved=saved)
            if verdict is None:
                # Count decodes vs rejects for the report (re-running the
                # decode is cheaper than widening check_frame's return).
                try:
                    (decode_saved if saved else decode_message)(frame)
                except CodecError:
                    rejected += 1
                else:
                    decoded += 1
            elif len(escapes) < 32:  # enough to debug, bounded to report
                escapes.append(FuzzEscape(
                    case=key, op=op, frame_hex=frame[:512].hex(),
                    error=verdict,
                ))
    return FuzzReport(
        seed=seed,
        frames=frames,
        decoded=decoded,
        rejected=rejected,
        escapes=tuple(escapes),
        corpus_digest=corpus_hash.hexdigest(),
        stream_digest=stream_hash.hexdigest(),
        frames_per_case=per_case,
    )


__all__ = [
    "MUTATION_OPERATORS",
    "FuzzEscape",
    "FuzzReport",
    "check_frame",
    "mutate",
    "run_fuzz",
    "seed_corpus",
]
