"""Membership epochs: add/remove-node as an ordered, quorum-certified
decision with a first-class lifecycle.

A membership change in this system is an ordinary decision — it is batched,
three-phase ordered, and commit-certified like any other proposal, and it
surfaces to the replica through the existing ``Reconfig`` path
(``Controller.decide`` on the deliver path, ``Controller._do_sync`` on the
sync-learned path).  What was missing before this module is an owner for the
*arithmetic* of that lifecycle: which committee certifies which sequence.

* :class:`MembershipConfig` — one epoch's frozen membership: the epoch
  number, the sorted node ids, and the quorum arithmetic derived from them.
* :class:`MembershipChange` — the delta between two adjacent epochs, pinned
  to the decision (sequence + proposal digest) that ordered it.
* :class:`MembershipDirectory` — the cluster-level epoch timeline keyed by
  decision sequence.  The membership-change decision itself is certified by
  the membership of the epoch it RETIRES (its signers are the old
  committee); every decision after it belongs to the new one.  The
  epoch-aware invariant checks (testing/invariants.py) and the chaos churn
  actions read this.

Parity model: reference pkg/types/types.go (Reconfig) and
pkg/consensus/consensus.go:166-252 (the rebuild); the reference leaves the
epoch bookkeeping to the application — this module is that bookkeeping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from consensus_tpu.utils.quorum import compute_quorum


@dataclass(frozen=True)
class MembershipConfig:
    """One epoch's membership: ids are stored sorted, so two configs with
    the same member set compare equal regardless of input order."""

    epoch: int
    nodes: tuple[int, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "nodes", tuple(sorted(self.nodes)))

    @property
    def n(self) -> int:
        return len(self.nodes)

    @property
    def quorum(self) -> int:
        return compute_quorum(self.n)[0]

    @property
    def f(self) -> int:
        return compute_quorum(self.n)[1]

    def __contains__(self, node_id: int) -> bool:
        return node_id in self.nodes

    def validate(self) -> None:
        errs = []
        if self.epoch < 0:
            errs.append("epoch must be >= 0")
        if not self.nodes:
            errs.append("membership must not be empty")
        if any(node <= 0 for node in self.nodes):
            errs.append(f"node ids must be positive: {list(self.nodes)}")
        if len(set(self.nodes)) != len(self.nodes):
            errs.append(f"membership contains duplicate ids: {list(self.nodes)}")
        if errs:
            raise ValueError("invalid membership config: " + "; ".join(errs))


@dataclass(frozen=True)
class MembershipChange:
    """The transition ``old -> new``, ordered by the decision at ``seq``
    whose proposal digest is ``digest`` (the idempotence key: every replica
    delivers the same decision, and a lagging replica re-surfaces it through
    sync)."""

    seq: int
    digest: str
    old: MembershipConfig
    new: MembershipConfig

    @property
    def added(self) -> tuple[int, ...]:
        return tuple(i for i in self.new.nodes if i not in self.old.nodes)

    @property
    def removed(self) -> tuple[int, ...]:
        return tuple(i for i in self.old.nodes if i not in self.new.nodes)

    def __str__(self) -> str:
        parts = []
        if self.added:
            parts.append("+" + ",".join(map(str, self.added)))
        if self.removed:
            parts.append("-" + ",".join(map(str, self.removed)))
        delta = " ".join(parts) or "(no delta)"
        return (
            f"epoch {self.old.epoch} -> {self.new.epoch} at seq {self.seq}: {delta}"
        )


class MembershipDirectory:
    """Cluster-level epoch timeline: which membership certifies which
    decision sequence.

    Deliveries are totally ordered (every replica commits the same decisions
    in the same order), so the first replica to surface a change assigns the
    next epoch number deterministically; every later sighting of the same
    proposal digest — another replica's delivery, or a sync replay — is
    idempotent and returns the already-recorded config.
    """

    def __init__(self, initial_nodes: Sequence[int]) -> None:
        base = MembershipConfig(epoch=0, nodes=tuple(initial_nodes))
        base.validate()
        #: ``(first_seq, config)`` — config certifies decisions at
        #: sequences >= first_seq (until the next entry takes over).
        self._timeline: list[tuple[int, MembershipConfig]] = [(0, base)]
        self._by_digest: dict[str, MembershipChange] = {}
        #: Every recorded transition, in epoch order.
        self.changes: list[MembershipChange] = []

    @property
    def current(self) -> MembershipConfig:
        return self._timeline[-1][1]

    @property
    def current_epoch(self) -> int:
        return self.current.epoch

    def record_change(
        self, digest: str, seq: int, nodes: Sequence[int]
    ) -> MembershipConfig:
        """Record the membership decision ``digest`` committed at ``seq``,
        idempotently, and return the config of the epoch it opens.

        The change decision itself (at ``seq``) is certified by the OLD
        committee — its commit signatures were gathered before anyone
        learned of the change — so the new config takes over at ``seq + 1``.
        """
        existing = self._by_digest.get(digest)
        if existing is not None:
            return existing.new
        old = self.current
        new = MembershipConfig(epoch=old.epoch + 1, nodes=tuple(nodes))
        new.validate()
        change = MembershipChange(seq=seq, digest=digest, old=old, new=new)
        self._by_digest[digest] = change
        self.changes.append(change)
        self._timeline.append((seq + 1, new))
        return new

    def membership_at(self, seq: Optional[int]) -> MembershipConfig:
        """The membership whose quorum certifies the decision at ``seq``
        (the latest config whose reign starts at or before ``seq``)."""
        if seq is None:
            return self.current
        cfg = self._timeline[0][1]
        for first_seq, candidate in self._timeline:
            if seq < first_seq:
                break
            cfg = candidate
        return cfg

    def config_for_epoch(self, epoch: int) -> Optional[MembershipConfig]:
        for _, cfg in self._timeline:
            if cfg.epoch == epoch:
                return cfg
        return None

    def ever_removed(self) -> set[int]:
        """Every id that was a member of some epoch and is not one now."""
        seen: set[int] = set()
        for _, cfg in self._timeline:
            seen.update(cfg.nodes)
        return seen - set(self.current.nodes)


__all__ = ["MembershipConfig", "MembershipChange", "MembershipDirectory"]
