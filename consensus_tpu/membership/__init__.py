"""Membership-epoch subsystem: elastic add/remove-node as an ordered,
quorum-certified decision with a first-class lifecycle.

See epoch.py for the epoch arithmetic (which committee certifies which
sequence) and bootstrap.py for the joining-node catch-up driver.
"""

from consensus_tpu.membership.bootstrap import JoinBootstrap
from consensus_tpu.membership.epoch import (
    MembershipChange,
    MembershipConfig,
    MembershipDirectory,
)

__all__ = [
    "JoinBootstrap",
    "MembershipChange",
    "MembershipConfig",
    "MembershipDirectory",
]
