"""Joining-node bootstrap: a ``LedgerSynchronizer`` run with retry/backoff.

A node admitted by a grow decision boots with an empty ledger and a WAL that
knows nothing; its catch-up is exactly the wire sync path (chunked fetch,
``f + 1`` honest-endorsement bar per decision — sync/client.py).  This class
drives that path to completion on the injected scheduler:

* attempts are spaced by exponential backoff (``initial_delay * backoff^k``,
  capped at ``max_delay``) so a join under heavy injected loss keeps retrying
  without hammering the network;
* when the membership EPOCH advances while the join is still running (the
  cluster reconfigured again mid-join), the peer set the synchronizer probes
  has changed — the backoff resets to ``initial_delay`` and the next probe
  goes out promptly instead of waiting out a delay computed against a stale
  membership;
* every attempt and every retry is counted into the pinned membership
  metrics (``membership_join_attempts`` / ``membership_join_retries``), so a
  wedged join is visible on the obs plane.

Everything runs on the scheduler — no wall clock, no threads — so a chaos
run containing a join replays byte-identically from its seed.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

logger = logging.getLogger("consensus_tpu.membership")


class JoinBootstrap:
    """Retry/backoff driver around a node's ``controller.sync()``.

    Callables (not objects) are injected because reconfiguration REBUILDS
    the controller: a captured bound method would go stale the moment the
    join itself succeeds.
    """

    def __init__(
        self,
        scheduler,
        *,
        sync: Callable[[], None],
        caught_up: Callable[[], bool],
        current_epoch: Optional[Callable[[], int]] = None,
        metrics=None,
        initial_delay: float = 2.0,
        max_delay: float = 60.0,
        backoff: float = 2.0,
    ) -> None:
        self._sched = scheduler
        self._sync = sync
        self._caught_up = caught_up
        self._current_epoch = current_epoch
        self._metrics = metrics
        self._initial_delay = initial_delay
        self._max_delay = max_delay
        self._backoff = backoff

        self.attempts = 0
        self.retries = 0
        self.done = False
        self._delay = initial_delay
        self._seen_epoch: Optional[int] = None
        self._timer = None

    def start(self) -> None:
        """Arm the first probe (immediately, on the next scheduler turn)."""
        if self._timer is None and not self.done:
            self._timer = self._sched.call_later(
                0.0, self._attempt, name="join-bootstrap"
            )

    def stop(self) -> None:
        self.done = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _attempt(self) -> None:
        self._timer = None
        if self.done:
            return
        if self._caught_up():
            self.done = True
            logger.info("join bootstrap complete after %d attempt(s)", self.attempts)
            return
        if self._current_epoch is not None:
            epoch = self._current_epoch()
            if self._seen_epoch is not None and epoch != self._seen_epoch:
                # The cluster reconfigured again mid-join: the peer set
                # changed under us — re-probe promptly against the new one.
                self._delay = self._initial_delay
            self._seen_epoch = epoch
        self.attempts += 1
        if self._metrics is not None:
            self._metrics.count_join_attempts.add(1)
        if self.attempts > 1:
            self.retries += 1
            if self._metrics is not None:
                self._metrics.count_join_retries.add(1)
        try:
            self._sync()
        except Exception:
            logger.exception("join bootstrap sync attempt failed; will retry")
        self._timer = self._sched.call_later(
            self._delay, self._attempt, name="join-bootstrap"
        )
        self._delay = min(self._delay * self._backoff, self._max_delay)


__all__ = ["JoinBootstrap"]
