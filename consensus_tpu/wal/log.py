"""Segmented, CRC-chained, fsync-on-append write-ahead log.

Parity: reference pkg/wal/writeaheadlog.go:60-810 — same guarantees, fresh
layout:

* **Append durability** — every ``append`` writes one framed record and
  fsyncs before returning (the protocol persists *before* broadcasting, so a
  crashed replica can never have said something it doesn't remember).
* **Chained CRC** — each record's checksum covers its payload *and* the
  previous record's checksum, so silent mid-stream corruption or record
  reordering breaks the chain (reference chains CRC32-Castagnoli the same
  way; stdlib CRC-32 here — the polynomial is an implementation detail).
* **Segmented files** — the log rolls to a new segment at
  ``segment_max_bytes``; each segment opens with an anchor record carrying
  the running CRC so any segment is independently readable
  (reference CRC_ANCHOR, pkg/wal/logrecord.proto).
* **Truncation** — ``append(..., truncate_to=True)`` marks the record as a
  stable restore point: all *older segments* are deleted (the current one is
  kept), bounding disk use (reference writeaheadlog.go:661-708).
* **Repair** — a torn tail (crash mid-write) is detected by ``read_all`` and
  chopped off by ``repair``, which truncates after the last intact record
  (reference writeaheadlog.go:293-337).

Record frame (all integers little-endian):

    u32 payload_length | u32 crc | payload | zero padding to 8-byte multiple

Payload = 1 type byte (ENTRY / ANCHOR) + 1 flag byte (truncate_to) + data.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import struct
import zlib
from typing import Optional

logger = logging.getLogger("consensus_tpu.wal")

_HEADER = struct.Struct("<II")
_TYPE_ENTRY = 0x01
_TYPE_ANCHOR = 0x02
_FLAG_TRUNCATE_TO = 0x01

_SEGMENT_RE = re.compile(r"^(\d{16})\.wal$")

DEFAULT_SEGMENT_MAX_BYTES = 64 * 1024 * 1024
_INITIAL_CRC = 0

#: Consecutive group-commit fsync failures tolerated before the log moves
#: into degraded mode (appends refused, waiters still drain on the retry
#: timer).  Persist-before-broadcast is unsatisfiable while the disk won't
#: fsync, so looping silently forever would let the protocol queue unbounded
#: unpersisted work.
DEFAULT_FSYNC_RETRY_CAP = 5

#: Subdirectory corrupt segment suffixes are renamed into.  Nothing under it
#: is ever read back or deleted by this module — the bytes are preserved for
#: operator forensics while the replica rebuilds through verified sync.
QUARANTINE_DIRNAME = "quarantine"


class WALError(Exception):
    """Base class for WAL failures."""


class CorruptLogError(WALError):
    """The log fails CRC/framing validation.

    ``segment`` / ``offset`` locate the first bad byte so ``repair`` can
    truncate there; ``entries`` holds everything intact before the fault.
    """

    def __init__(self, msg: str, *, segment: str, offset: int, entries: list[bytes]):
        super().__init__(f"{msg} (segment={segment!r}, offset={offset})")
        self.segment = segment
        self.offset = offset
        self.entries = entries


@dataclasses.dataclass(frozen=True)
class WALRecovery:
    """What corruption recovery salvaged and set aside.

    Attached to ``WriteAheadLog.recovery`` by the quarantine paths so the
    embedding node can decide whether it must fence itself as a non-voting
    learner (it lost durable records it may have acted on) before rejoining
    the vote."""

    #: Quarantine-relative names of the files renamed aside (never deleted).
    quarantined: tuple[str, ...]
    #: Entry records recovered from the intact prefix.
    intact_entries: int
    #: The CorruptLogError (or read failure) that triggered recovery.
    reason: str


def _pad(n: int) -> int:
    return (8 - n % 8) % 8


def _segment_name(index: int) -> str:
    return f"{index:016d}.wal"


def _list_segments(directory: str) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), name))
    out.sort()
    return out


def _fsync_dir(directory: str) -> None:
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class WriteAheadLog:
    """Append-only log over a directory of segment files.

    Use :func:`create` for a fresh directory, :func:`open_` for an existing
    one, or :func:`initialize_and_read_all` for the boot-time "create or
    open+repair+read" flow (reference pkg/wal/writeaheadlog.go:754-810).
    """

    def __init__(
        self,
        directory: str,
        *,
        segment_max_bytes: int = DEFAULT_SEGMENT_MAX_BYTES,
        sync: bool = True,
        group_commit_window: float = 0.0,
        scheduler=None,
        metrics=None,
        fault_plan=None,
    ) -> None:
        """``group_commit_window`` > 0 (requires a ``scheduler``) batches
        fsyncs: appends write immediately but durability callbacks are
        deferred until one fsync covers every append in the window —
        amortizing the reference's 2-fsyncs-per-decision critical path
        (reference internal/bft/view.go:412,508) across concurrent
        decisions.  In group mode, callers that need the persist-before-
        broadcast invariant MUST pass ``on_durable`` and defer their send
        until it fires."""
        if group_commit_window > 0 and scheduler is None:
            raise ValueError("group_commit_window requires a scheduler")
        if group_commit_window > 0 and not sync:
            raise ValueError("group_commit_window is meaningless with sync=False")
        #: Optional MetricsWAL bundle; gauge parity: reference
        #: pkg/wal/metrics.go:8-15 (wal_count_of_files).
        self._metrics = metrics
        #: Optional testing FaultPlan (consensus_tpu/testing/faults.py).  The
        #: seams below are a single ``is None`` check when unarmed — no lock,
        #: no extra flush/fsync on the hot path.
        self.fault_plan = fault_plan
        self._dir = directory
        self._segment_max_bytes = segment_max_bytes
        self._sync = sync
        self._group_window = group_commit_window
        self._scheduler = scheduler
        self._sync_pending = False
        self._sync_timer = None
        self._sync_waiters: list = []
        self._file: Optional[object] = None  # io.BufferedWriter
        self._segment_index = 0
        self._crc = _INITIAL_CRC
        self._closed = False
        #: Data-file fsyncs issued (per-append, group flush, segment roll) —
        #: the denominator of the group-commit coalescing ratio, and what
        #: the pipelining regression guard counts per decision.
        self.fsync_count = 0
        #: Entry records written since the last data fsync (the numerator).
        self._records_since_fsync = 0
        #: Optional MetricsConsensus bundle for the coalescing-ratio gauge.
        self._consensus_metrics = None
        #: Optional decision-lifecycle tracer (trace.Tracer); None when off.
        self._tracer = None
        #: Entries found by :func:`open_`'s validation scan (None for a
        #: freshly created log) — lets boot avoid a second full-disk read.
        self.entries_at_open: Optional[list[bytes]] = None
        #: Whether the append path is currently refusing work (ENOSPC, or
        #: the group-commit fsync-retry cap was hit).  Degraded is a MODE,
        #: not an error: reads and segment scans keep working, and the log
        #: auto-recovers the moment an append or probe fsync succeeds.
        self.degraded = False
        #: Human-readable reason for the current degraded episode.
        self.degraded_reason: Optional[str] = None
        #: Callbacks ``fn(degraded: bool)`` fired on every degraded-mode
        #: transition — the controller fences proposing/voting off this.
        self.degrade_hooks: list = []
        #: Set by the quarantine paths; non-None means durable records were
        #: set aside and the embedding replica may have amnesia.
        self.recovery: Optional[WALRecovery] = None
        self._recovery_booked = False
        self._fsync_failures = 0
        self._fsync_retry_cap = DEFAULT_FSYNC_RETRY_CAP
        self._degraded_probe_timer = None
        #: Injectable file-open seams — testing/storage.py swaps these for
        #: fault-wrapped opens; production code never touches them.
        self._open_for_append = open
        self._open_for_read = open

    def attach_consensus_metrics(self, metrics) -> None:
        """Publish the group-commit coalescing ratio
        (``consensus_wal_records_per_fsync``) into a MetricsConsensus
        bundle on every data fsync."""
        self._consensus_metrics = metrics

    def attach_tracer(self, tracer) -> None:
        """Emit ``wal.append``/``wal.fsync`` instants into a decision
        tracer; the fsync instant carries the same records-per-fsync value
        the ``consensus_wal_records_per_fsync`` gauge publishes."""
        self._tracer = tracer

    def _count_fsync(self) -> None:
        self.fsync_count += 1
        if self._consensus_metrics is not None and self._records_since_fsync:
            self._consensus_metrics.wal_records_per_fsync.set(
                self._records_since_fsync
            )
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "wal", "wal.fsync", records=self._records_since_fsync
            )
        self._records_since_fsync = 0

    # --- construction ------------------------------------------------------

    @classmethod
    def create(cls, directory: str, **kw) -> "WriteAheadLog":
        """Create a brand-new log; the directory must be empty or absent.

        Parity: reference pkg/wal/writeaheadlog.go:125-205.
        """
        os.makedirs(directory, exist_ok=True)
        if _list_segments(directory):
            raise WALError(f"directory {directory!r} already contains a WAL")
        wal = cls(directory, **kw)
        wal._start_segment(1)
        return wal

    @classmethod
    def open_(cls, directory: str, repair: bool = False, **kw) -> "WriteAheadLog":
        """Open an existing log for appending after the last intact record.

        ``repair`` makes the open-time contract explicit: ``False`` (the
        default) raises :class:`CorruptLogError` on a torn tail so the
        caller decides; ``True`` runs :func:`repair` and retries — tail
        tears only, non-tail corruption still raises (see
        :func:`initialize_and_read_all` for the quarantine flow).  Parity:
        reference writeaheadlog.go:207-291.
        """
        segments = _list_segments(directory)
        if not segments:
            raise WALError(f"no WAL in {directory!r}")
        wal = cls(directory, **kw)
        # Validate everything (raises CorruptLogError on damage) and leave
        # the chain CRC positioned after the final record.  The entries are
        # kept so boot (initialize_and_read_all) doesn't rescan the disk.
        try:
            wal.entries_at_open = wal._scan_all()
        except CorruptLogError:
            if not repair:
                raise
            _repair(directory)
            if not _list_segments(directory):
                raise WALError(
                    f"repair removed the only segment in {directory!r}"
                )
            return cls.open_(directory, repair=False, **kw)
        last_index, last_name = segments[-1]
        path = os.path.join(directory, last_name)
        wal._file = wal._open_for_append(path, "ab")
        wal._segment_index = last_index
        wal._update_file_count()
        return wal

    def close(self) -> None:
        if self._degraded_probe_timer is not None:
            self._degraded_probe_timer.cancel()
            self._degraded_probe_timer = None
        if self._sync_timer is not None:
            self._sync_timer.cancel()
            self._sync_timer = None
        if self._sync_waiters or self._sync_pending:
            if not self.flush_group():
                # flush_group re-armed a retry; a post-close retry firing
                # protocol callbacks would be worse than failing loudly.
                if self._sync_timer is not None:
                    self._sync_timer.cancel()
                    self._sync_timer = None
                raise WALError(
                    "close: pending records could not be made durable"
                )
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None
        self._closed = True

    def abandon(self) -> None:
        """Simulated process death: drop the file handle WITHOUT flushing
        pending group-commit state or firing durability callbacks.  Unlike
        :meth:`close`, records whose fsync had not yet happened are simply
        lost — which is exactly what a crash does.  Used by the crash-matrix
        harness; production shutdown should keep using ``close``."""
        if self._degraded_probe_timer is not None:
            self._degraded_probe_timer.cancel()
            self._degraded_probe_timer = None
        if self._sync_timer is not None:
            self._sync_timer.cancel()
            self._sync_timer = None
        self._sync_waiters = []
        self._sync_pending = False
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        self._closed = True

    # --- appending ---------------------------------------------------------

    def append(
        self, data: bytes, truncate_to: bool = False, on_durable=None
    ) -> None:
        """Durably append one record; returns after fsync (default mode).

        With group commit configured, the write lands immediately but the
        fsync is deferred to the window; ``on_durable()`` fires once the
        record is actually on stable storage.

        ``truncate_to=True`` marks a stable restore point and deletes all
        older segments.  Parity: reference writeaheadlog.go:403-497.
        """
        if self._closed or self._file is None:
            raise WALError("log is closed")
        if on_durable is not None and not self._sync:
            raise WALError("on_durable requires a sync-enabled log")
        plan = self.fault_plan
        if plan is not None:
            plan.crash("wal.append.pre_write")
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "wal", "wal.append", bytes=len(data), truncate=truncate_to
            )
        flags = _FLAG_TRUNCATE_TO if truncate_to else 0
        try:
            self._write_record(_TYPE_ENTRY, flags, data)
        except OSError as err:
            # ENOSPC/EIO on the write or fsync: persist-before-broadcast is
            # unsatisfiable, so the log degrades (the controller's degrade
            # hook stops proposing/voting) instead of letting the replica
            # keep acting on records that never reached stable storage.
            self._enter_degraded(f"append failed: {err}")
            raise WALError(f"append failed: {err}") from err
        if self.degraded and not self._group_window:
            # The write (and, in sync mode, its fsync) succeeded: the disk
            # recovered, so the degraded episode is over.
            self._exit_degraded()
        if on_durable is not None and self._group_window:
            # Queue BEFORE any eager flush below, so a truncate-triggered
            # flush covers this record's callback too.
            self._sync_waiters.append(on_durable)
        if truncate_to:
            if self._group_window:
                # The restore point must be durable BEFORE the history it
                # replaces is deleted, or a crash in the window loses both.
                # On fsync failure the deletion rides the retry queue — with
                # the segment index captured NOW: by retry time a rollover
                # may have bumped it, and deleting against the new index
                # would destroy the segment holding the restore point.
                if self.flush_group():
                    self._drop_old_segments()
                else:
                    keep = self._segment_index
                    self._sync_waiters.append(
                        lambda: self._drop_segments_below(keep)
                    )
            else:
                self._drop_old_segments()
        if self._file.tell() >= self._segment_max_bytes:
            if plan is not None:
                plan.crash("wal.segment.roll")
            self._start_segment(self._segment_index + 1)
        if on_durable is not None and not self._group_window:
            on_durable()  # already fsynced synchronously

    def flush_group(self) -> bool:
        """Fsync now and complete every deferred durability callback;
        returns whether durability was actually achieved.

        An fsync failure (ENOSPC/EIO) keeps the waiters queued and retries
        after another window — records must never be reported durable when
        they are not, and the error must not strand the queue silently."""
        if self._sync_timer is not None:
            self._sync_timer.cancel()
            self._sync_timer = None
        if self._file is None:
            # Closed (or never opened) with work still queued: durability is
            # unachievable — never fire the callbacks as if it happened.
            return not self._sync_waiters
        if self._sync:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._count_fsync()
            except OSError:
                self._fsync_failures += 1
                if self._metrics is not None:
                    self._metrics.fsync_retries.add(1)
                if self._tracer is not None and self._tracer.enabled:
                    self._tracer.instant(
                        "wal", "wal.fsync.retry",
                        consecutive=self._fsync_failures,
                    )
                logger.exception(
                    "WAL group fsync failed (%d consecutive); retrying in %.3fs",
                    self._fsync_failures,
                    self._group_window or 0.05,
                )
                if self._fsync_failures >= self._fsync_retry_cap:
                    # Capped: stop pretending this is transient.  The retry
                    # timer keeps running so queued waiters still drain the
                    # moment the disk heals, but the replica must stop
                    # generating new unpersistable work NOW.
                    self._enter_degraded(
                        f"fsync retry cap ({self._fsync_retry_cap}) hit"
                    )
                if self._scheduler is not None:
                    self._sync_pending = True
                    self._sync_timer = self._scheduler.call_later(
                        self._group_window or 0.05,
                        self.flush_group,
                        name="wal-group-commit-retry",
                    )
                    return False
                raise
            self._fsync_failures = 0
            if self.degraded:
                self._exit_degraded()
        self._sync_pending = False
        waiters, self._sync_waiters = self._sync_waiters, []
        for waiter in waiters:
            try:
                waiter()
            except Exception:
                logger.exception("on_durable callback failed; continuing with the rest")
        return True

    def _write_record(self, rtype: int, flags: int, data: bytes) -> None:
        payload = bytes([rtype, flags]) + data
        prev_crc = self._crc
        self._crc = zlib.crc32(payload, self._crc) & 0xFFFFFFFF
        frame = _HEADER.pack(len(payload), self._crc) + payload + b"\x00" * _pad(
            len(payload)
        )
        plan = self.fault_plan
        if plan is not None and rtype == _TYPE_ENTRY:
            if plan.will_fire("wal.append.torn_write"):
                # Worst-case torn write: half the frame reaches stable
                # storage, then the process dies — repair() must chop it.
                self._file.write(frame[: max(_HEADER.size, len(frame) // 2)])
                self._file.flush()
                os.fsync(self._file.fileno())
            plan.crash("wal.append.torn_write")
        try:
            self._file.write(frame)
            self._file.flush()
        except OSError:
            # The frame did not (fully) reach the file: rewind the chain CRC
            # so a later successful append continues from the last record
            # that actually landed on disk.
            self._crc = prev_crc
            raise
        if rtype == _TYPE_ENTRY:
            self._records_since_fsync += 1
        if self._sync:
            if self._group_window:
                # Group commit: one fsync covers every append in the window
                # (constructor guarantees a scheduler exists).
                if not self._sync_pending:
                    self._sync_pending = True
                    self._sync_timer = self._scheduler.call_later(
                        self._group_window, self.flush_group, name="wal-group-commit"
                    )
            else:
                if plan is not None and rtype == _TYPE_ENTRY:
                    plan.crash("wal.fsync.pre")
                os.fsync(self._file.fileno())
                self._count_fsync()
                if plan is not None and rtype == _TYPE_ENTRY:
                    plan.crash("wal.fsync.post")

    # --- degraded mode & quarantine ---------------------------------------

    def _enter_degraded(self, reason: str) -> None:
        if self.degraded:
            return
        self.degraded = True
        self.degraded_reason = reason
        logger.warning("WAL degraded: %s (appends refused)", reason)
        if self._metrics is not None:
            self._metrics.degraded.set(1)
            self._metrics.degraded_transitions.add(1)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant("wal", "wal.degraded", reason=reason)
        for hook in list(self.degrade_hooks):
            try:
                hook(True)
            except Exception:
                logger.exception("WAL degrade hook failed; continuing")
        self._arm_degraded_probe()

    def _exit_degraded(self) -> None:
        if not self.degraded:
            return
        self.degraded = False
        self.degraded_reason = None
        self._fsync_failures = 0
        if self._degraded_probe_timer is not None:
            self._degraded_probe_timer.cancel()
            self._degraded_probe_timer = None
        logger.info("WAL recovered from degraded mode; appends resume")
        if self._metrics is not None:
            self._metrics.degraded.set(0)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant("wal", "wal.recovered")
        for hook in list(self.degrade_hooks):
            try:
                hook(False)
            except Exception:
                logger.exception("WAL degrade hook failed; continuing")

    def _arm_degraded_probe(self) -> None:
        # The group-commit retry timer already doubles as a recovery probe
        # (its success path exits degraded mode); only arm a dedicated probe
        # when no retry is in flight and a scheduler exists to clock it.
        if (
            self._scheduler is None
            or self._sync_timer is not None
            or self._degraded_probe_timer is not None
        ):
            return
        self._degraded_probe_timer = self._scheduler.call_later(
            max(self._group_window, 0.05),
            self._probe_degraded,
            name="wal-degraded-probe",
        )

    def _probe_degraded(self) -> None:
        self._degraded_probe_timer = None
        if not self.degraded or self._closed or self._file is None:
            return
        try:
            self._file.flush()
            if self._sync:
                os.fsync(self._file.fileno())
        except OSError:
            self._arm_degraded_probe()
            return
        self._exit_degraded()

    def quarantine_corrupt(self, err: CorruptLogError) -> WALRecovery:
        """Live-quarantine the corrupt suffix (scrub detection path): move
        the damaged segment's suffix and every later segment into the
        quarantine directory, then reopen positioned after the last intact
        record.  Pending group-commit durability callbacks are DROPPED —
        records in the lost suffix can never be reported durable, and the
        embedding replica is expected to fence itself and rebuild through
        verified sync (see ``recovery``)."""
        if self._sync_timer is not None:
            self._sync_timer.cancel()
            self._sync_timer = None
        self._sync_pending = False
        self._sync_waiters = []
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        moved = quarantine(self._dir, err)
        segments = _list_segments(self._dir)
        if not segments:
            self._crc = _INITIAL_CRC
            entries: list[bytes] = []
            self._start_segment(1)
        else:
            entries = self._scan_all()  # repositions the chain CRC
            last_index, last_name = segments[-1]
            self._file = self._open_for_append(
                os.path.join(self._dir, last_name), "ab"
            )
            self._segment_index = last_index
        self._update_file_count()
        self.recovery = WALRecovery(
            quarantined=tuple(moved),
            intact_entries=len(entries),
            reason=str(err),
        )
        self._book_recovery()
        return self.recovery

    def _book_recovery(self) -> None:
        """Book the quarantine exactly once, whenever metrics are ready
        (boot-path quarantines happen before attach_metrics)."""
        if self.recovery is None or self._recovery_booked:
            return
        if self._metrics is None:
            return
        self._recovery_booked = True
        self._metrics.quarantines.add(1)
        self._metrics.scrub_corruptions.add(1)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "wal", "wal.quarantine",
                files=len(self.recovery.quarantined),
                intact=self.recovery.intact_entries,
            )

    def attach_metrics(self, metrics) -> None:
        """Attach a MetricsWAL bundle after construction (the facade calls
        this: the embedder builds the WAL before the metrics provider is
        known) and publish the current file count."""
        self._metrics = metrics
        self._update_file_count()
        if self.degraded:
            metrics.degraded.set(1)
        self._book_recovery()

    def _update_file_count(self) -> None:
        if self._metrics is not None:
            self._metrics.count_of_files.set(len(_list_segments(self._dir)))

    def _start_segment(self, index: int) -> None:
        if self._file is not None:
            self._file.flush()
            if self._sync:
                os.fsync(self._file.fileno())
                self._count_fsync()
            self._file.close()
        path = os.path.join(self._dir, _segment_name(index))
        self._file = self._open_for_append(path, "ab")
        self._segment_index = index
        # Anchor: carries the running chain CRC so this segment can be
        # validated without its predecessors.
        anchor_data = struct.pack("<I", self._crc)
        self._write_record(_TYPE_ANCHOR, 0, anchor_data)
        if self._sync:
            _fsync_dir(self._dir)
        self._update_file_count()

    def _drop_old_segments(self) -> None:
        self._drop_segments_below(self._segment_index)

    def _drop_segments_below(self, keep_index: int) -> None:
        for index, name in _list_segments(self._dir):
            if index < keep_index:
                os.unlink(os.path.join(self._dir, name))
        if self._sync:
            _fsync_dir(self._dir)
        self._update_file_count()

    # --- reading -----------------------------------------------------------

    def read_all(self) -> list[bytes]:
        """All intact entry payloads, oldest first.

        Raises :class:`CorruptLogError` on a broken chain or torn tail.
        Parity: reference writeaheadlog.go:510-602.
        """
        return self._scan_all()

    def _scan_all(self) -> list[bytes]:
        entries: list[bytes] = []
        crc = _INITIAL_CRC
        first = True
        for _, name in _list_segments(self._dir):
            path = os.path.join(self._dir, name)
            with self._open_for_read(path, "rb") as f:
                buf = f.read()
            crc, first = self._scan_segment(name, buf, crc, first, entries)
        self._crc = crc
        return entries

    def _scan_segment(
        self,
        name: str,
        buf: bytes,
        crc: int,
        first_segment: bool,
        entries: list[bytes],
    ) -> tuple[int, bool]:
        off = 0
        first_record = True
        while off < len(buf):
            if off + _HEADER.size > len(buf):
                raise CorruptLogError(
                    "torn frame header", segment=name, offset=off, entries=entries
                )
            length, want_crc = _HEADER.unpack_from(buf, off)
            body_start = off + _HEADER.size
            body_end = body_start + length
            if length < 2 or body_end + _pad(length) > len(buf):
                raise CorruptLogError(
                    "torn frame payload", segment=name, offset=off, entries=entries
                )
            payload = buf[body_start:body_end]
            rtype, flags = payload[0], payload[1]
            if first_record:
                # Every segment must open with an anchor matching the chain.
                if rtype != _TYPE_ANCHOR or len(payload) < 6:
                    raise CorruptLogError(
                        "segment missing anchor", segment=name, offset=off, entries=entries
                    )
                anchor_crc = struct.unpack("<I", payload[2:6])[0]
                if not first_segment and anchor_crc != crc:
                    raise CorruptLogError(
                        "anchor breaks CRC chain", segment=name, offset=off, entries=entries
                    )
                crc = anchor_crc  # trust the anchor when this is the oldest kept segment
                first_record = False
            got = zlib.crc32(payload, crc) & 0xFFFFFFFF
            if got != want_crc:
                raise CorruptLogError(
                    "CRC mismatch", segment=name, offset=off, entries=entries
                )
            crc = got
            if rtype == _TYPE_ENTRY:
                if flags & _FLAG_TRUNCATE_TO:
                    # A stable restore point retires everything before it,
                    # including earlier records in this same segment (older
                    # segments were already deleted at append time).
                    # Parity: reference pkg/wal/writeaheadlog.go:549-551.
                    entries.clear()
                entries.append(payload[2:])
            elif rtype != _TYPE_ANCHOR:
                raise CorruptLogError(
                    f"unknown record type {rtype}", segment=name, offset=off, entries=entries
                )
            off = body_end + _pad(length)
        if first_record:
            raise CorruptLogError(
                "empty segment", segment=name, offset=0, entries=entries
            )
        return crc, False


def repair(directory: str) -> None:
    """Chop a torn tail: truncate the damaged segment after its last intact
    record (taking a ``.bak`` copy first).

    Only the *last* segment can legitimately be torn (a crash mid-append);
    corruption in an earlier, fully-fsynced segment means durable records
    were damaged at rest — silently discarding them would make the replica
    forget messages it already broadcast, so that case raises for operator
    intervention instead.  Parity: reference pkg/wal/writeaheadlog.go:293-337
    (verifies all-but-last, truncates only the last file).
    """
    probe = WriteAheadLog(directory)
    try:
        probe._scan_all()
        return  # nothing to repair
    except CorruptLogError as err:
        bad_segment, offset = err.segment, err.offset

    segments = _list_segments(directory)
    if segments and bad_segment != segments[-1][1]:
        raise WALError(
            f"corruption in non-tail segment {bad_segment!r}: durable records "
            "are damaged; refusing to auto-repair"
        )
    path = os.path.join(directory, bad_segment)
    backup = path + ".bak"
    with open(path, "rb") as src, open(backup, "wb") as dst:
        dst.write(src.read())
        dst.flush()
        os.fsync(dst.fileno())
    if offset == 0:
        # Nothing salvageable in this segment: remove it entirely.
        os.unlink(path)
    else:
        with open(path, "r+b") as f:
            f.truncate(offset)
            f.flush()
            os.fsync(f.fileno())
    _fsync_dir(directory)


# open_(repair=...) shadows the module function with its parameter name;
# this alias keeps the call reachable from inside the class.
_repair = repair


def quarantine(directory: str, err: CorruptLogError) -> list[str]:
    """Set aside the corrupt suffix, preserving the intact prefix.

    The damaged segment (from the corruption offset) and every later
    segment are RENAMED into ``quarantine/`` — never deleted: the replica
    may have broadcast votes recorded in those bytes, so they stay on disk
    for operator forensics while the node rebuilds through verified sync.
    When the corruption sits mid-segment, the segment's intact prefix (a
    whole number of records, ending just before ``err.offset``) is written
    back so those entries survive.  Returns the quarantined file names.
    """
    qdir = os.path.join(directory, QUARANTINE_DIRNAME)
    os.makedirs(qdir, exist_ok=True)
    bad_index = None
    for index, name in _list_segments(directory):
        if name == err.segment:
            bad_index = index
            break
    if bad_index is None:
        raise WALError(
            f"quarantine: segment {err.segment!r} not found in {directory!r}"
        )
    moved: list[str] = []
    for index, name in _list_segments(directory):
        if index < bad_index:
            continue
        src = os.path.join(directory, name)
        prefix = None
        if name == err.segment and err.offset > 0:
            with open(src, "rb") as f:
                prefix = f.read(err.offset)
        dst = os.path.join(qdir, name)
        bump = 0
        while os.path.exists(dst):
            bump += 1
            dst = os.path.join(qdir, f"{name}.{bump}")
        os.replace(src, dst)
        moved.append(os.path.basename(dst))
        if prefix is not None:
            with open(src, "wb") as f:
                f.write(prefix)
                f.flush()
                os.fsync(f.fileno())
    _fsync_dir(qdir)
    _fsync_dir(directory)
    return moved


def initialize_and_read_all(
    directory: str, quarantine_corrupt: bool = False, **kw
) -> tuple[WriteAheadLog, list[bytes]]:
    """Boot-time flow: create a fresh log, or open an existing one (repairing
    a torn tail if needed) and return its entries.

    ``quarantine_corrupt`` enables the amnesia-safe path: corruption beyond
    the tail (which :func:`repair` refuses — durable records were damaged
    at rest) no longer kills the boot.  The corrupt suffix is quarantined,
    the log reopens from the intact prefix, and ``wal.recovery`` carries
    what was lost so the embedding replica fences itself as a non-voting
    learner until verified sync passes a checkpoint above the intact
    prefix.  Parity: reference pkg/wal/writeaheadlog.go:754-810 (original
    repair-only flow).
    """
    os.makedirs(directory, exist_ok=True)
    if not _list_segments(directory):
        return WriteAheadLog.create(directory, **kw), []
    try:
        wal = WriteAheadLog.open_(directory, **kw)
    except CorruptLogError as err:
        try:
            repair(directory)
        except WALError:
            if not quarantine_corrupt:
                raise
            moved = quarantine(directory, err)
            if not _list_segments(directory):
                wal = WriteAheadLog.create(directory, **kw)
                entries: list[bytes] = []
            else:
                wal = WriteAheadLog.open_(directory, **kw)
                entries = (
                    wal.entries_at_open
                    if wal.entries_at_open is not None else []
                )
            wal.recovery = WALRecovery(
                quarantined=tuple(moved),
                intact_entries=len(entries),
                reason=str(err),
            )
            return wal, entries
        if not _list_segments(directory):
            # The only segment was damaged beyond its anchor: start fresh.
            return WriteAheadLog.create(directory, **kw), []
        wal = WriteAheadLog.open_(directory, **kw)
    return wal, wal.entries_at_open if wal.entries_at_open is not None else []


__all__ = [
    "WriteAheadLog",
    "WALError",
    "CorruptLogError",
    "WALRecovery",
    "repair",
    "quarantine",
    "initialize_and_read_all",
    "DEFAULT_SEGMENT_MAX_BYTES",
    "DEFAULT_FSYNC_RETRY_CAP",
    "QUARANTINE_DIRNAME",
]
