"""Segmented CRC-chained write-ahead log (crash recovery substrate).

Parity: reference pkg/wal/.  The scrub/quarantine/degraded self-healing
layer (wal/scrub.py, WALRecovery, quarantine) is a consensus_tpu addition.
"""

from consensus_tpu.wal.log import (
    DEFAULT_FSYNC_RETRY_CAP,
    DEFAULT_SEGMENT_MAX_BYTES,
    QUARANTINE_DIRNAME,
    CorruptLogError,
    WALError,
    WALRecovery,
    WriteAheadLog,
    initialize_and_read_all,
    quarantine,
    repair,
)
from consensus_tpu.wal.scrub import DEFAULT_SCRUB_INTERVAL, WalScrubber

__all__ = [
    "WriteAheadLog",
    "WALError",
    "CorruptLogError",
    "WALRecovery",
    "WalScrubber",
    "repair",
    "quarantine",
    "initialize_and_read_all",
    "DEFAULT_SEGMENT_MAX_BYTES",
    "DEFAULT_FSYNC_RETRY_CAP",
    "DEFAULT_SCRUB_INTERVAL",
    "QUARANTINE_DIRNAME",
]
