"""Segmented CRC-chained write-ahead log (crash recovery substrate).

Parity: reference pkg/wal/.
"""

from consensus_tpu.wal.log import (
    DEFAULT_SEGMENT_MAX_BYTES,
    CorruptLogError,
    WALError,
    WriteAheadLog,
    initialize_and_read_all,
    repair,
)

__all__ = [
    "WriteAheadLog",
    "WALError",
    "CorruptLogError",
    "repair",
    "initialize_and_read_all",
    "DEFAULT_SEGMENT_MAX_BYTES",
]
