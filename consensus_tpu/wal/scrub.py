"""Background WAL scrubber: re-walk the CRC chain, catch bit-rot early.

Appends verify their own frames, but bytes already on disk are only
re-read at restart — a flipped bit in a committed region can therefore sit
latent for the whole life of a process and only surface (fatally, pre-PR)
at the next boot.  The scrubber closes that window: on a scheduler-clocked
cadence it re-reads every segment through the log's (injectable) read seam
and re-verifies the full chain — anchors, CRCs, framing, segment
inventory — exactly the validation :func:`WriteAheadLog.read_all` runs at
open time.

Every pass books the pinned ``wal_scrub_runs_total`` /
``wal_scrub_records_total`` counters; a detection books
``wal_scrub_corruptions_total``, emits a ``wal.scrub.corruption`` trace
instant, and hands the :class:`CorruptLogError` to the ``on_corruption``
callback — the embedding node quarantines the suffix
(:meth:`WriteAheadLog.quarantine_corrupt`), snapshots a flight record, and
fences itself as a non-voting learner until verified sync carries it past
the damage (core/controller.py).  An unreadable segment (EIO) is treated
as corruption at offset 0 of that segment: the bytes may be fine, but a
replica that cannot read its own durable state must not keep voting on
the assumption that it can.

The scrubber holds no lock: the simulation scheduler is single-threaded
and every append flushes its full frame before returning, so a pass always
observes a record-aligned on-disk state.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

from .log import CorruptLogError, WriteAheadLog, _INITIAL_CRC, _list_segments

logger = logging.getLogger("consensus_tpu.wal.scrub")

#: Default seconds (injected clock) between scrub passes.  Deliberately
#: long relative to protocol timescales — scrubbing is a bit-rot bound,
#: not a hot path.
DEFAULT_SCRUB_INTERVAL = 30.0


class WalScrubber:
    """Scheduler-clocked re-verification of a live :class:`WriteAheadLog`.

    ``on_corruption(err)`` is invoked at most once per detection with the
    triggering :class:`CorruptLogError`; the scrubber keeps running
    afterwards (the callback is expected to quarantine, leaving a clean
    log behind).
    """

    def __init__(
        self,
        wal: WriteAheadLog,
        scheduler,
        *,
        interval: float = DEFAULT_SCRUB_INTERVAL,
        metrics=None,
        tracer=None,
        on_corruption: Optional[Callable[[CorruptLogError], None]] = None,
    ) -> None:
        if interval <= 0:
            raise ValueError("scrub interval must be positive")
        self._wal = wal
        self._scheduler = scheduler
        self._interval = interval
        self._metrics = metrics
        self._tracer = tracer
        self._on_corruption = on_corruption
        self._timer = None
        self._stopped = False
        #: Passes completed (mirrors the pinned counter for tests that run
        #: without a metrics provider).
        self.runs = 0
        #: Corruptions detected over the scrubber's lifetime.
        self.corruptions = 0

    def start(self) -> None:
        self._stopped = False
        self._arm()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _arm(self) -> None:
        if self._stopped or self._timer is not None:
            return
        self._timer = self._scheduler.call_later(
            self._interval, self._tick, name="wal-scrub"
        )

    def _tick(self) -> None:
        self._timer = None
        if self._stopped:
            return
        self.scrub_now()
        self._arm()

    def scrub_now(self) -> Optional[CorruptLogError]:
        """Run one full pass immediately; returns the detection, if any."""
        self.runs += 1
        if self._metrics is not None:
            self._metrics.scrub_runs.add(1)
        try:
            records = self._rewalk()
        except CorruptLogError as err:
            self.corruptions += 1
            if self._metrics is not None:
                self._metrics.scrub_corruptions.add(1)
            if self._tracer is not None and self._tracer.enabled:
                self._tracer.instant(
                    "wal", "wal.scrub.corruption",
                    segment=err.segment, offset=err.offset,
                )
            logger.warning("scrub detected corruption: %s", err)
            if self._on_corruption is not None:
                try:
                    self._on_corruption(err)
                except Exception:
                    logger.exception("on_corruption handler failed")
            return err
        if self._metrics is not None:
            self._metrics.scrub_records.add(records)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant("wal", "wal.scrub", records=records)
        return None

    def _rewalk(self) -> int:
        """Re-verify every segment through the log's read seam without
        touching the live log's chain state; returns intact entry count."""
        wal = self._wal
        directory = wal._dir
        entries: list[bytes] = []
        crc = _INITIAL_CRC
        first = True
        for _, name in _list_segments(directory):
            path = os.path.join(directory, name)
            try:
                with wal._open_for_read(path, "rb") as f:
                    buf = f.read()
            except OSError as err:
                raise CorruptLogError(
                    f"unreadable segment: {err}",
                    segment=name, offset=0, entries=entries,
                )
            # _scan_segment is stateless w.r.t. the instance; borrowing the
            # live log's keeps exactly one validation implementation.
            crc, first = wal._scan_segment(name, buf, crc, first, entries)
        return len(entries)


__all__ = ["WalScrubber", "DEFAULT_SCRUB_INTERVAL"]
