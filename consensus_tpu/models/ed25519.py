"""Batched Ed25519 signature verification: the TPU replacement for the
reference's per-vote goroutine + sequential CPU ECDSA
(reference internal/bft/view.go:537-541).

Split of labor:

* **Host** (cheap, irregular): parse signatures, range-check ``S < L`` and
  ``y < p``, hash ``k = SHA-512(R || A || M) mod L`` (hashing is
  variable-length and byte-oriented — the wrong shape for the MXU/VPU), and
  pack scalars/field elements into fixed-shape limb/bit arrays.
* **Device** (the 99%: elliptic-curve math): decompress R and A, then the
  double-scalar multiplication ``[S]B + [k](-A)`` — the variable half as a
  64-step 4-bit-window ``lax.scan``, the fixed-base half as an 8-bit comb
  over constant tables — and a projective comparison against R.  Everything
  is f32 8-bit-limb arithmetic (:mod:`consensus_tpu.ops.field25519`)
  batched on the trailing axis — one compiled kernel per padded batch size
  verifies the whole quorum.  Inputs ship as uint8 (4x less transfer).

Batches are padded to the next power of two (``Configuration.crypto_pad_pow2``)
so XLA compiles a handful of shapes once and reuses them forever.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from consensus_tpu.obs.kernels import instrumented_jit, kernel_lane_suffix

from consensus_tpu.ops import ed25519 as ed
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops import limbs

#: Group order of edwards25519 (RFC 8032).
L = 2**252 + 27742317777372353535851937790883648493

_SCALAR_BITS = 256


def _bytes_rows_to_bits(rows: np.ndarray) -> np.ndarray:
    """(n, 32) little-endian byte rows -> (n, 256) LSB-first bit rows
    (uint8 — every host-side array stays at the wire width; the kernel
    widens on device)."""
    return np.unpackbits(rows, axis=-1, bitorder="little")


_WINDOW_BITS = 4
_WINDOWS = 256 // _WINDOW_BITS  # 64
_TABLE = 9  # signed digits: |d| <= 8 -> multiples 0..8 of (-A)


# Shared opt-in plumbing for the whole-scan-in-VMEM experiment (both curve
# families) lives in consensus_tpu/ops/pallas_scan.py; these thin wrappers
# keep the import LAZY — importing jax.experimental.pallas costs ~1 s of
# process cold-start, which every replica process would pay for a
# default-off experiment (the 1-core box runs n of them).


def _pallas_scan_config(batch: int):
    from consensus_tpu.ops.pallas_scan import scan_config

    return scan_config(batch)


def suppress_pallas_scan():
    from consensus_tpu.ops.pallas_scan import suppress_pallas_scan as real

    return real()


def verify_impl(
    y_r: jnp.ndarray,       # (32, batch) R.y limbs, uint8 on the wire
    sign_r: jnp.ndarray,    # (batch,)    R.x sign bits
    y_a: jnp.ndarray,       # (32, batch) A.y limbs, uint8 on the wire
    sign_a: jnp.ndarray,    # (batch,)    A.x sign bits
    s_digits8: jnp.ndarray, # (32, batch) S 8-bit window digits, LSB window first
    k_digits: jnp.ndarray,  # (64, batch) k signed 4-bit digits + 8, MSB window first
    host_ok: jnp.ndarray,   # (batch,)    host-side pre-checks passed
) -> jnp.ndarray:
    """Un-jitted kernel body — every op is independent per batch element
    (batch is the trailing axis, riding the vector lanes), so this function
    shards over the batch axis unchanged (see :mod:`consensus_tpu.parallel`).

    acc = [S]B + [k](-A) is split by operand class: the variable half
    [k](-A) runs a signed-4-bit-windowed Horner scan (64 steps of 4
    doubles + 1 table add; j*(-A) for j <= 8 built per batch with 7
    additions, sign applied by a mul-free conditional negate), while the
    fixed-base half [S]B — B is a compile-time constant — uses an 8-bit
    comb over precomputed tables (:func:`consensus_tpu.ops.ed25519
    .fixed_base_mul_comb`): 32 constant lookups + mixed adds, zero doubles,
    with the lookups riding the MXU.  Lookups are one-hot contractions (no
    gathers), and digit 0 adds the identity — the complete addition
    formulas make that branch-free."""
    # Inputs arrive in the narrowest dtype that holds them (uint8 limbs and
    # digits) — 4x less host->device transfer, which rides a slow tunnel in
    # the single-chip deployment.  Widen to the compute dtypes on device.
    y_r = y_r.astype(jnp.float32)
    y_a = y_a.astype(jnp.float32)
    sign_r = sign_r.astype(jnp.int32)
    sign_a = sign_a.astype(jnp.int32)
    s_digits8 = s_digits8.astype(jnp.int32)
    k_digits = k_digits.astype(jnp.int32)
    # Decompress R and A in ONE instance of the (large) decompression graph
    # by stacking them along the trailing batch axis — same total runtime
    # work, half the traced/compiled graph.
    batch = y_r.shape[-1]
    pt, pt_ok = ed.decompress(
        jnp.concatenate([y_r, y_a], axis=-1),
        jnp.concatenate([sign_r, sign_a], axis=-1),
    )
    r_point = ed.Point(
        x=pt.x[..., :batch], y=pt.y[..., :batch],
        z=pt.z[..., :batch], t=pt.t[..., :batch],
    )
    a_point = ed.Point(
        x=pt.x[..., batch:], y=pt.y[..., batch:],
        z=pt.z[..., batch:], t=pt.t[..., batch:],
    )
    r_ok, a_ok = pt_ok[..., :batch], pt_ok[..., batch:]
    neg_a = ed.negate(a_point)
    pallas_cfg = _pallas_scan_config(batch)
    if pallas_cfg is not None:
        # Opt-in whole-scan-in-VMEM Pallas kernel (CTPU_PALLAS_SCAN=1):
        # same arithmetic, different scheduling — see ops/pallas_scan.py.
        tile, interpret = pallas_cfg
        from consensus_tpu.ops.pallas_scan import horner_scan

        acc = horner_scan(
            neg_a.x, neg_a.y, neg_a.z, neg_a.t, k_digits,
            tile=tile, interpret=interpret,
        )
    else:
        # The table coords inherit the inputs' sharding variance so the
        # scan carry type-checks under shard_map.
        a_table = ed.multiples_table(neg_a, _TABLE)

        lanes = jnp.arange(_TABLE, dtype=jnp.int32)[:, None]  # (9, 1)

        def step(acc: ed.Point, k_w):
            d = k_w - 8             # signed digit in [-8, 7]
            k_oh = (jnp.abs(d)[None] == lanes).astype(jnp.float32)  # (9, batch)
            # 3 T-free doubles as an inner scan (one body in the graph) +
            # the final T-producing double — graph size, not runtime,
            # economy.
            acc, _ = limbs.counted_scan(
                lambda a, _: (ed.double(a, need_t=False), None), acc, None, length=3
            )
            acc = ed.double(acc)
            q = ed.table_lookup(a_table, k_oh)
            q = ed.select(d < 0, ed.negate(q), q)  # two field subs, no muls
            acc = ed.add(acc, q)
            return acc, None

        acc, _ = limbs.counted_scan(step, ed.identity_like(y_r), k_digits)
    acc = ed.add(acc, ed.fixed_base_mul_comb(s_digits8))

    return host_ok & r_ok & a_ok & ed.equal(acc, r_point)


_verify_kernel = instrumented_jit(
    verify_impl, "ed25519.verify" + kernel_lane_suffix()
)


_P_BYTES_BE = np.frombuffer(fe.P.to_bytes(32, "big"), dtype=np.uint8)


def _prep_compressed(points: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Compressed point bytes -> (y limbs, sign bits, y<p validity).

    Fully vectorized: byte rows -> unpacked bits -> grouped limb dot; the
    canonical-range check (y < p) is a lexicographic byte comparison."""
    n = len(points)
    ok = np.ones(n, dtype=bool)
    chunks: list[bytes] = []
    for i, raw in enumerate(points):
        if len(raw) == 32:
            chunks.append(raw)
        else:
            ok[i] = False
            chunks.append(b"\x00" * 32)
    # One bulk copy instead of n tiny frombuffer calls.
    rows = np.frombuffer(b"".join(chunks), dtype=np.uint8).reshape(n, 32)
    signs = (rows[:, 31] >> 7)  # uint8
    rows = rows.copy()
    rows[:, 31] &= 0x7F

    # y < p, vectorized: compare big-endian byte rows against p's bytes.
    rows_be = rows[:, ::-1]
    diff = rows_be != _P_BYTES_BE
    first = np.argmax(diff, axis=1)
    lt = rows_be[np.arange(n), first] < _P_BYTES_BE[first]
    ok &= np.where(diff.any(axis=1), lt, False)  # y == p is out of range too

    return rows, signs, ok  # byte-sized limbs: the bytes ARE the limbs


def _bits_to_signed_window_digits(bits: np.ndarray) -> np.ndarray:
    """(n, 256) LSB-first bit rows -> (64, n) SIGNED 4-bit digits in
    [-8, 7], wire-encoded as d+8 (uint8), MSB window first.

    Signed digits halve the scan's per-batch table: |d| <= 8 needs 9
    multiples of (-A) instead of 16 (negation is two mul-free field subs
    on device).  The LSB-to-MSB carry cannot escape: k < L < 2^253, so
    the top window is at most 1 before carry — no 65th window ever
    needed."""
    weights = np.array([1, 2, 4, 8], dtype=np.int32)
    u = bits.reshape(bits.shape[0], _WINDOWS, _WINDOW_BITS) @ weights  # (n, 64)
    d = np.zeros_like(u)
    carry = np.zeros(u.shape[0], dtype=u.dtype)
    for j in range(_WINDOWS):
        t = u[:, j] + carry
        over = t >= 8
        d[:, j] = np.where(over, t - 16, t)
        carry = over.astype(u.dtype)
    if carry.any():  # unreachable for canonical k (< 2^253)
        raise ValueError("scalar overflow in signed-digit recoding")
    return np.ascontiguousarray(d[:, ::-1].T + 8).astype(np.uint8)


def _bits_to_comb_digits8(bits: np.ndarray) -> np.ndarray:
    """(n, 256) LSB-first bit rows -> (32, n) 8-bit digits, LSB window
    first (the comb sums windows, order-free)."""
    weights = np.array([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.int32)
    digits = bits.reshape(bits.shape[0], 32, 8) @ weights
    return np.ascontiguousarray(digits.T).astype(np.uint8)


def to_kernel_layout(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok):
    """Host row-major arrays -> device layout: limbs/digits leading (on the
    sublanes), batch trailing (on the lanes); S as 8-bit comb digits, k as
    MSB-first 4-bit Horner digits.  Everything ships as the narrowest
    integer dtype (uint8/bool) — the kernel widens on device."""
    return (
        jnp.asarray(np.ascontiguousarray(y_r.T)),
        jnp.asarray(sign_r),
        jnp.asarray(np.ascontiguousarray(y_a.T)),
        jnp.asarray(sign_a),
        jnp.asarray(_bits_to_comb_digits8(s_bits)),
        jnp.asarray(_bits_to_signed_window_digits(k_bits)),
        jnp.asarray(host_ok),
    )


def _next_pow2(n: int, minimum: int = 8) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


class Ed25519BatchVerifier:
    """Verify many (message, signature, public key) triples at once.

    ``verify_batch`` returns a boolean numpy array.  ``pad_pow2`` keeps the
    set of compiled kernel shapes small; ``min_device_batch`` routes tiny
    batches to the host path (kernel launch overhead dominates below it).
    """

    def __init__(
        self,
        *,
        pad_pow2: bool = True,
        min_device_batch: int = 1,
        pad_to: int = 0,
        device: Optional[object] = None,
    ) -> None:
        """``pad_to`` > 0 pads every device batch to that fixed size (one
        compiled kernel shape for the whole deployment — no mid-run compiles
        on underfull batches); larger batches fall back to the pow-2
        ladder."""
        self._pad_pow2 = pad_pow2
        self._min_device_batch = min_device_batch
        self._pad_to = pad_to
        self._device = device

    @property
    def preferred_wave_size(self) -> int:
        """The smallest padded batch that saturates this engine — the
        device-batch floor rounded through the padding knobs.  Coalescers
        (models/engine.py) read it to size cross-tenant waves; the mesh
        engines override it with the whole-slice shard multiple."""
        from consensus_tpu.parallel.topology import engine_padded_size

        return engine_padded_size(
            max(1, self._min_device_batch),
            1,
            pad_to=self._pad_to,
            pad_pow2=self._pad_pow2,
        )

    def _prepare(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence[bytes],
    ) -> tuple[np.ndarray, ...]:
        """Host-side parse/hash/pack: returns the 7 unpadded kernel inputs
        ``(y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok)``."""
        n = len(messages)
        host_ok = np.ones(n, dtype=bool)
        zeros32 = b"\x00" * 32
        r_bytes: list[bytes] = []
        s_chunks: list[bytes] = []
        k_chunks: list[bytes] = []
        sha512 = hashlib.sha512
        from_bytes = int.from_bytes
        for i in range(n):
            sig = signatures[i]
            if len(sig) != 64:
                host_ok[i] = False
                r_bytes.append(zeros32)
                s_chunks.append(zeros32)
                k_chunks.append(zeros32)
                continue
            r_raw, s_raw = sig[:32], sig[32:]
            r_bytes.append(r_raw)
            if from_bytes(s_raw, "little") >= L:  # malleability, RFC 8032 §5.1.7
                host_ok[i] = False
                s_chunks.append(zeros32)
                k_chunks.append(zeros32)
                continue
            k = (
                from_bytes(sha512(r_raw + public_keys[i] + messages[i]).digest(), "little")
                % L
            )
            s_chunks.append(s_raw)
            k_chunks.append(k.to_bytes(32, "little"))
        # Bulk copies + one vectorized unpack (no per-row numpy calls).
        s_rows = np.frombuffer(b"".join(s_chunks), dtype=np.uint8).reshape(n, 32)
        k_rows = np.frombuffer(b"".join(k_chunks), dtype=np.uint8).reshape(n, 32)
        s_bits = _bytes_rows_to_bits(s_rows)
        k_bits = _bytes_rows_to_bits(k_rows)

        y_r, sign_r, r_ok = _prep_compressed(r_bytes)
        y_a, sign_a, a_ok = _prep_compressed(list(public_keys))
        host_ok &= r_ok & a_ok
        return y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok

    def verify_batch(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence[bytes],
    ) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)

        y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok = self._prepare(
            messages, signatures, public_keys
        )

        if self._pad_to >= n:
            padded = self._pad_to
        else:
            padded = _next_pow2(n) if self._pad_pow2 else n
        if padded != n:
            pad = padded - n
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            s_bits = np.pad(s_bits, ((0, pad), (0, 0)))
            k_bits = np.pad(k_bits, ((0, pad), (0, 0)))
            host_ok_padded = np.pad(host_ok, (0, pad))
        else:
            host_ok_padded = host_ok

        result = _verify_kernel(*to_kernel_layout(
            y_r, sign_r, y_a, sign_a, s_bits, k_bits, host_ok_padded
        ))
        return np.asarray(result)[:n]

    @staticmethod
    def _canonical_ok(signatures, public_keys) -> np.ndarray:
        """The device kernel's host-side pre-checks, standalone: sig length,
        S < L (RFC 8032 §5.1.7 malleability), and canonical compressed
        encodings (y < p) for both R and A."""
        n = len(signatures)
        ok = np.ones(n, dtype=bool)
        for i in range(n):
            sig, key = signatures[i], public_keys[i]
            if len(sig) != 64 or len(key) != 32:
                ok[i] = False
                continue
            if int.from_bytes(sig[32:], "little") >= L:
                ok[i] = False
                continue
            y_r = int.from_bytes(sig[:32], "little") & ((1 << 255) - 1)
            y_a = int.from_bytes(key, "little") & ((1 << 255) - 1)
            if y_r >= fe.P or y_a >= fe.P:
                ok[i] = False
        return ok

    @classmethod
    def _verify_host(cls, messages, signatures, public_keys) -> np.ndarray:
        """Sequential host fallback: the ``cryptography`` package when
        installed (C speed), else the pure-Python RFC 8032 reference below.

        Ed25519 verifiers disagree on adversarial edge cases (non-canonical
        encodings, S >= L), and in BFT a vote's validity must not depend on
        which replica (or batch size) checked it — so the device kernel's
        strict pre-checks run here too, and all replicas must use identical
        verifier config (min_device_batch included in quorum-relevant
        paths only via config parity)."""
        out = cls._canonical_ok(signatures, public_keys)
        try:
            from cryptography.exceptions import InvalidSignature
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PublicKey,
            )
        except ImportError:
            for i in range(len(out)):
                if out[i]:
                    out[i] = ref_verify(
                        bytes(public_keys[i]), bytes(signatures[i]),
                        bytes(messages[i]),
                    )
            return out
        for i, (msg, sig, key) in enumerate(zip(messages, signatures, public_keys)):
            if not out[i]:
                continue
            try:
                Ed25519PublicKey.from_public_bytes(bytes(key)).verify(
                    bytes(sig), bytes(msg)
                )
            except (InvalidSignature, ValueError):
                out[i] = False
        return out

    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        """Public seam for the coalescer's wedged-device escape hatch:
        verify on the host regardless of batch size, same strict semantics
        as the device path.  (A forwarding method, not a class-level alias,
        so subclass overrides of ``_verify_host`` take effect here too.)"""
        return self._verify_host(messages, signatures, public_keys)


# --- randomized batch verification ------------------------------------------
# One aggregate check for the whole batch: Σ zᵢ(SᵢB − kᵢAᵢ − Rᵢ) = 0 with
# independent 128-bit coefficients zᵢ.  A batch containing any forgery
# passes with probability <= 2^-128 over the choice of z (see SAFETY.md §7);
# the win is that the 256-bit variable-base doubling chain — ~2,000 of the
# strict kernel's ~2,800 M/sig — is paid once per BATCH, not per signature.

_Z_BITS = 128
#: Signed-4-bit windows for a 128-bit coefficient: 32 value windows plus one
#: for the recoding carry.
_Z_WINDOWS = _Z_BITS // _WINDOW_BITS + 1  # 33
_Z_TAG = b"ctpu/batchz/v1"


def _transcript_coefficients(
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
    public_keys: Sequence[bytes],
) -> list[int]:
    """Deterministic per-batch coefficients zᵢ ∈ [1, 2^128).

    Fiat–Shamir over the whole batch: every byte of every (message,
    signature, key) triple — length-framed so no two transcripts collide —
    feeds a root hash, and zᵢ = H(root || i).  An adversary must commit to
    the batch contents before learning any zᵢ, which is exactly the game
    the 2^-128 soundness bound is proved in; and there is no wallclock or
    ambient RNG, so same-seed runs stay byte-identical (repo determinism
    rule)."""
    sha512 = hashlib.sha512

    def frame(raw: bytes) -> bytes:
        return len(raw).to_bytes(8, "little") + bytes(raw)

    leaves = [
        sha512(frame(m) + frame(s) + frame(a)).digest()
        for m, s, a in zip(messages, signatures, public_keys)
    ]
    root = sha512(
        _Z_TAG + len(leaves).to_bytes(8, "little") + b"".join(leaves)
    ).digest()
    return [
        int.from_bytes(
            sha512(root + i.to_bytes(8, "little")).digest()[:_Z_BITS // 8],
            "little",
        )
        or 1
        for i in range(len(leaves))
    ]


def _signed_digits_int(value: int, windows: int) -> list[int]:
    """Host-integer twin of :func:`_bits_to_signed_window_digits`: signed
    4-bit digits in [-8, 7], MSB window first.  ``windows`` must leave one
    window of headroom for the recoding carry."""
    digits = [0] * windows
    carry = 0
    for j in range(windows):
        t = (value & 15) + carry
        value >>= 4
        if t >= 8:
            digits[j] = t - 16
            carry = 1
        else:
            digits[j] = t
            carry = 0
    if carry or value:
        raise ValueError("scalar too wide for signed-digit recoding")
    return digits[::-1]


def batch_verify_impl(
    y_r: jnp.ndarray,        # (32, batch) R.y limbs
    sign_r: jnp.ndarray,     # (batch,)    R.x sign bits
    y_a: jnp.ndarray,        # (32, batch) A.y limbs
    sign_a: jnp.ndarray,     # (batch,)    A.x sign bits
    zs_digits8: jnp.ndarray, # (32, 1)     Σ zᵢsᵢ mod L, 8-bit comb digits
    zk_digits: jnp.ndarray,  # (64, batch) zᵢkᵢ mod L signed 4-bit + 8, MSB first
    z_digits: jnp.ndarray,   # (33, batch) zᵢ signed 4-bit + 8, MSB first
    host_ok: jnp.ndarray,    # (batch,)    host pre-checks passed
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Un-jitted randomized-batch kernel body.

    Computes [Σzᵢsᵢ mod L]B + Σ[zᵢkᵢ mod L](−Aᵢ) + Σ[zᵢ](−Rᵢ) as one
    shared-doubling Straus MSM (:func:`consensus_tpu.ops.ed25519
    .straus_shared_msm`) plus a batch-1 fixed-base comb, and tests the
    accumulator against the identity.  Returns ``(eq_ok, valid)``:
    ``eq_ok`` is the scalar aggregate verdict, ``valid`` flags entries that
    decompressed (host pre-checks included).  Entries with ``valid`` false
    have their digits masked to zero so they contribute the identity —
    padding lanes ride the same mechanism — and the driver re-checks the
    surviving subset, so an undecompressable R/A can never poison the
    aggregate verdict of its batchmates."""
    y_r = y_r.astype(jnp.float32)
    y_a = y_a.astype(jnp.float32)
    sign_r = sign_r.astype(jnp.int32)
    sign_a = sign_a.astype(jnp.int32)
    zs_digits8 = zs_digits8.astype(jnp.int32)
    zk_digits = zk_digits.astype(jnp.int32)
    z_digits = z_digits.astype(jnp.int32)

    batch = y_r.shape[-1]
    pt, pt_ok = ed.decompress(
        jnp.concatenate([y_r, y_a], axis=-1),
        jnp.concatenate([sign_r, sign_a], axis=-1),
    )
    r_point = ed.Point(
        x=pt.x[..., :batch], y=pt.y[..., :batch],
        z=pt.z[..., :batch], t=pt.t[..., :batch],
    )
    a_point = ed.Point(
        x=pt.x[..., batch:], y=pt.y[..., batch:],
        z=pt.z[..., batch:], t=pt.t[..., batch:],
    )
    valid = host_ok & pt_ok[..., :batch] & pt_ok[..., batch:]

    # Digit 0 is encoded as 8; masking an invalid lane's digits to 8 makes
    # every one of its window contributions the identity point.
    zk_digits = jnp.where(valid[None], zk_digits, 8)
    z_digits = jnp.where(valid[None], z_digits, 8)

    a_table = ed.multiples_table9(ed.negate(a_point))
    r_table = ed.multiples_table9(ed.negate(r_point))
    acc = ed.straus_shared_msm(a_table, r_table, zk_digits, z_digits)
    acc = ed.add(acc, ed.fixed_base_mul_comb(zs_digits8))
    return ed.is_identity(acc)[0], valid


_batch_verify_kernel = instrumented_jit(
    batch_verify_impl, "ed25519.batch_verify" + kernel_lane_suffix()
)


def _ref_negate(p):
    x, y, z, t = p
    return ((fe.P - x) % fe.P, y, z, (fe.P - t) % fe.P)


class Ed25519RandomizedBatchVerifier(Ed25519BatchVerifier):
    """Randomized batch verification with bisection fallback.

    Same ``verify_batch`` contract (and, for honest inputs, the same result
    vector) as :class:`Ed25519BatchVerifier`, at an amortized per-signature
    cost that approaches the add-dominated floor as batches grow: one
    aggregate check replaces n independent double chains.  When the
    aggregate fails, the batch is split in half and each half re-checked
    with FRESH transcript coefficients — forgeries are localized in
    O(f · log n) aggregate checks, and every subset below
    ``min_randomized`` is decided by the strict verifier, so the final
    boolean vector for any input the strict kernel rejects-by-math is
    bit-identical to the strict path's (see SAFETY.md §7 for the one
    caveat class: small-order torsion components, which honest signers
    never produce).

    ``min_device_batch`` picks between the shared-doubling device kernel
    and a host big-int Straus with the identical two-phase window schedule.
    """

    randomized = True

    def __init__(
        self,
        *,
        pad_pow2: bool = True,
        min_device_batch: int = 1,
        pad_to: int = 0,
        device: Optional[object] = None,
        min_randomized: int = 2,
    ) -> None:
        super().__init__(
            pad_pow2=pad_pow2,
            min_device_batch=min_device_batch,
            pad_to=pad_to,
            device=device,
        )
        self._min_randomized = max(2, int(min_randomized))

    def verify_batch(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence[bytes],
    ) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        results = np.zeros(n, dtype=bool)
        if n == 0:
            return results
        host_ok = self._canonical_ok(signatures, public_keys)
        scalars: dict[int, tuple[int, int]] = {}
        for i in range(n):
            if not host_ok[i]:
                continue  # stays False, exactly like the strict kernel
            sig = bytes(signatures[i])
            key = bytes(public_keys[i])
            k = int.from_bytes(
                hashlib.sha512(sig[:32] + key + bytes(messages[i])).digest(),
                "little",
            ) % L
            scalars[i] = (int.from_bytes(sig[32:], "little"), k)
        self._check(
            [i for i in range(n) if host_ok[i]],
            messages, signatures, public_keys, scalars, results,
        )
        return results

    def _check(self, idx, messages, signatures, public_keys, scalars, results):
        """Recursive bisection: decide every index in ``idx``."""
        if not idx:
            return
        if len(idx) < self._min_randomized:
            sub = super().verify_batch(
                [messages[i] for i in idx],
                [signatures[i] for i in idx],
                [public_keys[i] for i in idx],
            )
            for j, i in enumerate(idx):
                results[i] = bool(sub[j])
            return
        zs = _transcript_coefficients(
            [messages[i] for i in idx],
            [signatures[i] for i in idx],
            [public_keys[i] for i in idx],
        )
        if len(idx) >= self._min_device_batch:
            eq_ok, valid = self._aggregate_device(idx, signatures, public_keys, scalars, zs)
        else:
            eq_ok, valid = self._aggregate_host(idx, signatures, public_keys, scalars, zs)
        if not all(valid):
            # Decompression failures are definitively invalid (strict
            # parity: the strict kernel rejects them the same way); their
            # digits were masked out of the aggregate, but re-check the
            # survivors under a fresh transcript rather than trusting a
            # verdict whose membership changed.
            survivors = [i for i, ok in zip(idx, valid) if ok]
            self._check(survivors, messages, signatures, public_keys, scalars, results)
            return
        if eq_ok:
            for i in idx:
                results[i] = True
            return
        mid = len(idx) // 2
        self._check(idx[:mid], messages, signatures, public_keys, scalars, results)
        self._check(idx[mid:], messages, signatures, public_keys, scalars, results)

    def _aggregate_inputs(self, idx, signatures, scalars, zs):
        """Shared host math for both backends: per-entry scalars
        (zk mod L, z) and the aggregate base-point scalar Σzᵢsᵢ mod L."""
        zk = [(z * scalars[i][1]) % L for z, i in zip(zs, idx)]
        u = 0
        for z, i in zip(zs, idx):
            u += z * scalars[i][0]
        return zk, u % L

    def _aggregate_device(self, idx, signatures, public_keys, scalars, zs):
        """One shared-doubling kernel launch over the subset."""
        m = len(idx)
        zk, u = self._aggregate_inputs(idx, signatures, scalars, zs)
        y_r, sign_r, _ = _prep_compressed([bytes(signatures[i])[:32] for i in idx])
        y_a, sign_a, _ = _prep_compressed([bytes(public_keys[i]) for i in idx])
        zk_digits = np.array(
            [_signed_digits_int(v, _WINDOWS) for v in zk], dtype=np.int16
        ).T
        z_digits = np.array(
            [_signed_digits_int(z, _Z_WINDOWS) for z in zs], dtype=np.int16
        ).T
        zk_digits = (zk_digits + 8).astype(np.uint8)
        z_digits = (z_digits + 8).astype(np.uint8)
        u_row = np.frombuffer(u.to_bytes(32, "little"), dtype=np.uint8).reshape(1, 32)
        zs_digits8 = _bits_to_comb_digits8(_bytes_rows_to_bits(u_row))
        host_ok = np.ones(m, dtype=bool)

        if self._pad_to >= m:
            padded = self._pad_to
        else:
            padded = _next_pow2(m) if self._pad_pow2 else m
        if padded != m:
            pad = padded - m
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            # Padding lanes: host_ok=False masks their digits to identity
            # contributions inside the kernel; the pad value just keeps the
            # encoding in range.
            zk_digits = np.pad(zk_digits, ((0, 0), (0, pad)), constant_values=8)
            z_digits = np.pad(z_digits, ((0, 0), (0, pad)), constant_values=8)
            host_ok = np.pad(host_ok, (0, pad))

        eq_ok, valid = _batch_verify_kernel(
            jnp.asarray(np.ascontiguousarray(y_r.T)),
            jnp.asarray(sign_r),
            jnp.asarray(np.ascontiguousarray(y_a.T)),
            jnp.asarray(sign_a),
            jnp.asarray(zs_digits8),
            jnp.asarray(zk_digits),
            jnp.asarray(z_digits),
            jnp.asarray(host_ok),
        )
        return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:m])

    def _aggregate_host(self, idx, signatures, public_keys, scalars, zs):
        """Host big-int twin of the kernel: the SAME two-phase shared-window
        schedule in plain integers (~113 point adds per signature vs ~380
        for per-signature double-and-add — the host path needs the
        amortization too, it backs every CPU deployment and test)."""
        m = len(idx)
        a_pts = [_ref_decompress(bytes(public_keys[i])) for i in idx]
        r_pts = [_ref_decompress(bytes(signatures[i])[:32]) for i in idx]
        valid = [a is not None and r is not None for a, r in zip(a_pts, r_pts)]
        if not all(valid):
            return False, valid
        zk, u = self._aggregate_inputs(idx, signatures, scalars, zs)

        def table(p):
            neg = _ref_negate(p)
            tbl = [_REF_IDENTITY, neg]
            for _ in range(_TABLE - 2):
                tbl.append(_ref_add(tbl[-1], neg))
            return tbl

        a_tbl = [table(p) for p in a_pts]
        r_tbl = [table(p) for p in r_pts]
        zk_digits = [_signed_digits_int(v, _WINDOWS) for v in zk]
        z_digits = [_signed_digits_int(z, _Z_WINDOWS) for z in zs]

        acc = _REF_IDENTITY
        low_start = _WINDOWS - _Z_WINDOWS
        for w in range(_WINDOWS):
            for _ in range(4):
                acc = _ref_add(acc, acc)
            for j in range(m):
                d = zk_digits[j][w]
                if d:
                    acc = _ref_add(
                        acc, a_tbl[j][d] if d > 0 else _ref_negate(a_tbl[j][-d])
                    )
                if w >= low_start:
                    d = z_digits[j][w - low_start]
                    if d:
                        acc = _ref_add(
                            acc, r_tbl[j][d] if d > 0 else _ref_negate(r_tbl[j][-d])
                        )
        acc = _ref_add(acc, _ref_mul(u, _BASE_POINT))
        eq_ok = acc[0] % fe.P == 0 and (acc[1] - acc[2]) % fe.P == 0
        return eq_ok, valid


# --- pure-Python RFC 8032 reference (host) ---------------------------------
# Plain-integer edwards25519: keygen, sign, verify.  Serves two roles: the
# host-verification fallback when the ``cryptography`` package is not
# installed, and the signing backend for models.verifier.Ed25519Signer in
# the same situation — real Ed25519 (interoperable with any conformant
# implementation), just Python-speed.  Verification keeps the strict
# semantics of the device kernel: S < L, canonical (y < p) encodings.

_D_REF = (-121665 * pow(121666, fe.P - 2, fe.P)) % fe.P
_BASE_Y = (4 * pow(5, fe.P - 2, fe.P)) % fe.P


def _ref_recover_x(y: int, sign: int) -> Optional[int]:
    x2 = (y * y - 1) * pow(_D_REF * y * y + 1, fe.P - 2, fe.P) % fe.P
    x = pow(x2, (fe.P + 3) // 8, fe.P)
    if (x * x - x2) % fe.P:
        x = x * pow(2, (fe.P - 1) // 4, fe.P) % fe.P
    if (x * x - x2) % fe.P:
        return None
    if x == 0 and sign:
        return None  # RFC 8032 §5.1.3 step 4
    if x & 1 != sign:
        x = fe.P - x
    return x


_REF_IDENTITY = (0, 1, 1, 0)


def _ref_add(p, q):
    # Extended homogeneous coordinates, RFC 8032 §5.1.4.
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % fe.P
    b = (y1 + x1) * (y2 + x2) % fe.P
    c = 2 * t1 * t2 * _D_REF % fe.P
    d = 2 * z1 * z2 % fe.P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % fe.P, g * h % fe.P, f * g % fe.P, e * h % fe.P)


def _ref_mul(s: int, p):
    q = _REF_IDENTITY
    while s:
        if s & 1:
            q = _ref_add(q, p)
        p = _ref_add(p, p)
        s >>= 1
    return q


_BASE_POINT = (
    _ref_recover_x(_BASE_Y, 0),
    _BASE_Y,
    1,
    _ref_recover_x(_BASE_Y, 0) * _BASE_Y % fe.P,
)


def _ref_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, fe.P - 2, fe.P)
    x, y = x * zinv % fe.P, y * zinv % fe.P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _ref_decompress(raw: bytes):
    if len(raw) != 32:
        return None
    y = int.from_bytes(raw, "little")
    sign, y = y >> 255, y & ((1 << 255) - 1)
    if y >= fe.P:
        return None
    x = _ref_recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % fe.P)


def _ref_scalars(seed: bytes) -> tuple[int, bytes]:
    if len(seed) != 32:
        raise ValueError("Ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def ref_public_key(seed: bytes) -> bytes:
    """RFC 8032 §5.1.5: the 32-byte public key for a 32-byte seed."""
    a, _ = _ref_scalars(seed)
    return _ref_compress(_ref_mul(a, _BASE_POINT))


def ref_sign(seed: bytes, message: bytes) -> bytes:
    """RFC 8032 §5.1.6: the 64-byte signature R || S."""
    a, prefix = _ref_scalars(seed)
    a_enc = _ref_compress(_ref_mul(a, _BASE_POINT))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % L
    r_enc = _ref_compress(_ref_mul(r, _BASE_POINT))
    k = int.from_bytes(
        hashlib.sha512(r_enc + a_enc + message).digest(), "little"
    ) % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def ref_verify(public_key: bytes, signature: bytes, message: bytes) -> bool:
    """RFC 8032 §5.1.7 with the device kernel's strict pre-checks."""
    if len(signature) != 64 or len(public_key) != 32:
        return False
    r_enc, s_raw = signature[:32], signature[32:]
    s = int.from_bytes(s_raw, "little")
    if s >= L:
        return False
    a_pt = _ref_decompress(public_key)
    r_pt = _ref_decompress(r_enc)
    if a_pt is None or r_pt is None:
        return False
    k = int.from_bytes(
        hashlib.sha512(r_enc + public_key + message).digest(), "little"
    ) % L
    lhs = _ref_mul(s, _BASE_POINT)
    rhs = _ref_add(r_pt, _ref_mul(k, a_pt))
    return (
        (lhs[0] * rhs[2] - rhs[0] * lhs[2]) % fe.P == 0
        and (lhs[1] * rhs[2] - rhs[1] * lhs[2]) % fe.P == 0
    )


__all__ = [
    "Ed25519BatchVerifier",
    "Ed25519RandomizedBatchVerifier",
    "L",
    "ref_public_key",
    "ref_sign",
    "ref_verify",
]
