"""Engine supervision: fault-classed circuit breakers and a degrade ladder.

The accelerator-resident engines (fused, mesh-sharded, half-agg) made the
device a single point of failure that only the coalescer's *timeout* path
survived — a launch that raises, a lost mesh shard, or a device silently
returning garbage killed the decision or corrupted a verdict.  This module
makes acceleration an optimization, never a liveness or soundness
dependency:

* :class:`EngineSupervisor` wraps any engine stack and classifies failures
  into three fault classes — ``launch_timeout`` (:class:`LaunchTimeout`,
  the coalescer's wedged-device signal), ``launch_raise`` (XLA error,
  device loss, compile failure), and ``wrong_answer`` (caught by a
  deterministic sampled host cross-check against the big-int twins).
* Each fault class runs its own circuit breaker (closed → open → half-open
  re-probe with exponential backoff).  Time is INJECTED — a ``clock``
  callable, usually ``scheduler.now`` — so breaker behavior is replayable
  under SimScheduler; without a clock the supervisor counts launches,
  which is equally deterministic.
* An open breaker degrades the supervisor down an explicit ladder
  (fused → unfused device → host twin; N mesh shards → single device →
  host), re-promoting automatically when the breaker closes after a
  successful half-open probe.  While ANY host twin exists, no launch ever
  raises out of :meth:`EngineSupervisor.verify_batch`.
* Every transition is triple-booked: the pinned
  ``engine_degrade_total{reason}`` / ``engine_recovered_total`` /
  ``engine_crosscheck_*`` metric families, ``engine.degrade`` /
  ``engine.recover`` trace instants, and (via the health surface the obs
  sampler reads) the edge-triggered ``engine_degraded`` detector; a
  degrade also snapshots the flight recorder when one is attached.

:class:`EngineHealth` / :class:`EngineHealthRegistry` replace the private
``_device_suspect`` flag each ``ThreadCoalescingVerifier`` used to keep:
every coalescer (and every tenant behind a sidecar) wrapping the same
engine now shares one suspect state, so a wedge seen by one waiter routes
everyone to host immediately.
"""

from __future__ import annotations

import logging
import threading
import weakref
from typing import Callable, Optional, Sequence

import numpy as np

logger = logging.getLogger("consensus_tpu.models.supervisor")

#: The three supervised fault classes, in degrade-reason label order.
FAULT_CLASSES = ("launch_timeout", "launch_raise", "wrong_answer")


class LaunchTimeout(TimeoutError):
    """A device launch exceeded its deadline (wedged tunnel, hung transfer).

    Raised into the supervisor by integration points that can observe a
    timeout without blocking forever — the coalescer's waiter path, or the
    chaos plane's injected launch wrappers, which model a hang as this
    exception so SimScheduler runs stay deterministic (a real thread hang
    would not replay)."""


class EngineHealth:
    """Shared suspect state for one engine, thread-safe.

    ``ThreadCoalescingVerifier`` instances (one per replica, or one per
    sidecar tenant lane) wrapping the same engine share one of these via
    :data:`ENGINE_HEALTH`, so a device wedge observed by any of them routes
    all of them to the host path at once."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._suspect = False
        self.reason = ""
        #: Total times this engine was marked suspect (diagnostics only).
        self.suspect_marks = 0

    @property
    def suspect(self) -> bool:
        return self._suspect

    def mark_suspect(self, reason: str = "") -> bool:
        """Mark the engine suspect; returns True on the CLEAR -> SUSPECT
        edge (callers log / book only on the edge)."""
        with self._lock:
            edge = not self._suspect
            self._suspect = True
            self.reason = reason
            self.suspect_marks += 1
            return edge

    def clear(self) -> bool:
        """Clear the suspect flag; returns True on the SUSPECT -> CLEAR
        edge."""
        with self._lock:
            edge = self._suspect
            self._suspect = False
            self.reason = ""
            return edge


class EngineHealthRegistry:
    """Process-wide map from engine instance to its shared
    :class:`EngineHealth` — weak-keyed, so engines die normally."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_engine: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    def for_engine(self, engine) -> EngineHealth:
        with self._lock:
            try:
                health = self._by_engine.get(engine)
            except TypeError:  # unhashable / unweakrefable engine
                return EngineHealth()
            if health is None:
                health = EngineHealth()
                try:
                    self._by_engine[engine] = health
                except TypeError:
                    pass
            return health


#: The process-wide registry coalescers default to.
ENGINE_HEALTH = EngineHealthRegistry()


class CircuitBreaker:
    """Closed → open → half-open breaker with exponential backoff.

    Pure state machine over an injected ``now`` — no clock of its own, so
    it replays identically under SimScheduler or a launch-count clock."""

    def __init__(
        self,
        *,
        failure_threshold: int = 1,
        backoff_initial: float = 30.0,
        backoff_max: float = 480.0,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if backoff_initial <= 0 or backoff_max < backoff_initial:
            raise ValueError("backoff must satisfy 0 < initial <= max")
        self.failure_threshold = failure_threshold
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.state = "closed"
        self.failures = 0
        self.opened_count = 0
        self._backoff = backoff_initial
        self._retry_at: Optional[float] = None

    def record_failure(self, now: float) -> bool:
        """Book one failure; returns True when the breaker (re)opens."""
        self.failures += 1
        if self.state == "half_open":
            # Failed re-probe: reopen with doubled backoff.
            self._backoff = min(self._backoff * 2.0, self.backoff_max)
            self.state = "open"
            self.opened_count += 1
            self._retry_at = now + self._backoff
            return True
        if self.state == "closed" and self.failures >= self.failure_threshold:
            self.state = "open"
            self.opened_count += 1
            self._retry_at = now + self._backoff
            return True
        if self.state == "open":
            self._retry_at = now + self._backoff
        return False

    def probe_due(self, now: float) -> bool:
        """True when an open breaker's backoff has elapsed — transitions to
        half-open, granting the caller exactly one re-probe."""
        if self.state == "open" and now >= (self._retry_at or 0.0):
            self.state = "half_open"
            return True
        return self.state == "half_open"

    def record_success(self, now: float) -> bool:
        """Book a successful probe (or healthy call); returns True on the
        half-open -> closed edge."""
        was_probe = self.state == "half_open"
        self.state = "closed"
        self.failures = 0
        self._backoff = self.backoff_initial
        self._retry_at = None
        return was_probe


class HostTwin:
    """The ladder's final rung: big-int host verification of a device
    engine — slow, but ground truth (SAFETY §12)."""

    randomized = False

    def __init__(self, engine) -> None:
        host = getattr(engine, "verify_host", None)
        if host is None:
            raise ValueError(f"{type(engine).__name__} has no host twin")
        self._engine = engine
        self._host = host

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        return np.asarray(
            self._host(messages, signatures, public_keys), dtype=bool
        )

    # The twin of a twin is itself: coalescers wrapping a supervisor whose
    # ladder bottomed out still find a host fallback.
    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        return self.verify_batch(messages, signatures, public_keys)


class EngineSupervisor:
    """Wraps a best-first ladder of engines with fault-classed breakers.

    ``rungs`` is a non-empty best-first sequence (e.g. ``[fused, unfused]``
    or ``[two_shard, single_device]``); unless ``append_host`` is False, a
    :class:`HostTwin` of the last rung is appended as the ladder's floor.
    ``clock`` is a zero-arg callable (``scheduler.now`` under simulation,
    ``time.monotonic`` from real-thread call sites); without one the
    supervisor counts launches, which keeps backoff deterministic.
    ``crosscheck_interval=k`` host-cross-checks every k-th launch (0 = off);
    sampling is launch-counter based, never random, so a fixed-seed run
    cross-checks the same launches every replay.
    """

    def __init__(
        self,
        rungs: Sequence,
        *,
        clock: Optional[Callable[[], float]] = None,
        crosscheck_interval: int = 0,
        failure_threshold: int = 1,
        backoff_initial: float = 30.0,
        backoff_max: float = 480.0,
        append_host: bool = True,
        metrics=None,
        tracer=None,
        flight_recorder=None,
        health: Optional[EngineHealth] = None,
        name: str = "engine",
    ) -> None:
        rungs = list(rungs)
        if not rungs:
            raise ValueError("supervisor needs at least one engine rung")
        if crosscheck_interval < 0:
            raise ValueError("crosscheck_interval must be >= 0")
        if append_host and not isinstance(rungs[-1], HostTwin):
            if getattr(rungs[-1], "verify_host", None) is not None:
                rungs.append(HostTwin(rungs[-1]))
        self._rungs = rungs
        self._has_host = isinstance(rungs[-1], HostTwin)
        self._clock = clock
        self._crosscheck_interval = crosscheck_interval
        self._lock = threading.RLock()
        self._rung = 0
        self._launches = 0
        self._probing: Optional[str] = None
        #: One reason per degrade step taken, newest last.
        self._degrade_stack: list[str] = []
        self.breakers = {
            cls: CircuitBreaker(
                failure_threshold=failure_threshold,
                backoff_initial=backoff_initial,
                backoff_max=backoff_max,
            )
            for cls in FAULT_CLASSES
        }
        self._metrics = getattr(metrics, "engine", metrics)
        self._tracer = tracer
        self._flight = flight_recorder
        self.health = health if health is not None else ENGINE_HEALTH.for_engine(self)
        self.name = name
        #: ``fn(kind, reason, rung)`` with kind in {"degrade", "recover"}.
        self.on_transition: list[Callable[[str, str, int], None]] = []
        if self._metrics is not None:
            self._metrics.rung.set(0)

    # -- introspection -------------------------------------------------------

    @property
    def rung(self) -> int:
        """Current ladder position (0 = as configured)."""
        return self._rung

    @property
    def degraded(self) -> bool:
        return self._rung > 0

    @property
    def rung_count(self) -> int:
        return len(self._rungs)

    @property
    def engine(self):
        """The engine currently serving (for tests / diagnostics)."""
        return self._rungs[self._rung]

    def rung_label(self, rung: int) -> str:
        """Human-readable rung name: the engine class, annotated with its
        ``shard_count`` when it has one — a mesh ladder's rungs are the
        same class at different widths, and "ShardedEd25519Verifier[2] ->
        ShardedEd25519Verifier[1]" is the readable transition."""
        engine = self._rungs[rung]
        label = type(engine).__name__
        shards = getattr(engine, "shard_count", None)
        if shards is not None:
            label += f"[{shards}]"
        return label

    def __getattr__(self, attr):
        # Engine-shape attributes (randomized, pad_to, min_device_batch,
        # shard_count, preferred_wave_size, …) come from the PRIMARY rung:
        # callers size batches — and coalescers size slice-filling waves —
        # for the engine they configured, and degrades must not change
        # wire-visible semantics mid-flight (SAFETY §12).
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self._rungs[0], attr)

    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        """The ladder's ground truth (used by coalescers as fallback)."""
        return np.asarray(
            self._rungs[-1].verify_batch(messages, signatures, public_keys),
            dtype=bool,
        )

    # -- clock ---------------------------------------------------------------

    def _now(self) -> float:
        if self._clock is not None:
            return float(self._clock())
        return float(self._launches)

    # -- verify --------------------------------------------------------------

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        with self._lock:
            self._launches += 1
            now = self._now()
            self._maybe_repromote(now)
            rung = self._rung
            while True:
                engine = self._rungs[rung]
                if isinstance(engine, HostTwin):
                    # Ground truth: nothing to classify, nothing to check.
                    result = engine.verify_batch(messages, signatures, public_keys)
                    self._note_success(rung, now)
                    return result
                try:
                    result = np.asarray(
                        engine.verify_batch(messages, signatures, public_keys),
                        dtype=bool,
                    )
                except LaunchTimeout as exc:
                    if rung + 1 >= len(self._rungs):
                        raise  # no rung left below — fail loud
                    rung = self._fault(rung, "launch_timeout", exc, now)
                    continue
                except BaseException as exc:
                    if rung + 1 >= len(self._rungs):
                        raise  # no rung left below — fail loud
                    rung = self._fault(rung, "launch_raise", exc, now)
                    continue
                if self._crosscheck_due():
                    host = self._host_truth(messages, signatures, public_keys)
                    if host is not None and not np.array_equal(result, host):
                        self._book_crosscheck(mismatch=True)
                        self._fault(
                            rung,
                            "wrong_answer",
                            ValueError("host cross-check contradicted device"),
                            now,
                        )
                        # The device verdict is untrusted; the host twin's
                        # answer is the one that leaves this call.
                        return host
                    self._book_crosscheck(mismatch=False)
                self._note_success(rung, now)
                return result

    def _crosscheck_due(self) -> bool:
        k = self._crosscheck_interval
        return k > 0 and self._has_host and self._launches % k == 0

    def _host_truth(self, messages, signatures, public_keys):
        if not self._has_host:
            return None
        return self._rungs[-1].verify_batch(messages, signatures, public_keys)

    def _book_crosscheck(self, *, mismatch: bool) -> None:
        if self._metrics is None:
            return
        self._metrics.count_crosscheck.add(1)
        if mismatch:
            self._metrics.count_crosscheck_mismatch.add(1)

    # -- transitions ---------------------------------------------------------

    def _fault(self, rung: int, reason: str, exc: BaseException, now: float) -> int:
        """Book one classified fault at ``rung``; returns the rung the
        current call should be served from."""
        breaker = self.breakers[reason]
        was_probe = self._probing == reason
        if was_probe:
            self._probing = None  # failed half-open probe
        breaker.record_failure(now)
        below = min(rung + 1, len(self._rungs) - 1)
        if breaker.state == "open" and self._rung <= rung and below > self._rung:
            # A failed probe re-enters the degrade step it was probing out
            # of — book the transition but don't double-push the stack.
            self._degrade(
                reason, exc, from_rung=rung, to_rung=below, push=not was_probe
            )
        return below

    def _degrade(self, reason: str, exc: BaseException, *,
                 from_rung: int, to_rung: int, push: bool = True) -> None:
        self._rung = to_rung
        if push:
            self._degrade_stack.append(reason)
        self.health.mark_suspect(reason)
        detail = (
            f"{self.name}: {self.rung_label(from_rung)} fault "
            f"({reason}: {exc!r}) — degrading to rung {to_rung} "
            f"({self.rung_label(to_rung)})"
        )
        logger.error("%s", detail)
        if self._metrics is not None:
            _labeled(self._metrics.count_degrade, reason).add(1)
            self._metrics.rung.set(to_rung)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "engine", "engine.degrade",
                reason=reason, rung=to_rung, name=self.name,
            )
        if self._flight is not None:
            try:
                self._flight.trigger(f"engine-degrade-{reason}", detail=detail)
            except Exception:
                logger.exception("flight-record snapshot failed (ignored)")
        for hook in self.on_transition:
            hook("degrade", reason, to_rung)

    def _maybe_repromote(self, now: float) -> None:
        """Climb one rung when the breaker that degraded us grants a
        half-open probe (the current call serves as the probe), or freely
        when that breaker already re-closed — a probe one step up already
        vouched for the fault class."""
        if not self._degrade_stack or self._probing is not None:
            return
        reason = self._degrade_stack[-1]
        breaker = self.breakers[reason]
        if breaker.state == "closed":
            self._degrade_stack.pop()
            self._rung -= 1
            self._book_recover(reason, now)
            return
        if breaker.probe_due(now):
            self._probing = reason
            self._rung -= 1

    def _note_success(self, rung: int, now: float) -> None:
        if rung != self._rung:
            return  # served from an emergency rung below; state already moved
        reason = self._probing
        if reason is None:
            return
        self._probing = None
        self.breakers[reason].record_success(now)
        if self._degrade_stack and self._degrade_stack[-1] == reason:
            self._degrade_stack.pop()
        logger.warning(
            "%s: half-open probe at rung %d succeeded — breaker %s closed, "
            "re-promoted", self.name, rung, reason,
        )
        self._book_recover(reason, now)

    def _book_recover(self, reason: str, now: float) -> None:
        if not self._degrade_stack:
            self.health.clear()
        if self._metrics is not None:
            self._metrics.count_recovered.add(1)
            self._metrics.rung.set(self._rung)
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.instant(
                "engine", "engine.recover",
                reason=reason, rung=self._rung, name=self.name,
            )
        for hook in self.on_transition:
            hook("recover", reason, self._rung)


def _labeled(instrument, value: str):
    """The labeled child series, or the base instrument when the bundle has
    no label dimension (metrics must never break the verify path)."""
    try:
        return instrument.with_labels(value)
    except Exception:
        return instrument


__all__ = [
    "CircuitBreaker",
    "ENGINE_HEALTH",
    "EngineHealth",
    "EngineHealthRegistry",
    "EngineSupervisor",
    "FAULT_CLASSES",
    "HostTwin",
    "LaunchTimeout",
]
