"""First-class engine registry: ``(curve, mode, topology, device_prep,
mxu)`` -> batch-engine builder.

``engine_for_config``'s routing used to be an if-ladder over four
orthogonal knobs; every new axis (curves, randomized lanes, fused
front-ends, mesh topologies) multiplied its branches.  The registry makes
the matrix explicit: each supported combination is REGISTERED under an
:class:`EngineKey`, lookups of unregistered keys fail loudly with the
curve-specific reason (randomized and fused lanes are Ed25519-only), and
the supervisor's degrade ladder (`degrade_ladder_configs`) is derived by
walking registered keys — mesh -> single device, then fused -> host prep —
instead of hand-rolled config surgery.

Builders are lazy: nothing here imports jax or the engine modules until a
key is actually built, so the registry (like the config plane) stays
importable on boxes without the accelerator stack.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Callable, Optional

#: The two verification modes an engine key can select.
MODES = ("strict", "randomized")
#: The two launch topologies: one device, or a device mesh (any shape —
#: the key deliberately abstracts over mesh GEOMETRY, which is per-replica
#: free and carried separately by the MeshTopology handed to the builder).
TOPOLOGIES = ("single", "mesh")


class UnknownEngineError(ValueError):
    """No engine is registered under the requested key (the message names
    the reason: unknown curve, Ed25519-only lane, or plain unregistered)."""


@dataclass(frozen=True)
class EngineKey:
    """One cell of the engine matrix.

    ``topology`` is the coarse launch class (``"single"`` vs ``"mesh"``) —
    mesh geometry ((8,) vs (2, 4)) never changes which engine CLASS runs,
    only the device layout, so it stays out of the key and rides the
    ``MeshTopology`` argument to the builder instead.
    """

    curve: str = "ed25519"
    mode: str = "strict"
    topology: str = "single"
    device_prep: bool = False
    #: The MXU field-arithmetic lane (``CTPU_MXU_LIMBS=1``).  Env-derived
    #: only — the lane is selected at trace time by the environment, so a
    #: config knob would let key and traced graph disagree; the key axis
    #: exists so the registry can refuse cells the lane does not cover
    #: (P-256 has no MXU MSM) instead of silently falling back.
    mxu: bool = False

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.topology not in TOPOLOGIES:
            raise ValueError(
                f"topology must be one of {TOPOLOGIES}, got {self.topology!r}"
            )


class EngineRegistry:
    """Pluggable ``EngineKey`` -> builder map with loud lookup failures.

    A builder is ``fn(topology, compile_cache, **kw) -> engine`` where
    ``topology`` is a :class:`~consensus_tpu.parallel.topology.MeshTopology`
    (ignored by single-device builders), ``compile_cache`` opts into the
    process-wide compiled-kernel memo, and ``kw`` carries the padding knobs
    (``pad_pow2``, ``min_device_batch``).
    """

    def __init__(self) -> None:
        self._builders: dict[EngineKey, Callable] = {}

    def register(self, key: EngineKey, builder: Callable) -> None:
        if key in self._builders:
            raise ValueError(f"engine already registered under {key}")
        self._builders[key] = builder

    def __contains__(self, key: EngineKey) -> bool:
        return key in self._builders

    def keys(self) -> tuple:
        """Every registered key (stable registration order)."""
        return tuple(self._builders)

    def curves(self) -> tuple:
        seen = []
        for key in self._builders:
            if key.curve not in seen:
                seen.append(key.curve)
        return tuple(seen)

    def builder(self, key: EngineKey) -> Callable:
        b = self._builders.get(key)
        if b is None:
            raise UnknownEngineError(self._missing_reason(key))
        return b

    def _missing_reason(self, key: EngineKey) -> str:
        if key.curve not in self.curves():
            return f"unknown curve {key.curve!r}"
        if key.mxu and key.curve != "ed25519":
            return (
                "CTPU_MXU_LIMBS engines are Ed25519-only: P-256 has no MXU "
                "Straus/MSM kernel yet, and building a P-256 engine under "
                "an MXU key would silently run a half-MXU lane the A/B "
                "never measured — unset CTPU_MXU_LIMBS for P-256 engines"
            )
        if key.curve == "p256" and key.mode == "randomized":
            return "batch_verify_mode is Ed25519-only (no randomized P-256 lane)"
        if key.curve == "p256" and key.device_prep:
            return "device_prep is Ed25519-only (no fused P-256 front-end)"
        return (
            f"no engine registered under {key} "
            f"(registered: {', '.join(str(k) for k in self.keys())})"
        )

    def build(
        self,
        key: EngineKey,
        topology=None,
        *,
        compile_cache: bool = True,
        **kw,
    ):
        return self.builder(key)(topology, compile_cache, **kw)

    def degrade_keys(self, key: EngineKey) -> list:
        """The best-first key ladder supervision degrades down from ``key``:
        mesh -> single device, then fused -> host prep, pruned to keys that
        are actually registered.  (The host twin is not a key — the
        supervisor appends it as the ladder's floor itself.)"""
        ladder = [key]
        cur = key
        if cur.topology == "mesh":
            cur = replace(cur, topology="single")
            ladder.append(cur)
        if cur.device_prep:
            cur = replace(cur, device_prep=False)
            ladder.append(cur)
        return [ladder[0]] + [k for k in ladder[1:] if k in self]


# --- the default matrix ------------------------------------------------------
#
# 2 curves x strict/randomized x single/mesh x host-prep/device-prep, minus
# the Ed25519-only lanes: randomized and fused have no P-256 counterpart,
# so those cells stay UNREGISTERED and lookups explain why.


def _require_mxu_lane() -> None:
    """A builder registered under an ``mxu=True`` key promises a graph the
    process only traces when the environment selects the lane — building
    it without ``CTPU_MXU_LIMBS=1`` would hand back a silently-VPU engine
    under an MXU label, exactly the mislabeled A/B the registry exists to
    prevent."""
    if os.environ.get("CTPU_MXU_LIMBS", "") != "1":
        raise RuntimeError(
            "EngineKey.mxu=True but CTPU_MXU_LIMBS is not '1': the MXU "
            "lane is selected by the environment at trace time, so this "
            "build would trace the VPU lane under an MXU key — set "
            "CTPU_MXU_LIMBS=1 in the process environment first"
        )


def _ed25519_single(topology, compile_cache, *, randomized, fused, mxu=False, **kw):
    if mxu:
        _require_mxu_lane()
    if fused:
        from consensus_tpu.models.fused import (
            FusedEd25519BatchVerifier,
            FusedEd25519RandomizedBatchVerifier,
        )

        cls = (
            FusedEd25519RandomizedBatchVerifier
            if randomized
            else FusedEd25519BatchVerifier
        )
    else:
        from consensus_tpu.models.ed25519 import (
            Ed25519BatchVerifier,
            Ed25519RandomizedBatchVerifier,
        )

        cls = (
            Ed25519RandomizedBatchVerifier if randomized else Ed25519BatchVerifier
        )
    return cls(**kw)


def _ed25519_mesh(topology, compile_cache, *, randomized, fused, mxu=False, **kw):
    if mxu:
        _require_mxu_lane()
    from consensus_tpu.parallel import sharding

    cls = {
        (False, False): sharding.ShardedEd25519Verifier,
        (True, False): sharding.ShardedEd25519RandomizedVerifier,
        (False, True): sharding.ShardedFusedEd25519Verifier,
        (True, True): sharding.ShardedFusedEd25519RandomizedVerifier,
    }[(randomized, fused)]
    return cls(topology, compile_cache=compile_cache, **kw)


def _p256_single(topology, compile_cache, **kw):
    from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier

    return EcdsaP256BatchVerifier(**kw)


def _p256_mesh(topology, compile_cache, **kw):
    from consensus_tpu.parallel.sharding import ShardedEcdsaP256Verifier

    return ShardedEcdsaP256Verifier(topology, compile_cache=compile_cache, **kw)


def _default_registry() -> EngineRegistry:
    from functools import partial

    reg = EngineRegistry()
    for mode in MODES:
        for fused in (False, True):
            for mxu in (False, True):
                randomized = mode == "randomized"
                reg.register(
                    EngineKey("ed25519", mode, "single", fused, mxu),
                    partial(
                        _ed25519_single,
                        randomized=randomized, fused=fused, mxu=mxu,
                    ),
                )
                reg.register(
                    EngineKey("ed25519", mode, "mesh", fused, mxu),
                    partial(
                        _ed25519_mesh,
                        randomized=randomized, fused=fused, mxu=mxu,
                    ),
                )
    # p256 x mxu stays UNREGISTERED (no MXU MSM for P-256);
    # _missing_reason names the refusal.
    reg.register(EngineKey("p256", "strict", "single", False), _p256_single)
    reg.register(EngineKey("p256", "strict", "mesh", False), _p256_mesh)
    return reg


#: The process-wide registry ``engine_for_config`` routes through.
#: Embedders may ``register`` additional curves/lanes at startup.
ENGINE_REGISTRY = _default_registry()


def engine_key_for(config, curve: str = "ed25519") -> EngineKey:
    """The registry key a ``Configuration``'s crypto knobs select."""
    from consensus_tpu.parallel.topology import topology_for_config

    mesh = topology_for_config(config).shard_count > 1
    return EngineKey(
        curve=curve,
        mode=(
            "randomized"
            if bool(getattr(config, "batch_verify_mode", False))
            else "strict"
        ),
        topology="mesh" if mesh else "single",
        device_prep=bool(getattr(config, "device_prep", False)),
        # Env-derived on purpose (no config attr): the lane is chosen at
        # trace time by CTPU_MXU_LIMBS, so the key mirrors the env instead
        # of introducing a knob the traced graphs could contradict.
        mxu=os.environ.get("CTPU_MXU_LIMBS", "") == "1",
    )


__all__ = [
    "ENGINE_REGISTRY",
    "EngineKey",
    "EngineRegistry",
    "MODES",
    "TOPOLOGIES",
    "UnknownEngineError",
    "engine_key_for",
]
