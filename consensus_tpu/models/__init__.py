"""Batched signature-verification models built on :mod:`consensus_tpu.ops`."""

from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    L,
)
from consensus_tpu.models.engine import BatchCoalescer, ThreadCoalescingVerifier
from consensus_tpu.models.supervisor import (
    ENGINE_HEALTH,
    FAULT_CLASSES,
    CircuitBreaker,
    EngineHealth,
    EngineHealthRegistry,
    EngineSupervisor,
    HostTwin,
    LaunchTimeout,
)
from consensus_tpu.models.fused import (
    FusedEd25519BatchVerifier,
    FusedEd25519RandomizedBatchVerifier,
)
from consensus_tpu.models.verifier import (
    EcdsaP256Signer,
    EcdsaP256VerifierMixin,
    Ed25519Signer,
    Ed25519VerifierMixin,
    commit_message,
    degrade_ladder_configs,
    engine_for_config,
    raw_message,
)

__all__ = [
    "EcdsaP256BatchVerifier",
    "EcdsaP256Signer",
    "EcdsaP256VerifierMixin",
    "Ed25519BatchVerifier",
    "Ed25519RandomizedBatchVerifier",
    "FusedEd25519BatchVerifier",
    "FusedEd25519RandomizedBatchVerifier",
    "L",
    "BatchCoalescer",
    "ThreadCoalescingVerifier",
    "CircuitBreaker",
    "ENGINE_HEALTH",
    "EngineHealth",
    "EngineHealthRegistry",
    "EngineSupervisor",
    "FAULT_CLASSES",
    "HostTwin",
    "LaunchTimeout",
    "Ed25519Signer",
    "Ed25519VerifierMixin",
    "commit_message",
    "degrade_ladder_configs",
    "engine_for_config",
    "raw_message",
]
