"""Batched ECDSA-P256 signature verification — the second crypto model
family (BASELINE.json config 1 pairs naive_chain with ECDSA-P256).

Same architecture as :mod:`consensus_tpu.models.ed25519`: the host parses,
range-checks, hashes (SHA-256) and computes the scalar pair u1 = e/s,
u2 = r/s (mod n, Python big-int — modular inversion of the *scalar* field
is irregular host work); the device runs the regular 99%: an on-curve check
for the public key and the double-scalar multiplication R' = u1*G + u2*Q
over complete P-256 formulas — [u2]Q as a 64-step 4-bit-window scan,
[u1]G as an 8-bit fixed-base comb (zero doubles; G is a compile-time
constant) — then the projective acceptance test X == r * Z (with the
r + n second candidate when it exists).

Native formats: signature = 64 bytes big-endian r || s; public key =
65 bytes SEC1 uncompressed (0x04 || X || Y).  DER/cryptography interop
helpers are provided for tests and embedders.
"""

from __future__ import annotations

import functools
import hashlib
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from consensus_tpu.obs.kernels import instrumented_jit, kernel_lane_suffix

from consensus_tpu.models.ed25519 import _next_pow2
from consensus_tpu.ops import field_p256 as fp
from consensus_tpu.ops import p256

N = p256.N

_WINDOW_BITS = 4
_WINDOWS = 256 // _WINDOW_BITS
#: Signed digits: |d| <= 8 needs multiples 0..8 of Q (9 entries, 7 adds to
#: build) instead of 0..15 (15 adds), and shrinks every step's one-hot
#: lookup contraction from 16 rows to 9.  Negation is one field sub.
_TABLE_SIGNED = 9


def _be_bytes_to_limb_rows(rows_be: np.ndarray) -> np.ndarray:
    """(n, 32) big-endian byte rows -> (n, 32) little-endian limb rows
    (uint8 — the wire width; the kernel widens on device)."""
    return rows_be[:, ::-1]


def _scalars_to_signed_window_digits(values: list[int]) -> np.ndarray:
    """Scalars -> (65, n) SIGNED 4-bit digits in [-8, 7], wire-encoded as
    d+8 (uint8), MSB window first.

    Unlike Ed25519's k < L < 2^253 (top window can never overflow), u2 can
    occupy all 256 bits (u2 < n ~ 2^256), so the LSB-to-MSB recoding carry
    CAN escape the top window.  The carry c in {0, 1} is prepended as a
    65th most-significant window: the Horner scan just consumes it first
    (its 4 doubles act on the identity, and 64 subsequent x16 rounds give
    it weight 2^256 exactly)."""
    n = len(values)
    rows = np.zeros((n, 32), dtype=np.uint8)
    for i, v in enumerate(values):
        rows[i] = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
    bits = np.unpackbits(rows, axis=-1, bitorder="little")  # (n, 256) LSB first
    weights = np.array([1, 2, 4, 8], dtype=np.int32)
    u = bits.reshape(n, _WINDOWS, _WINDOW_BITS) @ weights  # (n, 64) LSB first
    d = np.zeros_like(u)
    carry = np.zeros(n, dtype=u.dtype)
    for j in range(_WINDOWS):
        t = u[:, j] + carry
        over = t >= 8
        d[:, j] = np.where(over, t - 16, t)
        carry = over.astype(u.dtype)
    full = np.concatenate([carry[:, None], d[:, ::-1]], axis=1)  # (n, 65) MSB first
    return np.ascontiguousarray(full.T + 8).astype(np.uint8)


def _scalars_to_comb_digits8(values: list[int]) -> np.ndarray:
    """Scalars -> (32, n) 8-bit digits, LSB window first: with byte-sized
    windows the little-endian bytes ARE the digits (the comb sums windows,
    order-free)."""
    n = len(values)
    rows = np.zeros((n, 32), dtype=np.uint8)
    for i, v in enumerate(values):
        rows[i] = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
    return np.ascontiguousarray(rows.T)


def verify_impl(
    qx: jnp.ndarray,        # (32, batch) public key X limbs
    qy: jnp.ndarray,        # (32, batch) public key Y limbs
    u1_digits: jnp.ndarray, # (32, batch) 8-bit comb digits of u1 = e/s, LSB first
    u2_digits: jnp.ndarray, # (65, batch) signed 4-bit windows of u2 = r/s
                            # (encoded d+8), MSB first incl. recoding carry
    r1: jnp.ndarray,        # (32, batch) r as field limbs
    r2: jnp.ndarray,        # (32, batch) r + n as field limbs (when valid)
    has_r2: jnp.ndarray,    # (batch,) whether r + n < p
    host_ok: jnp.ndarray,   # (batch,) host-side pre-checks passed
) -> jnp.ndarray:
    """Un-jitted kernel body; shards over the trailing batch axis.

    R' = u1*G + u2*Q split by operand class: the variable half [u2]Q runs
    the signed-4-bit Horner scan (65 windows incl. the recoding carry, each
    4 doubles + 1 table add; the 9-entry |d|*Q table built per batch, sign
    applied by a mul-free negate); the fixed-base half [u1]G — G is a
    compile-time constant — uses the 8-bit comb
    (:func:`consensus_tpu.ops.p256.fixed_base_mul_comb`):
    32 constant lookups + adds, zero doubles, no per-batch table."""
    # Inputs ship as uint8 (limbs/digits all fit) — 4x less transfer;
    # widen to the compute dtypes on device.
    qx = qx.astype(jnp.float32)
    qy = qy.astype(jnp.float32)
    u1_digits = u1_digits.astype(jnp.int32)
    u2_digits = u2_digits.astype(jnp.int32)
    r1 = r1.astype(jnp.float32)
    r2 = r2.astype(jnp.float32)
    q = p256.affine_like(qx, qy)
    q_ok = p256.on_curve(qx, qy)
    from consensus_tpu.ops.pallas_scan import scan_config

    pallas_cfg = scan_config(qx.shape[-1])
    if pallas_cfg is not None:
        # Opt-in whole-scan-in-VMEM Pallas kernel (CTPU_PALLAS_SCAN=1):
        # same arithmetic, different scheduling — see ops/pallas_scan.py.
        tile, interpret = pallas_cfg
        from consensus_tpu.ops.pallas_scan import horner_scan_p256

        acc = horner_scan_p256(
            qx, qy, u2_digits, tile=tile, interpret=interpret
        )
    else:
        q_table = p256.multiples_table(q, _TABLE_SIGNED)
        lanes = jnp.arange(_TABLE_SIGNED, dtype=jnp.int32)[:, None]

        def step(acc: p256.Point, w):
            d = w - 8  # signed digit in [-8, 7] ({0, 1} for the carry window)
            oh2 = (jnp.abs(d)[None] == lanes).astype(jnp.float32)
            # 4 doubles as an inner scan: one double body in the graph
            # instead of four (trace/compile-size economy, identical
            # runtime schedule).
            acc, _ = jax.lax.scan(
                lambda a, _: (p256.double(a), None), acc, None, length=4
            )
            t = p256.table_lookup(q_table, oh2)
            t = p256.select(d < 0, p256.negate(t), t)
            acc = p256.add(acc, t)
            return acc, None

        acc, _ = jax.lax.scan(step, p256.identity_like(qx), u2_digits)
    acc = p256.add(acc, p256.fixed_base_mul_comb(u1_digits))

    # Accept iff R' is not the identity and x(R') ≡ r (mod n):
    # X == r * Z or (r + n < p and X == (r + n) * Z), projectively.
    nonzero = ~fp.is_zero(acc.z)
    match1 = fp.eq(acc.x, fp.mul(r1, acc.z))
    match2 = has_r2 & fp.eq(acc.x, fp.mul(r2, acc.z))
    return host_ok & q_ok & nonzero & (match1 | match2)


_verify_kernel = instrumented_jit(
    verify_impl, "ecdsa_p256.verify" + kernel_lane_suffix()
)


def pad_prepared(prepped, padded: int):
    """Pad the 8 host-side arrays to ``padded`` batch elements."""
    qx, qy, u1d, u2d, r1, r2, has_r2, host_ok = prepped
    pad = padded - len(host_ok)
    if pad:
        qx = np.pad(qx, ((0, pad), (0, 0)))
        qy = np.pad(qy, ((0, pad), (0, 0)))
        u1d = np.pad(u1d, ((0, 0), (0, pad)))
        u2d = np.pad(u2d, ((0, 0), (0, pad)))
        r1 = np.pad(r1, ((0, pad), (0, 0)))
        r2 = np.pad(r2, ((0, pad), (0, 0)))
        has_r2 = np.pad(has_r2, (0, pad))
        host_ok = np.pad(host_ok, (0, pad))
    return qx, qy, u1d, u2d, r1, r2, has_r2, host_ok


def to_kernel_layout(qx, qy, u1d, u2d, r1, r2, has_r2, host_ok):
    """Host row-major arrays -> device layout (vector axis leading),
    shipped as the narrowest dtype (uint8/bool); the kernel widens on
    device."""
    return (
        jnp.asarray(np.ascontiguousarray(qx.T)),
        jnp.asarray(np.ascontiguousarray(qy.T)),
        jnp.asarray(u1d),
        jnp.asarray(u2d),
        jnp.asarray(np.ascontiguousarray(r1.T)),
        jnp.asarray(np.ascontiguousarray(r2.T)),
        jnp.asarray(has_r2),
        jnp.asarray(host_ok),
    )


class EcdsaP256BatchVerifier:
    """Verify many (message, signature, public key) triples at once."""

    def __init__(
        self,
        *,
        pad_pow2: bool = True,
        min_device_batch: int = 1,
        pad_to: int = 0,
    ) -> None:
        """``pad_to`` > 0 pads every device batch to that fixed size (one
        compiled kernel shape for the whole deployment — no mid-run compiles
        on underfull batches); batches larger than ``pad_to`` fall back to
        the pow-2 ladder."""
        self._pad_pow2 = pad_pow2
        self._min_device_batch = min_device_batch
        self._pad_to = pad_to

    @property
    def preferred_wave_size(self) -> int:
        """The smallest padded batch that saturates this engine (see the
        Ed25519 twin) — coalescers read it to size cross-tenant waves."""
        from consensus_tpu.parallel.topology import engine_padded_size

        return engine_padded_size(
            max(1, self._min_device_batch),
            1,
            pad_to=self._pad_to,
            pad_pow2=self._pad_pow2,
        )

    @staticmethod
    def _batch_invert_mod_n(values: list[int]) -> list[int]:
        """Montgomery batch inversion mod the group order: ONE modular
        exponentiation + 3 multiplications per element, vs one ~25 µs
        ``pow(s, n-2, n)`` per signature — the dominant host-prep cost at
        proposal-sized batches.  Zeros pass through as zero (callers have
        already marked them invalid)."""
        prefix: list[int] = []
        acc = 1
        for v in values:
            prefix.append(acc)
            if v:
                acc = (acc * v) % N
        inv = pow(acc, N - 2, N)
        out = [0] * len(values)
        for i in range(len(values) - 1, -1, -1):
            if values[i]:
                out[i] = (inv * prefix[i]) % N
                inv = (inv * values[i]) % N
        return out

    def _prepare(self, messages, signatures, public_keys):
        n = len(messages)
        host_ok = np.ones(n, dtype=bool)
        qx_rows = np.zeros((n, 32), dtype=np.uint8)
        qy_rows = np.zeros((n, 32), dtype=np.uint8)
        u1s = [0] * n
        u2s = [0] * n
        r1_rows = np.zeros((n, 32), dtype=np.uint8)
        r2_rows = np.zeros((n, 32), dtype=np.uint8)
        has_r2 = np.zeros(n, dtype=bool)
        rs = [0] * n
        ss = [0] * n
        es = [0] * n
        for i in range(n):
            sig = signatures[i]
            key = public_keys[i]
            if len(sig) != 64 or len(key) != 65 or key[0] != 0x04:
                host_ok[i] = False
                continue
            r = int.from_bytes(sig[:32], "big")
            s = int.from_bytes(sig[32:], "big")
            if not (1 <= r < N and 1 <= s < N):
                host_ok[i] = False
                continue
            qx = int.from_bytes(key[1:33], "big")
            qy = int.from_bytes(key[33:], "big")
            if qx >= fp.P or qy >= fp.P:
                host_ok[i] = False
                continue
            rs[i], ss[i] = r, s
            es[i] = int.from_bytes(hashlib.sha256(messages[i]).digest(), "big")
            qx_rows[i] = np.frombuffer(key[1:33], dtype=np.uint8)
            qy_rows[i] = np.frombuffer(key[33:], dtype=np.uint8)
            r1_rows[i] = np.frombuffer(r.to_bytes(32, "big"), dtype=np.uint8)
            if r + N < fp.P:
                has_r2[i] = True
                r2_rows[i] = np.frombuffer((r + N).to_bytes(32, "big"), dtype=np.uint8)
        ws = self._batch_invert_mod_n(ss)
        for i in range(n):
            if not ss[i]:
                continue
            u1s[i] = (es[i] * ws[i]) % N
            u2s[i] = (rs[i] * ws[i]) % N
        return (
            _be_bytes_to_limb_rows(qx_rows),
            _be_bytes_to_limb_rows(qy_rows),
            _scalars_to_comb_digits8(u1s),
            _scalars_to_signed_window_digits(u2s),
            _be_bytes_to_limb_rows(r1_rows),
            _be_bytes_to_limb_rows(r2_rows),
            has_r2,
            host_ok,
        )

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        prepped = self._prepare(messages, signatures, public_keys)
        if self._pad_to >= n:
            padded = self._pad_to
        else:
            padded = _next_pow2(n) if self._pad_pow2 else n
        result = _verify_kernel(*to_kernel_layout(*pad_prepared(prepped, padded)))
        return np.asarray(result)[:n]

    @staticmethod
    def _verify_host(messages, signatures, public_keys) -> np.ndarray:
        """Sequential fallback via the ``cryptography`` package."""
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature,
        )

        out = np.zeros(len(messages), dtype=bool)
        for i, (msg, sig, key) in enumerate(zip(messages, signatures, public_keys)):
            try:
                pub = ec.EllipticCurvePublicKey.from_encoded_point(
                    ec.SECP256R1(), bytes(key)
                )
                der = encode_dss_signature(
                    int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:], "big")
                )
                pub.verify(der, bytes(msg), ec.ECDSA(hashes.SHA256()))
                out[i] = True
            except (InvalidSignature, ValueError):
                out[i] = False
        return out

    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        """Public seam for the coalescer's wedged-device escape hatch:
        verify on the host regardless of batch size, same semantics as the
        device path.  (A forwarding method, not a class-level alias, so
        subclass overrides of ``_verify_host`` take effect here too.)"""
        return self._verify_host(messages, signatures, public_keys)


def raw_signature_from_der(der: bytes) -> bytes:
    """DER ECDSA signature -> 64-byte big-endian r || s."""
    from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature

    r, s = decode_dss_signature(der)
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


__all__ = [
    "EcdsaP256BatchVerifier",
    "raw_signature_from_der",
    "pad_prepared",
    "to_kernel_layout",
    "N",
]
