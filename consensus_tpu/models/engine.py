"""Micro-batching coalescer: merges verification work from many sources into
single device launches.

The View already batches per quorum (one ``verify_consenter_sigs_batch`` per
decision), but a host running several replicas — or a replica pipelining
decisions — produces many small batches in a short window.  The coalescer
holds submissions for ``window`` seconds (or until ``max_batch`` items are
pending) and flushes them as one kernel call, trading a bounded latency for
multiplied arithmetic intensity.  The window must stay well under the
network RTT to not hurt p50 commit latency (SURVEY §7 hard part 3).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle


class BatchCoalescer:
    """Generic (items -> results) coalescer on the replica scheduler.

    ``run_batch`` receives the concatenated items of all pending
    submissions and must return one result per item, in order.
    """

    def __init__(
        self,
        scheduler: Scheduler,
        run_batch: Callable[[Sequence], Sequence],
        *,
        window: float = 0.002,
        max_batch: int = 1024,
    ) -> None:
        self._sched = scheduler
        self._run_batch = run_batch
        self._window = window
        self._max_batch = max_batch
        self._pending: list[tuple[list, Callable[[Sequence], None]]] = []
        self._pending_count = 0
        self._timer: Optional[TimerHandle] = None

    def submit(self, items: Sequence, on_results: Callable[[Sequence], None]) -> None:
        """Queue ``items``; ``on_results`` fires with their results once the
        batch they rode in completes."""
        items = list(items)
        if not items:
            on_results([])
            return
        self._pending.append((items, on_results))
        self._pending_count += len(items)
        if self._pending_count >= self._max_batch:
            self.flush()
        elif self._timer is None:
            self._timer = self._sched.call_later(
                self._window, self.flush, name="crypto-batch-window"
            )

    def flush(self) -> None:
        """Run everything pending as one batch."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        pending, self._pending, self._pending_count = self._pending, [], 0
        if not pending:
            return
        merged: list = []
        for items, _ in pending:
            merged.extend(items)
        results = self._run_batch(merged)
        if len(results) != len(merged):
            raise ValueError(
                f"run_batch returned {len(results)} results for {len(merged)} items"
            )
        offset = 0
        for items, on_results in pending:
            on_results(results[offset : offset + len(items)])
            offset += len(items)

    @property
    def pending_count(self) -> int:
        return self._pending_count


__all__ = ["BatchCoalescer"]
