"""Micro-batching coalescer: merges verification work from many sources into
single device launches.

The View already batches per quorum (one ``verify_consenter_sigs_batch`` per
decision), but a host running several replicas — or a replica pipelining
decisions — produces many small batches in a short window.  The coalescer
holds submissions for ``window`` seconds (or until ``max_batch`` items are
pending) and flushes them as one kernel call, trading a bounded latency for
multiplied arithmetic intensity.  The window must stay well under the
network RTT to not hurt p50 commit latency (SURVEY §7 hard part 3).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from consensus_tpu.models.supervisor import ENGINE_HEALTH, EngineHealth
from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle

logger = logging.getLogger("consensus_tpu.models.engine")


def _split_results(results: Sequence, sizes: Sequence[int]):
    """Slice a merged result vector back into per-submission pieces,
    refusing short results (a truncated slice must never read as 'all
    valid' downstream)."""
    total = sum(sizes)
    if len(results) != total:
        raise ValueError(
            f"run_batch returned {len(results)} results for {total} items"
        )
    out, offset = [], 0
    for size in sizes:
        out.append(results[offset : offset + size])
        offset += size
    return out


class BatchCoalescer:
    """Generic (items -> results) coalescer on the replica scheduler.

    ``run_batch`` receives the concatenated items of all pending
    submissions and must return one result per item, in order.
    """

    def __init__(
        self,
        scheduler: Scheduler,
        run_batch: Callable[[Sequence], Sequence],
        *,
        window: float = 0.002,
        max_batch: int = 1024,
    ) -> None:
        self._sched = scheduler
        self._run_batch = run_batch
        self._window = window
        self._max_batch = max_batch
        self._pending: list[tuple[list, Callable[[Sequence], None]]] = []
        self._pending_count = 0
        self._timer: Optional[TimerHandle] = None

    def submit(self, items: Sequence, on_results: Callable[[Sequence], None]) -> None:
        """Queue ``items``; ``on_results`` fires with their results once the
        batch they rode in completes."""
        items = list(items)
        if not items:
            on_results([])
            return
        self._pending.append((items, on_results))
        self._pending_count += len(items)
        if self._pending_count >= self._max_batch:
            self.flush()
        elif self._timer is None:
            self._timer = self._sched.call_later(
                self._window, self.flush, name="crypto-batch-window"
            )

    def flush(self) -> None:
        """Run everything pending as one batch."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        pending, self._pending, self._pending_count = self._pending, [], 0
        if not pending:
            return
        merged: list = []
        for items, _ in pending:
            merged.extend(items)
        results = self._run_batch(merged)
        slices = _split_results(results, [len(items) for items, _ in pending])
        for (_, on_results), piece in zip(pending, slices):
            on_results(piece)

    @property
    def pending_count(self) -> int:
        return self._pending_count


class _Pending:
    __slots__ = (
        "messages", "signatures", "keys", "done", "result", "error", "waiterless",
    )

    def __init__(self, messages, signatures, keys, *, waiterless: bool = False):
        self.messages = messages
        self.signatures = signatures
        self.keys = keys
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # Recovery probes have no waiter: nobody consumes their results, so
        # failure paths shouldn't burn host CPU computing them.
        self.waiterless = waiterless


def _slice_wave_target(engine, cap: int) -> int:
    """The early-flush signature count for a coalescer over ``engine``.

    Multi-device engines advertise ``preferred_wave_size`` — the smallest
    padded wave that saturates the WHOLE topology (every shard fed at least
    its device-batch floor), not one chip — so once that many signatures
    are aboard the coalescer launches without waiting out the window:
    the slice is already full, further waiting is pure latency.  Engines
    without a multi-device topology keep the plain size cap, so
    single-device coalescing behavior is bit-for-bit unchanged."""
    if int(getattr(engine, "shard_count", 1) or 1) <= 1:
        return cap
    preferred = int(getattr(engine, "preferred_wave_size", 0) or 0)
    if preferred <= 0:
        return cap
    return min(cap, preferred)


class ThreadCoalescingVerifier:
    """Thread-safe verify coalescer for replicas *sharing one device*.

    In a deployment where several replica threads (or processes behind a
    sidecar) share a single TPU, each replica independently batch-verifies
    the same proposal's signatures — n device launches per decision, each
    paying the fixed dispatch/transfer overhead.  This wrapper merges
    concurrent ``verify_batch`` calls from any thread into one kernel
    launch: submissions wait up to ``window`` seconds (or until
    ``max_batch`` signatures are pending) and ride a single padded device
    call, then each caller gets its own slice of the results.

    The per-replica semantics are unchanged — every replica still checks
    exactly the signatures it chose to check; only the *execution* is
    fused.  (The reference has no equivalent: each Go replica burns its own
    cores — reference internal/bft/view.go:537-541.)

    ``hard_cap`` bounds a single launch (whole submissions are never
    split); overflow waits for the next flush.  Set it to the engine's
    ``pad_to`` so a mid-run launch can never hit a never-compiled shape.
    Submissions larger than ``hard_cap`` are chunked and enqueued together
    (they share flushes; results are re-concatenated for the caller).

    ``bypass_below``: submissions smaller than this go straight to the
    wrapped engine on the caller's thread with NO window wait.  Merging
    only pays off for *device* launches (amortizing dispatch overhead);
    host-path work gains nothing from fusion, so single-signature checks
    (heartbeats, view-change messages, quorum votes) shouldn't pay the
    window latency.  Match it to the engine's ``min_device_batch``.

    ``wait_timeout``: a wedged device (e.g. a hung TPU tunnel) must not
    block a replica past its protocol timeouts.  A waiter whose flush has
    not completed after this many seconds falls back to the engine's host
    path (``engine.verify_host``) on its own thread — the decision still
    completes, just without acceleration — and the coalescer marks the
    device *suspect* so subsequent submissions skip the queue entirely and
    go straight to host.  The first successful device flush clears the
    flag (tunnel recovered).  Size it above the worst-case first-compile
    time; engines without a ``verify_host`` method keep the old fail-loud
    behavior (raise on timeout).
    """

    def __init__(
        self,
        engine,
        *,
        window: float = 0.010,
        max_batch: int = 8192,
        hard_cap: int = 0,
        bypass_below: int = 0,
        wait_timeout: Optional[float] = None,
        scheduler: Optional[Scheduler] = None,
        health: Optional[EngineHealth] = None,
        name: str = "verify-coalescer",
    ) -> None:
        self._engine = engine
        self._window = window
        self._max_batch = max_batch
        # Early-flush point: the engine's slice-filling wave size on mesh
        # engines, the plain cap otherwise (see _slice_wave_target).
        self._flush_target = _slice_wave_target(engine, max_batch)
        self._hard_cap = hard_cap if hard_cap > 0 else max(max_batch, 1)
        self._bypass_below = bypass_below
        self._host_fallback = getattr(engine, "verify_host", None)
        if wait_timeout is None:
            # With a host escape hatch, timing out early just means one
            # slower-but-correct decision (and the flag clears on the next
            # successful flush, e.g. when a long first compile lands).
            # Without one, a timeout is a hard error — keep the generous
            # budget that covers worst-case first compiles.
            wait_timeout = 60.0 if self._host_fallback is not None else 300.0
        self._wait_timeout = wait_timeout
        self._cv = threading.Condition()
        self._pending: list[_Pending] = []
        self._count = 0
        self._closed = False
        # Suspect state is SHARED across every coalescer (and tenant lane)
        # wrapping the same engine: a wedge seen by one waiter routes all
        # of them host-side.  An engine carrying its own health surface
        # (e.g. an EngineSupervisor) contributes it; otherwise the
        # process-wide registry keys one per engine instance.
        if health is None:
            health = getattr(engine, "health", None)
            if not isinstance(health, EngineHealth):
                health = ENGINE_HEALTH.for_engine(engine)
        self._health = health
        # Suspect re-probe pacing: protocol-clocked when the embedder hands
        # us its scheduler; only the real-thread sidecar path (no scheduler
        # available) reads the wall clock.
        if scheduler is not None:
            self._probe_clock = scheduler.now
        else:
            self._probe_clock = time.monotonic  # wallclock-ok
        self._probe_interval = 30.0
        self._last_probe = -float("inf")
        self._thread = threading.Thread(target=self._loop, daemon=True, name=name)
        self._thread.start()

    @property
    def device_suspect(self) -> bool:
        """True while the device is considered wedged (submissions are
        routed straight to the host path)."""
        return self._health.suspect

    @property
    def health(self) -> EngineHealth:
        """The shared engine-health entry this coalescer reports into."""
        return self._health

    @property
    def _device_suspect(self) -> bool:
        return self._health.suspect

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if self._device_suspect and self._host_fallback is not None:
            # Wedged device: don't queue behind a flusher that may be stuck
            # inside a hung device call — verify on the caller's thread.
            # A no-wait copy of the work probes the device for recovery.
            self._maybe_probe_device(messages, signatures, public_keys)
            return np.asarray(self._host_fallback(messages, signatures, public_keys))
        if n < self._bypass_below:
            # Too small to ever ride the device: verify on the caller's
            # thread, zero added latency (the engine routes it host-side).
            return np.asarray(self._engine.verify_batch(messages, signatures, public_keys))
        # Chunk oversized submissions so no launch exceeds the compiled
        # shape, enqueueing ALL chunks before waiting on any (they may
        # share flushes — waiting per-chunk would serialize windows).
        cap = self._hard_cap
        items = [
            _Pending(
                list(messages[i : i + cap]),
                list(signatures[i : i + cap]),
                list(public_keys[i : i + cap]),
            )
            for i in range(0, n, cap)
        ]
        with self._cv:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            for item in items:
                self._pending.append(item)
                self._count += len(item.messages)
            self._cv.notify_all()
        for item in items:
            if not item.done.wait(timeout=self._wait_timeout):
                if self._host_fallback is None:
                    raise RuntimeError(
                        f"verify flush did not complete within {self._wait_timeout}s "
                        "(wedged device?)"
                    )
                self._abandon_to_host(items)
                break
            if item.error is not None:
                if self._host_fallback is not None:
                    # A flush error with a host twin available is a degrade,
                    # not a decision-killer: mark the device suspect and
                    # complete the wave on the caller's thread via host
                    # (mirrors the timeout path above — errors reaching a
                    # waiter here mean the flusher's own host attempt hit a
                    # transient, so retry it where the waiter can see it).
                    self._abandon_to_host(items, reason="launch_raise")
                    break
                # A merged flush fails for every waiter; raising the SAME
                # exception object from N threads would interleave their
                # frames into one shared traceback — wrap per waiter.
                raise RuntimeError(
                    f"coalesced verify flush failed: {item.error!r}"
                ) from item.error
        if len(items) == 1:
            return items[0].result
        return np.concatenate([item.result for item in items])

    def _maybe_probe_device(self, messages, signatures, public_keys) -> None:
        """While suspect, periodically enqueue a no-waiter copy of real work
        so the flusher (once it unwedges / recovers) runs a device flush and
        clears the flag.  At most one probe is queued at a time, and probes
        are rate-limited — a stuck flusher can't accumulate a backlog."""
        # Probe pacing through the injected clock (scheduler.now when the
        # embedder provided one; the real-thread sidecar path falls back to
        # the audited wall clock chosen in __init__).
        now = self._probe_clock()
        with self._cv:
            if (
                self._closed
                or self._pending
                or now - self._last_probe < self._probe_interval
            ):
                return
            self._last_probe = now
            cap = min(len(messages), self._hard_cap)
            item = _Pending(
                list(messages[:cap]),
                list(signatures[:cap]),
                list(public_keys[:cap]),
                waiterless=True,
            )
            self._pending.append(item)
            self._count += cap
            self._cv.notify_all()

    def _abandon_to_host(
        self, items: list["_Pending"], reason: str = "launch_timeout"
    ) -> None:
        """Waiter-side escape hatch: the flush never completed within
        ``wait_timeout`` (hung device call, e.g. a wedged TPU tunnel).
        Mark the device suspect, pull any chunks still queued out of the
        flusher's reach, and verify everything on the caller's thread via
        the engine's host path so the replica completes its decision within
        protocol timeouts.  Results the stuck flusher produces later for
        these items are simply ignored."""
        with self._cv:
            if self._health.mark_suspect(reason):
                logger.error(
                    "verify flush did not complete (%s) — device suspect; "
                    "falling back to HOST verification (slower, still "
                    "correct) until a device flush succeeds",
                    reason,
                )
            for item in items:
                if item in self._pending:
                    self._pending.remove(item)
                    self._count -= len(item.messages)
        for item in items:
            if item.done.is_set() and item.error is None and item.result is not None:
                continue  # completed while we were escaping — keep it
            item.result = np.asarray(
                self._host_fallback(item.messages, item.signatures, item.keys)
            )
            item.error = None
            item.done.set()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        # A legitimate in-flight flush (first compile, big host pass) may
        # run long — grant it the same budget as waiters before calling
        # the device wedged.
        self._thread.join(timeout=self._wait_timeout)
        if self._thread.is_alive():
            # Daemon thread — it can't block process exit; shutdown itself
            # must not crash on a wedged device.
            logger.error(
                "coalescer flusher did not exit within %.1fs (wedged device?)",
                self._wait_timeout,
            )

    # -- flusher thread ----------------------------------------------------

    def _take_batch(self) -> list[_Pending]:
        """Pop whole pending submissions up to ``hard_cap`` signatures."""
        taken, total = [], 0
        while self._pending:
            nxt = len(self._pending[0].messages)
            if taken and total + nxt > self._hard_cap:
                break
            item = self._pending.pop(0)
            taken.append(item)
            total += nxt
        self._count -= total
        return taken

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                deadline = time.monotonic() + self._window  # wallclock-ok
                while self._count < self._flush_target and not self._closed:
                    remaining = deadline - time.monotonic()  # wallclock-ok
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._take_batch()
            if not batch:
                continue
            messages: list = []
            signatures: list = []
            keys: list = []
            for item in batch:
                messages.extend(item.messages)
                signatures.extend(item.signatures)
                keys.extend(item.keys)
            try:
                results = np.asarray(self._engine.verify_batch(messages, signatures, keys))
                slices = _split_results(results, [len(i.messages) for i in batch])
            except BaseException as exc:
                if self._host_fallback is not None:
                    # Device call failed fast (not hung): serve this flush
                    # from the host path so waiters complete, and mark the
                    # device suspect so new submissions skip the queue.
                    logger.error(
                        "device verify flush failed (%r) — serving %d "
                        "signatures via HOST fallback; device suspect",
                        exc,
                        len(messages),
                    )
                    self._health.mark_suspect("launch_raise")
                    for item in batch:
                        if item.waiterless:
                            item.done.set()  # failed probe: nothing to serve
                            continue
                        try:
                            item.result = np.asarray(
                                self._host_fallback(
                                    item.messages, item.signatures, item.keys
                                )
                            )
                        except BaseException as host_exc:
                            # The host path failing too (e.g. malformed
                            # inputs) must not kill the flusher thread —
                            # deliver it as this waiter's error.
                            item.error = host_exc
                        item.done.set()
                    continue
                for item in batch:  # no host path: propagate to every waiter
                    item.error = exc
                    item.done.set()
                continue
            if self._health.clear():
                logger.warning(
                    "device verify flush succeeded — clearing suspect flag, "
                    "resuming device batching"
                )
            for item, piece in zip(batch, slices):
                item.result = piece
                item.done.set()


class AdmissionReject(Exception):
    """A tenant's bounded queue is full: the submission is REJECTED with
    structure (who, how deep, the limit) instead of stalling — the caller
    retries or falls back locally, and other tenants' waves are untouched."""

    def __init__(self, tenant: str, queue_depth: int, limit: int) -> None:
        super().__init__(
            f"tenant {tenant!r} admission rejected: "
            f"{queue_depth} signatures queued, limit {limit}"
        )
        self.tenant = tenant
        self.queue_depth = queue_depth
        self.limit = limit


class _TenantPending(_Pending):
    __slots__ = ("tenant", "group")

    def __init__(self, tenant, messages, signatures, keys, group=None):
        super().__init__(messages, signatures, keys)
        self.tenant = tenant
        self.group = group


class FairShareWaveFormer:
    """Multi-tenant wave forming over one engine: per-tenant bounded queues,
    round-robin draining, cross-tenant coalescing into single launches.

    The sidecar's single-tenant coalescer (:class:`ThreadCoalescingVerifier`)
    merges submissions but knows nothing about who they belong to — one
    flooding client can fill every launch and starve the rest.  This former
    gives each tenant its own queue with three properties:

    * **Admission control** — a submission that would push the tenant's
      queued signature count past ``tenant_queue_limit`` raises
      :class:`AdmissionReject` immediately (bounded memory, structured
      reject, never a stall).  Other tenants are unaffected: their queues,
      their limits.
    * **Fair share** — waves are formed round-robin across tenant queues,
      one whole submission per tenant per pass, and the rotation order
      advances every wave, so a heavy tenant gets the leftover capacity
      but can never exclude a light one from the next launch.
    * **Deadline-aware coalescing** — a wave closes when the flush target
      is aboard or ``window`` seconds after the first pending submission,
      whichever is first; until then, cross-tenant submissions keep joining
      the same launch.  The flush target is ``max_wave``, except over a
      mesh engine, where the former learns the engine's
      ``preferred_wave_size`` — the padded shard-multiple that saturates
      the whole slice — and launches as soon as the slice is full instead
      of waiting out the window.

    ``on_wave(tenant_counts, total)`` fires after each successful launch
    with the per-tenant signature counts that rode it — the sidecar's
    metrics/kernel-accounting hook.

    **Cross-GROUP coalescing** (consensus sharding): ``submit`` takes an
    optional ``group`` id.  When present, the admission identity becomes
    (group, tenant) — each group's replicas get their own bounded queues
    and their own fair-share slot — and one fused launch serves
    submissions from several consensus groups at once.  SAFETY §7 is
    preserved by construction: waves are formed from WHOLE submissions
    (``_take_wave`` never splits one), so every quorum cert's signatures
    ride a single engine call and no cert ever mixes engines.  Per-wave
    group composition is booked through ``groups_metrics`` (a
    :class:`~consensus_tpu.metrics.MetricsGroups` bundle: one
    ``groups_wave_span`` observation per launch, plus the multi-group
    counter when a launch spans two or more groups) and surfaced raw via
    ``on_group_wave(group_counts, total)``.
    """

    def __init__(
        self,
        engine,
        *,
        window: float = 0.005,
        max_wave: int = 8192,
        tenant_queue_limit: int = 4096,
        on_wave: Optional[Callable[[dict, int], None]] = None,
        on_group_wave: Optional[Callable[[dict, int], None]] = None,
        groups_metrics=None,
        wait_timeout: float = 300.0,
        name: str = "verify-waves",
    ) -> None:
        self._engine = engine
        self._window = window
        self._max_wave = max(1, max_wave)
        # Early-flush point: the engine's slice-filling wave size on mesh
        # engines, the plain cap otherwise (see _slice_wave_target).
        self._wave_target = _slice_wave_target(engine, self._max_wave)
        self._tenant_queue_limit = max(1, tenant_queue_limit)
        self._on_wave = on_wave
        self._on_group_wave = on_group_wave
        self._groups_metrics = groups_metrics
        self._wait_timeout = wait_timeout
        self._cv = threading.Condition()
        self._queues: dict[str, list[_TenantPending]] = {}
        self._rr: list[str] = []
        self._count = 0
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True, name=name)
        self._thread.start()

    @staticmethod
    def _admission_key(tenant: str, group: Optional[str]) -> str:
        """The queue/fair-share identity: the tenant alone (sidecar mode),
        or (group, tenant) under consensus sharding — a group's replicas
        never contend on another group's admission budget."""
        return tenant if group is None else f"{group}\x1f{tenant}"

    def queue_depth(self, tenant: str, group: Optional[str] = None) -> int:
        """Signatures currently queued for ``tenant`` (within ``group``
        when the group id is part of the admission identity)."""
        key = self._admission_key(tenant, group)
        with self._cv:
            return sum(len(i.messages) for i in self._queues.get(key, ()))

    @property
    def pending_count(self) -> int:
        return self._count

    def submit(
        self, tenant: str, messages, signatures, public_keys,
        *, group: Optional[str] = None,
    ) -> np.ndarray:
        """Queue one tenant submission and block until its wave lands.
        Raises :class:`AdmissionReject` when the tenant's queue is full.
        ``group`` joins the admission identity under consensus sharding —
        the submission stays whole either way (SAFETY §7)."""
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        key = self._admission_key(tenant, group)
        with self._cv:
            if self._closed:
                raise RuntimeError("wave former is closed")
            depth = sum(len(i.messages) for i in self._queues.get(key, ()))
            if depth + n > self._tenant_queue_limit:
                raise AdmissionReject(key, depth, self._tenant_queue_limit)
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = []
                self._rr.append(key)
            item = _TenantPending(
                tenant, list(messages), list(signatures), list(public_keys),
                group=group,
            )
            q.append(item)
            self._count += n
            self._cv.notify_all()
        if not item.done.wait(timeout=self._wait_timeout):
            raise RuntimeError(
                f"verify wave did not complete within {self._wait_timeout}s "
                "(wedged device?)"
            )
        if item.error is not None:
            raise RuntimeError(
                f"coalesced verify wave failed: {item.error!r}"
            ) from item.error
        return item.result

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=self._wait_timeout)
        if self._thread.is_alive():
            logger.error(
                "wave former thread did not exit within %.1fs (wedged device?)",
                self._wait_timeout,
            )

    # -- wave thread -------------------------------------------------------

    def _take_wave(self) -> list[_TenantPending]:
        """Pop whole submissions round-robin across tenant queues up to
        ``max_wave`` signatures, then advance the rotation so the next wave
        starts with a different tenant."""
        taken: list[_TenantPending] = []
        total = 0
        progress = True
        while progress and total < self._max_wave:
            progress = False
            for tenant in self._rr:
                q = self._queues.get(tenant)
                if not q:
                    continue
                nxt = len(q[0].messages)
                if taken and total + nxt > self._max_wave:
                    continue
                taken.append(q.pop(0))
                total += nxt
                progress = True
        if self._rr:
            self._rr.append(self._rr.pop(0))
        self._count -= total
        return taken

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._count and not self._closed:
                    self._cv.wait()
                if not self._count and self._closed:
                    return
                # Real-thread deadline: wave closes at first-pending + window
                # or the size cap, whichever fires first.
                deadline = time.monotonic() + self._window  # wallclock-ok
                while self._count < self._wave_target and not self._closed:
                    remaining = deadline - time.monotonic()  # wallclock-ok
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                wave = self._take_wave()
            if not wave:
                continue
            messages: list = []
            signatures: list = []
            keys: list = []
            for item in wave:
                messages.extend(item.messages)
                signatures.extend(item.signatures)
                keys.extend(item.keys)
            try:
                results = np.asarray(
                    self._engine.verify_batch(messages, signatures, keys)
                )
                slices = _split_results(results, [len(i.messages) for i in wave])
            except BaseException as exc:
                for item in wave:
                    item.error = exc
                    item.done.set()
                continue
            if self._on_wave is not None:
                tenant_counts: dict[str, int] = {}
                for item in wave:
                    tenant_counts[item.tenant] = (
                        tenant_counts.get(item.tenant, 0) + len(item.messages)
                    )
                try:
                    self._on_wave(tenant_counts, len(messages))
                except Exception:
                    logger.exception("on_wave hook failed (ignored)")
            group_counts: dict[str, int] = {}
            for item in wave:
                if item.group is not None:
                    group_counts[item.group] = (
                        group_counts.get(item.group, 0) + len(item.messages)
                    )
            if group_counts and self._groups_metrics is not None:
                self._groups_metrics.wave_span.observe(float(len(group_counts)))
                if len(group_counts) >= 2:
                    self._groups_metrics.count_wave_multi_group.add(1)
            if group_counts and self._on_group_wave is not None:
                try:
                    self._on_group_wave(group_counts, len(messages))
                except Exception:
                    logger.exception("on_group_wave hook failed (ignored)")
            for item, piece in zip(wave, slices):
                item.result = piece
                item.done.set()


__all__ = [
    "AdmissionReject",
    "BatchCoalescer",
    "FairShareWaveFormer",
    "ThreadCoalescingVerifier",
]
