"""Half-aggregation of Ed25519 quorum certificates (arXiv:2302.00418).

A quorum cert of n commit signatures ``(Rᵢ, sᵢ)`` collapses to
``(R₁..Rₙ, s_agg)`` with ``s_agg = Σ zᵢ·sᵢ mod L`` — ~64n cert bytes
shrink to ~32n + 32.  The coefficients are transcript-derived
(Fiat–Shamir over the length-framed ``(message, R, key)`` triples, same
derivation discipline as the PR-6 batch transcript: no wallclock, no
ambient RNG, so same-seed runs stay byte-identical) with ``z₁ = 1`` —
the classic half-aggregation shape, sound to 2⁻¹²⁸ (SAFETY.md §9).

Verification checks ``[s_agg]B + Σ[zᵢkᵢ mod L](−Aᵢ) + Σ[zᵢ](−Rᵢ) = 0``
with ``kᵢ = SHA-512(Rᵢ‖Aᵢ‖mᵢ) mod L`` — *literally* the PR-6
batch-verify equation with the aggregate base-point scalar supplied by
the cert instead of recomputed from per-signer scalars.  Both backends
therefore already exist: the shared-doubling Straus MSM device kernel
(:func:`consensus_tpu.models.ed25519.batch_verify_impl`, re-wrapped here
under its own kernel-accounting name so launch histograms attribute cert
verifies separately — ONE launch per cert) and the big-int host twin
with the identical two-phase window schedule.

Aggregation is self-checking: the aggregator verifies the cert it just
built before releasing it, and on failure bisects with fresh transcripts
— subsets below the bisection floor are decided by STRICT per-signature
verification, so the set of localized bad components has exact parity
with the strict verifier on every rejection class (forged bytes, S ≥ L,
wrong key, non-decodable R).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from consensus_tpu.obs.kernels import instrumented_jit, kernel_lane_suffix
from consensus_tpu.ops import field25519 as fe

from consensus_tpu.models.ed25519 import (
    _BASE_POINT,
    _REF_IDENTITY,
    _TABLE,
    _WINDOWS,
    _Z_WINDOWS,
    Ed25519BatchVerifier,
    L,
    _bits_to_comb_digits8,
    _bytes_rows_to_bits,
    _next_pow2,
    _prep_compressed,
    _ref_add,
    _ref_decompress,
    _ref_mul,
    _ref_negate,
    _signed_digits_int,
    batch_verify_impl,
)

#: Domain separation for the half-aggregation transcript.  Distinct from
#: the PR-6 batch tag (``ctpu/batchz/v1``): that transcript commits to the
#: full signatures, but a half-agg VERIFIER never sees per-signer sᵢ, so
#: the cert transcript commits to (message, R, key) triples only.
_HALFAGG_TAG = b"ctpu/halfagg/v1"

#: Same MSM body as the randomized batch verifier, instrumented under its
#: own name: the "exactly one MSM launch per aggregate cert" gate reads
#: this counter without PR-6 batch_verify traffic polluting it.
_halfagg_verify_kernel = instrumented_jit(
    batch_verify_impl, "ed25519.halfagg_verify" + kernel_lane_suffix()
)


def halfagg_coefficients(
    messages: Sequence[bytes],
    rs: Sequence[bytes],
    public_keys: Sequence[bytes],
) -> list[int]:
    """Deterministic cert coefficients: ``z₁ = 1``, ``zᵢ = H(root‖i)[:16]``
    for i ≥ 2, with the root a Fiat–Shamir commitment to every
    length-framed ``(message, R, key)`` triple.  An adversary must commit
    to all cert contents before learning any coefficient — the game the
    2⁻¹²⁸ soundness bound is proved in (SAFETY.md §9)."""
    if not messages:
        return []
    sha512 = hashlib.sha512

    def frame(raw: bytes) -> bytes:
        return len(raw).to_bytes(8, "little") + bytes(raw)

    leaves = [
        sha512(frame(m) + frame(r) + frame(a)).digest()
        for m, r, a in zip(messages, rs, public_keys)
    ]
    root = sha512(
        _HALFAGG_TAG + len(leaves).to_bytes(8, "little") + b"".join(leaves)
    ).digest()
    zs = [1]
    for i in range(1, len(leaves)):
        zs.append(
            int.from_bytes(
                sha512(root + i.to_bytes(8, "little")).digest()[:16], "little"
            )
            or 1
        )
    return zs


def _challenge(r: bytes, key: bytes, message: bytes) -> int:
    """RFC 8032 per-signature challenge kᵢ = SHA-512(Rᵢ ‖ Aᵢ ‖ mᵢ) mod L."""
    return (
        int.from_bytes(
            hashlib.sha512(bytes(r) + bytes(key) + bytes(message)).digest(),
            "little",
        )
        % L
    )


_Y_MASK = (1 << 255) - 1


class HalfAggregator:
    """Aggregate and verify half-aggregated Ed25519 quorum certs.

    Backend knobs mirror (and, when ``engine`` is given, are inherited
    from) :class:`Ed25519BatchVerifier`, so a deployment's device/host
    routing and padding policy apply to cert verifies unchanged —
    chaos-engine clusters built with ``min_device_batch=10**9`` exercise
    the host big-int twin, device-parity tests the kernel.
    """

    def __init__(
        self,
        *,
        engine: Optional[object] = None,
        pad_pow2: bool = True,
        min_device_batch: int = 1,
        pad_to: int = 0,
        min_bisect: int = 2,
        device_prep: Optional[bool] = None,
    ) -> None:
        if engine is not None:
            pad_pow2 = getattr(engine, "_pad_pow2", pad_pow2)
            min_device_batch = getattr(
                engine, "_min_device_batch", min_device_batch
            )
            pad_to = getattr(engine, "_pad_to", pad_to)
            if device_prep is None:
                # Inherit the fused front-end from the engine: a
                # device_prep deployment's cert verifies go bytes-in →
                # verdict-out too.
                device_prep = bool(getattr(engine, "fused", False))
        self._engine = engine
        self._pad_pow2 = pad_pow2
        self._min_device_batch = min_device_batch
        self._pad_to = pad_to
        self._min_bisect = max(2, int(min_bisect))
        self._device_prep = bool(device_prep)
        #: Aggregate-equation checks performed (each is one MSM launch on
        #: the device path / one host-twin evaluation).
        self.aggregate_checks = 0
        #: Aggregations whose self-check failed and fell back to the
        #: bisection localizer.
        self.fallback_bisections = 0

    # --- aggregation (the committing replica holds full signatures) -------

    def aggregate(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence[bytes],
    ) -> tuple[Optional[tuple[tuple[bytes, ...], bytes]], tuple[int, ...]]:
        """Build ``(rs, s_agg)`` from full signatures, self-checking the
        result before release.

        Returns ``((rs, s_agg), ())`` on success, or ``(None, bad_indices)``
        when any component is invalid — ``bad_indices`` localized by
        bisection with strict per-signature parity, so the caller can shed
        exactly the strict-invalid components (or keep the full tuple)."""
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("aggregate length mismatch")
        if n == 0:
            return None, ()
        rs: list[bytes] = []
        ss: list[int] = []
        bad: list[int] = []
        for i in range(n):
            sig = bytes(signatures[i])
            if len(sig) != 64 or int.from_bytes(sig[32:], "little") >= L:
                bad.append(i)
                rs.append(b"\x00" * 32)
                ss.append(0)
                continue
            rs.append(sig[:32])
            ss.append(int.from_bytes(sig[32:], "little"))
        if not bad:
            zs = halfagg_coefficients(messages, rs, public_keys)
            s_agg = sum(z * s for z, s in zip(zs, ss)) % L
            s_bytes = s_agg.to_bytes(32, "little")
            if self.verify(messages, rs, s_bytes, public_keys):
                return (tuple(rs), s_bytes), ()
        self.fallback_bisections += 1
        bad_set = set(bad)
        bad_set.update(
            self._bisect(
                [i for i in range(n) if i not in bad_set],
                messages, signatures, public_keys,
            )
        )
        return None, tuple(sorted(bad_set))

    def _bisect(self, idx, messages, signatures, public_keys) -> list[int]:
        """Localize bad components: aggregate-check subsets under FRESH
        transcripts, strict-verify below the floor (PR-6 discipline)."""
        if not idx:
            return []
        if len(idx) < self._min_bisect:
            sub = self._strict(
                [messages[i] for i in idx],
                [bytes(signatures[i]) for i in idx],
                [public_keys[i] for i in idx],
            )
            return [i for j, i in enumerate(idx) if not sub[j]]
        msgs = [messages[i] for i in idx]
        rs = [bytes(signatures[i])[:32] for i in idx]
        keys = [public_keys[i] for i in idx]
        zs = halfagg_coefficients(msgs, rs, keys)
        s_agg = (
            sum(
                z * int.from_bytes(bytes(signatures[i])[32:], "little")
                for z, i in zip(zs, idx)
            )
            % L
        ).to_bytes(32, "little")
        if self.verify(msgs, rs, s_agg, keys):
            return []
        mid = len(idx) // 2
        return self._bisect(
            idx[:mid], messages, signatures, public_keys
        ) + self._bisect(idx[mid:], messages, signatures, public_keys)

    def _strict(self, messages, signatures, public_keys) -> np.ndarray:
        if self._engine is not None:
            return np.asarray(
                self._engine.verify_host(messages, signatures, public_keys)
            )
        return Ed25519BatchVerifier._verify_host(
            messages, signatures, public_keys
        )

    # --- verification (any replica; full sigs never needed) ---------------

    def verify(
        self,
        messages: Sequence[bytes],
        rs: Sequence[bytes],
        s_agg: bytes,
        public_keys: Sequence[bytes],
    ) -> bool:
        """One aggregate-equation check, all-or-nothing: True iff every
        component encoding is canonical/decodable AND the MSM lands on the
        identity.  Rejection classes have exact accept/reject parity with
        strict verification of an honest cert's components (SAFETY.md §9:
        a cert never carries individual verdicts — no mixed-mode quorum)."""
        n = len(messages)
        if not (n == len(rs) == len(public_keys)):
            raise ValueError("verify length mismatch")
        if n == 0:
            return False
        s_agg = bytes(s_agg)
        if len(s_agg) != 32:
            return False
        u = int.from_bytes(s_agg, "little")
        if u >= L:  # canonical aggregate scalar: same reject class as S >= L
            return False
        for raw in list(rs) + list(public_keys):
            raw = bytes(raw)
            if len(raw) != 32 or (
                int.from_bytes(raw, "little") & _Y_MASK
            ) >= fe.P:
                return False
        self.aggregate_checks += 1
        if self._device_prep and n >= self._min_device_batch:
            # Fused path: coefficient transcript, challenge hashing, and
            # the mod-L products all happen inside the one MSM launch
            # (models/fused.py) — the host work above was byte compares.
            from consensus_tpu.models.fused import fused_aggregate_check

            eq_ok, valid = fused_aggregate_check(
                name="ed25519.fused_halfagg_verify",
                tag=_HALFAGG_TAG,
                messages=messages,
                rs=rs,
                keys=public_keys,
                leaf_mids=rs,
                pad_to=self._pad_to,
                pad_pow2=self._pad_pow2,
                u_bytes=s_agg,
                fixed_z1=True,
            )
            return bool(all(valid) and eq_ok)
        zs = halfagg_coefficients(messages, rs, public_keys)
        zk = [
            (z * _challenge(r, a, m)) % L
            for z, r, a, m in zip(zs, rs, public_keys, messages)
        ]
        if n >= self._min_device_batch:
            return self._verify_device(rs, public_keys, u, zk, zs)
        return self._verify_host(rs, public_keys, u, zk, zs)

    def _verify_device(self, rs, public_keys, u, zk, zs) -> bool:
        """One shared-doubling MSM launch for the whole cert."""
        m = len(rs)
        y_r, sign_r, _ = _prep_compressed([bytes(r) for r in rs])
        y_a, sign_a, _ = _prep_compressed([bytes(a) for a in public_keys])
        zk_digits = np.array(
            [_signed_digits_int(v, _WINDOWS) for v in zk], dtype=np.int16
        ).T
        z_digits = np.array(
            [_signed_digits_int(z, _Z_WINDOWS) for z in zs], dtype=np.int16
        ).T
        zk_digits = (zk_digits + 8).astype(np.uint8)
        z_digits = (z_digits + 8).astype(np.uint8)
        u_row = np.frombuffer(u.to_bytes(32, "little"), dtype=np.uint8).reshape(1, 32)
        zs_digits8 = _bits_to_comb_digits8(_bytes_rows_to_bits(u_row))
        host_ok = np.ones(m, dtype=bool)

        if self._pad_to >= m:
            padded = self._pad_to
        else:
            padded = _next_pow2(m) if self._pad_pow2 else m
        if padded != m:
            pad = padded - m
            y_r = np.pad(y_r, ((0, pad), (0, 0)))
            y_a = np.pad(y_a, ((0, pad), (0, 0)))
            sign_r = np.pad(sign_r, (0, pad))
            sign_a = np.pad(sign_a, (0, pad))
            zk_digits = np.pad(zk_digits, ((0, 0), (0, pad)), constant_values=8)
            z_digits = np.pad(z_digits, ((0, 0), (0, pad)), constant_values=8)
            host_ok = np.pad(host_ok, (0, pad))

        eq_ok, valid = _halfagg_verify_kernel(
            jnp.asarray(np.ascontiguousarray(y_r.T)),
            jnp.asarray(sign_r),
            jnp.asarray(np.ascontiguousarray(y_a.T)),
            jnp.asarray(sign_a),
            jnp.asarray(zs_digits8),
            jnp.asarray(zk_digits),
            jnp.asarray(z_digits),
            jnp.asarray(host_ok),
        )
        # A non-decodable R or A is masked to the identity inside the
        # kernel, so eq_ok alone could still be True — the whole cert must
        # reject (strict parity with the non-decodable class).
        return bool(np.asarray(valid)[:m].all()) and bool(np.asarray(eq_ok))

    def _verify_host(self, rs, public_keys, u, zk, zs) -> bool:
        """Host big-int twin: the SAME two-phase shared-window schedule as
        the kernel, in plain integers (backs every CPU deployment/test)."""
        m = len(rs)
        a_pts = [_ref_decompress(bytes(a)) for a in public_keys]
        r_pts = [_ref_decompress(bytes(r)) for r in rs]
        if any(p is None for p in a_pts) or any(p is None for p in r_pts):
            return False

        def table(p):
            neg = _ref_negate(p)
            tbl = [_REF_IDENTITY, neg]
            for _ in range(_TABLE - 2):
                tbl.append(_ref_add(tbl[-1], neg))
            return tbl

        a_tbl = [table(p) for p in a_pts]
        r_tbl = [table(p) for p in r_pts]
        zk_digits = [_signed_digits_int(v, _WINDOWS) for v in zk]
        z_digits = [_signed_digits_int(z, _Z_WINDOWS) for z in zs]

        acc = _REF_IDENTITY
        low_start = _WINDOWS - _Z_WINDOWS
        for w in range(_WINDOWS):
            for _ in range(4):
                acc = _ref_add(acc, acc)
            for j in range(m):
                d = zk_digits[j][w]
                if d:
                    acc = _ref_add(
                        acc, a_tbl[j][d] if d > 0 else _ref_negate(a_tbl[j][-d])
                    )
                if w >= low_start:
                    d = z_digits[j][w - low_start]
                    if d:
                        acc = _ref_add(
                            acc,
                            r_tbl[j][d] if d > 0 else _ref_negate(r_tbl[j][-d]),
                        )
        acc = _ref_add(acc, _ref_mul(u, _BASE_POINT))
        return acc[0] % fe.P == 0 and (acc[1] - acc[2]) % fe.P == 0


__all__ = [
    "HalfAggregator",
    "halfagg_coefficients",
]
