"""Ed25519-backed implementations of the consensus crypto ports.

The reference leaves ``Signer``/``Verifier`` entirely to the application
(Fabric brings MSP crypto).  This module ships a ready-made Ed25519 identity
layer whose *batch* verification paths run on the TPU engine
(:class:`consensus_tpu.models.ed25519.Ed25519BatchVerifier`), so a consensus
deployment gets the accelerated quorum verification without writing any
crypto:

* :class:`Ed25519Signer` — holds this replica's private key (host-side;
  secrets never leave the host), signs raw payloads and proposals.
* :class:`Ed25519VerifierMixin` — implements the four signature-verification
  methods of the ``Verifier`` port against a node-id -> public-key registry,
  draining ``verify_consenter_sigs_batch`` / ``verify_requests_batch``
  into single device batches.  Applications mix it in and add their
  proposal/request semantics (``verify_proposal``, ``requests_from_proposal``).

Message binding: a consenter signature covers
``b"ctpu/commit" + proposal-digest + len(aux) + aux``, so the signature
commits to both the proposal content and the auxiliary prepare-vouch list
(the blacklist redemption evidence, reference internal/bft/view.go:472-481).
"""

from __future__ import annotations

import struct
from typing import Mapping, Optional, Sequence

from consensus_tpu.api.deps import Signer, Verifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
)
from consensus_tpu.types import Proposal, QuorumCert, RequestInfo, Signature

_COMMIT_TAG = b"ctpu/commit"
_RAW_TAG = b"ctpu/raw"


def commit_message(proposal: Proposal, aux: bytes) -> bytes:
    digest = bytes.fromhex(proposal.digest())
    return _COMMIT_TAG + digest + struct.pack(">I", len(aux)) + aux


def raw_message(data: bytes) -> bytes:
    return _RAW_TAG + data


def engine_for_config(config, curve: str = "ed25519", *, metrics=None):
    """The batch engine matching a ``Configuration``'s crypto knobs
    (``batch_verify_mode``, ``crypto_pad_pow2``, ``crypto_tpu_min_batch``,
    ``mesh_shards`` / ``mesh_topology``, ``device_prep``), routed through
    the engine registry (:mod:`consensus_tpu.models.registry`): the config
    maps to an ``EngineKey`` and an unregistered key fails loudly with the
    curve-specific reason.  A multi-device topology — ``mesh_shards > 1``
    or a non-empty ``mesh_topology`` such as ``(2, 4)`` — selects the
    sharded engines from :mod:`consensus_tpu.parallel` over that device
    layout; ``mesh_shards = 1`` returns today's single-device engines
    bit-for-bit.  ``device_prep`` swaps in the fused bytes-in → verdict-out
    engines (:mod:`consensus_tpu.models.fused`) on either topology.  Every
    replica in a cluster must agree on the VERDICT-affecting knobs
    (``batch_verify_mode``, the curve) — verdict parity across replicas is
    a quorum-safety requirement; the topology knobs and ``device_prep``
    only change the launch layout and may differ per replica.

    ``config.compile_cache`` governs construction cost: the in-process
    compiled-kernel memo means rebuilding an engine over the same topology
    (restart, supervisor ladder, tenant churn) books zero new compiles in
    the kernel ledger, and a non-empty ``persistent_dir`` additionally
    wires jax's on-disk compilation cache.  Pass a node ``Metrics`` bundle
    as ``metrics`` to book this construction's memo hits/misses into the
    pinned ``engine_compile_cache_{hits,misses}_total`` counters.

    ``engine_supervision`` wraps the result in an
    :class:`~consensus_tpu.models.supervisor.EngineSupervisor` over the
    config's degrade ladder (:func:`degrade_ladder_configs`): fault-classed
    circuit breakers route launches down fused → unfused → host (and
    mesh → single device → host) and re-promote when the breaker
    closes.  Supervision, too, changes only WHERE work runs — never the
    verdict — so it is per-replica free."""
    from consensus_tpu.obs.kernels import COMPILE_CACHE

    before = COMPILE_CACHE.snapshot()
    if not getattr(config, "engine_supervision", False):
        engine = _engine_for_config(config, curve)
    else:
        from consensus_tpu.models.supervisor import EngineSupervisor

        rungs = [
            _engine_for_config(c, curve) for c in degrade_ladder_configs(config)
        ]
        engine = EngineSupervisor(
            rungs,
            crosscheck_interval=int(
                getattr(config, "engine_crosscheck_interval", 0) or 0
            ),
            name=f"{curve}-engine",
        )
    if metrics is not None:
        after = COMPILE_CACHE.snapshot()
        metrics.engine.count_compile_cache_hits.add(
            after["hits"] - before["hits"]
        )
        metrics.engine.count_compile_cache_misses.add(
            after["misses"] - before["misses"]
        )
    return engine


def degrade_ladder_configs(config) -> list:
    """The best-first ``Configuration`` ladder supervision degrades down:
    as configured, then mesh → single device, then fused → unfused
    host-prep.  Derived by walking the engine registry's degrade keys
    (:meth:`~consensus_tpu.models.registry.EngineRegistry.degrade_keys`)
    and mapping each key transition back onto the config, so the ladder
    always mirrors what is actually registered.  (The host twin is not a
    config — the supervisor appends it as the ladder's floor itself.)"""
    from consensus_tpu.models.registry import ENGINE_REGISTRY, engine_key_for

    ladder = [config]
    keys = ENGINE_REGISTRY.degrade_keys(engine_key_for(config))
    for prev_key, next_key in zip(keys, keys[1:]):
        prev = ladder[-1]
        if prev_key.topology == "mesh" and next_key.topology == "single":
            ladder.append(prev.with_(mesh_shards=1, mesh_topology=()))
        elif prev_key.device_prep and not next_key.device_prep:
            ladder.append(prev.with_(device_prep=False))
    return ladder


def _engine_for_config(config, curve: str = "ed25519"):
    """The unsupervised engine routing (see :func:`engine_for_config`):
    config -> ``EngineKey`` -> registered builder."""
    from consensus_tpu.models.registry import ENGINE_REGISTRY, engine_key_for
    from consensus_tpu.parallel.topology import (
        apply_compile_cache,
        topology_for_config,
    )

    cache = getattr(config, "compile_cache", None)
    apply_compile_cache(cache)
    return ENGINE_REGISTRY.build(
        engine_key_for(config, curve),
        topology=topology_for_config(config),
        compile_cache=bool(getattr(cache, "enabled", True)),
        pad_pow2=config.crypto_pad_pow2,
        min_device_batch=config.crypto_tpu_min_batch,
    )


class Ed25519Signer(Signer):
    """This replica's signing identity (private key stays host-side).

    Uses the ``cryptography`` package when installed; otherwise signs with
    the pure-Python RFC 8032 reference in :mod:`consensus_tpu.models
    .ed25519` — same keys, same signatures, Python-speed."""

    def __init__(self, node_id: int, private_key_bytes: Optional[bytes] = None) -> None:
        self.node_id = node_id
        self._key = None
        try:
            from cryptography.hazmat.primitives import serialization
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey,
            )
        except ImportError:
            import os

            from consensus_tpu.models.ed25519 import ref_public_key, ref_sign

            seed = (
                private_key_bytes if private_key_bytes is not None
                else os.urandom(32)
            )
            self.public_bytes = ref_public_key(seed)
            self._sign_fn = lambda data, _seed=seed: ref_sign(_seed, data)
            return
        if private_key_bytes is None:
            self._key = Ed25519PrivateKey.generate()
        else:
            self._key = Ed25519PrivateKey.from_private_bytes(private_key_bytes)
        self.public_bytes = self._key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        self._sign_fn = self._key.sign

    def sign_raw(self, data: bytes) -> bytes:
        """Sign ``data`` exactly as given (no domain tag) — for embedders
        that bring their own message framing (e.g. client requests)."""
        return self._sign_fn(data)

    def sign(self, data: bytes) -> bytes:
        return self._sign_fn(raw_message(data))

    def sign_proposal(self, proposal: Proposal, aux: bytes = b"") -> Signature:
        return Signature(
            id=self.node_id,
            value=self._sign_fn(commit_message(proposal, aux)),
            msg=aux,
        )


class Ed25519VerifierMixin(Verifier):
    """Signature-verification half of the ``Verifier`` port, batched onto the
    device.  Subclasses provide the application half (proposal/request checks).
    """

    def __init__(
        self,
        public_keys: Mapping[int, bytes],
        *,
        engine: Optional[Ed25519BatchVerifier] = None,
        batch_verify_mode: bool = False,
    ) -> None:
        """``batch_verify_mode`` (Configuration.batch_verify_mode) selects
        the randomized aggregate-check engine as the default; an explicit
        ``engine`` wins, but passing a non-randomized engine together with
        the flag is a config contradiction and raises."""
        self._public_keys = dict(public_keys)
        if engine is None:
            engine = (
                Ed25519RandomizedBatchVerifier()
                if batch_verify_mode
                else Ed25519BatchVerifier()
            )
        elif batch_verify_mode and not getattr(engine, "randomized", False):
            raise ValueError(
                "batch_verify_mode=True requires a randomized engine "
                "(got %r)" % type(engine).__name__
            )
        self._engine = engine
        #: Consumed by api.deps facades (CryptoApp etc.) to decide whether
        #: the default multi-batch loop may coalesce through this verifier.
        self.batch_verify_enabled = bool(getattr(engine, "randomized", False))
        self._aggregator = None

    #: Half-aggregated quorum certs are Ed25519-only (the aggregator's MSM
    #: rides the Ed25519 shared-doubling kernel); the P-256 subclass
    #: overrides this back to False.
    supports_cert_aggregation = True

    @property
    def aggregator(self):
        """The lazily-built :class:`~consensus_tpu.models.aggregate.
        HalfAggregator` sharing this verifier's engine (same padding and
        device-threshold knobs, so cert checks route host/device exactly
        like the engine's own batches)."""
        if self._aggregator is None:
            from consensus_tpu.models.aggregate import HalfAggregator

            self._aggregator = HalfAggregator(engine=self._engine)
        return self._aggregator

    def set_public_keys(self, public_keys: Mapping[int, bytes]) -> None:
        """Swap the key registry (reconfiguration)."""
        self._public_keys = dict(public_keys)

    @property
    def engine(self):
        """The batch engine behind this verifier — lets applications fuse
        their own signature waves (e.g. client requests) into the same
        launch, provided they use THIS engine (SAFETY.md §7: never mix
        engines within one quorum cert's worth of verdicts)."""
        return self._engine

    def consenter_sig_triples(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> tuple[list[bytes], list[bytes], list[bytes], list[bool]]:
        """The (messages, sigs, keys, known) arrays that
        :meth:`verify_consenter_sigs_batch` would launch — exposed so a
        caller can append them to a larger wave and run ONE engine call
        covering requests + consenter certs."""
        if isinstance(signatures, QuorumCert):
            raise ValueError(
                "consenter_sig_triples cannot flatten a half-aggregated "
                "QuorumCert into a strict-verification wave — route it "
                "through verify_aggregate_cert instead"
            )
        messages, sigs, keys = [], [], []
        known: list[bool] = []
        for sig in signatures:
            key = self._public_keys.get(sig.id)
            known.append(key is not None)
            messages.append(commit_message(proposal, sig.msg))
            sigs.append(sig.value)
            keys.append(key if key is not None else b"")
        return messages, sigs, keys, known

    # --- half-aggregated quorum certs (models/aggregate.py) --------------

    def aggregate_cert(
        self, proposal: Proposal, signatures: Sequence[Signature]
    ) -> Optional[QuorumCert]:
        if not self.supports_cert_aggregation:
            return None
        if isinstance(signatures, QuorumCert):
            return signatures
        sigs = list(signatures)
        if not sigs:
            return None
        messages, values, keys = [], [], []
        for sig in sigs:
            key = self._public_keys.get(sig.id)
            if key is None:
                return None
            messages.append(commit_message(proposal, sig.msg))
            values.append(sig.value)
            keys.append(key)
        agg, _bad = self.aggregator.aggregate(messages, values, keys)
        if agg is None:
            return None
        rs, s_agg = agg
        aux_table: list[bytes] = []
        aux_index: list[int] = []
        seen: dict[bytes, int] = {}
        for sig in sigs:
            idx = seen.get(sig.msg)
            if idx is None:
                idx = len(aux_table)
                seen[sig.msg] = idx
                aux_table.append(sig.msg)
            aux_index.append(idx)
        return QuorumCert(
            signer_ids=tuple(s.id for s in sigs),
            rs=tuple(rs),
            s_agg=s_agg,
            aux_table=tuple(aux_table),
            aux_index=tuple(aux_index),
        )

    def verify_aggregate_cert(
        self, cert: QuorumCert, proposal: Proposal
    ) -> Optional[list[bytes]]:
        if not self.supports_cert_aggregation or len(cert) == 0:
            return None
        messages, keys, aux = [], [], []
        for comp in cert:
            key = self._public_keys.get(comp.id)
            if key is None:
                return None
            messages.append(commit_message(proposal, comp.msg))
            keys.append(key)
            aux.append(comp.msg)
        try:
            ok = self.aggregator.verify(
                messages, list(cert.rs), cert.s_agg, keys
            )
        except ValueError:
            return None
        return aux if ok else None

    # --- single-signature paths (host) ----------------------------------

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        result = self.verify_consenter_sigs_batch([signature], proposal)[0]
        if result is None:
            raise ValueError(f"invalid consenter signature from {signature.id}")
        return result

    def verify_signature(self, signature: Signature) -> None:
        key = self._public_keys.get(signature.id)
        if key is None:
            raise ValueError(f"unknown signer {signature.id}")
        ok = self._engine.verify_batch(
            [raw_message(signature.msg)], [signature.value], [key]
        )
        if not ok[0]:
            raise ValueError(f"invalid signature from {signature.id}")

    # --- batch paths (device) --------------------------------------------

    def verify_consenter_sigs_batch(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list[Optional[bytes]]:
        if isinstance(signatures, QuorumCert):
            aux = self.verify_aggregate_cert(signatures, proposal)
            if aux is None:
                return [None] * len(signatures)
            return list(aux)
        messages, sigs, keys, known = self.consenter_sig_triples(
            signatures, proposal
        )
        ok = self._engine.verify_batch(messages, sigs, keys)
        return [
            signatures[i].msg if (known[i] and ok[i]) else None
            for i in range(len(signatures))
        ]

    def verify_consenter_sigs_multi_batch(
        self, groups: Sequence[tuple[Proposal, Sequence[Signature]]]
    ) -> list[list[Optional[bytes]]]:
        """Flatten every (proposal, quorum cert) group into ONE device batch
        — the per-item message array already lets signatures over different
        proposals share a launch, so a whole sync chunk verifies at the same
        kernel throughput as a single quorum.

        Half-aggregated groups verify one aggregate check per cert instead;
        mixing cert kinds in one call raises (contradiction guard — see the
        port default in api/deps.py)."""
        if groups:
            kinds = {isinstance(sigs, QuorumCert) for _, sigs in groups}
            if len(kinds) > 1:
                raise ValueError(
                    "verify_consenter_sigs_multi_batch: groups mix "
                    "half-aggregated QuorumCerts with full signature tuples "
                    "— cert modes contradict; partition the groups first"
                )
            if kinds == {True}:
                return [
                    self.verify_consenter_sigs_batch(cert, proposal)
                    for proposal, cert in groups
                ]
        messages, sigs, keys, known = [], [], [], []
        for proposal, cert in groups:
            for sig in cert:
                key = self._public_keys.get(sig.id)
                known.append(key is not None)
                messages.append(commit_message(proposal, sig.msg))
                sigs.append(sig.value)
                keys.append(key if key is not None else b"")
        if not messages:
            return [[] for _ in groups]
        ok = self._engine.verify_batch(messages, sigs, keys)
        out: list[list[Optional[bytes]]] = []
        i = 0
        for _, cert in groups:
            row: list[Optional[bytes]] = []
            for sig in cert:
                row.append(sig.msg if (known[i] and ok[i]) else None)
                i += 1
            out.append(row)
        return out

    def auxiliary_data(self, msg: bytes) -> bytes:
        return msg


class EcdsaP256Signer(Signer):
    """ECDSA-P256 replica identity (private key host-side); signatures are
    the framework's raw 64-byte r||s format."""

    def __init__(self, node_id: int, private_key=None) -> None:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec

        self.node_id = node_id
        self._key = private_key or ec.generate_private_key(ec.SECP256R1())
        self._hash = ec.ECDSA(hashes.SHA256())
        self.public_bytes = self._key.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint
        )

    def sign_raw(self, data: bytes) -> bytes:
        """Sign ``data`` exactly as given (no domain tag); returns the
        framework's raw 64-byte r||s format."""
        from consensus_tpu.models.ecdsa_p256 import raw_signature_from_der

        return raw_signature_from_der(self._key.sign(data, self._hash))

    _sign_raw = sign_raw  # backward-compat internal alias

    def sign(self, data: bytes) -> bytes:
        return self.sign_raw(raw_message(data))

    def sign_proposal(self, proposal: Proposal, aux: bytes = b"") -> Signature:
        return Signature(
            id=self.node_id,
            value=self._sign_raw(commit_message(proposal, aux)),
            msg=aux,
        )


class EcdsaP256VerifierMixin(Ed25519VerifierMixin):
    """Signature-verification half of the Verifier port over ECDSA-P256 —
    same registry/batching semantics as the Ed25519 mixin, different curve
    engine."""

    # Half-aggregation is Ed25519-only: the aggregate relation rides the
    # Ed25519 group law, there is no P-256 analogue here.
    supports_cert_aggregation = False

    def __init__(self, public_keys: Mapping[int, bytes], *, engine=None) -> None:
        from consensus_tpu.models.ecdsa_p256 import EcdsaP256BatchVerifier

        super().__init__(public_keys, engine=engine or EcdsaP256BatchVerifier())


__all__ = [
    "Ed25519Signer",
    "Ed25519VerifierMixin",
    "EcdsaP256Signer",
    "EcdsaP256VerifierMixin",
    "commit_message",
    "engine_for_config",
    "raw_message",
]
