"""Fused bytes-in → verdict-out Ed25519 engines (``Configuration.device_prep``).

The legacy engines split a wave into host prep (SHA-512 challenge hashing,
mod-L reduction, canonical-range checks, digit recoding — a Python loop per
signature) and a device MSM launch; the last live device measurement
attributed its throughput to *pipelining* that prep behind the kernel.
These engines delete the tax instead: the host does byte movement only
(slice ``R ‖ A ‖ M`` into padded SHA-512 block layout — :func:`consensus_tpu
.ops.sha512.pad_messages`), and one jitted graph per wave does everything
else on device:

    SHA-512 → reduce mod L → digit recode → canonical checks →
    decompress → MSM → verdict

For the randomized-batch and half-agg paths the Fiat–Shamir transcript
itself moves on device: per-lane leaf hashes, the root hash assembled from
the leaf digests *without leaving the device* (:func:`consensus_tpu.ops
.sha512.pack_bytes_device`), coefficient hashes ``zᵢ = H(root ‖ i)``, the
products ``zᵢkᵢ mod L`` / ``Σ zᵢsᵢ mod L``, and the shared-doubling MSM —
one launch per aggregate check, no host round-trip between hashing and MSM.

Parity contract (SAFETY.md §10): with ``device_prep`` on, accept/reject is
bit-identical to the host-prep engines on every rejection class — forged
and tampered lanes reject by math, ``S ≥ L`` / non-canonical ``y`` reject
by the same range checks (now computed on device for the strict path), and
the randomized transcript bytes are identical, so bisection takes identical
paths.  ``device_prep`` off is bit-for-bit the previous protocol: these
classes are additive.

Graph shapes: the strict kernel is shape-polymorphic over (block count ×
batch) like the legacy kernel ladder; the aggregate kernels additionally
specialize on the live subset size ``n`` (the transcript's root message
length is ``len(tag) + 8 + 64n`` bytes — a different committed count IS a
different hash).  Waves formed at fixed sizes (``pad_to``/coalescer) hit
one compiled graph forever.

Input buffers are donated to the runtime on accelerator backends (the
block arrays are the dominant transfer; donation lets XLA alias them into
scratch instead of holding both copies) — donation is skipped on CPU,
which would only warn.
"""

from __future__ import annotations

import functools
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from consensus_tpu.obs.kernels import instrumented_jit, kernel_lane_suffix
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops import scalar25519 as sc
from consensus_tpu.ops import sha512 as sh

from consensus_tpu.models.ed25519 import (
    _WINDOWS,
    _Z_TAG,
    _Z_WINDOWS,
    _next_pow2,
    _transcript_coefficients,
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    L,
    batch_verify_impl,
    verify_impl,
)

_L_BYTES_BE = np.frombuffer(L.to_bytes(32, "big"), dtype=np.uint8)
_P_BYTES_BE = np.frombuffer(fe.P.to_bytes(32, "big"), dtype=np.uint8)


# --- host-side helpers (byte movement + vectorized range checks) -----------


def _rows_lt_be(rows_be: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """Vectorized big-endian lexicographic ``row < bound`` (row == bound
    compares False, matching the exclusive canonical ranges)."""
    n = rows_be.shape[0]
    diff = rows_be != bound_be
    first = np.argmax(diff, axis=1)
    lt = rows_be[np.arange(n), first] < bound_be[first]
    return np.where(diff.any(axis=1), lt, False)


def canonical_ok_fast(signatures, public_keys) -> np.ndarray:
    """Vectorized twin of ``Ed25519BatchVerifier._canonical_ok`` — same
    classes (sig/key length, S < L, canonical y for R and A), no per-lane
    big-int loop.  The randomized fused engine pre-filters its subset with
    this so transcript membership matches the legacy path exactly."""
    n = len(signatures)
    ok = np.ones(n, dtype=bool)
    sig_chunks: list[bytes] = []
    key_chunks: list[bytes] = []
    for i in range(n):
        sig, key = bytes(signatures[i]), bytes(public_keys[i])
        if len(sig) != 64:
            ok[i] = False
            sig = b"\x00" * 64
        if len(key) != 32:
            ok[i] = False
            key = b"\x00" * 32
        sig_chunks.append(sig)
        key_chunks.append(key)
    if n == 0:
        return ok
    sig_rows = np.frombuffer(b"".join(sig_chunks), dtype=np.uint8).reshape(n, 64)
    key_rows = np.frombuffer(b"".join(key_chunks), dtype=np.uint8).reshape(n, 32)
    ok &= _rows_lt_be(sig_rows[:, :31:-1], _L_BYTES_BE)  # S < L
    y_r = sig_rows[:, 31::-1].copy()
    y_r[:, 0] &= 0x7F
    ok &= _rows_lt_be(y_r, _P_BYTES_BE)
    y_a = key_rows[:, ::-1].copy()
    y_a[:, 0] &= 0x7F
    ok &= _rows_lt_be(y_a, _P_BYTES_BE)
    return ok


def _byte_rows(chunks: Sequence[bytes], width: int) -> np.ndarray:
    return np.frombuffer(b"".join(chunks), dtype=np.uint8).reshape(
        len(chunks), width
    )


def _pad_wave(arrays: Sequence[np.ndarray], n: int, padded: int):
    """Zero-pad the trailing batch dim of row-major host arrays."""
    if padded == n:
        return list(arrays)
    out = []
    for a in arrays:
        pad = [(0, 0)] * a.ndim
        pad[0] = (0, padded - n)
        out.append(np.pad(a, pad))
    return out


def _pack_blocks(messages: Sequence[bytes], *, min_blocks: int = 1):
    """Pad+pack messages, quantizing the block axis to a power of two so
    the compiled-shape set stays a short ladder."""
    longest = max((len(m) for m in messages), default=0)
    want = _next_pow2(sh.padded_blocks_for(longest), minimum=min_blocks)
    return sh.pad_messages(messages, min_blocks=want)


# --- the fused strict kernel -----------------------------------------------


def fused_verify_impl(
    sig_rows: jnp.ndarray,   # (64, batch) signature bytes R ‖ S
    key_rows: jnp.ndarray,   # (32, batch) public-key bytes
    blocks: jnp.ndarray,     # (B, 16, 2, batch) padded SHA-512(R‖A‖M) blocks
    n_blocks: jnp.ndarray,   # (batch,) active block counts
    host_ok: jnp.ndarray,    # (batch,) host length checks passed
) -> jnp.ndarray:
    """Un-jitted fused strict body: the whole front-end on device, then the
    legacy MSM body (:func:`consensus_tpu.models.ed25519.verify_impl`).
    Shards over the batch axis unchanged — every stage keeps batch
    trailing."""
    sig = sig_rows.astype(jnp.int32)
    key = key_rows.astype(jnp.int32)

    digest = sh.digest_bytes(sh.sha512_blocks(blocks, n_blocks))
    k_bytes = sc.reduce_bytes_mod_l(digest)
    k_digits = sc.signed_window_digits(k_bytes, _WINDOWS)

    s_bytes = sig[32:]
    y_r = jnp.concatenate([sig[:31], (sig[31] & 0x7F)[None]], axis=0)
    sign_r = sig[31] >> 7
    y_a = jnp.concatenate([key[:31], (key[31] & 0x7F)[None]], axis=0)
    sign_a = key[31] >> 7

    ok = (
        host_ok
        & sc.lt_l(s_bytes)        # RFC 8032 §5.1.7 malleability
        & fe.bytes_lt_p(y_r)      # canonical encodings
        & fe.bytes_lt_p(y_a)
    )
    return verify_impl(y_r, sign_r, y_a, sign_a, s_bytes, k_digits, ok)


@functools.lru_cache(maxsize=None)
def _fused_verify_kernel():
    donate = (2,) if jax.default_backend() != "cpu" else ()
    return instrumented_jit(
        fused_verify_impl,
        "ed25519.fused_verify" + kernel_lane_suffix(),
        donate_argnums=donate,
    )


class FusedEd25519BatchVerifier(Ed25519BatchVerifier):
    """Strict verifier with the on-device front-end.

    Same contract and bit-identical verdicts as
    :class:`~consensus_tpu.models.ed25519.Ed25519BatchVerifier`; the host
    work per wave is one pass of byte slicing into the block layout.
    """

    fused = True

    def _prepare_fused(self, messages, signatures, public_keys):
        n = len(messages)
        host_ok = np.ones(n, dtype=bool)
        sig_chunks: list[bytes] = []
        key_chunks: list[bytes] = []
        prehash: list[bytes] = []
        for i in range(n):
            sig, key = bytes(signatures[i]), bytes(public_keys[i])
            if len(sig) != 64:
                host_ok[i] = False
                sig = b"\x00" * 64
            if len(key) != 32:
                host_ok[i] = False
                key = b"\x00" * 32
            sig_chunks.append(sig)
            key_chunks.append(key)
            prehash.append(sig[:32] + key + bytes(messages[i]))
        sig_rows = _byte_rows(sig_chunks, 64)
        key_rows = _byte_rows(key_chunks, 32)
        blocks, n_blocks = _pack_blocks(prehash)
        return sig_rows, key_rows, blocks, n_blocks, host_ok

    def _device_args(self, messages, signatures, public_keys):
        """Pack one wave into padded device arrays (dispatchable args)."""
        n = len(messages)
        sig_rows, key_rows, blocks, n_blocks, host_ok = self._prepare_fused(
            messages, signatures, public_keys
        )
        if self._pad_to >= n:
            padded = self._pad_to
        else:
            padded = _next_pow2(n) if self._pad_pow2 else n
        sig_rows, key_rows, n_blocks, host_ok = _pad_wave(
            [sig_rows, key_rows, n_blocks, host_ok], n, padded
        )
        if padded != n:
            blocks = np.pad(blocks, ((0, 0),) * 3 + ((0, padded - n),))
        return (
            jnp.asarray(np.ascontiguousarray(sig_rows.T)),
            jnp.asarray(np.ascontiguousarray(key_rows.T)),
            jnp.asarray(blocks),
            jnp.asarray(n_blocks),
            jnp.asarray(host_ok),
        )

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if n < self._min_device_batch:
            return self._verify_host(messages, signatures, public_keys)
        result = _fused_verify_kernel()(
            *self._device_args(messages, signatures, public_keys)
        )
        return np.asarray(result)[:n]

    def verify_stream(
        self, waves: Iterable[Tuple[Sequence, Sequence, Sequence]]
    ) -> Iterable[np.ndarray]:
        """Double-buffered streaming: pack + dispatch wave ``i+1`` before
        blocking on wave ``i``'s verdict, so host byte packing and the
        host→device transfer overlap device compute (JAX dispatch is
        async — the blocking point is the ``np.asarray`` fetch)."""
        kernel = _fused_verify_kernel()
        pending: Optional[tuple[int, object]] = None
        for messages, signatures, public_keys in waves:
            n = len(messages)
            out = kernel(*self._device_args(messages, signatures, public_keys))
            if pending is not None:
                prev_n, prev_out = pending
                yield np.asarray(prev_out)[:prev_n]
            pending = (n, out)
        if pending is not None:
            yield np.asarray(pending[1])[: pending[0]]


# --- the fused aggregate kernels (randomized batch + half-agg) -------------


def _aggregate_constants(tag: bytes, n: int, padded: int):
    """Host constants baked into one aggregate graph: the transcript
    prefix/trailers and the per-lane index rows."""
    prefix = tag + n.to_bytes(8, "little")
    root_len = len(prefix) + 64 * n
    root_blocks = sh.padded_blocks_for(root_len)
    root_prefix = np.frombuffer(prefix, dtype=np.uint8)[:, None]
    root_trailer = np.frombuffer(sh.pad_trailer(root_len), dtype=np.uint8)[:, None]
    z_trailer = np.broadcast_to(
        np.frombuffer(sh.pad_trailer(72), dtype=np.uint8)[:, None], (56, padded)
    )
    idx_rows = _byte_rows(
        [i.to_bytes(8, "little") for i in range(padded)], 8
    ).T  # (8, padded)
    return root_prefix, root_trailer, root_blocks, z_trailer, idx_rows


@functools.lru_cache(maxsize=None)
def _fused_aggregate_kernel(
    name: str, tag: bytes, n: int, padded: int, fixed_z1: bool, u_input: bool
):
    """Build + jit one aggregate graph: device Fiat–Shamir transcript
    (leaves → root → coefficients) feeding the shared-doubling MSM.

    ``fixed_z1`` pins lane 0's coefficient to 1 (half-aggregation);
    ``u_input`` takes the aggregate base scalar from the cert instead of
    computing ``Σ zᵢsᵢ mod L`` from per-lane S (which half-agg verifiers
    never see).  Specialized per (n, padded) — stats still accumulate
    under one kernel-accounting ``name``.
    """
    (
        root_prefix, root_trailer, root_blocks, z_trailer, idx_rows
    ) = _aggregate_constants(tag, n, padded)
    one_z = np.zeros((16, 1), dtype=np.int32)
    one_z[0, 0] = 1

    def impl(
        r_rows,       # (32, padded) R bytes
        s_rows,       # (32, padded) S bytes (zeros when u_input)
        key_rows,     # (32, padded) A bytes
        k_blocks,     # (Bk, 16, 2, padded) SHA-512(R‖A‖M) blocks
        k_nblocks,    # (padded,)
        leaf_blocks,  # (Bl, 16, 2, padded) transcript leaf blocks
        leaf_nblocks, # (padded,)
        u_bytes,      # (32, 1) aggregate base scalar (ignored unless u_input)
        host_ok,      # (padded,)
    ):
        r = r_rows.astype(jnp.int32)
        key = key_rows.astype(jnp.int32)

        # Challenge scalars kᵢ = H(Rᵢ‖Aᵢ‖mᵢ) mod L.
        k_digest = sh.digest_bytes(sh.sha512_blocks(k_blocks, k_nblocks))
        k_bytes = sc.reduce_bytes_mod_l(k_digest)

        # Transcript: leaves on every lane, root over the live n, then
        # zᵢ = H(root ‖ i)[:16] (or 1) — all without leaving the device.
        leaves = sh.digest_bytes(sh.sha512_blocks(leaf_blocks, leaf_nblocks))
        root_rows = jnp.concatenate(
            [
                jnp.asarray(root_prefix, jnp.int32),
                leaves[:, :n].T.reshape(64 * n, 1),
                jnp.asarray(root_trailer, jnp.int32),
            ],
            axis=0,
        )
        root_state = sh.sha512_blocks(
            sh.pack_bytes_device(root_rows),
            jnp.full((1,), root_blocks, jnp.int32),
        )
        root = sh.digest_bytes(root_state)  # (64, 1)

        z_rows = jnp.concatenate(
            [
                jnp.broadcast_to(root, (64, padded)),
                jnp.asarray(idx_rows, jnp.int32),
                jnp.asarray(z_trailer, jnp.int32),
            ],
            axis=0,
        )
        z_digest = sh.digest_bytes(
            sh.sha512_blocks(
                sh.pack_bytes_device(z_rows), jnp.ones((padded,), jnp.int32)
            )
        )
        z = z_digest[:16]
        z = jnp.where(
            (z == 0).all(axis=0)[None], jnp.asarray(one_z), z
        )  # z = 0 is re-mapped to 1, same as the host derivation
        if fixed_z1:
            lane0 = (jnp.arange(padded) == 0)[None]
            z = jnp.where(lane0, jnp.asarray(one_z), z)

        zk = sc.mul_mod_l(z, k_bytes)
        zk_digits = sc.signed_window_digits(zk, _WINDOWS)
        z_digits = sc.signed_window_digits(z, _Z_WINDOWS)

        if u_input:
            u = u_bytes.astype(jnp.int32)
        else:
            u = sc.sum_mod_l(sc.mul_mod_l(z, s_rows.astype(jnp.int32)))

        y_r = jnp.concatenate([r[:31], (r[31] & 0x7F)[None]], axis=0)
        y_a = jnp.concatenate([key[:31], (key[31] & 0x7F)[None]], axis=0)
        return batch_verify_impl(
            y_r, r[31] >> 7, y_a, key[31] >> 7, u, zk_digits, z_digits, host_ok
        )

    donate = (3, 5) if jax.default_backend() != "cpu" else ()
    return instrumented_jit(impl, name, donate_argnums=donate)


def _frame(raw: bytes) -> bytes:
    return len(raw).to_bytes(8, "little") + bytes(raw)


def fused_aggregate_check(
    *,
    name: str,
    tag: bytes,
    messages: Sequence[bytes],
    rs: Sequence[bytes],
    keys: Sequence[bytes],
    leaf_mids: Sequence[bytes],
    pad_to: int,
    pad_pow2: bool,
    s_rows: Optional[np.ndarray] = None,
    u_bytes: Optional[bytes] = None,
    fixed_z1: bool = False,
) -> tuple[bool, list[bool]]:
    """Run one fused aggregate check: returns ``(eq_ok, valid)``.

    ``leaf_mids`` is the middle frame of each transcript leaf — the full
    signature for the randomized batch (``ctpu/batchz/v1``), R alone for
    half-agg (``ctpu/halfagg/v1``).  Callers guarantee every lane already
    passed the canonical host pre-checks (transcript membership must match
    the host twin exactly).
    """
    n = len(messages)
    r_rows = _byte_rows([bytes(r) for r in rs], 32)
    key_rows = _byte_rows([bytes(a) for a in keys], 32)
    k_blocks, k_nblocks = _pack_blocks(
        [bytes(r) + bytes(a) + bytes(m) for r, a, m in zip(rs, keys, messages)]
    )
    leaf_blocks, leaf_nblocks = _pack_blocks(
        [
            _frame(m) + _frame(mid) + _frame(a)
            for m, mid, a in zip(messages, leaf_mids, keys)
        ]
    )
    if s_rows is None:
        s_rows = np.zeros((n, 32), dtype=np.uint8)
    host_ok = np.ones(n, dtype=bool)

    if pad_to >= n:
        padded = pad_to
    else:
        padded = _next_pow2(n) if pad_pow2 else n
    r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok = _pad_wave(
        [r_rows, s_rows, key_rows, k_nblocks, leaf_nblocks, host_ok], n, padded
    )
    if padded != n:
        batch_pad = ((0, 0),) * 3 + ((0, padded - n),)
        k_blocks = np.pad(k_blocks, batch_pad)
        leaf_blocks = np.pad(leaf_blocks, batch_pad)

    u_row = np.frombuffer(
        u_bytes if u_bytes is not None else b"\x00" * 32, dtype=np.uint8
    ).reshape(32, 1)

    kernel = _fused_aggregate_kernel(
        name, bytes(tag), n, padded, fixed_z1, u_bytes is not None
    )
    eq_ok, valid = kernel(
        jnp.asarray(np.ascontiguousarray(r_rows.T)),
        jnp.asarray(np.ascontiguousarray(s_rows.T)),
        jnp.asarray(np.ascontiguousarray(key_rows.T)),
        jnp.asarray(k_blocks),
        jnp.asarray(k_nblocks),
        jnp.asarray(leaf_blocks),
        jnp.asarray(leaf_nblocks),
        jnp.asarray(u_row),
        jnp.asarray(host_ok),
    )
    return bool(np.asarray(eq_ok)), list(np.asarray(valid)[:n])


class FusedEd25519RandomizedBatchVerifier(
    Ed25519RandomizedBatchVerifier, FusedEd25519BatchVerifier
):
    """Randomized batch verification with the transcript derived on device.

    Bit-identical verdicts to the host-prep
    :class:`~consensus_tpu.models.ed25519.Ed25519RandomizedBatchVerifier`:
    the device transcript hashes the same framed bytes, so coefficients,
    aggregate verdicts, and bisection paths coincide exactly.  Host
    challenge scalars are never computed on the device path — the
    ``hashlib`` loop only runs if a subset falls back to the host twin
    (``min_device_batch`` routing) or under the strict floor.
    """

    fused = True

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        results = np.zeros(n, dtype=bool)
        if n == 0:
            return results
        host_ok = canonical_ok_fast(signatures, public_keys)
        self._check(
            [i for i in range(n) if host_ok[i]],
            messages, signatures, public_keys, {}, results,
        )
        return results

    @staticmethod
    def _host_scalars(idx, messages, signatures, public_keys) -> dict:
        """Lazy (s, k) big-int scalars for the host-twin fallback only."""
        import hashlib

        scalars = {}
        for i in idx:
            sig = bytes(signatures[i])
            k = int.from_bytes(
                hashlib.sha512(
                    sig[:32] + bytes(public_keys[i]) + bytes(messages[i])
                ).digest(),
                "little",
            ) % L
            scalars[i] = (int.from_bytes(sig[32:], "little"), k)
        return scalars

    def _strict_floor(self, messages, signatures, public_keys) -> np.ndarray:
        """Strict verification under ``min_randomized`` — stays on the fused
        engine (the sharded subclass re-routes it onto the mesh)."""
        return FusedEd25519BatchVerifier.verify_batch(
            self, messages, signatures, public_keys
        )

    def _fused_aggregate(self, idx, messages, signatures, public_keys):
        """One fused aggregate check over the subset ``idx`` — the seam the
        sharded engine overrides with its mesh launch."""
        return fused_aggregate_check(
            name="ed25519.fused_batch_verify" + kernel_lane_suffix(),
            tag=_Z_TAG,
            messages=[messages[i] for i in idx],
            rs=[bytes(signatures[i])[:32] for i in idx],
            keys=[public_keys[i] for i in idx],
            leaf_mids=[signatures[i] for i in idx],
            s_rows=_byte_rows(
                [bytes(signatures[i])[32:] for i in idx], 32
            ),
            pad_to=self._pad_to,
            pad_pow2=self._pad_pow2,
        )

    def _check(self, idx, messages, signatures, public_keys, scalars, results):
        if not idx:
            return
        if len(idx) < self._min_randomized:
            sub = self._strict_floor(
                [messages[i] for i in idx],
                [signatures[i] for i in idx],
                [public_keys[i] for i in idx],
            )
            for j, i in enumerate(idx):
                results[i] = bool(sub[j])
            return
        if len(idx) >= self._min_device_batch:
            eq_ok, valid = self._fused_aggregate(
                idx, messages, signatures, public_keys
            )
        else:
            zs = _transcript_coefficients(
                [messages[i] for i in idx],
                [signatures[i] for i in idx],
                [public_keys[i] for i in idx],
            )
            eq_ok, valid = self._aggregate_host(
                idx, signatures, public_keys,
                self._host_scalars(idx, messages, signatures, public_keys), zs,
            )
        if not all(valid):
            survivors = [i for i, ok in zip(idx, valid) if ok]
            self._check(
                survivors, messages, signatures, public_keys, scalars, results
            )
            return
        if eq_ok:
            for i in idx:
                results[i] = True
            return
        mid = len(idx) // 2
        self._check(idx[:mid], messages, signatures, public_keys, scalars, results)
        self._check(idx[mid:], messages, signatures, public_keys, scalars, results)


__all__ = [
    "FusedEd25519BatchVerifier",
    "FusedEd25519RandomizedBatchVerifier",
    "canonical_ok_fast",
    "fused_aggregate_check",
    "fused_verify_impl",
]
