"""Deterministic binary codec for wire messages and WAL records.

Replaces the reference's protobuf layer (smartbftprotos/messages.pb.go) with a
compact hand-rolled tag + length-prefixed encoding.  Properties the protocol
relies on:

* **Deterministic** — the same message always encodes to the same bytes
  (protobuf does not guarantee this across implementations).  ViewData
  signatures and WAL CRC chains are computed over these bytes.
* **Self-delimiting** — every value knows its own length, so records can be
  concatenated (WAL) or nested (SignedViewData.raw_view_data).
* **Versioned** — one format-version byte leads every envelope so the codec
  can evolve.

Primitive layer: u8, u64 (big-endian), bool, bytes (u32 length prefix),
str (utf-8 bytes), and homogeneous sequences (u32 count prefix).
"""

from __future__ import annotations

import struct
from typing import Callable, Optional, Sequence

from consensus_tpu.types import Proposal, QuorumCert, Signature
from consensus_tpu.wire.messages import (
    Cert,
    Commit,
    ConsensusMessage,
    EpochTagged,
    HeartBeat,
    HeartBeatResponse,
    NewView,
    PrePrepare,
    Prepare,
    PreparesFrom,
    ProposedRecord,
    SavedCommit,
    SavedMessage,
    SavedNewView,
    SavedTwoPC,
    SavedViewChange,
    SignedViewData,
    StateTransferRequest,
    StateTransferResponse,
    SyncChunk,
    SyncRequest,
    SyncSnapshotMeta,
    TWOPC_PHASES,
    ViewChange,
    ViewData,
    ViewMetadata,
)

_VERSION = 1
# Wire v2: cert-carrying fields (PrePrepare.prev_commit_signatures,
# SyncChunk.quorum_certs, ViewData.last_decision_signatures) gain a
# cert-kind discriminator so a half-aggregated QuorumCert can ride where a
# signature tuple used to.  v2 is emitted ONLY when a QuorumCert is
# actually present (lowest-lossless-version rule, same as the WAL's
# ProposedRecord pattern), so cert_mode="full" traffic stays bit-for-bit
# the v1 seed encoding.
_WIRE_VERSION = 2

# Domain discriminators: the second envelope byte separates the wire-message
# and WAL-record encodings so bytes from one domain can never silently decode
# in the other (e.g. a misrouted buffer during crash recovery).
_DOMAIN_WIRE = 0x57  # 'W'
_DOMAIN_SAVED = 0x4C  # 'L'


class CodecError(ValueError):
    """Raised on malformed input bytes."""


class _Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> None:
        self._parts.append(struct.pack(">B", v))

    def u64(self, v: int) -> None:
        self._parts.append(struct.pack(">Q", v))

    def boolean(self, v: bool) -> None:
        self._parts.append(b"\x01" if v else b"\x00")

    def blob(self, v: bytes) -> None:
        self._parts.append(struct.pack(">I", len(v)))
        self._parts.append(v)

    def text(self, v: str) -> None:
        self.blob(v.encode("utf-8"))

    def seq(self, items: Sequence, write_item: Callable) -> None:
        self._parts.append(struct.pack(">I", len(items)))
        for item in items:
            write_item(item)

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class _Reader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise CodecError(
                f"truncated input: need {n} bytes at offset {self._pos}, "
                f"have {len(self._buf) - self._pos}"
            )
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def boolean(self) -> bool:
        b = self._take(1)[0]
        if b not in (0, 1):
            raise CodecError(f"invalid bool byte {b!r}")
        return b == 1

    def blob(self) -> bytes:
        n = struct.unpack(">I", self._take(4))[0]
        return self._take(n)

    def text(self) -> str:
        try:
            return self.blob().decode("utf-8")
        except UnicodeDecodeError as e:
            raise CodecError(f"invalid utf-8: {e}") from e

    def seq(self, read_item: Callable) -> tuple:
        n = struct.unpack(">I", self._take(4))[0]
        if n > len(self._buf):  # cheap sanity bound: each item is >= 1 byte
            raise CodecError(f"implausible sequence count {n}")
        return tuple(read_item() for _ in range(n))

    def expect_end(self) -> None:
        if self._pos != len(self._buf):
            raise CodecError(f"{len(self._buf) - self._pos} trailing bytes")


# --- shared value encoders -----------------------------------------------


def _w_proposal(w: _Writer, p: Proposal) -> None:
    w.blob(p.header)
    w.blob(p.payload)
    w.blob(p.metadata)
    w.u64(p.verification_sequence)


def _r_proposal(r: _Reader) -> Proposal:
    header = r.blob()
    payload = r.blob()
    metadata = r.blob()
    vseq = r.u64()
    return Proposal(
        header=header, payload=payload, metadata=metadata, verification_sequence=vseq
    )


def _w_opt_proposal(w: _Writer, p: Optional[Proposal]) -> None:
    w.boolean(p is not None)
    if p is not None:
        _w_proposal(w, p)


def _r_opt_proposal(r: _Reader) -> Optional[Proposal]:
    return _r_proposal(r) if r.boolean() else None


def _w_signature(w: _Writer, s: Signature) -> None:
    w.u64(s.id)
    w.blob(s.value)
    w.blob(s.msg)


def _r_signature(r: _Reader) -> Signature:
    sid = r.u64()
    value = r.blob()
    msg = r.blob()
    return Signature(id=sid, value=value, msg=msg)


def _w_quorum_cert_body(w: _Writer, c: QuorumCert) -> None:
    if not (len(c.signer_ids) == len(c.rs) == len(c.aux_index)):
        raise CodecError(
            f"QuorumCert parallel-field length mismatch: "
            f"{len(c.signer_ids)} ids, {len(c.rs)} rs, "
            f"{len(c.aux_index)} aux indices"
        )
    w.seq(c.signer_ids, w.u64)
    w.seq(c.rs, w.blob)
    w.blob(c.s_agg)
    w.seq(c.aux_table, w.blob)
    w.seq(c.aux_index, w.u64)


def _r_quorum_cert_body(r: _Reader) -> QuorumCert:
    signer_ids = r.seq(r.u64)
    rs = r.seq(r.blob)
    s_agg = r.blob()
    aux_table = r.seq(r.blob)
    aux_index = r.seq(r.u64)
    if not (len(signer_ids) == len(rs) == len(aux_index)):
        raise CodecError(
            f"QuorumCert parallel-field length mismatch: "
            f"{len(signer_ids)} ids, {len(rs)} rs, {len(aux_index)} aux indices"
        )
    for i in aux_index:
        if i >= len(aux_table):
            raise CodecError(
                f"QuorumCert aux_index {i} out of range "
                f"(aux_table has {len(aux_table)} entries)"
            )
    return QuorumCert(
        signer_ids=signer_ids,
        rs=rs,
        s_agg=s_agg,
        aux_table=aux_table,
        aux_index=aux_index,
    )


def _w_cert(w: _Writer, cert: Cert) -> None:
    """v2 cert field: a one-byte kind discriminator, then either the v1
    signature-tuple body (kind 0) or a QuorumCert body (kind 1)."""
    if isinstance(cert, QuorumCert):
        w.u8(1)
        _w_quorum_cert_body(w, cert)
    else:
        w.u8(0)
        w.seq(cert, lambda s: _w_signature(w, s))


def _r_cert(r: _Reader) -> Cert:
    kind = r.u8()
    if kind == 0:
        return r.seq(lambda: _r_signature(r))
    if kind == 1:
        return _r_quorum_cert_body(r)
    raise CodecError(f"unknown cert kind {kind}")


def encoded_cert_size(cert: Cert) -> int:
    """Encoded byte size of ONE cert field (kind byte included) — the unit
    the pinned ``*_cert_bytes_total`` counters account in, so wire/WAL/sync
    byte ratios compare cert payloads, not the unrelated message framing
    around them."""
    w = _Writer()
    _w_cert(w, cert)
    return len(w.getvalue())


def _w_view_metadata(w: _Writer, m: ViewMetadata) -> None:
    w.u64(m.view_id)
    w.u64(m.latest_sequence)
    w.u64(m.decisions_in_view)
    w.seq(m.black_list, w.u64)
    w.blob(m.prev_commit_signature_digest)


def _r_view_metadata(r: _Reader) -> ViewMetadata:
    view_id = r.u64()
    latest_sequence = r.u64()
    decisions_in_view = r.u64()
    black_list = r.seq(r.u64)
    digest = r.blob()
    return ViewMetadata(
        view_id=view_id,
        latest_sequence=latest_sequence,
        decisions_in_view=decisions_in_view,
        black_list=black_list,
        prev_commit_signature_digest=digest,
    )


# --- per-message bodies ---------------------------------------------------


def _w_pre_prepare(w: _Writer, m: PrePrepare, version: int = 1) -> None:
    w.u64(m.view)
    w.u64(m.seq)
    _w_proposal(w, m.proposal)
    if version >= 2:
        _w_cert(w, m.prev_commit_signatures)
    else:
        if isinstance(m.prev_commit_signatures, QuorumCert):
            raise CodecError("QuorumCert prev_commit_signatures need wire v2")
        w.seq(m.prev_commit_signatures, lambda s: _w_signature(w, s))


def _r_pre_prepare(r: _Reader, version: int = 1) -> PrePrepare:
    view = r.u64()
    seq = r.u64()
    proposal = _r_proposal(r)
    if version >= 2:
        prev_sigs = _r_cert(r)
    else:
        prev_sigs = r.seq(lambda: _r_signature(r))
    return PrePrepare(
        view=view, seq=seq, proposal=proposal, prev_commit_signatures=prev_sigs
    )


def _w_prepare(w: _Writer, m: Prepare) -> None:
    w.u64(m.view)
    w.u64(m.seq)
    w.text(m.digest)
    w.boolean(m.assist)


def _r_prepare(r: _Reader) -> Prepare:
    view = r.u64()
    seq = r.u64()
    digest = r.text()
    assist = r.boolean()
    return Prepare(view=view, seq=seq, digest=digest, assist=assist)


def _w_commit(w: _Writer, m: Commit) -> None:
    w.u64(m.view)
    w.u64(m.seq)
    w.text(m.digest)
    _w_signature(w, m.signature)
    w.boolean(m.assist)


def _r_commit(r: _Reader) -> Commit:
    view = r.u64()
    seq = r.u64()
    digest = r.text()
    sig = _r_signature(r)
    assist = r.boolean()
    return Commit(view=view, seq=seq, digest=digest, signature=sig, assist=assist)


def _w_view_change(w: _Writer, m: ViewChange) -> None:
    w.u64(m.next_view)
    w.text(m.reason)


def _r_view_change(r: _Reader) -> ViewChange:
    next_view = r.u64()
    reason = r.text()
    return ViewChange(next_view=next_view, reason=reason)


def _w_signed_view_data(w: _Writer, m: SignedViewData) -> None:
    w.blob(m.raw_view_data)
    w.u64(m.signer)
    w.blob(m.signature)


def _r_signed_view_data(r: _Reader) -> SignedViewData:
    raw = r.blob()
    signer = r.u64()
    sig = r.blob()
    return SignedViewData(raw_view_data=raw, signer=signer, signature=sig)


def _w_new_view(w: _Writer, m: NewView) -> None:
    w.seq(m.signed_view_data, lambda s: _w_signed_view_data(w, s))


def _r_new_view(r: _Reader) -> NewView:
    return NewView(signed_view_data=r.seq(lambda: _r_signed_view_data(r)))


def _w_heart_beat(w: _Writer, m: HeartBeat) -> None:
    w.u64(m.view)
    w.u64(m.seq)


def _r_heart_beat(r: _Reader) -> HeartBeat:
    view = r.u64()
    seq = r.u64()
    return HeartBeat(view=view, seq=seq)


def _w_heart_beat_response(w: _Writer, m: HeartBeatResponse) -> None:
    w.u64(m.view)


def _r_heart_beat_response(r: _Reader) -> HeartBeatResponse:
    return HeartBeatResponse(view=r.u64())


def _w_str(w: _Writer, m: StateTransferRequest) -> None:
    pass


def _r_str(r: _Reader) -> StateTransferRequest:
    return StateTransferRequest()


def _w_sts(w: _Writer, m: StateTransferResponse) -> None:
    w.u64(m.view_num)
    w.u64(m.sequence)


def _r_sts(r: _Reader) -> StateTransferResponse:
    view_num = r.u64()
    sequence = r.u64()
    return StateTransferResponse(view_num=view_num, sequence=sequence)


def _w_sync_request(w: _Writer, m: SyncRequest) -> None:
    w.u64(m.from_seq)
    w.u64(m.to_seq)


def _r_sync_request(r: _Reader) -> SyncRequest:
    from_seq = r.u64()
    to_seq = r.u64()
    return SyncRequest(from_seq=from_seq, to_seq=to_seq)


def _w_sync_chunk(w: _Writer, m: SyncChunk, version: int = 1) -> None:
    if len(m.decisions) != len(m.quorum_certs):
        raise CodecError(
            f"SyncChunk decisions/quorum_certs length mismatch: "
            f"{len(m.decisions)} != {len(m.quorum_certs)}"
        )
    w.u64(m.from_seq)
    w.u64(m.height)
    w.seq(m.decisions, lambda p: _w_proposal(w, p))
    if version >= 2:
        w.seq(m.quorum_certs, lambda cert: _w_cert(w, cert))
    else:
        if any(isinstance(c, QuorumCert) for c in m.quorum_certs):
            raise CodecError("QuorumCert endorsements need wire v2")
        w.seq(
            m.quorum_certs,
            lambda cert: w.seq(cert, lambda s: _w_signature(w, s)),
        )


def _r_sync_chunk(r: _Reader, version: int = 1) -> SyncChunk:
    from_seq = r.u64()
    height = r.u64()
    decisions = r.seq(lambda: _r_proposal(r))
    if version >= 2:
        certs = r.seq(lambda: _r_cert(r))
    else:
        certs = r.seq(lambda: r.seq(lambda: _r_signature(r)))
    if len(decisions) != len(certs):
        raise CodecError(
            f"SyncChunk decisions/quorum_certs length mismatch: "
            f"{len(decisions)} != {len(certs)}"
        )
    return SyncChunk(
        from_seq=from_seq, height=height, decisions=decisions, quorum_certs=certs
    )


def _w_sync_snapshot_meta(w: _Writer, m: SyncSnapshotMeta) -> None:
    w.u64(m.height)
    w.text(m.last_digest)


def _r_sync_snapshot_meta(r: _Reader) -> SyncSnapshotMeta:
    height = r.u64()
    last_digest = r.text()
    return SyncSnapshotMeta(height=height, last_digest=last_digest)


def _w_epoch_tagged(w: _Writer, m: EpochTagged) -> None:
    if isinstance(m.msg, EpochTagged):
        raise CodecError("EpochTagged must not nest another EpochTagged")
    w.u64(m.epoch)
    w.blob(encode_message(m.msg))


def _r_epoch_tagged(r: _Reader) -> EpochTagged:
    epoch = r.u64()
    inner = decode_message(r.blob())
    if isinstance(inner, EpochTagged):
        raise CodecError("EpochTagged must not nest another EpochTagged")
    return EpochTagged(epoch=epoch, msg=inner)


def _w_quorum_cert(w: _Writer, m: QuorumCert) -> None:
    _w_quorum_cert_body(w, m)


def _r_quorum_cert(r: _Reader) -> QuorumCert:
    return _r_quorum_cert_body(r)


# Tag assignments mirror the reference's oneof field numbers
# (smartbftprotos/messages.proto:15-26) for easy cross-auditing; tags 11-15
# are ours — the reference has no sync wire protocol (Fabric's block puller
# fills that role outside the library).
_MESSAGE_CODECS: dict[int, tuple[type, Callable, Callable]] = {
    1: (PrePrepare, _w_pre_prepare, _r_pre_prepare),
    2: (Prepare, _w_prepare, _r_prepare),
    3: (Commit, _w_commit, _r_commit),
    4: (ViewChange, _w_view_change, _r_view_change),
    5: (SignedViewData, _w_signed_view_data, _r_signed_view_data),
    6: (NewView, _w_new_view, _r_new_view),
    7: (HeartBeat, _w_heart_beat, _r_heart_beat),
    8: (HeartBeatResponse, _w_heart_beat_response, _r_heart_beat_response),
    9: (StateTransferRequest, _w_str, _r_str),
    10: (StateTransferResponse, _w_sts, _r_sts),
    11: (SyncRequest, _w_sync_request, _r_sync_request),
    12: (SyncChunk, _w_sync_chunk, _r_sync_chunk),
    13: (SyncSnapshotMeta, _w_sync_snapshot_meta, _r_sync_snapshot_meta),
    # 14 is ours: the membership-epoch envelope (no reference counterpart).
    14: (EpochTagged, _w_epoch_tagged, _r_epoch_tagged),
    # 15 is ours: a standalone half-aggregated quorum cert (models/aggregate).
    15: (QuorumCert, _w_quorum_cert, _r_quorum_cert),
}

_TAG_BY_TYPE = {cls: tag for tag, (cls, _, _) in _MESSAGE_CODECS.items()}

# Message kinds whose body layout depends on the envelope version (their
# writers/readers take an extra version argument).
_VERSIONED_WIRE_TYPES = (PrePrepare, SyncChunk)


def _wire_version_for(msg: ConsensusMessage) -> int:
    """Lowest wire version that expresses ``msg`` losslessly.

    Same rule as :func:`_saved_version_for`: v2 is emitted ONLY when a
    half-aggregated QuorumCert is actually present, so cert_mode="full"
    traffic stays bit-for-bit the v1 seed encoding (and remains decodable
    by pre-upgrade binaries).  An EpochTagged envelope stays v1 even when
    its inner message needs v2 — the inner blob is self-versioned.
    """
    if isinstance(msg, QuorumCert):
        return 2
    if isinstance(msg, PrePrepare) and isinstance(
        msg.prev_commit_signatures, QuorumCert
    ):
        return 2
    if isinstance(msg, SyncChunk) and any(
        isinstance(c, QuorumCert) for c in msg.quorum_certs
    ):
        return 2
    return 1


def encode_message(msg: ConsensusMessage) -> bytes:
    """Serialize a consensus message to self-delimiting bytes."""
    tag = _TAG_BY_TYPE.get(type(msg))
    if tag is None:
        raise CodecError(f"not a wire message: {type(msg).__name__}")
    version = _wire_version_for(msg)
    w = _Writer()
    w.u8(version)
    w.u8(_DOMAIN_WIRE)
    w.u8(tag)
    if isinstance(msg, _VERSIONED_WIRE_TYPES):
        _MESSAGE_CODECS[tag][1](w, msg, version)
    else:
        _MESSAGE_CODECS[tag][1](w, msg)
    return w.getvalue()


def decode_message(buf: bytes) -> ConsensusMessage:
    """Parse bytes produced by :func:`encode_message` (any accepted
    version — mixed-version clusters exchange v1 traffic until a
    QuorumCert actually rides a message)."""
    r = _Reader(buf)
    version = r.u8()
    if not 1 <= version <= _WIRE_VERSION:
        raise CodecError(f"unsupported codec version {version}")
    if r.u8() != _DOMAIN_WIRE:
        raise CodecError("not a wire-message encoding (wrong domain byte)")
    tag = r.u8()
    entry = _MESSAGE_CODECS.get(tag)
    if entry is None:
        raise CodecError(f"unknown message tag {tag}")
    if issubclass(entry[0], _VERSIONED_WIRE_TYPES):
        msg = entry[2](r, version)
    else:
        msg = entry[2](r)
    r.expect_end()
    return msg


# --- ViewData (signed payload, not a top-level wire message) --------------


def encode_view_data(vd: ViewData) -> bytes:
    """Serialize ViewData — these bytes are what gets signed and embedded in
    ``SignedViewData.raw_view_data`` (reference viewchanger.go:433-456).

    v2 (emitted only when the last-decision proof is a half-aggregated
    QuorumCert) carries the cert-kind discriminator; full-signature view
    data stays bit-for-bit v1.
    """
    version = (
        2 if isinstance(vd.last_decision_signatures, QuorumCert) else _VERSION
    )
    w = _Writer()
    w.u8(version)
    w.u64(vd.next_view)
    _w_opt_proposal(w, vd.last_decision)
    if version >= 2:
        _w_cert(w, vd.last_decision_signatures)
    else:
        w.seq(vd.last_decision_signatures, lambda s: _w_signature(w, s))
    _w_opt_proposal(w, vd.in_flight_proposal)
    w.boolean(vd.in_flight_prepared)
    return w.getvalue()


def decode_view_data(buf: bytes) -> ViewData:
    r = _Reader(buf)
    version = r.u8()
    if not 1 <= version <= _WIRE_VERSION:
        raise CodecError(f"unsupported codec version {version}")
    next_view = r.u64()
    last_decision = _r_opt_proposal(r)
    if version >= 2:
        last_sigs = _r_cert(r)
    else:
        last_sigs = r.seq(lambda: _r_signature(r))
    in_flight = _r_opt_proposal(r)
    prepared = r.boolean()
    r.expect_end()
    return ViewData(
        next_view=next_view,
        last_decision=last_decision,
        last_decision_signatures=last_sigs,
        in_flight_proposal=in_flight,
        in_flight_prepared=prepared,
    )


def encode_prepares_from(pf: PreparesFrom) -> bytes:
    """Serialize the prepare-sender vouch list (commit signature aux data)."""
    w = _Writer()
    w.u8(_VERSION)
    w.seq(pf.ids, w.u64)
    return w.getvalue()


def decode_prepares_from(buf: bytes) -> PreparesFrom:
    r = _Reader(buf)
    version = r.u8()
    if version != _VERSION:
        raise CodecError(f"unsupported codec version {version}")
    ids = r.seq(r.u64)
    r.expect_end()
    return PreparesFrom(ids=ids)


def encode_view_metadata(m: ViewMetadata) -> bytes:
    """Serialize ViewMetadata — stamped into ``Proposal.metadata``."""
    w = _Writer()
    w.u8(_VERSION)
    _w_view_metadata(w, m)
    return w.getvalue()


def decode_view_metadata(buf: bytes) -> ViewMetadata:
    r = _Reader(buf)
    version = r.u8()
    if version != _VERSION:
        raise CodecError(f"unsupported codec version {version}")
    m = _r_view_metadata(r)
    r.expect_end()
    return m


# --- SavedMessage (WAL records) ------------------------------------------


def _w_proposed_record(w: _Writer, m: ProposedRecord, version: int = 2) -> None:
    # Saved v3 records encode the nested PrePrepare at wire v2 so its
    # prev-commit cert field can hold a QuorumCert.
    _w_pre_prepare(w, m.pre_prepare, 2 if version >= 3 else 1)
    _w_prepare(w, m.prepare)
    if version >= 2:
        w.boolean(m.verified)


def _r_proposed_record(r: _Reader, version: int) -> ProposedRecord:
    pp = _r_pre_prepare(r, 2 if version >= 3 else 1)
    p = _r_prepare(r)
    # v1 records predate the flag; they were only ever written after
    # verification succeeded (the strict verify-then-persist order).
    verified = r.boolean() if version >= 2 else True
    return ProposedRecord(pre_prepare=pp, prepare=p, verified=verified)


def _w_saved_commit(w: _Writer, m: SavedCommit, version: int = 1) -> None:
    _w_commit(w, m.commit)
    if version >= 3:
        w.boolean(m.cert is not None)
        if m.cert is not None:
            _w_quorum_cert_body(w, m.cert)
    elif m.cert is not None:
        raise CodecError("SavedCommit.cert needs saved v3")


def _r_saved_commit(r: _Reader, version: int) -> SavedCommit:
    commit = _r_commit(r)
    cert = None
    if version >= 3 and r.boolean():
        cert = _r_quorum_cert_body(r)
    return SavedCommit(commit=commit, cert=cert)


def _w_saved_new_view(w: _Writer, m: SavedNewView) -> None:
    _w_view_metadata(w, m.view_metadata)


def _r_saved_new_view(r: _Reader, version: int) -> SavedNewView:
    return SavedNewView(view_metadata=_r_view_metadata(r))


def _w_saved_view_change(w: _Writer, m: SavedViewChange) -> None:
    _w_view_change(w, m.view_change)


def _r_saved_view_change(r: _Reader, version: int) -> SavedViewChange:
    return SavedViewChange(view_change=_r_view_change(r))


def _w_saved_twopc(w: _Writer, m: SavedTwoPC) -> None:
    if m.phase not in TWOPC_PHASES:
        raise CodecError(f"unknown 2PC phase {m.phase!r}")
    w.text(m.txid)
    w.u8(TWOPC_PHASES.index(m.phase))
    w.seq(m.groups, w.text)
    w.text(m.coordinator)


def _r_saved_twopc(r: _Reader, version: int) -> SavedTwoPC:
    txid = r.text()
    phase_idx = r.u8()
    if phase_idx >= len(TWOPC_PHASES):
        raise CodecError(f"unknown 2PC phase index {phase_idx}")
    groups = r.seq(r.text)
    coordinator = r.text()
    return SavedTwoPC(
        txid=txid, phase=TWOPC_PHASES[phase_idx],
        groups=tuple(groups), coordinator=coordinator,
    )


# Tags mirror the SavedMessage oneof (smartbftprotos/messages.proto:113-120).
# Readers take (reader, envelope_version) — the WAL-record domain is
# versioned independently of the wire messages so a record-layout change
# cannot invalidate inter-replica traffic (and vice versa).
# v2: ProposedRecord gained `verified` (v1 record => True).
# v3: half-aggregated quorum certs — SavedCommit gained an optional
#     QuorumCert and ProposedRecord's nested PrePrepare is encoded at wire
#     v2 so its prev-commit field can carry one.
# v4: cross-group sharding — SavedTwoPC (tag 5) persists a 2PC participant
#     transition; only SavedTwoPC records emit v4, so every WAL without
#     cross-group transactions stays bit-for-bit its pre-groups encoding.
_SAVED_VERSION = 4

_SAVED_CODECS: dict[int, tuple[type, Callable, Callable]] = {
    1: (ProposedRecord, _w_proposed_record, _r_proposed_record),
    2: (SavedCommit, _w_saved_commit, _r_saved_commit),
    3: (SavedNewView, _w_saved_new_view, _r_saved_new_view),
    4: (SavedViewChange, _w_saved_view_change, _r_saved_view_change),
    5: (SavedTwoPC, _w_saved_twopc, _r_saved_twopc),
}

_SAVED_TAG_BY_TYPE = {cls: tag for tag, (cls, _, _) in _SAVED_CODECS.items()}


def _saved_version_for(msg: SavedMessage) -> int:
    """Lowest record version that expresses ``msg`` losslessly.

    Records stay at v1 whenever possible (a ProposedRecord's ``verified``
    flag defaults to True under v1 semantics, and the other three kinds are
    unchanged since v1), so a binary ROLLBACK after an upgrade still finds
    a WAL it can decode — the crash-recovery pin must survive downgrades,
    not just upgrades.  Only the rare mid-verification crash window
    (``verified=False``) needs v2, and such a record is rewritten at the
    next truncation anyway.  Only cert_mode="half-agg" records actually
    carrying a QuorumCert need v3, so full-mode WALs stay bit-for-bit the
    seed encoding.
    """
    if isinstance(msg, ProposedRecord):
        if isinstance(msg.pre_prepare.prev_commit_signatures, QuorumCert):
            return 3
        if not msg.verified:
            return 2
        return 1
    if isinstance(msg, SavedCommit) and msg.cert is not None:
        return 3
    if isinstance(msg, SavedTwoPC):
        # The record kind itself is new in v4; there is no older encoding
        # that could express it.
        return 4
    return 1


def encode_saved(msg: SavedMessage) -> bytes:
    """Serialize a WAL record."""
    tag = _SAVED_TAG_BY_TYPE.get(type(msg))
    if tag is None:
        raise CodecError(f"not a saved message: {type(msg).__name__}")
    version = _saved_version_for(msg)
    w = _Writer()
    w.u8(version)
    w.u8(_DOMAIN_SAVED)
    w.u8(tag)
    if isinstance(msg, ProposedRecord):
        _w_proposed_record(w, msg, version)
    elif isinstance(msg, SavedCommit):
        _w_saved_commit(w, msg, version)
    else:
        _SAVED_CODECS[tag][1](w, msg)
    return w.getvalue()


def decode_saved(buf: bytes) -> SavedMessage:
    """Parse bytes produced by :func:`encode_saved` (any accepted version —
    a WAL written before an upgrade must keep restoring, or the crash-
    recovery pin it carries is silently lost)."""
    r = _Reader(buf)
    version = r.u8()
    if not 1 <= version <= _SAVED_VERSION:
        raise CodecError(f"unsupported codec version {version}")
    if r.u8() != _DOMAIN_SAVED:
        raise CodecError("not a WAL-record encoding (wrong domain byte)")
    tag = r.u8()
    entry = _SAVED_CODECS.get(tag)
    if entry is None:
        raise CodecError(f"unknown saved-message tag {tag}")
    msg = entry[2](r, version)
    r.expect_end()
    return msg


__all__ = [
    "CodecError",
    "encode_message",
    "decode_message",
    "encode_view_data",
    "decode_view_data",
    "encode_prepares_from",
    "decode_prepares_from",
    "encode_view_metadata",
    "decode_view_metadata",
    "encode_saved",
    "decode_saved",
    "encoded_cert_size",
]
