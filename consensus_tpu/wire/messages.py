"""Consensus wire messages and WAL record types.

Parity: reference smartbftprotos/messages.proto:14-128 — the ``Message`` oneof
with 10 consensus message kinds, the ``SavedMessage`` oneof with 4 persisted
record kinds, plus ``ViewMetadata`` and ``PreparesFrom``.

These are plain frozen dataclasses; serialization lives in
:mod:`consensus_tpu.wire.codec` (a deterministic binary TLV codec — byte
compatibility with the Go protobuf wire is a non-goal, shape compatibility
is).  Sender identity travels *outside* the message, exactly like the
reference's ``HandleMessage(sender, msg)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

from consensus_tpu.types import Proposal, QuorumCert, Signature

#: A commit-signature quorum as carried on the wire / in the WAL: either the
#: full tuple of per-signer signatures (cert_mode="full", the seed encoding)
#: or a half-aggregated QuorumCert (cert_mode="half-agg", codec v2).
Cert = Union[tuple[Signature, ...], QuorumCert]


@dataclass(frozen=True)
class ViewMetadata:
    """Leader-stamped proposal metadata binding a proposal to its place in the
    protocol and carrying the rotation blacklist.

    Parity: reference smartbftprotos/messages.proto:103-109.
    """

    view_id: int = 0
    latest_sequence: int = 0
    decisions_in_view: int = 0
    black_list: tuple[int, ...] = ()
    prev_commit_signature_digest: bytes = b""


@dataclass(frozen=True)
class PrePrepare:
    """Leader's phase-1 proposal broadcast.

    ``prev_commit_signatures`` carries the quorum that committed the previous
    proposal — followers verify them and the blacklist update they imply.
    Under ``cert_mode="half-agg"`` it is a :class:`QuorumCert` instead of a
    signature tuple (wire v2; verified in one aggregate check).
    Parity: reference smartbftprotos/messages.proto:29-34.
    """

    view: int
    seq: int
    proposal: Proposal
    prev_commit_signatures: Cert = ()


@dataclass(frozen=True)
class Prepare:
    """Phase-2 echo of the proposal digest.

    ``assist`` marks retransmission-help replies that must not be re-answered
    (reference smartbftprotos/messages.proto:40).
    Parity: reference smartbftprotos/messages.proto:36-41.
    """

    view: int
    seq: int
    digest: str
    assist: bool = False


@dataclass(frozen=True)
class Commit:
    """Phase-3 vote carrying the voter's signature over the proposal.

    Parity: reference smartbftprotos/messages.proto:48-54.
    """

    view: int
    seq: int
    digest: str
    signature: Signature
    assist: bool = False


@dataclass(frozen=True)
class PreparesFrom:
    """The prepare-sender id list a consenter vouches for inside its commit
    signature's auxiliary payload (blacklist redemption voting).

    Parity: reference smartbftprotos/messages.proto:56-58.
    """

    ids: tuple[int, ...] = ()


@dataclass(frozen=True)
class ViewChange:
    """Vote to abandon the current view.

    Parity: reference smartbftprotos/messages.proto:60-63.
    """

    next_view: int
    reason: str = ""


@dataclass(frozen=True)
class ViewData:
    """A replica's signed account of its state, sent to the next leader.

    Parity: reference smartbftprotos/messages.proto:65-71.
    """

    next_view: int
    last_decision: Optional[Proposal] = None
    last_decision_signatures: Cert = ()
    in_flight_proposal: Optional[Proposal] = None
    in_flight_prepared: bool = False


@dataclass(frozen=True)
class SignedViewData:
    """ViewData as signed raw bytes + the signer's identity.

    Parity: reference smartbftprotos/messages.proto:73-77.
    """

    raw_view_data: bytes
    signer: int
    signature: bytes


@dataclass(frozen=True)
class NewView:
    """New leader's proof: a quorum of SignedViewData.

    Parity: reference smartbftprotos/messages.proto:79-81.
    """

    signed_view_data: tuple[SignedViewData, ...] = ()


@dataclass(frozen=True)
class HeartBeat:
    """Leader liveness beacon carrying its current (view, seq).

    Parity: reference smartbftprotos/messages.proto:83-86.
    """

    view: int
    seq: int = 0


@dataclass(frozen=True)
class HeartBeatResponse:
    """Follower's answer to a stale-view heartbeat (tells the leader the
    cluster moved on).  Parity: reference smartbftprotos/messages.proto:88-90.
    """

    view: int


@dataclass(frozen=True)
class StateTransferRequest:
    """Ask peers for their current (view, seq).

    Parity: reference smartbftprotos/messages.proto:122-123.
    """


@dataclass(frozen=True)
class StateTransferResponse:
    """Answer to a state-transfer request.

    Parity: reference smartbftprotos/messages.proto:126-128.
    """

    view_num: int
    sequence: int


# --- sync (catch-up) messages ---------------------------------------------
# The reference delegates state transfer entirely to the application (Fabric's
# block puller speaks the Deliver API on its own connections).  These three
# messages are our equivalent of that side protocol: they travel on the sync
# channel (consensus_tpu/sync/transport.py), never on the consensus Comm.


@dataclass(frozen=True)
class SyncRequest:
    """Ask a peer for decided proposals in the position range
    ``[from_seq, to_seq]`` (1-based, inclusive).  ``to_seq == 0`` is a
    metadata probe: the server answers :class:`SyncSnapshotMeta` only.
    """

    from_seq: int
    to_seq: int = 0


@dataclass(frozen=True)
class SyncChunk:
    """A server's bounded answer to a ranged :class:`SyncRequest`.

    ``decisions[i]`` is the proposal at position ``from_seq + i`` and
    ``quorum_certs[i]`` its commit-signature quorum — kept as parallel
    sequences so a client can drain every cert in the chunk into one
    batched verifier call.  ``height`` is the server's chain height at
    reply time (flow control: the client learns how far behind it still
    is without a second probe).
    """

    from_seq: int
    height: int
    decisions: tuple[Proposal, ...] = ()
    quorum_certs: tuple[Cert, ...] = ()


@dataclass(frozen=True)
class SyncSnapshotMeta:
    """A server's chain snapshot metadata: height and the digest of the
    decision at the tip (empty when the chain is empty)."""

    height: int
    last_digest: str = ""


@dataclass(frozen=True)
class EpochTagged:
    """Membership-epoch envelope around any other wire message.

    When ``Configuration.epoch_tagging`` is on, every outbound consensus
    message is wrapped with the sender's current membership epoch and the
    receiving facade drops traffic from other epochs at ingress — counted
    and traced, never fed to the collectors.  Exactly one level of wrapping
    is legal (the codec rejects a nested ``EpochTagged``).

    No reference counterpart: the reference leaves membership bookkeeping to
    the application and has no epoch discriminator on the wire.
    """

    epoch: int
    msg: "ConsensusMessage"


#: The "Message oneof": anything a replica may put on the wire.
#: QuorumCert (types.py) is a member too: a half-aggregated cert travels
#: standalone under codec tag 15 as well as embedded in PrePrepare /
#: SyncChunk / ViewData / SavedCommit cert fields.
ConsensusMessage = Union[
    QuorumCert,
    PrePrepare,
    Prepare,
    Commit,
    ViewChange,
    SignedViewData,
    NewView,
    HeartBeat,
    HeartBeatResponse,
    StateTransferRequest,
    StateTransferResponse,
    SyncRequest,
    SyncChunk,
    SyncSnapshotMeta,
    EpochTagged,
]


# --- WAL record kinds ----------------------------------------------------


@dataclass(frozen=True)
class ProposedRecord:
    """WAL record: a proposal was accepted and a prepare is about to be sent.

    ``verified`` records whether proposal verification had already succeeded
    when the record was written.  Followers verify before persisting, so
    their records say True; the leader persists (and reveals) its own
    proposal BEFORE verifying it (reveal-before-verify,
    core/view.py::_try_process_proposal), so its record says False until
    verification completes — and any restore from a False record must
    re-verify before re-arming the prepare endorsement.

    Parity: reference smartbftprotos/messages.proto:43-46 (the flag is an
    addition; the reference has no pre-verification persistence).
    """

    pre_prepare: PrePrepare
    prepare: Prepare
    verified: bool = True


@dataclass(frozen=True)
class SavedCommit:
    """WAL record: a prepared quorum was reached and a commit is about to be
    sent.  Wraps the commit message itself (the reference stores the whole
    ``Message``; we store the ``Commit`` directly).

    Parity: reference smartbftprotos/messages.proto:113-116 (``commit`` arm).

    ``cert`` (half-agg mode only, WAL v3) persists the assembled
    :class:`QuorumCert` for the decided sequence so a restart can re-serve
    the compact cert to sync clients and view changes without re-aggregating.
    """

    commit: Commit
    cert: Optional[QuorumCert] = None


@dataclass(frozen=True)
class SavedNewView:
    """WAL record: a new view was finalized; stores the restore point.

    Parity: reference smartbftprotos/messages.proto:117 (``new_view`` arm —
    a ViewMetadata).
    """

    view_metadata: ViewMetadata


@dataclass(frozen=True)
class SavedViewChange:
    """WAL record: we voted to leave a view.

    Parity: reference smartbftprotos/messages.proto:118 (``view_change`` arm).
    """

    view_change: ViewChange


#: The observable phases of a cross-group 2PC participant, in order.
TWOPC_PHASES = ("prepared", "committed", "aborted")


@dataclass(frozen=True)
class SavedTwoPC:
    """WAL record (saved v4): one cross-group 2PC participant transition.

    consensus_tpu addition (no reference counterpart): each consensus group
    participating in a cross-group atomic transaction persists its
    participant state machine — prepared, then committed OR aborted — so a
    restarted participant resumes knowing exactly which transactions it has
    promised and which it has resolved.  ``groups`` names every participant
    (the atomicity invariant's scope) and ``coordinator`` the group whose
    coordinator drives the decision.
    """

    txid: str
    phase: str  # one of TWOPC_PHASES
    groups: tuple = ()
    coordinator: str = ""


#: The "SavedMessage oneof": anything persisted to the WAL.
SavedMessage = Union[
    ProposedRecord, SavedCommit, SavedNewView, SavedViewChange, SavedTwoPC
]


def msg_to_string(msg: ConsensusMessage) -> str:
    """Compact human-readable rendering for logs.

    Parity: reference internal/bft/util.go:345-420 (MsgToString).
    """
    if isinstance(msg, PrePrepare):
        return (
            f"<PrePrepare view={msg.view} seq={msg.seq} "
            f"digest={msg.proposal.digest()[:8]}>"
        )
    if isinstance(msg, Prepare):
        return f"<Prepare view={msg.view} seq={msg.seq} digest={msg.digest[:8]} assist={msg.assist}>"
    if isinstance(msg, Commit):
        return (
            f"<Commit view={msg.view} seq={msg.seq} digest={msg.digest[:8]} "
            f"signer={msg.signature.id} assist={msg.assist}>"
        )
    if isinstance(msg, ViewChange):
        return f"<ViewChange next_view={msg.next_view} reason={msg.reason!r}>"
    if isinstance(msg, SignedViewData):
        return f"<SignedViewData signer={msg.signer}>"
    if isinstance(msg, NewView):
        return f"<NewView n={len(msg.signed_view_data)}>"
    if isinstance(msg, HeartBeat):
        return f"<HeartBeat view={msg.view} seq={msg.seq}>"
    if isinstance(msg, HeartBeatResponse):
        return f"<HeartBeatResponse view={msg.view}>"
    if isinstance(msg, StateTransferRequest):
        return "<StateTransferRequest>"
    if isinstance(msg, StateTransferResponse):
        return f"<StateTransferResponse view={msg.view_num} seq={msg.sequence}>"
    if isinstance(msg, SyncRequest):
        return f"<SyncRequest from={msg.from_seq} to={msg.to_seq}>"
    if isinstance(msg, SyncChunk):
        return (
            f"<SyncChunk from={msg.from_seq} n={len(msg.decisions)} "
            f"height={msg.height}>"
        )
    if isinstance(msg, SyncSnapshotMeta):
        return f"<SyncSnapshotMeta height={msg.height} tip={msg.last_digest[:8]}>"
    if isinstance(msg, EpochTagged):
        return f"<EpochTagged epoch={msg.epoch} msg={msg_to_string(msg.msg)}>"
    if isinstance(msg, QuorumCert):
        return f"<QuorumCert n={len(msg)} signers={list(msg.signer_ids)}>"
    return repr(msg)


__all__ = [
    "Cert",
    "QuorumCert",
    "ViewMetadata",
    "PrePrepare",
    "Prepare",
    "Commit",
    "PreparesFrom",
    "ViewChange",
    "ViewData",
    "SignedViewData",
    "NewView",
    "HeartBeat",
    "HeartBeatResponse",
    "StateTransferRequest",
    "StateTransferResponse",
    "SyncRequest",
    "SyncChunk",
    "SyncSnapshotMeta",
    "EpochTagged",
    "ConsensusMessage",
    "ProposedRecord",
    "SavedCommit",
    "SavedNewView",
    "SavedViewChange",
    "SavedTwoPC",
    "TWOPC_PHASES",
    "SavedMessage",
    "msg_to_string",
]
