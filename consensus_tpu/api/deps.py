"""The ports (dependency interfaces) of the consensus core.

Parity: reference pkg/api/dependencies.go:14-99 — Application, Comm,
Assembler, WriteAheadLog, Signer, Verifier, MembershipNotifier,
RequestInspector, Synchronizer (Logger is Python ``logging`` here).

TPU-first deviation: ``Verifier`` exposes *batch* verification entry points
(``verify_requests_batch``, ``verify_consenter_sigs_batch``) with looping
defaults.  The protocol core always calls the batch forms — a TPU-backed
verifier overrides them to drain whole quorums / request batches into one
vmap'd kernel launch (the reference instead spawns one goroutine per commit
signature, internal/bft/view.go:537-541).
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence

from consensus_tpu.types import (
    Decision,
    Proposal,
    QuorumCert,
    Reconfig,
    RequestInfo,
    Signature,
    SyncResponse,
)


class Application(abc.ABC):
    """The replicated state machine being driven by consensus.

    Parity: reference pkg/api/dependencies.go:14-19.
    """

    @abc.abstractmethod
    def deliver(self, proposal: Proposal, signatures: Sequence[Signature]) -> Reconfig:
        """Commit a decided proposal; returns membership/config changes."""


class Comm(abc.ABC):
    """Unreliable, unordered, fire-and-forget message transport.

    The protocol tolerates loss; delivery guarantees are *not* part of the
    contract.  Parity: reference pkg/api/dependencies.go:22-30.
    """

    @abc.abstractmethod
    def send_consensus(self, target_id: int, message) -> None: ...

    @abc.abstractmethod
    def send_transaction(self, target_id: int, request: bytes) -> None: ...

    @abc.abstractmethod
    def nodes(self) -> Sequence[int]: ...


class Assembler(abc.ABC):
    """Builds application proposals out of request batches.

    Parity: reference pkg/api/dependencies.go:33-37.
    """

    @abc.abstractmethod
    def assemble_proposal(self, metadata: bytes, requests: Sequence[bytes]) -> Proposal: ...


class WriteAheadLog(abc.ABC):
    """Persistence for protocol step records (crash recovery).

    ``on_durable`` (when given) must fire once the entry is on stable
    storage; implementations that fsync synchronously call it before
    returning, group-commit implementations defer it to the batched fsync.
    Parity: reference pkg/api/dependencies.go:40-44 (callback is ours — the
    seam that lets the protocol defer sends under group commit).
    """

    @abc.abstractmethod
    def append(
        self, entry: bytes, truncate_to: bool = False, on_durable=None
    ) -> None: ...


class Signer(abc.ABC):
    """This replica's signing identity.

    Parity: reference pkg/api/dependencies.go:47-52.
    """

    @abc.abstractmethod
    def sign(self, data: bytes) -> bytes: ...

    @abc.abstractmethod
    def sign_proposal(self, proposal: Proposal, aux: bytes = b"") -> Signature: ...

    def aggregate_cert(
        self, proposal: Proposal, signatures: Sequence[Signature]
    ) -> Optional[QuorumCert]:
        """Optionally compress a full commit-signature quorum into a
        half-aggregated :class:`~consensus_tpu.types.QuorumCert`
        (cert_mode="half-agg").  Default returns None — aggregation
        unsupported, the core keeps the full signature tuple, so
        third-party signers are unaffected."""
        return None


class Verifier(abc.ABC):
    """Validation of requests, proposals, and signatures.

    Parity: reference pkg/api/dependencies.go:55-71 (7 methods), plus the
    batch entry points the TPU engine accelerates.
    """

    @abc.abstractmethod
    def verify_proposal(self, proposal: Proposal) -> Sequence[RequestInfo]:
        """Fully verify a proposal (including its requests); returns their
        infos, or raises on failure."""

    @abc.abstractmethod
    def verify_request(self, raw_request: bytes) -> RequestInfo:
        """Verify a single client request; returns its info or raises."""

    @abc.abstractmethod
    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        """Verify a consenter's signature over a proposal; returns the
        auxiliary payload it vouches for (see blacklist redemption), or
        raises."""

    @abc.abstractmethod
    def verify_signature(self, signature: Signature) -> None:
        """Verify a raw signature (view-change data); raises on failure."""

    @abc.abstractmethod
    def verification_sequence(self) -> int:
        """The current membership/config epoch requests are verified under."""

    @abc.abstractmethod
    def requests_from_proposal(self, proposal: Proposal) -> Sequence[RequestInfo]:
        """Cheaply list the request infos inside a proposal (no verification)."""

    def auxiliary_data(self, msg: bytes) -> bytes:
        """Extract auxiliary data out of a signed message payload."""
        return b""

    def raw_requests_from_proposal(self, proposal: Proposal) -> Sequence[bytes]:
        """The raw request bytes inside a proposal, for re-admission to the
        request pool when a pipelined slot is abandoned during crash restore
        (the slot's requests live nowhere else after a reboot).  Default
        returns nothing — re-admission is then skipped and the requests are
        re-submitted by their clients, which is always correct (the pool
        dedups and delivery removal forgets decided identities)."""
        return ()

    # --- batch entry points (TPU acceleration seam) ---------------------

    #: True when this verifier is backed by a randomized batch-verification
    #: engine (Configuration.batch_verify_mode) — one aggregate check per
    #: batch amortizes the doubling chain, so the multi-batch default below
    #: coalesces every group into a single launch instead of looping.
    batch_verify_enabled: bool = False

    #: Facades that delegate signature checks to an inner crypto verifier
    #: (e.g. testing.crypto_app.CryptoApp) set this to that inner verifier
    #: so the coalesced multi-batch path reaches the engine in ONE call —
    #: without it the default loop would split a sync chunk's quorum certs
    #: into per-group launches and re-pay the doubling chain per group.
    multi_batch_delegate: Optional["Verifier"] = None

    #: True when this verifier can assemble AND check half-aggregated
    #: quorum certs (Configuration.cert_mode="half-agg").  Third-party
    #: verifiers keep the False default: the core then never aggregates
    #: and full signature tuples flow exactly as before.
    supports_cert_aggregation: bool = False

    def aggregate_cert(
        self, proposal: Proposal, signatures: Sequence[Signature]
    ) -> Optional[QuorumCert]:
        """Compress a verified commit-signature quorum over ``proposal``
        into a half-aggregated cert, or return None when aggregation is
        unsupported/fails (the caller keeps the full tuple — graceful
        fallback, never an error)."""
        return None

    def verify_aggregate_cert(
        self, cert: QuorumCert, proposal: Proposal
    ) -> Optional[list[bytes]]:
        """Verify a half-aggregated quorum cert over ``proposal`` in one
        aggregate check; returns the per-component auxiliary payloads on
        success, or None when the cert is invalid or this verifier cannot
        check aggregates (default — a full-mode replica REJECTS compact
        certs rather than crashing on them)."""
        return None

    def verify_requests_batch(self, raw_requests: Sequence[bytes]) -> list[Optional[RequestInfo]]:
        """Verify many requests; element is None where verification failed.

        Default loops over ``verify_request``; TPU verifiers override.
        """
        out: list[Optional[RequestInfo]] = []
        for raw in raw_requests:
            try:
                out.append(self.verify_request(raw))
            except Exception:
                out.append(None)
        return out

    def verify_consenter_sigs_batch(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list[Optional[bytes]]:
        """Verify many consenter signatures over one proposal; element is the
        auxiliary payload, or None where verification failed.

        Default loops over ``verify_consenter_sig``; TPU verifiers override.
        A half-aggregated :class:`QuorumCert` routes through
        ``verify_aggregate_cert`` instead — all-or-nothing, so a failed
        aggregate rejects every component (the engine's bisection, where
        available, localizes the culprit before results reach here).
        """
        if isinstance(signatures, QuorumCert):
            aux = self.verify_aggregate_cert(signatures, proposal)
            if aux is None:
                return [None] * len(signatures)
            return list(aux)
        out: list[Optional[bytes]] = []
        for sig in signatures:
            try:
                out.append(self.verify_consenter_sig(sig, proposal))
            except Exception:
                out.append(None)
        return out

    def verify_consenter_sigs_multi_batch(
        self, groups: Sequence[tuple[Proposal, Sequence[Signature]]]
    ) -> list[list[Optional[bytes]]]:
        """Verify consenter-signature quorums over MANY proposals at once —
        the sync client drains a whole catch-up chunk (dozens of decisions,
        each with a quorum cert) through this single entry point.

        Default loops over ``verify_consenter_sigs_batch``; TPU verifiers
        override to flatten every (proposal, signature) pair into one
        device batch.  When the randomized batch verifier is enabled
        (``batch_verify_enabled``) and a ``multi_batch_delegate`` is wired,
        the default instead forwards the whole group list to the delegate's
        coalescing implementation — one launch for all groups, with the
        engine's bisection localizing any failing group on its own.

        Groups must be cert-mode homogeneous: mixing half-aggregated
        QuorumCerts with full signature tuples in one call raises
        ValueError (contradiction guard, mirroring the batch_verify_mode
        all-replicas-agree rule) — a mixed chunk means the peers disagree
        on cert_mode and silently splitting it would mask that.  Callers
        spanning a cert_mode flip (sync catch-up across a membership epoch
        boundary) partition into homogeneous calls first.
        """
        if groups:
            kinds = {isinstance(sigs, QuorumCert) for _, sigs in groups}
            if len(kinds) > 1:
                raise ValueError(
                    "verify_consenter_sigs_multi_batch: groups mix "
                    "half-aggregated QuorumCerts with full signature tuples "
                    "— cert modes contradict; partition the groups first"
                )
        delegate = self.multi_batch_delegate
        if self.batch_verify_enabled and delegate is not None:
            return delegate.verify_consenter_sigs_multi_batch(groups)
        return [
            self.verify_consenter_sigs_batch(sigs, proposal)
            for proposal, sigs in groups
        ]

    def verify_proposal_and_prev_commits(
        self,
        proposal: Proposal,
        prev_commits: Sequence[Signature],
        prev_proposal: Proposal,
    ) -> tuple[Sequence[RequestInfo], list[Optional[bytes]]]:
        """Verify a proposal AND the previous decision's commit-signature
        quorum it carries — the two signature waves of one pre-prepare.

        Default runs them as two calls (exactly the split the core did
        before this entry point existed).  Verifiers whose request
        signatures and consenter certs share one engine override this to
        fuse both waves into a single launch; any request failure must
        still raise exactly as ``verify_proposal`` would, BEFORE cert
        results are consumed.
        """
        requests = self.verify_proposal(proposal)
        if not prev_commits:
            return requests, []
        cert_results = self.verify_consenter_sigs_batch(prev_commits, prev_proposal)
        return requests, cert_results


# Convenience alias for implementations that only provide the batch forms.
BatchVerifier = Verifier


class MembershipNotifier(abc.ABC):
    """Notified when a decision changed cluster membership.

    Parity: reference pkg/api/dependencies.go:74-77.
    """

    @abc.abstractmethod
    def membership_change(self) -> None: ...


class RequestInspector(abc.ABC):
    """Extracts (client, request) identity from raw request bytes.

    Parity: reference pkg/api/dependencies.go:80-83.
    """

    @abc.abstractmethod
    def request_id(self, raw_request: bytes) -> RequestInfo: ...


class Synchronizer(abc.ABC):
    """Application-level catch-up: fetch and deliver decided proposals from
    peers, returning the latest decision reached.

    Parity: reference pkg/api/dependencies.go:86-90.
    """

    @abc.abstractmethod
    def sync(self) -> SyncResponse: ...


class TracerPort(abc.ABC):
    """Decision-lifecycle tracing sink (no reference counterpart).

    Implemented by ``trace.Tracer`` and ``trace.NoopTracer``.  Call sites
    MUST guard emission with ``if tracer.enabled:`` so the disabled hot
    path stays allocation-free; ``seq``/``view`` key per-decision spans.
    """

    #: False on the no-op tracer; the emission guard reads this.
    enabled: bool = False

    @abc.abstractmethod
    def begin(self, track: str, name: str, *, seq=None, view=None, **args) -> None: ...

    @abc.abstractmethod
    def end(self, track: str, name: str, *, seq=None, view=None, **args) -> None: ...

    @abc.abstractmethod
    def instant(self, track: str, name: str, *, seq=None, view=None, **args) -> None: ...


__all__ = [
    "Application",
    "Comm",
    "Assembler",
    "WriteAheadLog",
    "Signer",
    "Verifier",
    "BatchVerifier",
    "MembershipNotifier",
    "RequestInspector",
    "Synchronizer",
    "TracerPort",
    "Decision",
]
