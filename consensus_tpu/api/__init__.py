"""Dependency-injection ports: the interfaces an embedding application
implements to wire the consensus core to its transport, storage, crypto, and
ledger.
"""

from consensus_tpu.api.deps import (  # noqa: F401
    Application,
    Assembler,
    BatchVerifier,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    TracerPort,
    Verifier,
    WriteAheadLog,
)
