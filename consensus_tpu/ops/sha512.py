"""Vectorized SHA-512 over lanes of padded blocks (FIPS 180-4).

The missing piece of the bytes-in → verdict-out pipeline: the Ed25519
challenge ``k = SHA-512(R ‖ A ‖ M) mod L`` and the Fiat–Shamir transcript
hashes all ran through ``hashlib`` on the host, serializing a Python loop
in front of every MSM launch.  This module hashes a whole wave per launch:
the batch rides the trailing axis (the vector lanes), the 80-round
compression runs as one ``lax.scan`` body, and multi-block messages scan
over a leading block axis with a per-lane active-block count so one fixed
shape serves every message length up to the padded maximum.

SHA-512 is 64-bit word arithmetic and the deployment runs without x64, so
a word is a ``(hi, lo)`` pair of uint32 lanes: adds propagate one carry
(``lo' < lo`` detects uint32 wraparound), rotates are static cross-half
shift pairs.  Bit-exact against ``hashlib.sha512`` including every padding
edge case (tests/test_sha512.py).

Layouts:

* host packing: :func:`pad_messages` → ``(blocks, n_blocks)`` with
  ``blocks`` uint32 of shape ``(B, 16, 2, batch)`` (block, word, hi/lo,
  lane) and ``n_blocks`` int32 ``(batch,)``.
* device: :func:`sha512_blocks` → state ``(8, 2, batch)`` uint32;
  :func:`digest_bytes` → ``(64, batch)`` int32 digest bytes in stream
  order (byte 0 first — little-endian weight ``2^(8i)`` for the scalar
  stack); :func:`pack_bytes_device` turns device-resident padded byte
  rows back into block layout (transcript hashing composes hashes of
  hashes without a host round-trip).
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

BLOCK_BYTES = 128

# --- constants (FIPS 180-4 §4.2.3 / §5.3.5) --------------------------------
# Derived, not transcribed: IV words are the fractional parts of sqrt(p) and
# the round constants of cbrt(p) over the first 8 / 80 primes, computed with
# exact integer roots — a typo here cannot survive the hashlib parity suite.


def _primes(count: int) -> list[int]:
    out: list[int] = []
    candidate = 2
    while len(out) < count:
        if all(candidate % p for p in out):
            out.append(candidate)
        candidate += 1
    return out


def _icbrt(n: int) -> int:
    x = 1 << ((n.bit_length() + 2) // 3)
    while True:
        y = (2 * x + n // (x * x)) // 3
        if y >= x:
            break
        x = y
    return x


_MASK64 = (1 << 64) - 1
_IV_INT = [math.isqrt(p << 128) & _MASK64 for p in _primes(8)]
_K_INT = [_icbrt(p << 192) & _MASK64 for p in _primes(80)]


def _split_words(values: Sequence[int]) -> np.ndarray:
    """64-bit ints -> (n, 2) uint32 rows of (hi, lo) halves."""
    return np.array(
        [[v >> 32, v & 0xFFFFFFFF] for v in values], dtype=np.uint32
    )


_IV = _split_words(_IV_INT)      # (8, 2)
_K = _split_words(_K_INT)        # (80, 2)


# --- 64-bit ops on (hi, lo) uint32 pairs -----------------------------------


def _add64(a, b):
    lo = a[1] + b[1]
    carry = (lo < b[1]).astype(jnp.uint32)
    return a[0] + b[0] + carry, lo


def _ror64(x, r: int):
    hi, lo = x
    if r >= 32:
        hi, lo = lo, hi
        r -= 32
    if r == 0:
        return hi, lo
    t = 32 - r
    return (hi >> r) | (lo << t), (lo >> r) | (hi << t)


def _shr64(x, r: int):
    hi, lo = x
    if r >= 32:
        return jnp.zeros_like(hi), hi >> (r - 32)
    return hi >> r, (lo >> r) | (hi << (32 - r))


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _big_sigma0(a):
    return _xor64(_xor64(_ror64(a, 28), _ror64(a, 34)), _ror64(a, 39))


def _big_sigma1(e):
    return _xor64(_xor64(_ror64(e, 14), _ror64(e, 18)), _ror64(e, 41))


def _small_sigma0(x):
    return _xor64(_xor64(_ror64(x, 1), _ror64(x, 8)), _shr64(x, 7))


def _small_sigma1(x):
    return _xor64(_xor64(_ror64(x, 19), _ror64(x, 61)), _shr64(x, 6))


def _ch(e, f, g):
    return (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])


def _maj(a, b, c):
    return (
        (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
        (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
    )


def _pair(stacked: jnp.ndarray):
    return stacked[0], stacked[1]


def _compress_block(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-512 compression: state (8, 2, batch) + block (16, 2, batch).

    The 80 rounds run as a single scanned body carrying the working
    variables and a rolling 16-word schedule window — the on-the-fly
    schedule (W[t+16] from the window) keeps the carry at 16 words instead
    of materializing all 80.
    """

    def round_step(carry, k):
        vars8, w = carry
        a, b, c, d = _pair(vars8[0]), _pair(vars8[1]), _pair(vars8[2]), _pair(vars8[3])
        e, f, g, h = _pair(vars8[4]), _pair(vars8[5]), _pair(vars8[6]), _pair(vars8[7])
        wt = _pair(w[0])
        k_pair = (k[0], k[1])
        t1 = _add64(
            _add64(h, _big_sigma1(e)),
            _add64(_ch(e, f, g), _add64(k_pair, wt)),
        )
        t2 = _add64(_big_sigma0(a), _maj(a, b, c))
        new_e = _add64(d, t1)
        new_a = _add64(t1, t2)
        nxt = _add64(
            _add64(_small_sigma1(_pair(w[14])), _pair(w[9])),
            _add64(_small_sigma0(_pair(w[1])), _pair(w[0])),
        )
        vars8 = jnp.stack(
            [
                jnp.stack(new_a), jnp.stack(a), jnp.stack(b), jnp.stack(c),
                jnp.stack(new_e), jnp.stack(e), jnp.stack(f), jnp.stack(g),
            ]
        )
        w = jnp.concatenate([w[1:], jnp.stack(nxt)[None]], axis=0)
        return (vars8, w), None

    (vars8, _), _ = jax.lax.scan(
        round_step, (state, block), jnp.asarray(_K, dtype=jnp.uint32)
    )
    lo = state[:, 1] + vars8[:, 1]
    carry = (lo < state[:, 1]).astype(jnp.uint32)
    hi = state[:, 0] + vars8[:, 0] + carry
    return jnp.stack([hi, lo], axis=1)


def sha512_blocks(blocks: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 state for a batch of pre-padded messages.

    ``blocks``: uint32 ``(B, 16, 2, batch)``; ``n_blocks``: int32
    ``(batch,)`` active blocks per lane.  Lanes whose message ends before
    block ``B`` simply stop absorbing — the select keeps their state
    frozen, so one compiled shape serves every length mix.  Returns the
    final state ``(8, 2, batch)`` uint32.
    """
    blocks = blocks.astype(jnp.uint32)
    n_blocks = n_blocks.astype(jnp.int32)
    batch = blocks.shape[-1]
    state0 = jnp.broadcast_to(
        jnp.asarray(_IV, dtype=jnp.uint32)[:, :, None], (8, 2, batch)
    )

    def block_step(state, xs):
        block, index = xs
        new_state = _compress_block(state, block)
        keep = index < n_blocks  # (batch,)
        return jnp.where(keep[None, None, :], new_state, state), None

    state, _ = jax.lax.scan(
        block_step,
        state0,
        (blocks, jnp.arange(blocks.shape[0], dtype=jnp.int32)),
    )
    return state


def digest_bytes(state: jnp.ndarray) -> jnp.ndarray:
    """State ``(8, 2, batch)`` -> digest bytes ``(64, batch)`` int32 in
    stream order (the order ``hashlib.sha512(...).digest()`` emits): each
    word big-endian, hi half first."""
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    # (8, 2, 4, batch): word, half, byte-within-half, lane.
    expanded = (state[:, :, None, :] >> shifts[None, None, :, None]) & jnp.uint32(0xFF)
    return expanded.reshape(64, state.shape[-1]).astype(jnp.int32)


def pack_bytes_device(rows: jnp.ndarray) -> jnp.ndarray:
    """Device-resident padded byte rows ``(B*128, batch)`` -> block layout
    ``(B, 16, 2, batch)`` uint32.  Lets transcript stages hash values that
    were themselves just hashed on device (leaves -> root -> coefficients)
    without a host round-trip."""
    total, batch = rows.shape
    if total % BLOCK_BYTES:
        raise ValueError("row length must be a multiple of 128")
    r = rows.astype(jnp.uint32).reshape(total // BLOCK_BYTES, 16, 2, 4, batch)
    return (
        (r[..., 0, :] << 24) | (r[..., 1, :] << 16) | (r[..., 2, :] << 8) | r[..., 3, :]
    )


# --- host packing ----------------------------------------------------------


def padded_blocks_for(length: int) -> int:
    """Blocks occupied by a ``length``-byte message after FIPS 180-4
    padding (0x80, zeros, 128-bit bit length)."""
    return (length + 17 + BLOCK_BYTES - 1) // BLOCK_BYTES


def pad_trailer(length: int) -> bytes:
    """The padding suffix for a ``length``-byte message: everything after
    the message bytes up to its final block boundary."""
    blocks = padded_blocks_for(length)
    zeros = blocks * BLOCK_BYTES - length - 1 - 16
    return b"\x80" + b"\x00" * zeros + (8 * length).to_bytes(16, "big")


def pad_messages(
    messages: Sequence[bytes], *, min_blocks: int = 1
) -> tuple[np.ndarray, np.ndarray]:
    """Pack variable-length messages into the fixed kernel block layout.

    Pure byte movement — no hashing, no big-int — this is the host cost
    that remains in fused mode.  Returns ``(blocks, n_blocks)``:
    ``blocks`` uint32 ``(B, 16, 2, n)`` with ``B`` the max padded block
    count (at least ``min_blocks``, so callers can pin a shape), and
    ``n_blocks`` int32 ``(n,)``.
    """
    n = len(messages)
    lengths = [len(m) for m in messages]
    n_blocks = np.array(
        [padded_blocks_for(length) for length in lengths], dtype=np.int32
    )
    total = max(int(n_blocks.max()) if n else 0, min_blocks)
    buf = np.zeros((n, total * BLOCK_BYTES), dtype=np.uint8)
    for i, message in enumerate(messages):
        length = lengths[i]
        end = int(n_blocks[i]) * BLOCK_BYTES
        buf[i, :length] = np.frombuffer(bytes(message), dtype=np.uint8)
        buf[i, length:end] = np.frombuffer(pad_trailer(length), dtype=np.uint8)
    words = buf.view(">u4").astype(np.uint32).reshape(n, total, 16, 2)
    return np.ascontiguousarray(words.transpose(1, 2, 3, 0)), n_blocks


__all__ = [
    "BLOCK_BYTES",
    "digest_bytes",
    "pack_bytes_device",
    "pad_messages",
    "pad_trailer",
    "padded_blocks_for",
    "sha512_blocks",
]
