"""On-device scalar arithmetic mod L (the edwards25519 group order).

The last host big-int holdout of the verification pipeline: reducing the
512-bit challenge hash mod L, the Fiat–Shamir coefficient products
``zᵢ·kᵢ mod L``, and the aggregate base scalar ``Σ zᵢ·sᵢ mod L`` all ran
as Python integers between the SHA-512 stage and the MSM kernels.  This
module does them in the same batched 8-bit-limb discipline as
:mod:`consensus_tpu.ops.field25519` — bytes on the trailing-batch lanes,
products held exactly in f32's 24-bit integer window, sequential int32
carries only at stage boundaries.

Reduction is Barrett-shaped but exploits L's sparse form
``L = 2^252 + δ`` (δ < 2^125):

1. **Byte fold** — a value given as little-endian bytes ``x = Σ bᵢ·2^8i``
   collapses to 32 limbs through one constant matmul with the
   ``(2^8i mod L)`` table: congruent mod L, every column sum < 2^23
   (f32-exact), and the contraction is MXU-shaped.
2. **Carry** to canonical bytes over two spare top limbs (the folded
   value is < 64·255·L < 2^267).
3. **Sparse fold** — split at bit 252: ``x = hi·2^252 + lo ≡ lo − hi·δ``.
   ``hi`` < 2^15 splits into two bytes against exact ``δ·2^8j`` tables,
   so the signed result lies in ``(−2^142, 2^252)`` — already below L
   (= 2^252 + δ) — and one borrow-driven conditional ``+L`` lands in
   ``[0, L)``.  No quotient estimation, no correction loop.

Every entry point is traceable (no host sync) and reports its work to the
field-op counting shim via :func:`consensus_tpu.ops.limbs.note_byte_muls`
so ``measure_field_ops`` covers the fused front-end too.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from consensus_tpu.ops import limbs

#: Group order of edwards25519 (RFC 8032) and its sparse-form tail.
L = 2**252 + 27742317777372353535851937790883648493
_DELTA = L - 2**252

#: L as little-endian bytes (canonical-range checks: S < L).
L_BYTES_LE = np.frombuffer(L.to_bytes(32, "little"), dtype=np.uint8)


def _int_to_bytes_row(value: int, width: int) -> np.ndarray:
    return np.frombuffer(value.to_bytes(width, "little"), dtype=np.uint8)


#: Row i = little-endian bytes of (2^8i mod L): the byte-fold matmul table.
_POW_TABLE = np.stack(
    [_int_to_bytes_row(pow(256, i, L), 32) for i in range(64)]
).astype(np.float32)  # (64, 32)

#: Row j = exact little-endian bytes of (δ << 8j) — NOT reduced: the sparse
#: fold subtracts hi·δ exactly (δ·2^16 < 2^141 fits 18 bytes).
_DELTA_SHIFT = np.stack(
    [_int_to_bytes_row(_DELTA << (8 * j), 32) for j in range(2)]
).astype(np.int32)  # (2, 32)

_L_LIMBS = _int_to_bytes_row(L, 32).astype(np.int32)

_WINDOW_BITS = 4


def reduce_bytes_mod_l(x_bytes: jnp.ndarray) -> jnp.ndarray:
    """Little-endian byte rows ``(n_bytes, batch)`` (n_bytes <= 64, each
    byte in [0, 255]) -> canonical bytes ``(32, batch)`` int32 of the value
    mod L.  Handles the full 512-bit SHA-512 digest range."""
    n_bytes, batch = x_bytes.shape
    if n_bytes > 64:
        raise ValueError("byte fold table covers 64 input bytes")
    table = jnp.asarray(_POW_TABLE[:n_bytes])  # (n_bytes, 32)
    limbs.note_byte_muls(n_bytes * 32, batch)
    folded = jnp.einsum(
        "ij,ib->jb", table, x_bytes.astype(jnp.float32)
    )  # (32, batch), columns < 64*255*255 < 2^23: f32-exact
    # Two spare limbs hold the fold's overflow (< 2^267 < 2^272).
    ext = jnp.concatenate(
        [folded.astype(jnp.int32), jnp.zeros((2, batch), jnp.int32)], axis=0
    )
    canon, top = limbs.carry_i32(ext)  # top carry provably 0

    # Sparse fold at bit 252: hi < 2^15 after the carry above.
    hi = (canon[31] >> 4) + (canon[32] << 4) + (canon[33] << 12) + (top << 20)
    lo = jnp.concatenate([canon[:31], (canon[31] & 0xF)[None]], axis=0)
    h_bytes = jnp.stack([hi & 0xFF, hi >> 8])  # (2, batch)
    limbs.note_byte_muls(2 * 32, batch)
    sub = jnp.einsum("jk,jb->kb", jnp.asarray(_DELTA_SHIFT), h_bytes)
    signed, borrow = limbs.carry_i32(lo - sub)
    # Value in (-2^142, 2^252): negative iff borrow < 0; one +L lands
    # canonical (2^252 < L, so the non-negative branch is already there).
    fixup = jnp.where(borrow < 0, jnp.asarray(_L_LIMBS)[:, None], 0)
    out, _ = limbs.carry_i32(signed + fixup)
    return out


def mul_mod_l(a_bytes: jnp.ndarray, b_bytes: jnp.ndarray) -> jnp.ndarray:
    """Product mod L of little-endian byte rows ``(na, batch)`` ×
    ``(nb, batch)`` with na·min(na,nb) small enough that schoolbook columns
    stay f32-exact (the pipeline's shapes are 16×32 and 32×32: columns
    <= 32·255² < 2^22)."""
    na, batch = a_bytes.shape
    nb = b_bytes.shape[0]
    if min(na, nb) > 32:
        raise ValueError("schoolbook columns would overflow the f32 window")
    a = a_bytes.astype(jnp.float32)
    b = b_bytes.astype(jnp.float32)
    limbs.note_byte_muls(na * nb, batch)
    cols = jnp.zeros((64, batch), jnp.float32)
    for i in range(na):  # static unroll: na broadcast-multiplies
        cols = cols.at[i : i + nb].add(a[i][None] * b)
    canon, _ = limbs.carry_i32(cols.astype(jnp.int32))  # < 2^384 << 2^512
    return reduce_bytes_mod_l(canon)


def sum_mod_l(vals_bytes: jnp.ndarray) -> jnp.ndarray:
    """Sum over the batch axis mod L: canonical byte rows ``(32, batch)``
    -> canonical bytes ``(32, 1)``.  Column sums stay int32-exact up to
    batch 2^23."""
    summed = vals_bytes.astype(jnp.int32).sum(axis=-1, keepdims=True)
    ext = jnp.concatenate([summed, jnp.zeros((32, 1), jnp.int32)], axis=0)
    canon, _ = limbs.carry_i32(ext)  # value < batch·L < 2^280 << 2^512
    return reduce_bytes_mod_l(canon)


def lt_l(s_bytes: jnp.ndarray) -> jnp.ndarray:
    """On-device malleability check ``S < L`` (RFC 8032 §5.1.7) over
    ``(32, batch)`` little-endian byte rows."""
    return limbs.lt_bytes(
        s_bytes.astype(jnp.int32), jnp.asarray(L_BYTES_LE, dtype=jnp.int32)
    )


def signed_window_digits(
    k_bytes: jnp.ndarray, windows: int = 64
) -> jnp.ndarray:
    """Canonical little-endian byte rows -> signed 4-bit window digits,
    wire-encoded ``d+8``, MSB window first — the device twin of
    ``models.ed25519._bits_to_signed_window_digits`` /
    ``_signed_digits_int``.  ``windows`` must leave carry headroom
    exactly as the host versions require (64 for k < 2^253, 33 for
    128-bit coefficients)."""
    k = k_bytes.astype(jnp.int32)
    nibbles = jnp.stack([k & 0xF, k >> 4], axis=1).reshape(
        2 * k.shape[0], k.shape[-1]
    )  # LSB-first 4-bit windows
    if nibbles.shape[0] > windows:
        nibbles = nibbles[:windows]
    elif nibbles.shape[0] < windows:
        nibbles = jnp.concatenate(
            [
                nibbles,
                jnp.zeros((windows - nibbles.shape[0], k.shape[-1]), jnp.int32),
            ],
            axis=0,
        )

    def step(carry, u):
        t = u + carry
        over = (t >= 8).astype(jnp.int32)
        return over, t - 16 * over

    _, digits = limbs.counted_scan(step, jnp.zeros_like(nibbles[0]), nibbles)
    return digits[::-1] + 8


__all__ = [
    "L",
    "L_BYTES_LE",
    "lt_l",
    "mul_mod_l",
    "reduce_bytes_mod_l",
    "signed_window_digits",
    "sum_mod_l",
]
