"""GF(p256) arithmetic as batched JAX float32 limb vectors, where
p256 = 2^256 - 2^224 + 2^192 + 2^96 - 1 (the NIST P-256 prime).

Same discipline as :mod:`consensus_tpu.ops.field25519` — 32 x 8-bit limbs
in float32, limbs leading / batch trailing, every product and column sum
exact inside the 24-bit integer window — but the reduction differs: p256 is
a Solinas prime, so 2^256 ≡ 2^224 - 2^192 - 2^96 + 1 (mod p), a *signed
4-term byte pattern* rather than curve25519's small constant.  Folding the
high half of a product is therefore four shifted adds/subs of the high
limbs, iterated until the spill-over above limb 31 vanishes.

Normalization contract: public ops take and return *weakly reduced*
elements — |limb| <= 600, value exact mod p and |value| < 2^262 —
multiplication-safe (600^2 * 32 < 2^24).  ``freeze`` produces the canonical
int32 representative in [0, p).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from consensus_tpu.ops import limbs
from consensus_tpu.ops.limbs import carry_i32


def _note_lanes(a, b=None) -> int:
    """Independent field elements an op touches (see field25519 twin)."""
    shape = a.shape if b is None else jnp.broadcast_shapes(a.shape, b.shape)
    lanes = 1
    for dim in shape[1:]:
        lanes *= int(dim)
    return lanes


LIMBS = 32
LIMB_BITS = 8
BASE = 256.0
INV_BASE = 1.0 / 256.0

P = 2**256 - 2**224 + 2**192 + 2**96 - 1

#: 2^256 mod p as a signed byte pattern: +1 at byte 0, -1 at byte 12,
#: -1 at byte 24, +1 at byte 28.
_FOLD_PATTERN: tuple[tuple[int, int], ...] = ((0, 1), (12, -1), (24, -1), (28, 1))
assert sum(s * (1 << (8 * pos)) for pos, s in _FOLD_PATTERN) == (2**256) % P


def int_to_limbs(value: int) -> np.ndarray:
    if not 0 <= value < 2**256:
        raise ValueError("value out of limb range")
    return np.array(
        [(value >> (LIMB_BITS * i)) & 0xFF for i in range(LIMBS)], dtype=np.float32
    )


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(LIMBS))


def _cexpand(const_limbs, like: jnp.ndarray) -> jnp.ndarray:
    return jnp.reshape(jnp.asarray(const_limbs), (LIMBS,) + (1,) * (like.ndim - 1))


def constant_like(value: int, like: jnp.ndarray) -> jnp.ndarray:
    return like * 0 + _cexpand(int_to_limbs(value % P), like)


def zeros_like(like: jnp.ndarray) -> jnp.ndarray:
    return like * 0


def _split(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    hi = jnp.floor(x * INV_BASE)
    return x - hi * BASE, hi


def _solinas_matrix() -> np.ndarray:
    """The FIPS 186-4 fast-reduction word assembly for P-256 as ONE constant
    (32, 64) signed matrix: the Solinas identity is linear in the 64 8-bit
    limbs, so ``s1 + 2 s2 + 2 s3 + s4 + s5 - s6 - s7 - s8 - s9`` collapses
    to a single matrix-vector product — a far smaller graph than 9
    concatenated word-group assemblies (measured trace-time win), and a
    (32x64) matmul the TPU can tile.  Built numerically from the word-group
    definition so the matrix provably equals the assembly it replaces."""
    x = np.eye(64, dtype=np.float64)

    def word(i):
        return x[4 * i : 4 * i + 4]

    zero4 = np.zeros((4, 64))

    def assemble(words):
        return np.concatenate(words, axis=0)

    s1 = x[:LIMBS]
    s2 = assemble([zero4, zero4, zero4, word(11), word(12), word(13), word(14), word(15)])
    s3 = assemble([zero4, zero4, zero4, word(12), word(13), word(14), word(15), zero4])
    s4 = assemble([word(8), word(9), word(10), zero4, zero4, zero4, word(14), word(15)])
    s5 = assemble([word(9), word(10), word(11), word(13), word(14), word(15), word(13), word(8)])
    s6 = assemble([word(11), word(12), word(13), zero4, zero4, zero4, word(8), word(10)])
    s7 = assemble([word(12), word(13), word(14), word(15), zero4, zero4, word(9), word(11)])
    s8 = assemble([word(13), word(14), word(15), word(8), word(9), word(10), zero4, word(12)])
    s9 = assemble([word(14), word(15), zero4, word(9), word(10), word(11), zero4, word(13)])
    m = s1 + 2.0 * s2 + 2.0 * s3 + s4 + s5 - s6 - s7 - s8 - s9
    assert np.abs(m).max() <= 4
    return m.astype(np.float32)


_SOLINAS_M = _solinas_matrix()


def _reduce_wide(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce a wide (<= 63 limb) signed vector to 32 weakly reduced limbs
    via the constant Solinas matrix (see :func:`_solinas_matrix`).

    One carry-save pass first keeps every matrix-product column sum inside
    f32's exact-integer window (|limb| < 2^16.1, row abs-coefficient sums
    <= ~10 -> |r| < 2^20)."""
    batch_pad = [(0, 0)] * (x.ndim - 1)
    if x.shape[0] > 2 * LIMBS - 1:
        raise ValueError(f"input too wide: {x.shape[0]}")
    if x.shape[0] < 2 * LIMBS - 1:
        x = jnp.pad(x, [(0, 2 * LIMBS - 1 - x.shape[0])] + batch_pad)
    # One carry-save pass: |limb| drops to < 255 + 2^16 (width 64 exactly).
    lo, hi = _split(x)
    x = jnp.pad(lo, [(0, 1)] + batch_pad) + jnp.pad(hi, [(1, 0)] + batch_pad)

    # Precision.HIGHEST: TPU f32 matmuls default to a bf16-pass MXU
    # decomposition that is NOT bit-exact; this arithmetic requires exact
    # integer sums inside the f32 window.
    import jax

    r = jnp.tensordot(
        jnp.asarray(_SOLINAS_M), x, axes=([1], [0]),
        precision=jax.lax.Precision.HIGHEST,
    )  # |limb| < 2^20
    if limbs.counting():
        limbs.note_dot(LIMBS, 1, 2 * LIMBS, _note_lanes(x))

    # Two light rounds: carry-save + fold the single overflow limb through
    # the 2^256 pattern.  Lands |limb| <= ~300.
    for _ in range(2):
        lo, hi = _split(r)
        carried = jnp.pad(lo, [(0, 1)] + batch_pad) + jnp.pad(hi, [(1, 0)] + batch_pad)
        r = carried[:LIMBS]
        top = carried[LIMBS]
        for pos, sign in _FOLD_PATTERN:
            r = r.at[pos].add(sign * top)
    return r


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    if limbs.counting():
        limbs.note_add(_note_lanes(a, b))
    return _reduce_wide(a + b)


#: 4p fits in 258 bits -> 33 limbs; keep a 32-limb bias of 8p? Use 2^8 * p
#: trick instead: bias with (2^262-ish multiple) ... simpler: 4p as 33 limbs
#: folded once at construction to a 32-limb *signed* equivalent: 4p mod
#: 2^256 + fold of the top bits.  We just precompute 4p - k*p == value
#: congruent 0 mod p that covers the subtrahend range; easiest correct
#: choice: 8p reduced to a signed 32-limb vector via _reduce on ints.
def _bias_limbs() -> np.ndarray:
    # A multiple of p, >= 2^262 in value, expressed in 32 signed limbs with
    # |limb| <= 300: take m = 128*p and greedily balance digits to +-128.
    m = 128 * P
    digits = []
    carry = 0
    v = m
    for _ in range(LIMBS):
        d = (v & 0xFF) + carry
        v >>= 8
        carry = 0
        if d > 128:
            d -= 256
            carry = 1
        digits.append(d)
    # Remaining v (from bit 256 up, incl. final carry) folds via the
    # Solinas pattern; it is tiny (< 2^7).
    top = v + carry
    for pos, sign in _FOLD_PATTERN:
        digits[pos] += sign * top
    arr = np.array(digits, dtype=np.float32)
    assert limbs_to_int_signed(arr) % P == 0
    return arr


def limbs_to_int_signed(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(LIMBS))


_BIAS = None  # initialized lazily below (needs limbs_to_int_signed defined)


def _get_bias() -> np.ndarray:
    global _BIAS
    if _BIAS is None:
        _BIAS = _bias_limbs()
    return _BIAS


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # Bias with a multiple of p large enough to keep the value positive for
    # any weakly reduced operands.
    if limbs.counting():
        limbs.note_add(_note_lanes(a, b))
    return _reduce_wide(a + _cexpand(_get_bias(), a) - b)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook convolution (32 broadcast multiplies + shifted adds) then
    the Solinas fold.  Weakly reduced inputs keep columns exact in f32.

    ``CTPU_MXU_LIMBS=1`` dispatches to the bit-identical MXU lane (before
    the note, so counted traces report dots instead of muls — same
    discipline as field25519.mul)."""
    from consensus_tpu.ops import mxu_limbs

    if mxu_limbs.lane_active():
        return mxu_limbs.mul_p256(a, b)
    if limbs.counting():
        limbs.note_mul(_note_lanes(a, b))
    batch_pad = [(0, 0)] * (a.ndim - 1)
    terms = [
        jnp.pad(a[i] * b, [(i, LIMBS - 1 - i)] + batch_pad) for i in range(LIMBS)
    ]
    return _reduce_wide(sum(terms))


def square(a: jnp.ndarray) -> jnp.ndarray:
    from consensus_tpu.ops import mxu_limbs

    if mxu_limbs.lane_active():
        return mxu_limbs.square_p256(a)
    if limbs.counting():
        limbs.note_square(_note_lanes(a))
    batch_pad = [(0, 0)] * (a.ndim - 1)
    doubled = a + a
    terms = []
    for i in range(LIMBS):
        row = jnp.concatenate([a[i : i + 1] * a[i], doubled[i + 1 :] * a[i]], axis=0)
        terms.append(jnp.pad(row, [(2 * i, LIMBS - 1 - i)] + batch_pad))
    return _reduce_wide(sum(terms))


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * k for small positive k (<= 64)."""
    return _reduce_wide(a * float(k))


_P_LIMBS_I32 = np.array(
    [(P >> (LIMB_BITS * i)) & 0xFF for i in range(LIMBS)], dtype=np.int32
)


def _carry_i32(x):
    """Exact sequential int32 carry pass (freeze-only path)."""
    return carry_i32(x, LIMB_BITS)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical int32 representative in [0, p)."""
    x = jnp.asarray(jnp.rint(a), dtype=jnp.int32)
    # Bias to positive using the signed multiple of p, then carry exactly.
    x = x + jnp.reshape(jnp.asarray(_get_bias().astype(np.int32)), (LIMBS,) + (1,) * (a.ndim - 1))
    # Sequential exact carry; value in (0, ~2^263): top carry folds via the
    # Solinas pattern (iterate twice — the first fold's carry is tiny).
    for _ in range(2):
        x, carry = _carry_i32(x)
        for pos, sign in _FOLD_PATTERN:
            x = x.at[pos].add(sign * carry)
    p_e = jnp.reshape(jnp.asarray(_P_LIMBS_I32), (LIMBS,) + (1,) * (a.ndim - 1))
    for _ in range(3):
        # Subtract p while the value still exceeds it (value < ~2^256 + eps
        # after the carry folds; p ~ 2^256 (1 - 2^-32), so <= 3 rounds).
        d, carry = _carry_i32(x - p_e)
        ge_p = carry == 0
        x = jnp.where(ge_p[None], d, x)
    return x


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == freeze(b), axis=0)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=0)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(cond[None], a, b)


__all__ = [
    "LIMBS",
    "P",
    "int_to_limbs",
    "limbs_to_int",
    "constant_like",
    "zeros_like",
    "add",
    "sub",
    "mul",
    "square",
    "mul_small",
    "freeze",
    "eq",
    "is_zero",
    "select",
]
