"""MXU-lane field multiplication: limb products as integer ``dot_general``
tiles (ROADMAP item 3; the ``CTPU_MXU_LIMBS=1`` backend).

The VPU lane (:mod:`consensus_tpu.ops.field25519`,
:mod:`consensus_tpu.ops.field_p256`) lowers schoolbook limb multiplication
to 32 broadcast multiplies + shifted column adds — elementwise work the
MXU never sees.  This module expresses the SAME arithmetic as two integer
contractions the MXU can tile:

1. a batched outer product ``P[n, i, j] = a_i(n) * b_j(n)`` via
   ``lax.dot_general`` over ``int16`` limb tiles with
   ``preferred_element_type=int32`` (operands are weakly reduced or one
   raw level, |limb| <= 680, so products stay <= 680^2 = 462,400 — exact
   in int32, and int16 holds the operands with 48x headroom);
2. a contraction of the flattened products against a constant (63, 1024)
   0/1 **column-assembly matrix** ``C[c, 32i+j] = [i + j == c]`` — the
   schoolbook convolution as one (63 x 1024) x (1024 x batch) integer
   matmul with a shared constant operand (the shape
   benchmarks/mxu_fieldmul.py's round-6 analysis said the MXU needs to
   win: reuse across the batch, not per-lane elementwise work).  Column
   sums are <= 32 * 462,400 < 2^24 — the same bound the f32 lane proves.

Reduction mod p is fused into the same tile as an **int32-domain mirror**
of the f32 lane's carry-save passes: arithmetic ``>> 8`` is exactly
``floor(x / 256)`` for negatives, so every intermediate integer equals the
f32 lane's value and the final weakly-reduced limbs are **bit-identical**
to the VPU lane after the f32 cast (|limb| <= 340 / ~300 — exact in f32).
Squaring dispatches through ``mul(a, a)``: the full product columns equal
the VPU square's diagonal-plus-doubled-cross columns as integers, so the
reduced output is bit-identical to the specialized VPU square as well.

Deliberately NOT done: folding the mod-p reduction into the assembly
matrix (e.g. columns 32..62 re-entering at weight 38).  That would change
the intermediate limb representation and void every bounds analysis the
curve formulas' lazy-reduction budget rests on; the mirror keeps the two
lanes byte-identical at every step instead.

Lane selection is **trace-time**: the field stacks consult
:func:`lane_active` inside ``mul``/``square``, so a process opts in with
``CTPU_MXU_LIMBS=1`` (read per trace — already-compiled shapes keep their
lane) and bench A/Bs flip lanes in-process with :func:`force_mxu_limbs` /
:func:`suppress_mxu_limbs` around fresh jits.  Pallas kernel bodies trace
under :func:`suppress_mxu_limbs` — a ``dot_general`` inside a Mosaic
kernel is unvalidated lowering risk, and the kernels' whole point is VPU
scheduling.

Counting: the shim (:mod:`consensus_tpu.ops.limbs`) records this lane's
work through :func:`~consensus_tpu.ops.limbs.note_dot` as dense MACs —
the outer product is 1024 MACs/lane and the column assembly 63 * 1024 =
64,512 MACs/lane, ~64x the VPU lane's useful multiplies.  That ratio is
the honest price of dense tiling (the MXU does not skip the zeros in C);
BASELINE.md records it as the measured denominator the device A/B must
beat with systolic-array throughput.
"""

from __future__ import annotations

import contextlib
import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

from consensus_tpu.ops import limbs

LIMBS = 32
_COLS = 2 * LIMBS - 1  # 63 schoolbook columns

#: curve25519 fold weights (mirrors field25519.FOLD / TOP_FOLD).
_FOLD = 38
_TOP_FOLD = 19

#: Trace-time lane overrides (module globals, mutated only under the
#: context managers below — same discipline as pallas_scan._SUPPRESSED).
_FORCED = False
_SUPPRESSED = False


def lane_active() -> bool:
    """True when field ``mul``/``square`` should trace the MXU lane.

    Checked per trace by the field stacks; already-compiled shapes keep
    whichever lane they were traced under.  Suppression wins over forcing
    (a Pallas kernel body must stay VPU-shaped even inside a forced A/B).
    """
    if _SUPPRESSED:
        return False
    if _FORCED:
        return True
    return os.environ.get("CTPU_MXU_LIMBS", "") == "1"


@contextlib.contextmanager
def force_mxu_limbs():
    """Trace the MXU lane inside this block regardless of the environment
    (bench in-process A/B: an env flip cannot retrace already-cached
    shapes, a fresh jit under this context can)."""
    global _FORCED
    prev = _FORCED
    _FORCED = True
    try:
        yield
    finally:
        _FORCED = prev


@contextlib.contextmanager
def suppress_mxu_limbs():
    """Trace the VPU lane inside this block regardless of the environment
    (Pallas kernel bodies; the bench A/B's control arm)."""
    global _SUPPRESSED
    prev = _SUPPRESSED
    _SUPPRESSED = True
    try:
        yield
    finally:
        _SUPPRESSED = prev


@functools.lru_cache(maxsize=1)
def _conv_matrix() -> np.ndarray:
    """(63, 1024) 0/1 column-assembly matrix: C @ flatten(outer(a, b))
    yields the schoolbook convolution columns.  int8 at rest (the MXU's
    native integer operand width); cast to int32 at the contraction."""
    c = np.zeros((_COLS, LIMBS * LIMBS), dtype=np.int8)
    for i in range(LIMBS):
        for j in range(LIMBS):
            c[i + j, LIMBS * i + j] = 1
    return c


def _schoolbook_columns(a: jnp.ndarray, b: jnp.ndarray):
    """Exact int32 schoolbook columns of a * b as two MXU contractions.

    Returns ``(cols, batch_shape)`` with ``cols`` of shape
    ``(63, *batch)`` — integer-identical to the f32 lane's
    ``sum(padded terms)``.  Operands must satisfy the field stacks' lazy
    budget (|a_limb| * |b_limb| <= 2^19), which also bounds them inside
    int16.
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    batch_shape = shape[1:]
    lanes = 1
    for dim in batch_shape:
        lanes *= int(dim)

    a16 = jnp.reshape(a, (LIMBS, lanes)).T.astype(jnp.int16)  # (B, 32)
    b16 = jnp.reshape(b, (LIMBS, lanes)).T.astype(jnp.int16)
    # Batched outer product: one rank-1 matmul per lane, int32 accumulation
    # (the products themselves overflow int16).
    outer = jax.lax.dot_general(
        a16[:, :, None],
        b16[:, None, :],
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # (B, 32, 32)
    # Column assembly: (63, 1024) x (1024, B) — the constant operand is
    # shared across the whole batch, the reuse shape the MXU wants.
    cols = jax.lax.dot_general(
        jnp.asarray(_conv_matrix(), dtype=jnp.int32),
        jnp.reshape(outer, (lanes, LIMBS * LIMBS)),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (63, B)
    if limbs.counting():
        limbs.note_dot(LIMBS, LIMBS, 1, lanes)          # outer products
        limbs.note_dot(_COLS, 1, LIMBS * LIMBS, lanes)  # column assembly
    return jnp.reshape(cols, (_COLS,) + batch_shape), batch_shape


# --- int32 mirrors of the f32 reductions (bit-identical by construction) ---


def _split_i32(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """int32 twin of the f32 ``_split``: arithmetic >> 8 IS floor(x/256)."""
    hi = x >> 8
    return x - (hi << 8), hi


def _relax_i32(x: jnp.ndarray) -> jnp.ndarray:
    lo, hi = _split_i32(x)
    rolled = jnp.concatenate([hi[LIMBS - 1 :] * _FOLD, hi[: LIMBS - 1]], axis=0)
    return lo + rolled


def _top_fold_i32(x: jnp.ndarray) -> jnp.ndarray:
    high = x[LIMBS - 1] >> 7
    return jnp.concatenate(
        [
            (x[0] + high * _TOP_FOLD)[None],
            x[1 : LIMBS - 1],
            (x[LIMBS - 1] - high * 128)[None],
        ],
        axis=0,
    )


def _weak_reduce_i32(x: jnp.ndarray) -> jnp.ndarray:
    x = _relax_i32(x)
    x = _relax_i32(x)
    x = _relax_i32(x)
    return _top_fold_i32(x)


def _reduce_cols_i32(cols: jnp.ndarray) -> jnp.ndarray:
    """int32 mirror of field25519._reduce_cols: same integers every step."""
    lo, hi = _split_i32(cols)
    c = jnp.concatenate([lo[:1], lo[1:] + hi[:-1], hi[-1:]], axis=0)  # width 64
    r = c[:LIMBS] + c[LIMBS:] * _FOLD
    return _weak_reduce_i32(r)


def mul25519(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """GF(2^255-19) multiplication on the MXU lane — bit-identical output
    to :func:`consensus_tpu.ops.field25519.mul` (weakly reduced,
    |limb| <= 340, exact in the f32 cast)."""
    cols, _ = _schoolbook_columns(a, b)
    return _reduce_cols_i32(cols).astype(jnp.float32)


def square25519(a: jnp.ndarray) -> jnp.ndarray:
    """MXU squaring = ``mul25519(a, a)``: the full product columns equal
    the VPU square's diagonal + doubled-cross columns as integers, so the
    output is bit-identical to the specialized square (and valid over its
    whole |limb| <= 500 domain, with margin to 680)."""
    return mul25519(a, a)


# --- P-256 (Solinas) reduction mirror --------------------------------------


@functools.lru_cache(maxsize=1)
def _solinas_i32() -> np.ndarray:
    """field_p256's (32, 64) Solinas matrix as exact int32 (entries are
    integers with |m| <= 4, so the f32 -> int32 cast is lossless).  A
    snapshot, deliberately NOT the live ``fp._SOLINAS_M`` global — the
    Pallas trace windows monkeypatch that, and this lane is suppressed
    inside kernels anyway."""
    from consensus_tpu.ops import field_p256 as fp

    return np.asarray(fp._solinas_matrix(), dtype=np.int32)


def _reduce_wide_i32(x: jnp.ndarray) -> jnp.ndarray:
    """int32 mirror of field_p256._reduce_wide: carry-save, Solinas matrix
    contraction (integer dot — no Precision knob needed, unlike the f32
    lane's HIGHEST-precision tensordot), two light fold rounds."""
    from consensus_tpu.ops import field_p256 as fp

    batch_pad = [(0, 0)] * (x.ndim - 1)
    if x.shape[0] > _COLS:
        raise ValueError(f"input too wide: {x.shape[0]}")
    if x.shape[0] < _COLS:
        x = jnp.pad(x, [(0, _COLS - x.shape[0])] + batch_pad)
    lo, hi = _split_i32(x)
    x = jnp.pad(lo, [(0, 1)] + batch_pad) + jnp.pad(hi, [(1, 0)] + batch_pad)

    lanes = 1
    for dim in x.shape[1:]:
        lanes *= int(dim)
    r = jnp.tensordot(jnp.asarray(_solinas_i32()), x, axes=([1], [0]))
    if limbs.counting():
        limbs.note_dot(LIMBS, 1, 2 * LIMBS, lanes)

    for _ in range(2):
        lo, hi = _split_i32(r)
        carried = (
            jnp.pad(lo, [(0, 1)] + batch_pad) + jnp.pad(hi, [(1, 0)] + batch_pad)
        )
        r = carried[:LIMBS]
        top = carried[LIMBS]
        for pos, sign in fp._FOLD_PATTERN:
            r = r.at[pos].add(sign * top)
    return r


def mul_p256(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """GF(p256) multiplication on the MXU lane — bit-identical output to
    :func:`consensus_tpu.ops.field_p256.mul`."""
    cols, _ = _schoolbook_columns(a, b)
    return _reduce_wide_i32(cols).astype(jnp.float32)


def square_p256(a: jnp.ndarray) -> jnp.ndarray:
    """MXU P-256 squaring via ``mul_p256(a, a)`` (same column-integer
    argument as :func:`square25519`)."""
    return mul_p256(a, a)


__all__ = [
    "lane_active",
    "force_mxu_limbs",
    "suppress_mxu_limbs",
    "mul25519",
    "square25519",
    "mul_p256",
    "square_p256",
]
