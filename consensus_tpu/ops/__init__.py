"""TPU numeric kernels: GF(2^255-19) limb arithmetic, edwards25519 group
ops, and the fused front-end's hashing/scalar stages (SHA-512, mod-L)."""

from consensus_tpu.ops import ed25519, field25519, scalar25519, sha512

__all__ = ["field25519", "ed25519", "scalar25519", "sha512"]
