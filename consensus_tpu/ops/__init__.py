"""TPU numeric kernels: GF(2^255-19) limb arithmetic + edwards25519 group ops."""

from consensus_tpu.ops import ed25519, field25519

__all__ = ["field25519", "ed25519"]
