"""NIST P-256 group operations on batched limb vectors.

Short Weierstrass curve y^2 = x^3 - 3x + b over GF(p256), homogeneous
projective coordinates (X : Y : Z), using the *complete* formulas of
Renes–Costello–Batina 2015 (EUROCRYPT 2016), Algorithms 4 (addition,
12M + 2mb) and 6 (doubling, 8M + 3S + 2mb) for a = -3: one branch-free
code path valid for every input including the identity (0 : 1 : 0) and
P + P — exactly what a fixed-shape batched scan needs.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax.numpy as jnp

from consensus_tpu.ops import field_p256 as fp

#: Curve constants (FIPS 186-4 / SEC2).
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
#: Group order.
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551


class Point(NamedTuple):
    """Batched projective point; each field is (32, *batch) float32."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray


def identity_like(ref: jnp.ndarray) -> Point:
    return Point(x=ref * 0, y=fp.constant_like(1, ref), z=ref * 0)


def base_point_like(ref: jnp.ndarray) -> Point:
    return Point(
        x=fp.constant_like(GX, ref),
        y=fp.constant_like(GY, ref),
        z=fp.constant_like(1, ref),
    )


def affine_like(x_limbs: jnp.ndarray, y_limbs: jnp.ndarray) -> Point:
    return Point(x=x_limbs, y=y_limbs, z=fp.constant_like(1, x_limbs))


def add(p: Point, q: Point) -> Point:
    """RCB15 Algorithm 4 (complete addition, a = -3)."""
    b = fp.constant_like(B, p.x)
    t0 = fp.mul(p.x, q.x)
    t1 = fp.mul(p.y, q.y)
    t2 = fp.mul(p.z, q.z)
    t3 = fp.add(p.x, p.y)
    t4 = fp.add(q.x, q.y)
    t3 = fp.mul(t3, t4)
    t4 = fp.add(t0, t1)
    t3 = fp.sub(t3, t4)
    t4 = fp.add(p.y, p.z)
    t5 = fp.add(q.y, q.z)
    t4 = fp.mul(t4, t5)
    t5 = fp.add(t1, t2)
    t4 = fp.sub(t4, t5)
    x3 = fp.add(p.x, p.z)
    y3 = fp.add(q.x, q.z)
    x3 = fp.mul(x3, y3)
    y3 = fp.add(t0, t2)
    y3 = fp.sub(x3, y3)
    z3 = fp.mul(b, t2)
    x3 = fp.sub(y3, z3)
    z3 = fp.add(x3, x3)
    x3 = fp.add(x3, z3)
    z3 = fp.sub(t1, x3)
    x3 = fp.add(t1, x3)
    y3 = fp.mul(b, y3)
    t1 = fp.add(t2, t2)
    t2 = fp.add(t1, t2)
    y3 = fp.sub(y3, t2)
    y3 = fp.sub(y3, t0)
    t1 = fp.add(y3, y3)
    y3 = fp.add(t1, y3)
    t1 = fp.add(t0, t0)
    t0 = fp.add(t1, t0)
    t0 = fp.sub(t0, t2)
    t1 = fp.mul(t4, y3)
    t2 = fp.mul(t0, y3)
    y3 = fp.mul(x3, z3)
    y3 = fp.add(y3, t2)
    x3 = fp.mul(t3, x3)
    x3 = fp.sub(x3, t1)
    z3 = fp.mul(t4, z3)
    t1 = fp.mul(t3, t0)
    z3 = fp.add(z3, t1)
    return Point(x=x3, y=y3, z=z3)


def double(p: Point) -> Point:
    """RCB15 Algorithm 6 (exception-free doubling, a = -3)."""
    b = fp.constant_like(B, p.x)
    t0 = fp.square(p.x)
    t1 = fp.square(p.y)
    t2 = fp.square(p.z)
    t3 = fp.mul(p.x, p.y)
    t3 = fp.add(t3, t3)
    z3 = fp.mul(p.x, p.z)
    z3 = fp.add(z3, z3)
    y3 = fp.mul(b, t2)
    y3 = fp.sub(y3, z3)
    x3 = fp.add(y3, y3)
    y3 = fp.add(x3, y3)
    x3 = fp.sub(t1, y3)
    y3 = fp.add(t1, y3)
    y3 = fp.mul(x3, y3)
    x3 = fp.mul(x3, t3)
    t3 = fp.add(t2, t2)
    t2 = fp.add(t2, t3)
    z3 = fp.mul(b, z3)
    z3 = fp.sub(z3, t2)
    z3 = fp.sub(z3, t0)
    t3 = fp.add(z3, z3)
    z3 = fp.add(z3, t3)
    t3 = fp.add(t0, t0)
    t0 = fp.add(t3, t0)
    t0 = fp.sub(t0, t2)
    t0 = fp.mul(t0, z3)
    y3 = fp.add(y3, t0)
    t0 = fp.mul(p.y, p.z)
    t0 = fp.add(t0, t0)
    z3 = fp.mul(t0, z3)
    x3 = fp.sub(x3, z3)
    z3 = fp.mul(t0, t1)
    z3 = fp.add(z3, z3)
    z3 = fp.add(z3, z3)
    return Point(x=x3, y=y3, z=z3)


def negate(p: Point) -> Point:
    """-(X : Y : Z) = (X : -Y : Z) — one mul-free field subtraction."""
    return Point(x=p.x, y=fp.sub(p.y * 0, p.y), z=p.z)


def select(cond: jnp.ndarray, p: Point, q: Point) -> Point:
    return Point(
        x=fp.select(cond, p.x, q.x),
        y=fp.select(cond, p.y, q.y),
        z=fp.select(cond, p.z, q.z),
    )


def table_lookup(table: Point, one_hot: jnp.ndarray) -> Point:
    """table[digit] via a one-hot contraction (no gathers); coords are
    (W, 32, *batch) or broadcastable."""
    oh = one_hot[:, None]

    def pick(coord: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(coord * oh, axis=0)

    return Point(x=pick(table.x), y=pick(table.y), z=pick(table.z))


def multiples_table(p: Point, size: int = 16) -> Point:
    """Built with a ``lax.scan`` so the add formula appears once in the
    graph regardless of table size (compile-time, not runtime, economy)."""
    import jax

    def step(prev: Point, _):
        nxt = add(prev, p)
        return nxt, nxt

    _, rest = jax.lax.scan(step, p, None, length=size - 2)
    ident = identity_like(p.x)
    return Point(
        x=jnp.concatenate([ident.x[None], p.x[None], rest.x]),
        y=jnp.concatenate([ident.y[None], p.y[None], rest.y]),
        z=jnp.concatenate([ident.z[None], p.z[None], rest.z]),
    )


def _add_int(p1, p2):
    """Host-side affine integer point add (None = identity) for
    constant-table generation."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % fp.P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 - 3) * pow(2 * y1, fp.P - 2, fp.P) % fp.P
    else:
        lam = (y2 - y1) * pow(x2 - x1, fp.P - 2, fp.P) % fp.P
    x3 = (lam * lam - x1 - x2) % fp.P
    return x3, (lam * (x1 - x3) - y1) % fp.P


_COMB_WINDOWS = 32
_COMB_BITS = 8


@functools.lru_cache(maxsize=1)
def _comb_table_np():
    """Fixed-base comb for G: projective (x, y, z) limb arrays of shape
    (32 windows, 256 entries, 32 limbs) with ``T[j][d] = d * 2^(8j) * G``
    (z = 0 encodes the identity at d = 0 — P-256's projective identity
    (0 : 1 : 0) has no affine form, so the comb adds stay the complete
    projective formula rather than a mixed add).

    G is a compile-time constant, so [u1]G needs NO doubles and NO
    per-batch table build: 32 constant lookups + adds instead of riding
    the Horner scan (64 table adds).  Host-side integer precompute
    (~0.3 s, cached per process; baked into the graph as constants)."""
    import numpy as np

    xs = np.zeros((_COMB_WINDOWS, 1 << _COMB_BITS, fp.LIMBS), dtype=np.float32)
    ys = np.zeros_like(xs)
    zs = np.zeros_like(xs)
    window_base = (GX, GY)  # 2^(8j) * G
    for j in range(_COMB_WINDOWS):
        entry = None
        for d in range(1 << _COMB_BITS):
            if entry is None:
                ys[j, d] = fp.int_to_limbs(1)  # (0 : 1 : 0)
            else:
                xs[j, d] = fp.int_to_limbs(entry[0])
                ys[j, d] = fp.int_to_limbs(entry[1])
                zs[j, d] = fp.int_to_limbs(1)
            entry = _add_int(entry, window_base)
        for _ in range(_COMB_BITS):
            window_base = _add_int(window_base, window_base)
    return xs, ys, zs


def fixed_base_mul_comb(digits8: jnp.ndarray) -> Point:
    """[u]G from 8-bit window digits ``digits8`` of shape (32, batch), LSB
    window first: one constant-table lookup (a one-hot contraction that
    lowers to a matmul — MXU work) + one complete add per window, zero
    doubles."""
    import jax

    xs, ys, zs = _comb_table_np()
    lanes = jnp.arange(1 << _COMB_BITS, dtype=jnp.int32)[:, None]  # (256, 1)

    def coords(arr) -> jnp.ndarray:
        return jnp.asarray(arr)[..., None]  # (32, 256, 32, 1)

    def step(acc: Point, inputs):
        digits, tx, ty, tz = inputs
        oh = (digits[None] == lanes).astype(jnp.float32)  # (256, batch)
        return add(acc, table_lookup(Point(x=tx, y=ty, z=tz), oh)), None

    ref = digits8.astype(jnp.float32)  # (32, batch) == (LIMBS, batch)
    acc, _ = jax.lax.scan(
        step, identity_like(ref), (digits8, coords(xs), coords(ys), coords(zs))
    )
    return acc


def on_curve(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """y^2 == x^3 - 3x + b (affine check for parsed public keys)."""
    lhs = fp.square(y)
    x3 = fp.mul(fp.square(x), x)
    rhs = fp.add(
        fp.sub(x3, fp.mul_small(x, 3)), fp.constant_like(B, x)
    )
    return fp.eq(lhs, rhs)


__all__ = [
    "Point",
    "B",
    "GX",
    "GY",
    "N",
    "identity_like",
    "base_point_like",
    "affine_like",
    "add",
    "double",
    "select",
    "table_lookup",
    "multiples_table",
    "fixed_base_mul_comb",
    "on_curve",
]
