"""Shared limb-vector helpers for the batched field stacks.

Both GF(2^255-19) (:mod:`consensus_tpu.ops.field25519`) and the P-256 field
(:mod:`consensus_tpu.ops.field_p256`) represent elements as 32x8-bit limb
vectors; the exact sequential int32 carry normalization is identical and
lives here so a carry-semantics fix can never diverge between curves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def carry_i32(x: jnp.ndarray, limb_bits: int = 8) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential int32 carry pass over the leading (limb) axis.

    A ``lax.scan`` so the body appears once in the graph instead of one
    unrolled step per limb (freeze shows up ~10x in a verify graph via
    eq/parity checks, so unrolling was a measured compile-time cost).
    Returns ``(normalized limbs, final carry)``; negative inputs borrow
    correctly through the arithmetic right shift.
    """
    mask = (1 << limb_bits) - 1

    def step(carry, limb):
        v = limb + carry
        return v >> limb_bits, v & mask

    carry, out = jax.lax.scan(step, jnp.zeros_like(x[0]), x)
    return out, carry


__all__ = ["carry_i32"]
