"""Shared limb-vector helpers for the batched field stacks.

Both GF(2^255-19) (:mod:`consensus_tpu.ops.field25519`) and the P-256 field
(:mod:`consensus_tpu.ops.field_p256`) represent elements as 32x8-bit limb
vectors; the exact sequential int32 carry normalization is identical and
lives here so a carry-semantics fix can never diverge between curves.

This module also hosts the **field-multiplication counting shim** that makes
kernel cost models *measured* instead of estimated (BASELINE.md).  The field
stacks report every ``mul``/``square`` through :func:`note_mul` /
:func:`note_square`, weighted by how many independent field elements the op
touches (the batch lanes) and by the length of every enclosing ``lax.scan``
(:func:`counted_scan` — JAX traces a scan body once regardless of trip
count, so the weight stack is what turns a trace into an operation count).
:func:`measure_field_ops` runs a kernel under ``jax.eval_shape`` — abstract
tracing only, no compilation, no device — so a batch-512 A/B costs seconds
on CPU.  When no counter is active every hook is a cheap no-op and
``counted_scan`` degrades to ``jax.lax.scan`` exactly.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp


def carry_i32(x: jnp.ndarray, limb_bits: int = 8) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential int32 carry pass over the leading (limb) axis.

    A ``lax.scan`` so the body appears once in the graph instead of one
    unrolled step per limb (freeze shows up ~10x in a verify graph via
    eq/parity checks, so unrolling was a measured compile-time cost).
    Returns ``(normalized limbs, final carry)``; negative inputs borrow
    correctly through the arithmetic right shift.
    """
    mask = (1 << limb_bits) - 1

    def step(carry, limb):
        v = limb + carry
        return v >> limb_bits, v & mask

    carry, out = jax.lax.scan(step, jnp.zeros_like(x[0]), x)
    return out, carry


def lt_bytes(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Little-endian lexicographic ``a < b`` over byte rows.

    ``a`` is ``(n_bytes, batch)``; ``b`` is a ``(n_bytes,)`` constant (a
    modulus bound: ``S < L``, ``y < p``).  Branch-free: locate the most
    significant differing byte with an argmax over the reversed
    difference mask and read both operands there through a one-hot
    contraction (same no-gather idiom as the point-table lookups).
    Equal inputs compare False — the canonical-range checks all exclude
    the bound itself.
    """
    n = a.shape[0]
    b_col = b.astype(a.dtype)[:, None]
    diff = a != b_col  # (n, batch)
    first = jnp.argmax(diff[::-1], axis=0)  # offset of MS difference
    one_hot = (
        jnp.arange(n, dtype=jnp.int32)[:, None] == (n - 1 - first)[None]
    ).astype(a.dtype)
    a_at = (a * one_hot).sum(axis=0)
    b_at = (b_col * one_hot).sum(axis=0)
    return jnp.where(diff.any(axis=0), a_at < b_at, False)


# --------------------------------------------------------------------------
# Field-operation counting shim
# --------------------------------------------------------------------------

#: Active counters (a stack so measurements may nest) and the stack of
#: enclosing-scan trip counts.  Trace-time state only — nothing here is ever
#: captured into a compiled graph.
_COUNTERS: list["FieldOpCount"] = []
_SCAN_WEIGHTS: list[int] = []

#: One squaring costs roughly this many generic multiplications in the
#: schoolbook limb stack (the symmetric half of the product terms).
SQUARE_M_RATIO = 0.55

#: One VPU field multiplication is 32x32 = 1024 byte-level MACs; dense
#: ``dot_general`` MACs convert to mul-equivalents at this rate so the
#: MXU-vs-VPU denominator compares like with like (note_byte_muls already
#: uses the same 1024-MAC yardstick).
DOT_MACS_PER_MUL = 1024


class FieldOpCount:
    """Tally of field operations observed during one traced region.

    ``muls``/``squares`` count semantic field ops on the VPU lane;
    ``dots``/``dot_macs`` count ``dot_general`` contractions (the MXU lane
    dispatches *before* noting, so a trace records muls OR dots per mul
    site, never both); ``adds`` counts field additions/subtractions —
    cheap, but the per-kernel breakdown (satellite of ISSUE 18) wants the
    full shape of the work, not just the expensive tail.
    """

    def __init__(self) -> None:
        self.muls = 0
        self.squares = 0
        self.adds = 0
        self.dots = 0
        self.dot_macs = 0

    @property
    def m_equiv(self) -> float:
        """Generic-multiplication equivalents (1 S ~ 0.55 M; 1024 dense
        dot MACs ~ 1 M — adds are deliberately excluded, matching the
        pinned round-7 baseline semantics)."""
        return (
            self.muls
            + SQUARE_M_RATIO * self.squares
            + self.dot_macs / DOT_MACS_PER_MUL
        )

    def as_dict(self) -> dict:
        """Per-kernel breakdown for bench JSON (muls vs dot-equivalents
        vs adds), so engine PRs inherit the richer denominator for free."""
        return {
            "muls": self.muls,
            "squares": self.squares,
            "adds": self.adds,
            "dots": self.dots,
            "dot_macs": self.dot_macs,
            "dot_m_equiv": round(self.dot_macs / DOT_MACS_PER_MUL, 3),
            "m_equiv": round(self.m_equiv, 3),
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"FieldOpCount(muls={self.muls}, squares={self.squares}, "
            f"adds={self.adds}, dots={self.dots}, dot_macs={self.dot_macs})"
        )


def counting() -> bool:
    """True while at least one :func:`count_field_ops` region is active."""
    return bool(_COUNTERS)


def _note(attr: str, lanes: int) -> None:
    weight = lanes
    for trip in _SCAN_WEIGHTS:
        weight *= trip
    for counter in _COUNTERS:
        setattr(counter, attr, getattr(counter, attr) + weight)


def note_mul(lanes: int = 1) -> None:
    """Record a field multiplication over ``lanes`` independent elements."""
    if _COUNTERS:
        _note("muls", lanes)


def note_square(lanes: int = 1) -> None:
    """Record a field squaring over ``lanes`` independent elements."""
    if _COUNTERS:
        _note("squares", lanes)


def note_add(lanes: int = 1) -> None:
    """Record a field addition/subtraction over ``lanes`` elements."""
    if _COUNTERS:
        _note("adds", lanes)


def note_dot(m: int, n: int, k: int, lanes: int = 1) -> None:
    """Record a ``dot_general`` contraction of an (m, k) by (k, n) tile
    per lane.  Counted as dense MACs — the MXU does not skip structural
    zeros in a constant operand, so m*n*k is the honest per-lane cost the
    device A/B has to amortize, not the nonzero count."""
    if _COUNTERS:
        _note("dots", lanes)
        _note("dot_macs", m * n * k * lanes)


def note_byte_muls(byte_muls: int, lanes: int = 1) -> None:
    """Record byte-level multiply work in field-mul equivalents.

    The scalar stack (mod-L reduction, coefficient products) multiplies
    byte limbs outside the 32x32 schoolbook shape; 1024 byte products is
    one field mul's worth, rounded up so small stages stay visible in the
    measured cost model."""
    if _COUNTERS:
        _note("muls", max(1, (byte_muls + 1023) // 1024) * lanes)


@contextlib.contextmanager
def count_field_ops():
    """Collect field-op notes emitted while tracing inside this block."""
    counter = FieldOpCount()
    _COUNTERS.append(counter)
    try:
        yield counter
    finally:
        _COUNTERS.remove(counter)


def counted_scan(f, init, xs=None, length=None, **kwargs):
    """``jax.lax.scan`` that weights the body's field-op notes by trip count.

    JAX traces a scan body exactly once, so a naive trace-time tally would
    count a 64-iteration Horner loop as one step.  While a counter is
    active the body runs under a weight equal to the scan length; otherwise
    this is ``jax.lax.scan`` verbatim.
    """
    if not _COUNTERS:
        return jax.lax.scan(f, init, xs, length=length, **kwargs)
    if length is not None:
        trips = int(length)
    else:
        leaves = jax.tree_util.tree_leaves(xs)
        trips = int(leaves[0].shape[0])

    def weighted(carry, x):
        _SCAN_WEIGHTS.append(trips)
        try:
            return f(carry, x)
        finally:
            _SCAN_WEIGHTS.pop()

    return jax.lax.scan(weighted, init, xs, length=length, **kwargs)


def measure_field_ops(fn, *args, **kwargs) -> FieldOpCount:
    """Exact field-op count for one abstract trace of ``fn(*args)``.

    Uses ``jax.eval_shape`` — no compilation, no execution, no device — so
    counting a batch-512 verify kernel takes seconds on any host.  ``fn``
    must be the *unjitted* implementation (a cached jit would skip tracing
    and report zero).  A fresh wrapper busts eval_shape's own trace cache
    each call — without it, measuring the same fn + shapes twice (the
    MXU-vs-VPU A/B does exactly that) silently reports zeros the second
    time.
    """
    with count_field_ops() as counter:
        jax.eval_shape(lambda *a, **k: fn(*a, **k), *args, **kwargs)
    return counter


__all__ = [
    "carry_i32",
    "DOT_MACS_PER_MUL",
    "FieldOpCount",
    "SQUARE_M_RATIO",
    "count_field_ops",
    "counted_scan",
    "counting",
    "lt_bytes",
    "measure_field_ops",
    "note_add",
    "note_byte_muls",
    "note_dot",
    "note_mul",
    "note_square",
]
