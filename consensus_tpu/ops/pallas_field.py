"""Pallas TPU kernel for the GF(2^255-19) multiply: the whole schoolbook
convolution + carry-save reduction fused in VMEM.

The default compute path (:mod:`consensus_tpu.ops.field25519`) is plain jnp:
XLA already fuses the elementwise conv/fold chains well, and a per-multiply
``pallas_call`` adds launch overhead without more fusion.  This kernel is
the building block for the *next* level — fusing an entire point operation
(8 muls + adds, ~40 intermediate (32, B) arrays) into one VMEM-resident
kernel so intermediates never round-trip HBM.  It is opt-in:

    from consensus_tpu.ops import pallas_field
    out = pallas_field.mul(a, b)          # same contract as field25519.mul

Correctness is validated against the jnp path in interpret mode (CPU) by
``tests/test_crypto.py``; on TPU the same kernel lowers natively.  Batch
must be a multiple of 128 (one lane tile); the verifier's pow-2 padding
guarantees that for every batch >= 128.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from consensus_tpu.ops import field25519 as fe

LANE = 128


def _mul_kernel(a_ref, b_ref, out_ref):
    """One batch tile: full schoolbook conv + fold + weak reduction in VMEM.

    Shapes: a_ref/b_ref/out_ref are (32, tile) f32.  All arithmetic is the
    exact-integer f32 discipline of :mod:`field25519` (products < 2^19 per
    operand pair, columns < 2^24)."""
    a = a_ref[:, :]
    b = b_ref[:, :]

    # Schoolbook convolution into 63 columns (i is a trace-time constant,
    # so each accumulate is a static overlapping-window update).
    cols = jnp.zeros((2 * fe.LIMBS - 1, a.shape[1]), dtype=jnp.float32)
    for i in range(fe.LIMBS):
        cols = cols.at[i : i + fe.LIMBS].add(a[i] * b)

    # The fold + weak reduction are the shared jnp helpers — they trace
    # inside the kernel, so the opt-in path can never diverge from the
    # default one.
    out_ref[:, :] = fe._reduce_cols(cols)


@functools.partial(jax.jit, static_argnames=("interpret",))
def mul(a: jnp.ndarray, b: jnp.ndarray, *, interpret: bool = False) -> jnp.ndarray:
    """Drop-in replacement for :func:`field25519.mul` via Pallas.

    ``a``/``b``: (32, batch) f32, batch a multiple of 128.  ``interpret``
    runs the kernel in the Pallas interpreter (for CPU tests)."""
    limbs, batch = a.shape
    if batch % LANE:
        raise ValueError(f"batch {batch} must be a multiple of {LANE}")
    grid = (batch // LANE,)
    return pl.pallas_call(
        _mul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((limbs, LANE), lambda i: (0, i)),
            pl.BlockSpec((limbs, LANE), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((limbs, LANE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((limbs, batch), jnp.float32),
        interpret=interpret,
    )(a, b)


__all__ = ["mul", "LANE"]
