"""GF(2^255-19) arithmetic as batched JAX float32 limb vectors.

The building block of the TPU Ed25519 batch verifier
(:mod:`consensus_tpu.models.ed25519`), which replaces the reference's
goroutine-per-signature CPU verification (reference
internal/bft/view.go:537-541) with one data-parallel kernel.

Representation: a field element is **32 limbs x 8 bits** stored as
``float32`` of shape ``(32, *batch)`` — limbs leading, batch trailing, so
the batch axis rides the TPU's 128-wide vector lanes.  Why float32 with
tiny limbs: the VPU has no native 32-bit integer multiply (int32 muls are
emulated and ~10x slower), while f32 FMAs are native — and with 8-bit limbs
every product is <= (255+85)^2 < 2^17 and every 32-term schoolbook column
sums below 2^22, comfortably inside f32's 24-bit exact-integer window.  All
arithmetic is therefore **bit-exact**; floats are used as fast small
integers, never rounded.

Multiplication is 32 broadcast-multiplies + shifted column adds (schoolbook
convolution) followed by *parallel* carry-save passes (split with
``floor(x/256)``, which is exact and floor-semantics for negatives, so
borrows propagate like arithmetic shifts).  There are no sequential carry
chains on the hot path.

Why pure XLA and no hand-written Pallas kernel *on this lane*: the verify
graph is a ``lax.scan`` of elementwise/broadcast limb arithmetic, which
XLA already fuses into large VPU kernels; a per-field-op ``pallas_call``
only adds launch overhead (a round-2 prototype confirmed parity but no
win and was removed).  The two deferred headroom items both landed behind
``CTPU_MXU_LIMBS=1``: :mod:`consensus_tpu.ops.mxu_limbs` re-expresses the
schoolbook convolution as integer ``dot_general`` tiles for the MXU
(``mul``/``square`` below dispatch there at trace time, bit-identical
output), and :mod:`consensus_tpu.ops.pallas_scan` grew the VMEM-resident
Straus/MSM kernel that keeps the 64-step doubling chain's table and
accumulator on-chip.  Measured CPU denominators for the A/B live in
BASELINE.md ("MXU lane" section).

Normalization contract: public ops take and return *weakly reduced*
elements — |limb| <= 340 with value within (-2^250, 2^255 + 2^13), exact
mod p.  ``freeze`` (rare path: comparisons/parity) converts to int32 and
produces the canonical representative in [0, p).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from consensus_tpu.ops import limbs
from consensus_tpu.ops.limbs import carry_i32


def _note_lanes(a, b=None) -> int:
    """Independent field elements an op touches: product of the broadcast
    batch dims (everything after the leading limb axis)."""
    shape = a.shape if b is None else jnp.broadcast_shapes(a.shape, b.shape)
    lanes = 1
    for dim in shape[1:]:
        lanes *= int(dim)
    return lanes

LIMBS = 32
LIMB_BITS = 8
BASE = 256.0
INV_BASE = 1.0 / 256.0

P = 2**255 - 19
#: 2^256 mod p — the weight of limb index 32 (used to fold product columns).
FOLD = (2**256) % P  # == 38
#: 2^255 mod p — the weight of bit 255 (used to fold limb 31's top bit).
TOP_FOLD = 19
#: d of edwards25519: -121665/121666 mod p.
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
#: sqrt(-1) mod p (for decompression's second root candidate).
SQRT_M1 = pow(2, (P - 1) // 4, P)


def int_to_limbs(value: int) -> np.ndarray:
    """Python int -> one limb vector (numpy, for constants and host prep)."""
    if not 0 <= value < 2**256:
        raise ValueError("value out of limb range")
    return np.array(
        [(value >> (LIMB_BITS * i)) & 0xFF for i in range(LIMBS)], dtype=np.float32
    )


def limbs_to_int(limbs) -> int:
    """Limb vector (limbs axis first) -> Python int (host-side)."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(LIMBS))


def constant(value: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(value % P))


def _cexpand(const_limbs, like: jnp.ndarray) -> jnp.ndarray:
    """Reshape a (32,) constant so it broadcasts against (32, *batch)."""
    return jnp.reshape(jnp.asarray(const_limbs), (LIMBS,) + (1,) * (like.ndim - 1))


def constant_like(value: int, like: jnp.ndarray) -> jnp.ndarray:
    """A constant broadcast to ``like``'s shape, inheriting its sharding
    variance (``like * 0 + c`` keeps shard_map's varying-axis typing)."""
    return like * 0 + _cexpand(int_to_limbs(value % P), like)


def from_int_broadcast(value: int, batch_shape) -> jnp.ndarray:
    c = jnp.asarray(int_to_limbs(value % P)).reshape(
        (LIMBS,) + (1,) * len(tuple(batch_shape))
    )
    return jnp.broadcast_to(c, (LIMBS, *batch_shape)).astype(jnp.float32)


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((LIMBS, *batch_shape), dtype=jnp.float32)


# --- reduction ------------------------------------------------------------


def _split(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """x -> (x mod 256, floor(x / 256)); exact for |x| < 2^24, floor
    semantics so negative limbs borrow correctly."""
    hi = jnp.floor(x * INV_BASE)
    return x - hi * BASE, hi


def _relax(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry-save pass over 32 limbs: 13-bit-free split into an
    8-bit residue plus a high part shifted one limb up; the top limb's high
    part folds back at weight 2^256 ≡ 38.  No sequential dependency."""
    lo, hi = _split(x)
    rolled = jnp.concatenate([hi[31:] * FOLD, hi[:31]], axis=0)
    return lo + rolled


def _top_fold(x: jnp.ndarray) -> jnp.ndarray:
    """Fold bit 255 (limb 31's bit >= 7) back at weight 19, bounding the
    value below 2^255 + epsilon so subtraction biases stay in range."""
    high = jnp.floor(x[31] * (1.0 / 128.0))
    return jnp.concatenate(
        [(x[0] + high * TOP_FOLD)[None], x[1:31], (x[31] - high * 128.0)[None]],
        axis=0,
    )


def _weak_reduce(x: jnp.ndarray) -> jnp.ndarray:
    """Parallel weak reduction for inputs with |limb| < 2^22: three relax
    passes plus a top fold land limbs within |limb| <= 340."""
    x = _relax(x)
    x = _relax(x)
    x = _relax(x)
    return _top_fold(x)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    if limbs.counting():
        limbs.note_add(_note_lanes(a, b))
    return _weak_reduce(a + b)


# --- lazy (unreduced) ops -------------------------------------------------
# Exactness budget: mul/square require |a_limb| * |b_limb| * 32 < 2^24,
# i.e. the product of the two operands' limb bounds must stay under 2^19
# (724^2).  Weakly reduced values have |limb| <= 340, so ONE level of
# unreduced add/sub (|limb| <= 680 / 600) can feed a multiplication
# directly — the curve formulas exploit this to skip ~half their carry
# passes.  Never stack two raw levels into a multiply.


def add_raw(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b without reduction: |limb| grows to |a| + |b| (<= 680 for two
    weakly reduced inputs — still multiplication-safe)."""
    return a + b


def sub_raw(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (bias 2p) without reduction: for weakly reduced inputs the
    limbs stay within [-345, 600] — multiplication-safe."""
    return a + _cexpand(_TWO_P, a) - b


#: 2p = 2^256 - 38 fits exactly in 32 limbs (top limb 255).
_TWO_P = np.array(
    [((2 * P) >> (LIMB_BITS * i)) & 0xFF for i in range(LIMBS)], dtype=np.float32
)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # a + 2p - b stays positive for any weakly reduced a, b (< 2p each).
    if limbs.counting():
        limbs.note_add(_note_lanes(a, b))
    return _weak_reduce(a + _cexpand(_TWO_P, a) - b)


def _reduce_cols(cols: jnp.ndarray) -> jnp.ndarray:
    """(63, *batch) schoolbook columns (|col| < 2^24) -> weakly reduced."""
    lo, hi = _split(cols)
    c = jnp.concatenate([lo[:1], lo[1:] + hi[:-1], hi[-1:]], axis=0)  # width 64
    # |r| <= ~2^21.2 with one-raw-level operands (columns up to ~1.48e7,
    # hi < 2^15.9, fold x38) — inside _weak_reduce's 2^22 domain with ~1.8x
    # margin.  Do NOT widen the lazy budget without redoing this analysis.
    r = c[:LIMBS] + c[LIMBS:] * FOLD
    return _weak_reduce(r)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched field multiplication: schoolbook convolution as 32 broadcast
    multiplies + shifted adds (full-lane VPU work), then parallel folds.

    Exact while |a_limb| * |b_limb| <= 2^19 (columns sum 32 products under
    the f32 24-bit integer window) — weakly reduced inputs and one raw
    add/sub level both qualify.

    With ``CTPU_MXU_LIMBS=1`` (trace-time) this dispatches to the
    bit-identical MXU lane, which records its work as ``note_dot`` MACs —
    the dispatch sits BEFORE the ``note_mul`` so a counted trace reports
    muls or dots per site, never both."""
    from consensus_tpu.ops import mxu_limbs

    if mxu_limbs.lane_active():
        return mxu_limbs.mul25519(a, b)
    if limbs.counting():
        limbs.note_mul(_note_lanes(a, b))
    batch_pad = [(0, 0)] * (a.ndim - 1)
    terms = [
        jnp.pad(a[i] * b, [(i, LIMBS - 1 - i)] + batch_pad) for i in range(LIMBS)
    ]
    return _reduce_cols(sum(terms))


def square(a: jnp.ndarray) -> jnp.ndarray:
    """Specialized squaring: the product matrix is symmetric, so only the
    upper triangle is computed (cross terms doubled) — ~half the multiplies
    of :func:`mul`.

    Exactness requires |limb| <= 500 (2 * 500^2 * 32 < 2^24); callers with
    one-raw-level inputs (bound 680) must use ``mul(x, x)`` instead.

    The MXU lane squares via ``mul(a, a)`` — the full product columns
    equal these doubled-triangle columns as integers, so the output stays
    bit-identical."""
    from consensus_tpu.ops import mxu_limbs

    if mxu_limbs.lane_active():
        return mxu_limbs.square25519(a)
    if limbs.counting():
        limbs.note_square(_note_lanes(a))
    batch_pad = [(0, 0)] * (a.ndim - 1)
    doubled = a + a
    terms = []
    for i in range(LIMBS):
        # Diagonal a_i^2 at column 2i, doubled cross terms a_i*a_j (j > i)
        # at columns i+j — one row per i, padded to the full 63 columns so
        # the terms sum as a parallel reduction tree (a chained scatter-add
        # would serialize all 32 updates).
        row = jnp.concatenate([a[i : i + 1] * a[i], doubled[i + 1 :] * a[i]], axis=0)
        terms.append(jnp.pad(row, [(2 * i, LIMBS - 1 - i)] + batch_pad))
    return _reduce_cols(sum(terms))


_P_LIMBS_I32 = np.array(
    [(P >> (LIMB_BITS * i)) & 0xFF for i in range(LIMBS)], dtype=np.int32
)
_TWO_P_I32 = _TWO_P.astype(np.int32)


def _carry_i32(x):
    """Exact sequential int32 carry pass (freeze-only path)."""
    return carry_i32(x, LIMB_BITS)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical int32 representative in [0, p).

    Weakly reduced values may be slightly negative (borrow limbs), so bias
    by 2p first, normalize exactly, fold the top bit, then subtract p while
    the value still exceeds it.  Rare path (comparisons/parity only)."""
    x = jnp.asarray(jnp.rint(a), dtype=jnp.int32)
    x = x + jnp.reshape(
        jnp.asarray(_TWO_P_I32), (LIMBS,) + (1,) * (a.ndim - 1)
    )
    x, top = _carry_i32(x)  # value in (0, 2^256 + 2^255); top in {0, 1}
    # Fold the carry-out (weight 2^256 ≡ 38) and bit 255 back.
    x = x.at[0].add(top * FOLD)
    high = x[31] >> 7
    x = x.at[31].set(x[31] & 0x7F)
    x = x.at[0].add(high * TOP_FOLD)
    x, _ = _carry_i32(x)
    p_e = jnp.reshape(jnp.asarray(_P_LIMBS_I32), (LIMBS,) + (1,) * (a.ndim - 1))
    for _ in range(2):
        d, borrow = _carry_i32(x - p_e)
        ge_p = borrow == 0  # no negative carry out => x >= p
        x = jnp.where(ge_p[None], d, x)
    return x


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality (boolean per batch element)."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=0)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-batch-element select between limb vectors (cond shape = batch)."""
    return jnp.where(cond[None], a, b)


def pow_const(x: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """x ** exponent for a fixed public exponent, via an MSB-first
    square-and-multiply ``lax.scan`` (compiles to a rolled loop — the graph
    stays small regardless of exponent length)."""
    bits = [(exponent >> i) & 1 for i in range(exponent.bit_length())][::-1]
    bits_arr = jnp.asarray(np.array(bits, dtype=np.int32))

    def step(acc, bit):
        acc = square(acc)
        acc = select(bit == 1, mul(acc, x), acc)
        return acc, None

    # First bit is always 1: start from x to save one square+mul.
    acc, _ = limbs.counted_scan(step, x, bits_arr[1:])
    return acc


def invert(x: jnp.ndarray) -> jnp.ndarray:
    """Field inverse via Fermat (x^(p-2)); x=0 maps to 0."""
    return pow_const(x, P - 2)


def _square_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """n successive squarings as a rolled scan (one body in the graph)."""
    if n == 1:
        return square(x)
    acc, _ = limbs.counted_scan(lambda a, _: (square(a), None), x, None, length=n)
    return acc


def pow_2_252_m3(x: jnp.ndarray) -> jnp.ndarray:
    """x^(2^252 - 3) — the RFC 8032 decompression square-root exponent
    ((p-5)/8) — via the standard 2^k-1 addition-chain ladder: 251 squarings
    + 11 multiplies.  The generic binary ladder (:func:`pow_const`) pays a
    multiply per *step* (the select evaluates both branches), ~251 of them
    for this exponent — this chain is the decompression hot-path's ~14%
    saving per signature."""
    t0 = square(x)            # x^2
    t1 = _square_n(t0, 2)     # x^8
    t1 = mul(x, t1)           # x^9
    t0 = mul(t0, t1)          # x^11
    t0 = square(t0)           # x^22
    t0 = mul(t1, t0)          # x^31   = x^(2^5 - 1)
    t1 = _square_n(t0, 5)
    t0 = mul(t1, t0)          # 2^10 - 1
    t1 = _square_n(t0, 10)
    t1 = mul(t1, t0)          # 2^20 - 1
    t2 = _square_n(t1, 20)
    t1 = mul(t2, t1)          # 2^40 - 1
    t1 = _square_n(t1, 10)
    t0 = mul(t1, t0)          # 2^50 - 1
    t1 = _square_n(t0, 50)
    t1 = mul(t1, t0)          # 2^100 - 1
    t2 = _square_n(t1, 100)
    t1 = mul(t2, t1)          # 2^200 - 1
    t1 = _square_n(t1, 50)
    t0 = mul(t1, t0)          # 2^250 - 1
    t0 = _square_n(t0, 2)     # 2^252 - 4
    return mul(x, t0)         # 2^252 - 3


#: p as little-endian bytes, for the on-device canonical-encoding check.
P_BYTES_LE = np.frombuffer(P.to_bytes(32, "little"), dtype=np.uint8)


def bytes_lt_p(y_bytes: jnp.ndarray) -> jnp.ndarray:
    """On-device canonical-range check ``y < p`` over ``(32, batch)``
    little-endian byte rows — the fused engine's twin of the host-side
    lexicographic compare in ``models.ed25519._prep_compressed``."""
    return limbs.lt_bytes(
        y_bytes.astype(jnp.int32), jnp.asarray(P_BYTES_LE, dtype=jnp.int32)
    )


__all__ = [
    "LIMBS",
    "LIMB_BITS",
    "P",
    "P_BYTES_LE",
    "bytes_lt_p",
    "D",
    "D2",
    "SQRT_M1",
    "FOLD",
    "int_to_limbs",
    "limbs_to_int",
    "constant",
    "constant_like",
    "from_int_broadcast",
    "zeros_like_batch",
    "add",
    "add_raw",
    "sub",
    "sub_raw",
    "mul",
    "square",
    "freeze",
    "eq",
    "is_zero",
    "select",
    "pow_const",
    "pow_2_252_m3",
    "invert",
]
