"""Whole-scan-in-VMEM Pallas kernel for the Ed25519 Horner scan.

The deferred round-3 experiment (BASELINE.md cost model; VERDICT r4 #3):
the [k](-A) double-scalar half of the verifier is 64 steps of 4 doubles +
1 table add over (32, batch) f32 limb tensors.  Under plain XLA this is a
``lax.scan`` whose carry (a 4-coordinate extended point, 512 B/lane) and
whose per-step intermediates live wherever XLA schedules them — any HBM
round trip between steps is pure overhead, since the arithmetic itself is
lane-local VPU work.  This kernel pins ONE batch tile's entire scan in
VMEM: the 9-entry per-batch table (~590 KB at tile 128) is built in
registers/VMEM, the 64-step loop runs to completion, and only the final
accumulator returns to HBM — HBM traffic becomes one read of the inputs
plus one write of the result, independent of step count.

The field/point arithmetic is the SAME code the XLA path uses
(:mod:`consensus_tpu.ops.field25519`, :mod:`consensus_tpu.ops.ed25519`) —
Pallas kernel bodies trace ordinary jax.numpy, so both paths share one
bit-exact implementation and the A/B compares *scheduling*, not math.

Correctness is CI-gated in interpret mode (tests/test_pallas_scan.py);
the Mosaic lowering + speed verdict needs the real device — the suite
records ``env CTPU_PALLAS_SCAN=1 python bench.py`` next to the XLA
number (benchmarks/run_device_suite.sh, priority 5).  The scan stays
opt-in (``CTPU_PALLAS_SCAN=1``) until that A/B proves a win.

This module also hosts the **MXU-lane Straus/MSM kernel**
(:func:`straus_msm`, gated on ``CTPU_MXU_LIMBS=1``): the randomized batch
verifier's shared-doubling multi-scalar multiplication with the TWO
9-entry window tables (A and R) and the running-sum accumulator resident
in VMEM across the whole 64-window chain.  It reuses this file's
constant-injection machinery; kernel bodies trace under
``mxu_limbs.suppress_mxu_limbs()`` so no ``dot_general`` reaches Mosaic —
inside a kernel the VPU schoolbook is the validated shape, and the MXU
lane's field contractions apply to the XLA-scheduled remainder of the
graph instead.

Reference context: this accelerates the commit-signature sweep the
reference runs as a sequential per-goroutine CPU loop
(reference internal/bft/view.go:537-541).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from consensus_tpu.ops import ed25519 as ed
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops import field_p256 as fp
from consensus_tpu.ops import mxu_limbs
from consensus_tpu.ops import p256

#: Lane tile: the TPU vector lane width is 128; larger tiles amortize the
#: per-program table build (7 point adds) over more lanes at the cost of
#: VMEM (~4.6 KB/lane for the table).
DEFAULT_TILE = 128

_TABLE = 9  # |signed digit| <= 8 -> multiples 0..8 of the variable point
_WINDOWS = 64
_WINDOWS_P256 = 65  # incl. the signed-recoding carry window


#: Set True around traces where pallas_call must not appear (the
#: shard_map multi-chip path — pallas-under-shard_map is unvalidated and
#: per-shard batch sizes would change the tiling decision anyway).
_SUPPRESSED = False


@contextlib.contextmanager
def suppress_pallas_scan():
    """Disable the opt-in Pallas scan for traces inside this context
    (used by the sharded verifiers; see :func:`scan_config`)."""
    global _SUPPRESSED
    prev = _SUPPRESSED
    _SUPPRESSED = True
    try:
        yield
    finally:
        _SUPPRESSED = prev


def scan_config(batch: int):
    """(tile, interpret) when the opt-in Pallas scan should be used for a
    batch of this (static, trace-time) size, else None.

    Opt-in via ``CTPU_PALLAS_SCAN=1`` until the on-device A/B proves a
    win (VERDICT r4 #3).  Read per trace, so a fresh process controls it
    with the environment; already-compiled shapes keep their path.

    A batch that cannot tile evenly under the explicit opt-in is an
    ERROR, not a silent XLA fallback — a fallback would let the A/B
    record a pure-XLA number under the pallas metric key and read as
    "no difference" while the kernel never ran."""
    if os.environ.get("CTPU_PALLAS_SCAN", "") != "1" or _SUPPRESSED:
        return None
    tile = int(os.environ.get("CTPU_PALLAS_TILE", "0")) or None
    if tile is None:
        tile = DEFAULT_TILE if batch >= DEFAULT_TILE else batch
    if batch % tile != 0:
        raise ValueError(
            f"CTPU_PALLAS_SCAN=1 but batch {batch} does not tile by "
            f"{tile}; fix CTPU_PALLAS_TILE or pad the batch — refusing a "
            "silent XLA fallback that would invalidate the A/B"
        )
    # Interpret mode on CPU backends: Mosaic is TPU-only; interpret keeps
    # the CI parity gate runnable everywhere.
    return tile, jax.default_backend() == "cpu"


def _const_bank_np() -> np.ndarray:
    """The three (32,) field constants the point formulas reach for —
    1 (identity coords), d2 (the add formula), and 2p (subtraction bias).
    Pallas forbids captured array constants in kernel bodies, so they ride
    in as one (3, 32) input instead."""
    return np.stack(
        [fe.int_to_limbs(1), fe.int_to_limbs(fe.D2), fe._TWO_P.copy()]
    ).astype(np.float32)


#: Serializes the monkeypatch windows below: tracing swaps module-level
#: globals (fe.constant_like / fp._SOLINAS_M / ...), so two threads tracing
#: concurrently — or one tracing the ed25519 kernel while another traces
#: P-256 — would see each other's patched globals or restore stale ones.
#: Held only during tracing (first call per shape), never on cached
#: executions.
_INJECT_LOCK = threading.RLock()


@contextlib.contextmanager
def _inject_consts(bank: jnp.ndarray):
    """During kernel tracing, point field25519's constant plumbing at the
    in-kernel bank rows: ``constant_like`` looks its value up, and the 2p
    subtraction bias global becomes the traced row.  Restored on exit —
    the XLA path keeps its baked numpy constants.  Serialized by
    ``_INJECT_LOCK``: the patch window mutates module globals."""
    _INJECT_LOCK.acquire()
    lookup = {1: bank[0], fe.D2: bank[1]}
    orig_constant_like = fe.constant_like
    orig_two_p = fe._TWO_P

    def traced_constant_like(value: int, like: jnp.ndarray) -> jnp.ndarray:
        row = lookup.get(value % fe.P)
        if row is None:  # pragma: no cover — scan body only uses 1 and d2
            raise ValueError(
                f"pallas scan body needs constant {value} not in the bank"
            )
        return like * 0 + jnp.reshape(row, (fe.LIMBS,) + (1,) * (like.ndim - 1))

    fe.constant_like = traced_constant_like
    fe._TWO_P = bank[2]
    try:
        # Kernel bodies must trace the VPU schoolbook even when the process
        # runs the MXU lane: a dot_general inside a Mosaic kernel is
        # unvalidated lowering, and the injection window IS the kernel
        # trace (serialized by _INJECT_LOCK, so the global flip is safe).
        with mxu_limbs.suppress_mxu_limbs():
            yield
    finally:
        fe.constant_like = orig_constant_like
        fe._TWO_P = orig_two_p
        _INJECT_LOCK.release()


def _scan_kernel(consts_ref, kd_ref, ax_ref, ay_ref, az_ref, at_ref,
                 ox_ref, oy_ref, oz_ref, ot_ref):
    """One batch tile: build the 9-entry table, run all 64 Horner steps,
    write the accumulator.  Everything between the refs lives in VMEM."""
    neg_a = ed.Point(ax_ref[...], ay_ref[...], az_ref[...], at_ref[...])
    kd = kd_ref[...]  # (64, tile) int32, digit + 8, MSB window first

    with _inject_consts(consts_ref[...]):
        # j * (-A) for j = 0..8 as an unrolled Python list — each entry is
        # a VMEM-resident value, and the adds trace inline (9 is small).
        table = [ed.identity_like(neg_a.x), neg_a]
        for _ in range(_TABLE - 2):
            table.append(ed.add(table[-1], neg_a))

        def lookup(d_abs: jnp.ndarray) -> ed.Point:
            # One-hot contraction over the 9 entries (no gather): d_abs is
            # (1, tile); each mask broadcasts against (32, tile) coords.
            # Deliberately NOT ed.table_lookup: that helper wants rank-3
            # stacked coords, and this kernel stays rank-2 end-to-end to
            # minimize Mosaic lowering risk (the whole experiment).  If
            # table_lookup's semantics ever change, re-sync here.
            coords = []
            for sel in ("x", "y", "z", "t"):
                acc = None
                for j, entry in enumerate(table):
                    mask = (d_abs == j).astype(jnp.float32)  # (1, tile)
                    term = getattr(entry, sel) * mask
                    acc = term if acc is None else acc + term
                coords.append(acc)
            return ed.Point(*coords)

        def step(i, carry):
            acc = ed.Point(*carry)
            d = jax.lax.dynamic_slice_in_dim(kd, i, 1, axis=0) - 8  # (1, tile)
            for _ in range(3):
                acc = ed.double(acc, need_t=False)
            acc = ed.double(acc)
            q = lookup(jnp.abs(d))
            q = ed.select(d[0] < 0, ed.negate(q), q)
            acc = ed.add(acc, q)
            return (acc.x, acc.y, acc.z, acc.t)

        ident = ed.identity_like(neg_a.x)
        x, y, z, t = jax.lax.fori_loop(
            0, _WINDOWS, step, (ident.x, ident.y, ident.z, ident.t)
        )
    ox_ref[...] = x
    oy_ref[...] = y
    oz_ref[...] = z
    ot_ref[...] = t


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def horner_scan(
    neg_a_x: jnp.ndarray,   # (32, batch) f32 — the four (-A) coordinates
    neg_a_y: jnp.ndarray,
    neg_a_z: jnp.ndarray,
    neg_a_t: jnp.ndarray,
    k_digits: jnp.ndarray,  # (64, batch) int32, digit + 8, MSB first
    *,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> ed.Point:
    """[k](-A) for the whole batch via one Pallas grid over batch tiles.

    Drop-in for the ``lax.scan`` half of
    :func:`consensus_tpu.models.ed25519.verify_impl`; the fixed-base comb
    and the final add/compare stay in XLA (the comb's constant-table
    lookups are MXU matmuls — already where they belong).
    """
    batch = neg_a_x.shape[-1]
    if batch % tile != 0:
        raise ValueError(f"batch {batch} not divisible by tile {tile}")
    grid = (batch // tile,)
    consts_spec = pl.BlockSpec((3, fe.LIMBS), lambda i: (0, 0))
    coord_spec = pl.BlockSpec((fe.LIMBS, tile), lambda i: (0, i))
    digit_spec = pl.BlockSpec((_WINDOWS, tile), lambda i: (0, i))
    out_shape = jax.ShapeDtypeStruct((fe.LIMBS, batch), jnp.float32)
    x, y, z, t = pl.pallas_call(
        _scan_kernel,
        grid=grid,
        in_specs=[consts_spec, digit_spec,
                  coord_spec, coord_spec, coord_spec, coord_spec],
        out_specs=[coord_spec] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(
        jnp.asarray(_const_bank_np()),
        k_digits.astype(jnp.int32),
        neg_a_x, neg_a_y, neg_a_z, neg_a_t,
    )
    return ed.Point(x=x, y=y, z=z, t=t)


# --- P-256 variant ----------------------------------------------------------


def _const_bank_p256_np() -> np.ndarray:
    """(2, 32) bank: the field constants the P-256 formulas reach for —
    1 (identity/affine z) and the curve b (add/double)."""
    return np.stack(
        [fp.int_to_limbs(1), fp.int_to_limbs(p256.B)]
    ).astype(np.float32)


@contextlib.contextmanager
def _inject_consts_p256(bank: jnp.ndarray, solinas: jnp.ndarray,
                        bias: jnp.ndarray):
    """P-256 analogue of :func:`_inject_consts`: the Solinas reduction
    matrix (every mul/square/add), the signed subtraction bias, and the
    value constants become traced kernel inputs for the duration.
    Serialized by the shared ``_INJECT_LOCK``."""
    _INJECT_LOCK.acquire()
    lookup = {1: bank[0], p256.B % fp.P: bank[1]}
    orig_constant_like = fp.constant_like
    orig_solinas = fp._SOLINAS_M
    orig_bias = fp._BIAS

    def traced_constant_like(value: int, like: jnp.ndarray) -> jnp.ndarray:
        row = lookup.get(value % fp.P)
        if row is None:  # pragma: no cover — scan body only uses 1 and b
            raise ValueError(
                f"pallas p256 scan body needs constant {value} not in bank"
            )
        return like * 0 + jnp.reshape(row, (fp.LIMBS,) + (1,) * (like.ndim - 1))

    fp.constant_like = traced_constant_like
    fp._SOLINAS_M = solinas
    fp._BIAS = bias
    try:
        with mxu_limbs.suppress_mxu_limbs():  # see _inject_consts
            yield
    finally:
        fp.constant_like = orig_constant_like
        fp._SOLINAS_M = orig_solinas
        fp._BIAS = orig_bias
        _INJECT_LOCK.release()


def _scan_kernel_p256(consts_ref, solinas_ref, bias_ref, kd_ref,
                      qx_ref, qy_ref, ox_ref, oy_ref, oz_ref):
    """One batch tile of the [u2]Q Horner scan: 9-entry table + 65 windows
    (incl. the recoding carry), all intermediates in VMEM."""
    kd = kd_ref[...]  # (65, tile) int32, digit + 8, MSB window first
    with _inject_consts_p256(
        consts_ref[...], solinas_ref[...], bias_ref[0]
    ):
        q = p256.affine_like(qx_ref[...], qy_ref[...])
        table = [p256.identity_like(q.x), q]
        for _ in range(_TABLE - 2):
            table.append(p256.add(table[-1], q))

        def lookup(d_abs: jnp.ndarray) -> p256.Point:
            # Rank-2-only one-hot contraction (see the ed25519 kernel's
            # note on Mosaic lowering risk).
            coords = []
            for sel in ("x", "y", "z"):
                acc = None
                for j, entry in enumerate(table):
                    mask = (d_abs == j).astype(jnp.float32)  # (1, tile)
                    term = getattr(entry, sel) * mask
                    acc = term if acc is None else acc + term
                coords.append(acc)
            return p256.Point(*coords)

        def step(i, carry):
            acc = p256.Point(*carry)
            d = jax.lax.dynamic_slice_in_dim(kd, i, 1, axis=0) - 8
            for _ in range(4):
                acc = p256.double(acc)
            t = lookup(jnp.abs(d))
            t = p256.select(d[0] < 0, p256.negate(t), t)
            acc = p256.add(acc, t)
            return (acc.x, acc.y, acc.z)

        ident = p256.identity_like(q.x)
        x, y, z = jax.lax.fori_loop(
            0, _WINDOWS_P256, step, (ident.x, ident.y, ident.z)
        )
    ox_ref[...] = x
    oy_ref[...] = y
    oz_ref[...] = z


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def horner_scan_p256(
    qx: jnp.ndarray,        # (32, batch) f32 — Q affine coordinates
    qy: jnp.ndarray,
    u2_digits: jnp.ndarray, # (65, batch) int32, digit + 8, MSB first
    *,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> p256.Point:
    """[u2]Q for the whole batch — the P-256 counterpart of
    :func:`horner_scan` (the [u1]G comb and the x ≡ r check stay in XLA).
    """
    batch = qx.shape[-1]
    if batch % tile != 0:
        raise ValueError(f"batch {batch} not divisible by tile {tile}")
    grid = (batch // tile,)
    consts_spec = pl.BlockSpec((2, fp.LIMBS), lambda i: (0, 0))
    solinas_spec = pl.BlockSpec(fp._SOLINAS_M.shape, lambda i: (0, 0))
    bias_spec = pl.BlockSpec((1, fp.LIMBS), lambda i: (0, 0))
    coord_spec = pl.BlockSpec((fp.LIMBS, tile), lambda i: (0, i))
    digit_spec = pl.BlockSpec((_WINDOWS_P256, tile), lambda i: (0, i))
    out_shape = jax.ShapeDtypeStruct((fp.LIMBS, batch), jnp.float32)
    x, y, z = pl.pallas_call(
        _scan_kernel_p256,
        grid=grid,
        in_specs=[consts_spec, solinas_spec, bias_spec, digit_spec,
                  coord_spec, coord_spec],
        out_specs=[coord_spec] * 3,
        out_shape=[out_shape] * 3,
        interpret=interpret,
    )(
        jnp.asarray(_const_bank_p256_np()),
        jnp.asarray(fp._SOLINAS_M, dtype=jnp.float32),
        jnp.asarray(fp._get_bias(), dtype=jnp.float32)[None],
        u2_digits.astype(jnp.int32),
        qx, qy,
    )
    return p256.Point(x=x, y=y, z=z)


# --- MXU-lane Straus/MSM kernel (CTPU_MXU_LIMBS=1) --------------------------


def msm_config(batch: int):
    """(tile, interpret) when the VMEM-resident Straus/MSM kernel should
    replace the XLA scan inside :func:`ed.straus_shared_msm`, else None.

    Rides the ``CTPU_MXU_LIMBS=1`` lane (ISSUE 18 tentpole b) — the MSM
    kernel is the VMEM half of the MXU bet, so one flag A/Bs both; opt
    back out of just the kernel with ``CTPU_MXU_MSM=0`` (e.g. to isolate
    the field-contraction win, or after a Mosaic lowering failure —
    record the failure in BASELINE.md, don't let it read as "no
    difference").  Suppression (:func:`suppress_pallas_scan`) wins: the
    sharded verifiers trace under it, so the mesh lanes keep the plain
    XLA MSM while the MXU *field* lane stays active under shard_map.

    Same no-silent-fallback contract as :func:`scan_config`: a batch that
    cannot tile under the explicit opt-in raises."""
    if not mxu_limbs.lane_active() or _SUPPRESSED:
        return None
    if os.environ.get("CTPU_MXU_MSM", "") == "0":
        return None
    tile = int(os.environ.get("CTPU_MXU_MSM_TILE", "0")) or None
    if tile is None:
        tile = DEFAULT_TILE if batch >= DEFAULT_TILE else batch
    if batch % tile != 0:
        raise ValueError(
            f"CTPU_MXU_LIMBS=1 selects the VMEM MSM kernel but batch "
            f"{batch} does not tile by {tile}; fix CTPU_MXU_MSM_TILE or "
            "pad the batch — refusing a silent XLA fallback that would "
            "invalidate the A/B (CTPU_MXU_MSM=0 opts out explicitly)"
        )
    return tile, jax.default_backend() == "cpu"


def _msm_kernel(n_low, consts_ref, zk_ref, z_ref,
                ax_ref, ay_ref, az_ref, at_ref,
                rx_ref, ry_ref, rz_ref, rt_ref,
                ox_ref, oy_ref, oz_ref, ot_ref):
    """One batch tile's full shared-doubling MSM: rebuild both 9-entry
    window tables in VMEM, run all 64 windows (``64 - n_low`` A-only, then
    ``n_low`` combined), reduce the tile to ONE partial-sum point.

    The in-kernel tables come from 7 sequential adds off the base points
    (table entry 1), not :func:`ed.multiples_table9`'s doubling-optimized
    build — different *projective representatives* of the same group
    elements, which is fine: per-tile partials add by linearity and the
    engines' verdict checks (``is_identity``, ``equal``) are invariant
    under projective scaling, so verdicts stay byte-identical to the XLA
    lane (the parity gate tests/test_mxu_limbs.py pins exactly that)."""
    zk = zk_ref[...]  # (64, tile) int32, digit + 8, MSB window first
    zz = z_ref[...]   # (n_low, tile)
    n_high = _WINDOWS - n_low
    with _inject_consts(consts_ref[...]):
        a1 = ed.Point(ax_ref[...], ay_ref[...], az_ref[...], at_ref[...])
        r1 = ed.Point(rx_ref[...], ry_ref[...], rz_ref[...], rt_ref[...])

        def build_table(p):
            tab = [ed.identity_like(p.x), p]
            for _ in range(_TABLE - 2):
                tab.append(ed.add(tab[-1], p))
            return tab

        a_tab = build_table(a1)
        r_tab = build_table(r1)

        def lookup(table, d):  # d: (1, tile) signed digit
            # Rank-2-only one-hot contraction (see _scan_kernel's note on
            # Mosaic lowering risk).
            coords = []
            for sel in ("x", "y", "z", "t"):
                acc = None
                for j, entry in enumerate(table):
                    mask = (jnp.abs(d) == j).astype(jnp.float32)
                    term = getattr(entry, sel) * mask
                    acc = term if acc is None else acc + term
                coords.append(acc)
            q = ed.Point(*coords)
            return ed.select(d[0] < 0, ed.negate(q), q)

        def fold(acc, contrib):
            for _ in range(3):
                acc = ed.double(acc, need_t=False)
            acc = ed.double(acc)  # materialize T for the add
            return ed.add(acc, ed.batch_sum(contrib))

        def step_high(i, carry):
            acc = ed.Point(*carry)
            d = jax.lax.dynamic_slice_in_dim(zk, i, 1, axis=0) - 8
            acc = fold(acc, lookup(a_tab, d))
            return (acc.x, acc.y, acc.z, acc.t)

        def step_low(w, carry):
            acc = ed.Point(*carry)
            dzk = jax.lax.dynamic_slice_in_dim(zk, n_high + w, 1, axis=0) - 8
            dz = jax.lax.dynamic_slice_in_dim(zz, w, 1, axis=0) - 8
            contrib = ed.add(lookup(a_tab, dzk), lookup(r_tab, dz))
            acc = fold(acc, contrib)
            return (acc.x, acc.y, acc.z, acc.t)

        ident = ed.identity_like(a1.x[..., :1])  # (32, 1) accumulator
        carry = (ident.x, ident.y, ident.z, ident.t)
        carry = jax.lax.fori_loop(0, n_high, step_high, carry)
        x, y, z, t = jax.lax.fori_loop(0, n_low, step_low, carry)
    ox_ref[...] = x
    oy_ref[...] = y
    oz_ref[...] = z
    ot_ref[...] = t


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def straus_msm(
    ax: jnp.ndarray,        # (32, batch) f32 — the negated A base points
    ay: jnp.ndarray,
    az: jnp.ndarray,
    at: jnp.ndarray,
    rx: jnp.ndarray,        # (32, batch) f32 — the R base points
    ry: jnp.ndarray,
    rz: jnp.ndarray,
    rt: jnp.ndarray,
    zk_digits: jnp.ndarray,  # (64, batch), digit + 8, MSB window first
    z_digits: jnp.ndarray,   # (Wz, batch), digit + 8, MSB window first
    *,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> ed.Point:
    """Σᵢ [zkᵢ]Aᵢ' + Σᵢ [zᵢ]Rᵢ' with the doubling chain, both window
    tables, and the accumulator VMEM-resident per batch tile.

    Each grid program pays its own 64-window doubling chain on a (32, 1)
    accumulator — ``batch/tile`` chains total vs the XLA lane's single
    chain.  The chain was already amortized noise at batch 512 (~256
    doubles against ~34k lookup/add muls); what the kernel buys is the
    scan carry, tables, and per-window intermediates never touching HBM.
    Per-tile partial sums come back and one log-depth :func:`ed.batch_sum`
    joins them."""
    batch = ax.shape[-1]
    if batch % tile != 0:
        raise ValueError(f"batch {batch} not divisible by tile {tile}")
    n_low = z_digits.shape[0]
    grid = (batch // tile,)
    consts_spec = pl.BlockSpec((3, fe.LIMBS), lambda i: (0, 0))
    coord_spec = pl.BlockSpec((fe.LIMBS, tile), lambda i: (0, i))
    zk_spec = pl.BlockSpec((_WINDOWS, tile), lambda i: (0, i))
    z_spec = pl.BlockSpec((n_low, tile), lambda i: (0, i))
    part_spec = pl.BlockSpec((fe.LIMBS, 1), lambda i: (0, i))
    part_shape = jax.ShapeDtypeStruct((fe.LIMBS, batch // tile), jnp.float32)
    x, y, z, t = pl.pallas_call(
        functools.partial(_msm_kernel, n_low),
        grid=grid,
        in_specs=[consts_spec, zk_spec, z_spec] + [coord_spec] * 8,
        out_specs=[part_spec] * 4,
        out_shape=[part_shape] * 4,
        interpret=interpret,
    )(
        jnp.asarray(_const_bank_np()),
        zk_digits.astype(jnp.int32),
        z_digits.astype(jnp.int32),
        ax, ay, az, at, rx, ry, rz, rt,
    )
    return ed.batch_sum(ed.Point(x=x, y=y, z=z, t=t))


__all__ = [
    "horner_scan",
    "horner_scan_p256",
    "msm_config",
    "scan_config",
    "straus_msm",
    "suppress_pallas_scan",
    "DEFAULT_TILE",
]
