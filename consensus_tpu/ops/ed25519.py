"""edwards25519 group operations on batched limb vectors.

Extended homogeneous coordinates (X : Y : Z : T) with x = X/Z, y = Y/Z,
T = XY/Z on the a = -1 twisted Edwards curve -x^2 + y^2 = 1 + d x^2 y^2.
Formulas: add-2008-hwcd-3 (8M) and dbl-2008-hwcd (4M + 4S) — complete for
this curve, so a single code path covers identity/doubling/negatives and
the double-scalar-mult scan needs no data-dependent branches (every step is
double + two selected adds of constant shape, exactly what XLA wants).

Point decompression (RFC 8032 §5.1.3) runs on-device too: the square root
is a fixed-exponent ``pow_const`` chain, so a batch of compressed keys and
R points decompresses in two scans — no per-element host math.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax.numpy as jnp

from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops import limbs

# Base point of edwards25519 (RFC 8032).
_BY = (4 * pow(5, fe.P - 2, fe.P)) % fe.P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202


class Point(NamedTuple):
    """Batched point in extended coordinates; each field is (20, *batch) int32."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(batch_shape) -> Point:
    return Point(
        x=fe.zeros_like_batch(batch_shape),
        y=fe.from_int_broadcast(1, batch_shape),
        z=fe.from_int_broadcast(1, batch_shape),
        t=fe.zeros_like_batch(batch_shape),
    )


def base_point(batch_shape) -> Point:
    return Point(
        x=fe.from_int_broadcast(_BX, batch_shape),
        y=fe.from_int_broadcast(_BY, batch_shape),
        z=fe.from_int_broadcast(1, batch_shape),
        t=fe.from_int_broadcast(_BX * _BY % fe.P, batch_shape),
    )


def identity_like(ref: jnp.ndarray) -> Point:
    """Identity point inheriting ``ref``'s (20, *batch) shape *and* sharding
    variance — required as a scan carry under ``shard_map`` (a broadcast
    constant would be 'unvarying' and fail the carry type check)."""
    return Point(
        x=ref * 0,
        y=fe.constant_like(1, ref),
        z=fe.constant_like(1, ref),
        t=ref * 0,
    )


def base_point_like(ref: jnp.ndarray) -> Point:
    return Point(
        x=fe.constant_like(_BX, ref),
        y=fe.constant_like(_BY, ref),
        z=fe.constant_like(1, ref),
        t=fe.constant_like(_BX * _BY % fe.P, ref),
    )


def negate(p: Point) -> Point:
    zero = p.x * 0
    return Point(x=fe.sub(zero, p.x), y=p.y, z=p.z, t=fe.sub(zero, p.t))


_D2 = fe.D2


def add(p: Point, q: Point) -> Point:
    """add-2008-hwcd-3: 8M + 1 constant mul.

    Every intermediate add/sub stays *unreduced* (one raw level, limb bound
    600/680) and feeds straight into a multiplication — all operand-bound
    products stay under the 2^19 exactness budget, so the formula needs no
    carry passes outside the multiplies themselves."""
    a = fe.mul(fe.sub_raw(p.y, p.x), fe.sub_raw(q.y, q.x))
    b = fe.mul(fe.add_raw(p.y, p.x), fe.add_raw(q.y, q.x))
    c = fe.mul(fe.mul(p.t, fe.constant_like(_D2, p.t)), q.t)
    d = fe.mul(fe.add_raw(p.z, p.z), q.z)
    e = fe.sub_raw(b, a)
    f = fe.sub_raw(d, c)
    g = fe.add_raw(d, c)
    h = fe.add_raw(b, a)
    return Point(x=fe.mul(e, f), y=fe.mul(g, h), z=fe.mul(f, g), t=fe.mul(e, h))


def double(p: Point, *, need_t: bool = True) -> Point:
    """dbl-2008-hwcd: 4M + 4S (3M + 4S with ``need_t=False`` — the T input
    is never read by doubling, so runs of doubles skip producing it).

    Lazy-reduction layout: A/B/ZZ use the half-cost specialized squaring
    (inputs weakly reduced), C/H/G/XY stay raw; only E and F — whose raw
    bounds would overflow the multiply budget — get reduced."""
    a = fe.square(p.x)
    b = fe.square(p.y)
    zz = fe.square(p.z)
    c = fe.add_raw(zz, zz)          # <= 680
    h = fe.add_raw(a, b)            # <= 680
    xy = fe.add_raw(p.x, p.y)       # <= 680: square() bound is 500 -> mul
    e = fe.sub(h, fe.mul(xy, xy))   # reduced: raw h - weak square
    g = fe.sub_raw(a, b)            # <= 600
    f = fe.add(c, g)                # reduced: 680 + 600 would exceed 724
    t = fe.mul(e, h) if need_t else p.t
    return Point(x=fe.mul(e, f), y=fe.mul(g, h), z=fe.mul(f, g), t=t)


def select(cond: jnp.ndarray, p: Point, q: Point) -> Point:
    """Per-element point select (cond shape = batch)."""
    return Point(
        x=fe.select(cond, p.x, q.x),
        y=fe.select(cond, p.y, q.y),
        z=fe.select(cond, p.z, q.z),
        t=fe.select(cond, p.t, q.t),
    )


def conditional_add(p: Point, q: Point, bit: jnp.ndarray) -> Point:
    """p + q where bit is set, else p — constant work either way."""
    return select(bit == 1, add(p, q), p)


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> tuple[Point, jnp.ndarray]:
    """Recover (x, y) from a compressed point's y limbs + x sign bit.

    Returns (point with Z=1, valid mask).  RFC 8032 §5.1.3: x^2 = (y^2-1) /
    (d y^2 + 1); candidate root x = u v^3 (u v^7)^((p-5)/8), fixed up by
    sqrt(-1) when v x^2 == -u, rejected when neither matches.
    """
    one = fe.constant_like(1, y_limbs)
    y2 = fe.square(y_limbs)
    u = fe.sub(y2, one)
    v = fe.add(fe.mul(fe.constant_like(fe.D, y_limbs), y2), one)

    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_2_252_m3(fe.mul(u, v7)))

    vx2 = fe.mul(v, fe.square(x))
    root_ok = fe.eq(vx2, u)
    neg_u = fe.sub(u * 0, u)
    root_neg = fe.eq(vx2, neg_u)
    x_fixed = fe.mul(x, fe.constant_like(fe.SQRT_M1, y_limbs))
    x = fe.select(root_neg, x_fixed, x)
    valid = root_ok | root_neg

    x_frozen = fe.freeze(x)
    x_is_zero = jnp.all(x_frozen == 0, axis=0)
    # x = 0 with sign bit set is invalid; u = 0 with x = 0 is the valid y=±1.
    valid = valid & ~(x_is_zero & (sign == 1))
    # Match the requested sign: x and p - x have opposite parities.
    parity = x_frozen[0] & 1
    x = fe.select((parity != sign) & ~x_is_zero, fe.sub(x * 0, x), x)

    return Point(x=x, y=y_limbs, z=one, t=fe.mul(x, y_limbs)), valid


def equal(p: Point, q: Point) -> jnp.ndarray:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    return fe.eq(fe.mul(p.x, q.z), fe.mul(q.x, p.z)) & fe.eq(
        fe.mul(p.y, q.z), fe.mul(q.y, p.z)
    )


def is_identity(p: Point) -> jnp.ndarray:
    """True where p is the neutral element: X = 0 and Y = Z.

    Complete for curve points — the only points with X = 0 are (0, 1)
    (identity) and the order-2 torsion point (0, -1), and Y = Z rejects the
    latter.  No multiplies, so cheaper than :func:`equal` against identity."""
    return fe.is_zero(p.x) & fe.eq(p.y, p.z)


# --- windowed scalar-mult support -----------------------------------------


def _edwards_add_int(p1, p2):
    """Host-side integer point addition (affine) for constant-table gen."""
    x1, y1 = p1
    x2, y2 = p2
    P_, D_ = fe.P, fe.D
    denom_x = (1 + D_ * x1 * x2 * y1 * y2) % P_
    denom_y = (1 - D_ * x1 * x2 * y1 * y2) % P_
    x3 = (x1 * y2 + x2 * y1) * pow(denom_x, P_ - 2, P_) % P_
    y3 = (y1 * y2 + x1 * x2) * pow(denom_y, P_ - 2, P_) % P_
    return x3, y3


def base_point_table_ints(size: int = 16) -> list[tuple[int, int]]:
    """Affine (x, y) for j*B, j = 0..size-1 (identity first)."""
    table = [(0, 1)]
    for _ in range(size - 1):
        table.append(_edwards_add_int(table[-1], (_BX, _BY)))
    return table


_COMB_WINDOWS = 32
_COMB_BITS = 8


@functools.lru_cache(maxsize=1)
def _comb_table_np() -> tuple["np.ndarray", "np.ndarray", "np.ndarray"]:
    """Fixed-base comb: affine (x, y, t=xy) limb arrays of shape
    (32 windows, 256 entries, 32 limbs) with ``T[j][d] = d * 2^(8j) * B``.

    B is a compile-time constant, so [S]B needs NO doubles and NO per-batch
    table build: 32 constant-table lookups + 31 adds, vs riding the shared
    Horner scan (64 table adds).  Host-side integer precompute (~0.2 s,
    cached for the process; the arrays are baked into the jitted graph as
    constants)."""
    import numpy as np

    xs = np.zeros((_COMB_WINDOWS, 1 << _COMB_BITS, fe.LIMBS), dtype=np.float32)
    ys = np.zeros_like(xs)
    ts = np.zeros_like(xs)
    window_base = (_BX, _BY)  # 2^(8j) * B
    for j in range(_COMB_WINDOWS):
        entry = (0, 1)  # identity
        for d in range(1 << _COMB_BITS):
            x, y = entry
            xs[j, d] = fe.int_to_limbs(x)
            ys[j, d] = fe.int_to_limbs(y)
            ts[j, d] = fe.int_to_limbs(x * y % fe.P)
            entry = _edwards_add_int(entry, window_base)
        for _ in range(_COMB_BITS):
            window_base = _edwards_add_int(window_base, window_base)
    return xs, ys, ts


def add_affine(p: Point, q_x: jnp.ndarray, q_y: jnp.ndarray, q_t: jnp.ndarray) -> Point:
    """Mixed addition p + q with q affine (Z=1, T=XY given): madd-2008-hwcd-3
    — 7M + 1 constant mul (the D = 2 Z1 Z2 multiply degenerates to a raw
    doubling of p.z).  Same lazy-reduction discipline as :func:`add`."""
    a = fe.mul(fe.sub_raw(p.y, p.x), fe.sub_raw(q_y, q_x))
    b = fe.mul(fe.add_raw(p.y, p.x), fe.add_raw(q_y, q_x))
    c = fe.mul(fe.mul(p.t, fe.constant_like(_D2, p.t)), q_t)
    d = fe.add_raw(p.z, p.z)
    e = fe.sub_raw(b, a)
    f = fe.sub_raw(d, c)
    g = fe.add_raw(d, c)
    h = fe.add_raw(b, a)
    return Point(x=fe.mul(e, f), y=fe.mul(g, h), z=fe.mul(f, g), t=fe.mul(e, h))


def fixed_base_mul_comb(s_digits8: jnp.ndarray) -> Point:
    """[S]B from 8-bit window digits ``s_digits8`` of shape (32, batch),
    LSB window first: one constant-table lookup + one mixed add per window,
    zero doubles.  The lookups are one-hot contractions against broadcast
    constants — they lower to (256 x 128) x batch matmuls (MXU work), while
    the adds stay on the VPU."""
    xs, ys, ts = _comb_table_np()
    lanes = jnp.arange(1 << _COMB_BITS, dtype=jnp.int32)[:, None]  # (256, 1)

    # Stack the per-window tables as scan inputs, limbs trailing the entry
    # axis: (32, 256, 32limbs, 1) broadcasting against (256, batch) one-hots.
    def coords(arr) -> jnp.ndarray:
        return jnp.asarray(arr)[..., None]  # (32, 256, 32, 1)

    def step(acc: Point, inputs):
        digits, tx, ty, tt = inputs  # (batch,), (256, 32, 1) x3
        oh = (digits[None] == lanes).astype(jnp.float32)  # (256, batch)

        def pick(tbl: jnp.ndarray) -> jnp.ndarray:
            return jnp.sum(tbl * oh[:, None], axis=0)  # (32, batch)

        return add_affine(acc, pick(tx), pick(ty), pick(tt)), None

    # The (32, batch)-shaped digit array doubles as the identity's shape /
    # sharding-variance reference (it IS (LIMBS, batch)).
    ref = s_digits8.astype(jnp.float32)
    acc, _ = limbs.counted_scan(
        step, identity_like(ref), (s_digits8, coords(xs), coords(ys), coords(ts))
    )
    return acc


def table_lookup(table: Point, one_hot: jnp.ndarray) -> Point:
    """Select table[digit] per batch element via a one-hot contraction —
    pure VPU multiply-adds, no gather (TPU gathers serialize).

    ``table`` coords are (W, 32, *batch) or (W, 32, 1); ``one_hot`` is
    (W, *batch) float32."""
    oh = one_hot[:, None]  # (W, 1, *batch)

    def pick(coord: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(coord * oh, axis=0)

    return Point(x=pick(table.x), y=pick(table.y), z=pick(table.z), t=pick(table.t))


def multiples_table(p: Point, size: int = 16) -> Point:
    """j*p for j = 0..size-1, coords stacked on a leading axis (identity
    first, so digit 0 adds the neutral element — the unified formulas make
    that a plain add, no branch).

    Built with a ``lax.scan`` so the add formula appears ONCE in the graph
    regardless of table size — inlining size-2 point adds was a measured
    chunk of the kernel's trace+compile time."""

    def step(prev: Point, _):
        nxt = add(prev, p)
        return nxt, nxt

    _, rest = limbs.counted_scan(step, p, None, length=size - 2)
    ident = identity_like(p.x)
    return Point(
        x=jnp.concatenate([ident.x[None], p.x[None], rest.x]),
        y=jnp.concatenate([ident.y[None], p.y[None], rest.y]),
        z=jnp.concatenate([ident.z[None], p.z[None], rest.z]),
        t=jnp.concatenate([ident.t[None], p.t[None], rest.t]),
    )


def multiples_table9(p: Point) -> Point:
    """j*p for j = 0..8 (the signed-4-bit window table), laid out exactly
    like ``multiples_table(p, 9)`` but built cheaper: even multiples come
    from doublings (4M+4S each, one of them vectorized over a trailing
    entry axis) instead of riding the sequential add chain — 3 adds + 4
    doubled lanes (43M + 16S) vs 7 adds (63M).  Worth the extra graph
    bodies in the randomized batch kernel, which builds TWO tables (A and
    R) per launch."""
    p2 = double(p)

    def step(prev: Point, _):
        nxt = add(prev, p2)
        return nxt, nxt

    # Odd chain 3p, 5p, 7p: one add body in the graph.
    _, odd = limbs.counted_scan(step, p, None, length=3)
    p3 = Point(*(c[0] for c in odd))
    p5 = Point(*(c[1] for c in odd))
    p7 = Point(*(c[2] for c in odd))
    # 4p, 6p = one double of (2p, 3p) stacked on a trailing entry axis.
    pair = double(Point(*(jnp.stack([a, b], axis=-1) for a, b in zip(p2, p3))))
    p4 = Point(*(c[..., 0] for c in pair))
    p6 = Point(*(c[..., 1] for c in pair))
    p8 = double(p4)
    entries = [identity_like(p.x), p, p2, p3, p4, p5, p6, p7, p8]
    return Point(
        *(
            jnp.concatenate([getattr(q, coord)[None] for q in entries])
            for coord in ("x", "y", "z", "t")
        )
    )


# --- shared-doubling batch multi-scalar multiplication --------------------


def batch_sum(p: Point) -> Point:
    """Sum a point batch down to batch 1 over the trailing axis.

    A binary halving tree: every level is ONE vectorized add over half the
    remaining lanes (odd widths carry their last lane to the next level), so
    n lanes cost n-1 adds in log2(n) full-width ops — the reduction shape
    the VPU wants, vs a sequential fold's n dependent adds."""
    n = p.x.shape[-1]

    def half_slice(coord: jnp.ndarray, lo: int, hi: int) -> jnp.ndarray:
        return coord[..., lo:hi]

    while n > 1:
        half = n // 2
        head = add(
            Point(*(half_slice(c, 0, half) for c in p)),
            Point(*(half_slice(c, half, 2 * half) for c in p)),
        )
        if n % 2:
            p = Point(
                *(
                    jnp.concatenate([hc, c[..., 2 * half :]], axis=-1)
                    for hc, c in zip(head, p)
                )
            )
        else:
            p = head
        n = half + (n % 2)
    return p


def _signed_window_contribution(table: Point, digits_row: jnp.ndarray) -> Point:
    """Per-lane table[|d|] with sign applied, from one row of encoded
    signed-4-bit digits (stored as d + 8, so 8 means digit 0 -> identity)."""
    size = table.x.shape[0]
    lanes = jnp.arange(size, dtype=jnp.int32)[:, None]
    d = digits_row.astype(jnp.int32) - 8
    oh = (jnp.abs(d)[None] == lanes).astype(jnp.float32)
    picked = table_lookup(table, oh)
    return select(d < 0, negate(picked), picked)


def straus_shared_msm(
    a_table: Point,
    r_table: Point,
    zk_digits: jnp.ndarray,
    z_digits: jnp.ndarray,
) -> Point:
    """Σᵢ [zkᵢ]Aᵢ' + Σᵢ [zᵢ]Rᵢ' with ONE doubling chain for the whole batch.

    ``a_table``/``r_table`` are per-signature multiples tables (9, 32limbs,
    batch) of the (already negated) points; ``zk_digits`` is (64, batch) and
    ``z_digits`` (Wz, batch), both signed-4-bit recodings stored as d + 8,
    MSB window first.  The accumulator has batch shape (1,): each window
    costs 4 doubles of that single lane, then every signature's looked-up
    contribution is folded in via :func:`batch_sum` — so the 256-bit
    double chain (the ~2,000 M/sig wall for independent verification) is
    paid once per batch, not once per signature.

    Because z < 2^128 its high windows are all zero, the scan runs in two
    phases — ``64 - Wz`` A-only windows, then ``Wz`` combined windows —
    instead of padding z to 64 rows of dead lookups/adds.

    Under ``CTPU_MXU_LIMBS=1`` (and outside ``suppress_pallas_scan`` —
    the sharded engines trace under it) this dispatches to the
    VMEM-resident Pallas kernel (:func:`pallas_scan.straus_msm`), seeded
    from each table's entry 1 (the base points).  Verdicts are invariant
    — see the kernel's projective-representative note.  Counted traces
    (``limbs.counting()``) keep the XLA path: a ``fori_loop`` body traces
    once without the scan-weight stack, so the kernel would silently
    undercount — the measured denominator describes the XLA-scheduled
    MSM with MXU field contractions."""
    if not limbs.counting():
        from consensus_tpu.ops import pallas_scan

        cfg = pallas_scan.msm_config(int(zk_digits.shape[-1]))
        if cfg is not None:
            tile, interpret = cfg
            return pallas_scan.straus_msm(
                a_table.x[1], a_table.y[1], a_table.z[1], a_table.t[1],
                r_table.x[1], r_table.y[1], r_table.z[1], r_table.t[1],
                zk_digits, z_digits, tile=tile, interpret=interpret,
            )
    n_low = z_digits.shape[0]
    n_high = zk_digits.shape[0] - n_low
    acc0 = identity_like(a_table.x[0][..., :1])  # (32limbs, 1)

    def quad_double(acc: Point) -> Point:
        acc, _ = limbs.counted_scan(
            lambda a, _: (double(a, need_t=False), None), acc, None, length=3
        )
        return double(acc)  # final double materializes T for the next add

    def step_high(acc: Point, zk_row):
        acc = quad_double(acc)
        contrib = _signed_window_contribution(a_table, zk_row)
        return add(acc, batch_sum(contrib)), None

    def step_low(acc: Point, rows):
        zk_row, z_row = rows
        acc = quad_double(acc)
        contrib = add(
            _signed_window_contribution(a_table, zk_row),
            _signed_window_contribution(r_table, z_row),
        )
        return add(acc, batch_sum(contrib)), None

    acc, _ = limbs.counted_scan(step_high, acc0, zk_digits[:n_high])
    acc, _ = limbs.counted_scan(step_low, acc, (zk_digits[n_high:], z_digits))
    return acc


__all__ = [
    "Point",
    "identity",
    "identity_like",
    "base_point",
    "base_point_like",
    "negate",
    "add",
    "double",
    "select",
    "conditional_add",
    "decompress",
    "equal",
    "is_identity",
    "base_point_table_ints",
    "table_lookup",
    "multiples_table",
    "multiples_table9",
    "add_affine",
    "fixed_base_mul_comb",
    "batch_sum",
    "straus_shared_msm",
]
