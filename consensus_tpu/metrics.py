"""Metrics: provider abstraction, no-op and in-memory implementations, and
the five instrument bundles the protocol reports into.

Parity: reference pkg/metrics/provider.go:11-18 (Provider / Counter / Gauge /
Histogram), pkg/metrics/disabled/provider.go (no-op), and
pkg/api/metrics.go:70-578 (the 5 bundles / 28 instruments, same names).
An embedder passes its own Provider (e.g. Prometheus-backed) to the facade;
the default is no-op.
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence


#: Stable, documented instrument names for the latency-attribution pair the
#: tracer mirrors (see consensus_tpu/trace/): the decision tracer's
#: ``verify.launch`` instants carry the exact values this histogram observes,
#: and its ``wal.fsync`` instants carry the per-flush record counts behind
#: this gauge.  Tests and embedder dashboards key on these constants, not on
#: string literals, so a rename breaks loudly.
VERIFY_LAUNCH_BATCH_KEY = "consensus_cross_slot_verify_batch"
WAL_RECORDS_PER_FSYNC_KEY = "consensus_wal_records_per_fsync"

#: Pinned instrument names for INJECTED network adversary events (the chaos
#: engine's SimNetwork primitives: loss/mutate/filter drops, duplication,
#: reordering, stale replay — consensus_tpu/testing/network.py).  The
#: network tracer mirrors each as a ``net.<kind>`` instant; the parity test
#: (tests/test_trace.py) holds counter and instant streams equal.  Order
#: matches network.INJECTED_EVENT_KINDS.
NET_DROPPED_KEY = "net_injected_dropped"
NET_DUPLICATED_KEY = "net_injected_duplicated"
NET_REORDERED_KEY = "net_injected_reordered"
NET_REPLAYED_KEY = "net_injected_replayed"
NET_INJECTED_KEYS = (
    NET_DROPPED_KEY, NET_DUPLICATED_KEY, NET_REORDERED_KEY, NET_REPLAYED_KEY,
)

#: Pinned instrument names for the real TCP transport's reconnect path
#: (consensus_tpu/net/transport.py).  The Comm contract stays
#: fire-and-forget, but connection-refused and mid-frame abrupt-close now
#: get bounded retry with backoff + jitter before a frame is dropped —
#: these counters make that recovery visible per process so the deploy
#: rig's soak scraper can attribute chaos-induced churn.
NET_RECONNECT_ATTEMPTS_KEY = "net_reconnect_attempts"
NET_RECONNECT_SUCCESS_KEY = "net_reconnect_success"
NET_SEND_RETRIED_KEY = "net_send_retried"
NET_SEND_DROPPED_KEY = "net_send_dropped"
NET_RECONNECT_KEYS = (
    NET_RECONNECT_ATTEMPTS_KEY, NET_RECONNECT_SUCCESS_KEY,
    NET_SEND_RETRIED_KEY, NET_SEND_DROPPED_KEY,
)

#: Pinned instrument names for the listener-hardening layer
#: (consensus_tpu/net/framing.py): every guard defense event is
#: triple-booked — one of these counters, a ``net.abuse`` trace instant,
#: and the ``wire_abuse`` obs detector.  ``net_malformed_total`` carries a
#: ``kind`` label drawn from framing.MALFORMED_KINDS.
NET_MALFORMED_KEY = "net_malformed_total"
NET_HANDSHAKE_TIMEOUT_KEY = "net_handshake_timeout_total"
NET_PEER_BANNED_KEY = "net_peer_banned_total"
NET_CONN_REJECTED_KEY = "net_conn_rejected_total"
NET_ABUSE_KEYS = (
    NET_MALFORMED_KEY, NET_HANDSHAKE_TIMEOUT_KEY,
    NET_PEER_BANNED_KEY, NET_CONN_REJECTED_KEY,
)

#: Pinned instrument names for the observability plane (consensus_tpu/obs/).
#: One counter per anomaly detector — the sampler bumps the affected node's
#: counter the moment a detector fires (edge-triggered), mirrored by an
#: ``obs.anomaly`` trace instant — plus the total sample count.  The chaos
#: detector-soundness matrix asserts on these names.
OBS_SAMPLES_KEY = "obs_samples_total"
OBS_ANOMALY_COMMIT_STALL_KEY = "obs_anomaly_commit_stall"
OBS_ANOMALY_VIEW_CHANGE_STORM_KEY = "obs_anomaly_view_change_storm"
OBS_ANOMALY_LEADER_FLAP_KEY = "obs_anomaly_leader_flap"
OBS_ANOMALY_SYNC_LAG_KEY = "obs_anomaly_sync_lag"
OBS_ANOMALY_VERIFY_COLLAPSE_KEY = "obs_anomaly_verify_collapse"
OBS_ANOMALY_MEMBERSHIP_CHURN_KEY = "obs_anomaly_membership_churn"
OBS_ANOMALY_ADMISSION_OVERLOAD_KEY = "obs_anomaly_admission_overload"
OBS_ANOMALY_DEDUP_STORM_KEY = "obs_anomaly_dedup_storm"
OBS_ANOMALY_ENGINE_DEGRADED_KEY = "obs_anomaly_engine_degraded"
OBS_ANOMALY_WAL_CORRUPTION_KEY = "obs_anomaly_wal_corruption"
OBS_ANOMALY_WAL_STALL_KEY = "obs_anomaly_wal_stall"
OBS_ANOMALY_CROSS_GROUP_STALL_KEY = "obs_anomaly_cross_group_stall"
OBS_ANOMALY_WIRE_ABUSE_KEY = "obs_anomaly_wire_abuse"
OBS_ANOMALY_KEYS = (
    OBS_ANOMALY_COMMIT_STALL_KEY,
    OBS_ANOMALY_VIEW_CHANGE_STORM_KEY,
    OBS_ANOMALY_LEADER_FLAP_KEY,
    OBS_ANOMALY_SYNC_LAG_KEY,
    OBS_ANOMALY_VERIFY_COLLAPSE_KEY,
    OBS_ANOMALY_MEMBERSHIP_CHURN_KEY,
    OBS_ANOMALY_ADMISSION_OVERLOAD_KEY,
    OBS_ANOMALY_DEDUP_STORM_KEY,
    OBS_ANOMALY_ENGINE_DEGRADED_KEY,
    OBS_ANOMALY_WAL_CORRUPTION_KEY,
    OBS_ANOMALY_WAL_STALL_KEY,
    OBS_ANOMALY_CROSS_GROUP_STALL_KEY,
    OBS_ANOMALY_WIRE_ABUSE_KEY,
)

#: Pinned instrument names for durable-state self-healing (wal/scrub.py,
#: wal/log.py's degrade path, testing/storage.py's injected faults).  Every
#: storage-fault transition is triple-booked: one of these instruments, a
#: ``wal.*`` trace instant, and the ``wal_corruption`` / ``wal_stall`` obs
#: detectors.  The chaos matrix asserts EXACTLY ONE quarantine or degraded
#: transition per injected fault, keyed on these names.
WAL_FSYNC_RETRY_KEY = "wal_fsync_retry_total"
WAL_SCRUB_RUNS_KEY = "wal_scrub_runs_total"
WAL_SCRUB_RECORDS_KEY = "wal_scrub_records_total"
WAL_SCRUB_CORRUPTIONS_KEY = "wal_scrub_corruptions_total"
WAL_QUARANTINE_KEY = "wal_quarantine_total"
WAL_DEGRADED_KEY = "wal_degraded"
WAL_DEGRADED_TOTAL_KEY = "wal_degraded_total"
WAL_STORAGE_KEYS = (
    WAL_FSYNC_RETRY_KEY,
    WAL_SCRUB_RUNS_KEY,
    WAL_SCRUB_RECORDS_KEY,
    WAL_SCRUB_CORRUPTIONS_KEY,
    WAL_QUARANTINE_KEY,
    WAL_DEGRADED_KEY,
    WAL_DEGRADED_TOTAL_KEY,
)

#: Pinned instrument names for the membership-epoch subsystem
#: (consensus_tpu/membership/): the facade's epoch gauge and stale-epoch
#: ingress drops, and the joining-node bootstrap's attempt/retry counters.
MEMBERSHIP_EPOCH_KEY = "membership_epoch"
MEMBERSHIP_STALE_EPOCH_DROPPED_KEY = "membership_stale_epoch_dropped"
MEMBERSHIP_JOIN_ATTEMPTS_KEY = "membership_join_attempts"
MEMBERSHIP_JOIN_RETRIES_KEY = "membership_join_retries"
MEMBERSHIP_KEYS = (
    MEMBERSHIP_EPOCH_KEY,
    MEMBERSHIP_STALE_EPOCH_DROPPED_KEY,
    MEMBERSHIP_JOIN_ATTEMPTS_KEY,
    MEMBERSHIP_JOIN_RETRIES_KEY,
)

#: Pinned instrument names for the multi-tenant verification sidecar
#: (consensus_tpu/net/sidecar.py).  Admission control (bounded per-tenant
#: queues with structured rejects, never stalls) and cross-tenant wave
#: forming (many tenants' signatures coalesced into one mesh launch) each
#: get a counter; per-tenant series hang off these via ``with_labels``.
SIDECAR_ADMISSION_ACCEPTED_KEY = "sidecar_admission_accepted"
SIDECAR_ADMISSION_REJECTS_KEY = "sidecar_admission_rejects"
SIDECAR_ADMISSION_QUEUE_DEPTH_KEY = "sidecar_admission_queue_depth"
SIDECAR_WAVE_LAUNCHES_KEY = "sidecar_wave_launches"
SIDECAR_WAVE_SIGNATURES_KEY = "sidecar_wave_signatures"
SIDECAR_WAVE_TENANTS_KEY = "sidecar_wave_tenants"
SIDECAR_KEYS = (
    SIDECAR_ADMISSION_ACCEPTED_KEY,
    SIDECAR_ADMISSION_REJECTS_KEY,
    SIDECAR_ADMISSION_QUEUE_DEPTH_KEY,
    SIDECAR_WAVE_LAUNCHES_KEY,
    SIDECAR_WAVE_SIGNATURES_KEY,
    SIDECAR_WAVE_TENANTS_KEY,
)

#: Pinned instrument names for the ingress plane (consensus_tpu/ingress/):
#: the admission layer's offered/admitted/rate-limited/dedup accounting,
#: the placement fleet's size and structured-reject reroutes, and the
#: open-loop driver's commit latency.  Every admission decision is
#: triple-booked: one of these counters, an ``ingress.<outcome>`` trace
#: instant, and (through health snapshots) the ``admission_overload`` /
#: ``dedup_storm`` obs detectors.
INGRESS_OFFERED_KEY = "ingress_offered_total"
INGRESS_ADMITTED_KEY = "ingress_admitted_total"
INGRESS_RATE_LIMITED_KEY = "ingress_rate_limited_total"
INGRESS_DEDUP_HITS_KEY = "ingress_dedup_hits_total"
INGRESS_REROUTE_KEY = "ingress_reroute_total"
INGRESS_FLEET_SIZE_KEY = "ingress_fleet_size"
INGRESS_COMMIT_LATENCY_KEY = "ingress_commit_latency"
INGRESS_KEYS = (
    INGRESS_OFFERED_KEY,
    INGRESS_ADMITTED_KEY,
    INGRESS_RATE_LIMITED_KEY,
    INGRESS_DEDUP_HITS_KEY,
    INGRESS_REROUTE_KEY,
    INGRESS_FLEET_SIZE_KEY,
    INGRESS_COMMIT_LATENCY_KEY,
)

#: Pinned instrument names for half-aggregated quorum certs
#: (consensus_tpu/models/aggregate.py, Configuration.cert_mode).  The byte
#: counters account encoded cert-field bytes (wire/codec.py
#: ``encoded_cert_size``) at each surface a cert crosses — leader broadcast,
#: WAL persistence, sync catch-up — so the full-vs-half-agg compression
#: ratio is directly observable per path; the launch/bisection counters
#: expose the one-MSM-launch economy and its strict fallback.
WAL_CERT_BYTES_KEY = "wal_cert_bytes_total"
SYNC_CERT_BYTES_KEY = "sync_cert_bytes_total"
NET_CERT_BYTES_KEY = "net_cert_bytes_total"
CERT_BYTES_PER_CERT_KEY = "cert_bytes_per_cert"
CERT_AGGREGATE_LAUNCHES_KEY = "cert_aggregate_launches"
CERT_FALLBACK_BISECTIONS_KEY = "cert_fallback_bisections"
CERT_KEYS = (
    WAL_CERT_BYTES_KEY,
    SYNC_CERT_BYTES_KEY,
    NET_CERT_BYTES_KEY,
    CERT_BYTES_PER_CERT_KEY,
    CERT_AGGREGATE_LAUNCHES_KEY,
    CERT_FALLBACK_BISECTIONS_KEY,
)

#: Pinned instrument names for the engine supervision layer
#: (consensus_tpu/models/supervisor.py).  Every degrade/recover transition
#: is triple-booked: one of these counters, an ``engine.degrade`` /
#: ``engine.recover`` trace instant, and the ``engine_degraded`` obs
#: detector.  Per-fault-class degrade series are children of the pinned
#: degrade name (``with_labels(reason)`` -> ``engine_degrade_total{reason}``
#: in the in-memory provider), so the aggregate name stays stable for
#: dashboards while the chaos matrix can read one fault class out.
ENGINE_DEGRADE_KEY = "engine_degrade_total"
ENGINE_RECOVERED_KEY = "engine_recovered_total"
ENGINE_CROSSCHECK_KEY = "engine_crosscheck_total"
ENGINE_CROSSCHECK_MISMATCH_KEY = "engine_crosscheck_mismatch_total"
ENGINE_RUNG_KEY = "engine_rung"
ENGINE_COMPILE_CACHE_HITS_KEY = "engine_compile_cache_hits_total"
ENGINE_COMPILE_CACHE_MISSES_KEY = "engine_compile_cache_misses_total"
ENGINE_KEYS = (
    ENGINE_DEGRADE_KEY,
    ENGINE_RECOVERED_KEY,
    ENGINE_CROSSCHECK_KEY,
    ENGINE_CROSSCHECK_MISMATCH_KEY,
    ENGINE_RUNG_KEY,
    ENGINE_COMPILE_CACHE_HITS_KEY,
    ENGINE_COMPILE_CACHE_MISSES_KEY,
)

#: Consensus-sharding (groups) plane.  Fed by the ingress GroupRouter
#: (routed counter + directory-size gauge), the shared FairShareWaveFormer
#: (cross-GROUP wave-span histogram + multi-group launch counter), and the
#: cross-group 2PC coordinator/participants.  Aggregate names are pinned;
#: per-group series are ``with_labels(group)`` children.
GROUPS_ROUTED_KEY = "groups_routed_total"
GROUPS_COUNT_KEY = "groups_count"
GROUPS_WAVE_SPAN_KEY = "groups_wave_span"
GROUPS_WAVE_MULTI_KEY = "groups_wave_multi_group_total"
GROUPS_TWOPC_STARTED_KEY = "groups_twopc_started_total"
GROUPS_TWOPC_COMMITTED_KEY = "groups_twopc_committed_total"
GROUPS_TWOPC_ABORTED_KEY = "groups_twopc_aborted_total"
GROUPS_KEYS = (
    GROUPS_ROUTED_KEY,
    GROUPS_COUNT_KEY,
    GROUPS_WAVE_SPAN_KEY,
    GROUPS_WAVE_MULTI_KEY,
    GROUPS_TWOPC_STARTED_KEY,
    GROUPS_TWOPC_COMMITTED_KEY,
    GROUPS_TWOPC_ABORTED_KEY,
)

#: THE module-level registry of every pinned instrument name: key -> one-line
#: description.  Tests and embedder dashboards key on this mapping; every
#: name here is created by a fresh ``Metrics`` bundle (asserted by
#: tests/test_obs.py), so a rename or a bundle regression breaks loudly in
#: one place instead of silently stranding a dashboard.
PINNED_METRIC_KEYS: dict[str, str] = {
    VERIFY_LAUNCH_BATCH_KEY:
        "commit signatures drained per batched verify launch (histogram)",
    WAL_RECORDS_PER_FSYNC_KEY:
        "group-commit coalescing ratio: WAL records per fsync (gauge)",
    NET_DROPPED_KEY: "messages dropped by network injection",
    NET_DUPLICATED_KEY: "messages delivered twice by network injection",
    NET_REORDERED_KEY: "messages held back past later sends",
    NET_REPLAYED_KEY: "stale captured messages re-delivered",
    NET_RECONNECT_ATTEMPTS_KEY:
        "TCP peer (re)connect attempts (refused/reset peers retried with "
        "backoff + jitter)",
    NET_RECONNECT_SUCCESS_KEY:
        "TCP peer (re)connects that completed the HELLO handshake",
    NET_SEND_RETRIED_KEY:
        "frames re-sent after a mid-frame abrupt close (peer killed)",
    NET_SEND_DROPPED_KEY:
        "frames dropped after exhausting connect/send retries "
        "(fire-and-forget contract)",
    NET_MALFORMED_KEY:
        "provably-malformed inbound frames booked as strikes "
        "(kind label: oversized/bad_hello/pre_hello/sender_pin/stall/garbage)",
    NET_HANDSHAKE_TIMEOUT_KEY:
        "inbound connections dropped for never completing HELLO/HMAC "
        "within the handshake deadline",
    NET_PEER_BANNED_KEY:
        "peers temporarily banned after crossing the malformed-frame "
        "strike limit",
    NET_CONN_REJECTED_KEY:
        "inbound connections refused at accept (active ban or a "
        "per-peer/global quota full)",
    OBS_SAMPLES_KEY: "observability-plane samples taken",
    OBS_ANOMALY_COMMIT_STALL_KEY:
        "detector firings: pending work but no ledger growth",
    OBS_ANOMALY_VIEW_CHANGE_STORM_KEY:
        "detector firings: view number churning within the storm window",
    OBS_ANOMALY_LEADER_FLAP_KEY:
        "detector firings: leader identity churning within the flap window",
    OBS_ANOMALY_SYNC_LAG_KEY:
        "detector firings: ledger height diverging from the running peers",
    OBS_ANOMALY_VERIFY_COLLAPSE_KEY:
        "detector firings: ledger growth with zero verify launches",
    OBS_ANOMALY_MEMBERSHIP_CHURN_KEY:
        "detector firings: membership epoch churning within the churn window",
    OBS_ANOMALY_ADMISSION_OVERLOAD_KEY:
        "detector firings: admission rejecting a sustained fraction of "
        "offered ingress load",
    OBS_ANOMALY_DEDUP_STORM_KEY:
        "detector firings: dedup cache absorbing a duplicate-retry storm",
    OBS_ANOMALY_ENGINE_DEGRADED_KEY:
        "detector firings: a supervised verify engine running below its "
        "configured rung",
    OBS_ANOMALY_WAL_CORRUPTION_KEY:
        "detector firings: a replica quarantined corrupt WAL state or is "
        "fenced as a non-voting learner",
    OBS_ANOMALY_WAL_STALL_KEY:
        "detector firings: a replica's WAL stopped accepting appends "
        "(degraded: ENOSPC or fsync-retry cap)",
    OBS_ANOMALY_CROSS_GROUP_STALL_KEY:
        "detector firings: a cross-group atomic transaction stuck "
        "unresolved past the stall window",
    OBS_ANOMALY_WIRE_ABUSE_KEY:
        "detector firings: a listener booked new abuse events (malformed "
        "strikes, handshake timeouts, bans, quota rejects) since the last "
        "sample",
    WAL_FSYNC_RETRY_KEY:
        "group-commit fsync attempts that failed and were re-armed",
    WAL_SCRUB_RUNS_KEY:
        "background scrub passes over the WAL segment inventory",
    WAL_SCRUB_RECORDS_KEY:
        "records re-walked (CRC re-verified) by the background scrubber",
    WAL_SCRUB_CORRUPTIONS_KEY:
        "corruptions detected by the scrubber or at open/restore time",
    WAL_QUARANTINE_KEY:
        "corrupt WAL suffixes renamed aside (never deleted) preserving the "
        "intact prefix",
    WAL_DEGRADED_KEY:
        "whether the WAL is refusing appends (1 = degraded: ENOSPC or "
        "fsync-retry cap; gauge)",
    WAL_DEGRADED_TOTAL_KEY:
        "transitions into wal_degraded (append path unsatisfiable)",
    INGRESS_OFFERED_KEY:
        "client requests offered to the ingress admission layer",
    INGRESS_ADMITTED_KEY:
        "client requests admitted past rate limiting and dedup",
    INGRESS_RATE_LIMITED_KEY:
        "client requests rejected by the per-client token bucket",
    INGRESS_DEDUP_HITS_KEY:
        "duplicate client requests absorbed by the dedup cache",
    INGRESS_REROUTE_KEY:
        "admitted batches rerouted to the hash ring's next fleet candidate "
        "after a structured admission reject",
    INGRESS_FLEET_SIZE_KEY:
        "verifier fleet servers currently in the placement ring (gauge)",
    INGRESS_COMMIT_LATENCY_KEY:
        "sim-seconds from open-loop arrival to fleet commit (histogram)",
    MEMBERSHIP_EPOCH_KEY:
        "membership epoch this replica is serving (gauge)",
    MEMBERSHIP_STALE_EPOCH_DROPPED_KEY:
        "inbound messages dropped at ingress for carrying another epoch",
    MEMBERSHIP_JOIN_ATTEMPTS_KEY:
        "join-bootstrap sync attempts (first try included)",
    MEMBERSHIP_JOIN_RETRIES_KEY:
        "join-bootstrap sync retries (attempts after the first)",
    SIDECAR_ADMISSION_ACCEPTED_KEY:
        "sidecar verification batches admitted to a tenant queue",
    SIDECAR_ADMISSION_REJECTS_KEY:
        "sidecar batches rejected at admission (tenant queue full)",
    SIDECAR_ADMISSION_QUEUE_DEPTH_KEY:
        "signatures queued across tenant queues at last admission (gauge)",
    SIDECAR_WAVE_LAUNCHES_KEY:
        "cross-tenant waves launched on the sidecar engine",
    SIDECAR_WAVE_SIGNATURES_KEY:
        "signatures verified across all sidecar waves",
    SIDECAR_WAVE_TENANTS_KEY:
        "tenants sharing a wave, summed over waves (launches divides it)",
    WAL_CERT_BYTES_KEY:
        "encoded quorum-cert bytes persisted to the WAL",
    SYNC_CERT_BYTES_KEY:
        "encoded quorum-cert bytes received in sync catch-up chunks",
    NET_CERT_BYTES_KEY:
        "encoded quorum-cert bytes broadcast in pre-prepares",
    CERT_BYTES_PER_CERT_KEY:
        "encoded bytes per quorum cert assembled or received (histogram)",
    CERT_AGGREGATE_LAUNCHES_KEY:
        "half-aggregated cert checks (one MSM launch each)",
    CERT_FALLBACK_BISECTIONS_KEY:
        "cert aggregations abandoned to bisection + full-tuple fallback",
    ENGINE_DEGRADE_KEY:
        "supervised engine degrades down the ladder (per-reason children)",
    ENGINE_RECOVERED_KEY:
        "supervised engine re-promotions after a breaker re-closed",
    ENGINE_CROSSCHECK_KEY:
        "sampled host cross-checks run against device verdicts",
    ENGINE_CROSSCHECK_MISMATCH_KEY:
        "host cross-checks that contradicted the device verdict",
    ENGINE_RUNG_KEY:
        "current degrade-ladder rung (0 = as configured; gauge)",
    ENGINE_COMPILE_CACHE_HITS_KEY:
        "engine constructions that reused an already-traced kernel from "
        "the in-process compiled-kernel memo",
    ENGINE_COMPILE_CACHE_MISSES_KEY:
        "engine constructions that traced a kernel fresh (first build of "
        "that topology, or the memo disabled)",
    GROUPS_ROUTED_KEY:
        "admitted requests routed to their owning consensus group",
    GROUPS_COUNT_KEY:
        "consensus groups currently in the placement directory (gauge)",
    GROUPS_WAVE_SPAN_KEY:
        "distinct consensus groups sharing one fused verify launch "
        "(histogram)",
    GROUPS_WAVE_MULTI_KEY:
        "fused verify launches serving submissions from two or more groups",
    GROUPS_TWOPC_STARTED_KEY:
        "cross-group atomic transactions entering the prepare phase",
    GROUPS_TWOPC_COMMITTED_KEY:
        "cross-group atomic transactions decided commit by every group",
    GROUPS_TWOPC_ABORTED_KEY:
        "cross-group atomic transactions decided abort by every group",
}


class Counter(abc.ABC):
    @abc.abstractmethod
    def add(self, delta: float = 1.0) -> None: ...

    def with_labels(self, *values: str) -> "Counter":
        """Bind label values (embedder dimensions, e.g. channel).  Parity:
        reference pkg/metrics Counter.With."""
        return self


class Gauge(abc.ABC):
    @abc.abstractmethod
    def set(self, value: float) -> None: ...

    @abc.abstractmethod
    def add(self, delta: float = 1.0) -> None: ...

    def with_labels(self, *values: str) -> "Gauge":
        return self


class Histogram(abc.ABC):
    @abc.abstractmethod
    def observe(self, value: float) -> None: ...

    def with_labels(self, *values: str) -> "Histogram":
        return self


def extend_label_names(
    base: Sequence[str], extra: Sequence[str]
) -> tuple[str, ...]:
    """Embedder label names appended to an instrument's own, extras sorted —
    the reference applies the same merge to every bundle so embedders can add
    per-channel dimensions.  ``with_labels`` values must follow this sorted
    order (same contract as the reference's makeStatsdFormat, which sorts
    names before appending).  Parity: reference pkg/api/metrics.go:16-68
    (NewGaugeOpts / makeLabelNames / makeStatsdFormat)."""
    return tuple(base) + tuple(sorted(extra))


class Provider(abc.ABC):
    """Parity: reference pkg/metrics/provider.go:11-18."""

    @abc.abstractmethod
    def new_counter(
        self, name: str, help: str = "", label_names: Sequence[str] = ()
    ) -> Counter: ...

    @abc.abstractmethod
    def new_gauge(
        self, name: str, help: str = "", label_names: Sequence[str] = ()
    ) -> Gauge: ...

    @abc.abstractmethod
    def new_histogram(
        self, name: str, help: str = "", label_names: Sequence[str] = ()
    ) -> Histogram: ...


class _NoopInstrument(Counter, Gauge, Histogram):
    def add(self, delta: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


class NoopProvider(Provider):
    """Parity: reference pkg/metrics/disabled/provider.go:13-17."""

    _instrument = _NoopInstrument()

    def new_counter(self, name, help="", label_names=()) -> Counter:
        return self._instrument

    def new_gauge(self, name, help="", label_names=()) -> Gauge:
        return self._instrument

    def new_histogram(self, name, help="", label_names=()) -> Histogram:
        return self._instrument


class _MemInstrument(Counter, Gauge, Histogram):
    def __init__(self, provider: "InMemoryProvider", name: str,
                 label_names: tuple[str, ...] = (),
                 bound_tail: tuple[str, ...] = ()) -> None:
        self._provider = provider
        self._name = name
        self.label_names = label_names
        self._bound_tail = bound_tail
        self.value = 0.0
        self.observations: list[float] = []

    def add(self, delta: float = 1.0) -> None:
        self.value += delta

    def set(self, value: float) -> None:
        self.value = value

    def observe(self, value: float) -> None:
        self.observations.append(value)

    def with_labels(self, *values: str) -> "_MemInstrument":
        """A child instrument keyed ``name{v1,v2}`` — one series per label
        value set, like a Prometheus vector.  Binding fewer values than
        label names binds the TRAILING names (the embedder extras
        ``extend_label_names`` appends): ``_Bundle.with_labels`` can bind
        the channel dimension first and the instrument's owner binds its
        own leading labels (e.g. ``reason``) later."""
        if len(values) > len(self.label_names):
            raise ValueError(
                f"{self._name}: {len(self.label_names)} label(s) expected, "
                f"got {len(values)}"
            )
        if not values:
            return self
        if len(values) < len(self.label_names):
            # Partial bind — not a series yet, so not registered with the
            # provider; the final child is created on the full bind below.
            return _MemInstrument(
                self._provider, self._name,
                self.label_names[: len(self.label_names) - len(values)],
                tuple(values) + self._bound_tail,
            )
        return self._provider._get(
            "%s{%s}" % (self._name,
                        ",".join(tuple(values) + self._bound_tail)), ()
        )


class InMemoryProvider(Provider):
    """Collects values in plain dicts — for tests and the bench harness."""

    def __init__(self) -> None:
        self.instruments: dict[str, _MemInstrument] = {}

    def _get(self, name: str, label_names=()) -> _MemInstrument:
        inst = self.instruments.get(name)
        if inst is None:
            inst = self.instruments[name] = _MemInstrument(
                self, name, tuple(label_names)
            )
        return inst

    def new_counter(self, name, help="", label_names=()) -> Counter:
        return self._get(name, label_names)

    def new_gauge(self, name, help="", label_names=()) -> Gauge:
        return self._get(name, label_names)

    def new_histogram(self, name, help="", label_names=()) -> Histogram:
        return self._get(name, label_names)

    def value(self, name: str) -> float:
        # Strict read: a misspelled/unwired name fails instead of
        # vacuously returning 0.
        return self.instruments[name].value

    def observations(self, name: str) -> list[float]:
        return self.instruments[name].observations

    def dump(self) -> dict[str, dict]:
        """Stable snapshot of every instrument, sorted by name: ``{name:
        {"value": <counter/gauge value>, "observations": [histogram
        samples]}}``.  The machine-readable surface the bench harness and
        trace-parity tests consume — names here are the documented contract
        (see :data:`VERIFY_LAUNCH_BATCH_KEY` /
        :data:`WAL_RECORDS_PER_FSYNC_KEY`)."""
        return {
            name: {
                "value": inst.value,
                "observations": list(inst.observations),
            }
            for name, inst in sorted(self.instruments.items())
        }


# --- instrument bundles (names mirror reference pkg/api/metrics.go) --------


class _Bundle:
    """Shared label plumbing: ``with_labels`` returns a copy of the bundle
    with every instrument bound to the given label values.  Parity:
    reference pkg/api/metrics.go With() on each bundle."""

    def with_labels(self, *values: str) -> "_Bundle":
        import copy

        clone = copy.copy(self)
        for k, v in vars(self).items():
            if isinstance(v, (Counter, Gauge, Histogram)):
                setattr(clone, k, v.with_labels(*values))
        return clone


class MetricsWAL(_Bundle):
    """Parity: reference pkg/wal/metrics.go:8-37 (1 instrument), plus the
    self-healing instruments (consensus_tpu addition): fsync-retry
    accounting, the background scrubber's pass/record/corruption counters,
    quarantine bookkeeping, and the degraded-mode gauge + transition
    counter.  The pinned names live in :data:`PINNED_METRIC_KEYS`."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_of_files = p.new_gauge(
            "wal_count_of_files", "Count of wal-files.", ln
        )
        self.count_of_files.add(0)  # reference Initialize()
        self.fsync_retries = p.new_counter(
            WAL_FSYNC_RETRY_KEY,
            "Group-commit fsync attempts that failed and were re-armed.",
            ln,
        )
        self.scrub_runs = p.new_counter(
            WAL_SCRUB_RUNS_KEY,
            "Background scrub passes over the WAL segment inventory.",
            ln,
        )
        self.scrub_records = p.new_counter(
            WAL_SCRUB_RECORDS_KEY,
            "Records re-walked (CRC re-verified) by the scrubber.",
            ln,
        )
        self.scrub_corruptions = p.new_counter(
            WAL_SCRUB_CORRUPTIONS_KEY,
            "Corruptions detected by the scrubber or at open/restore time.",
            ln,
        )
        self.quarantines = p.new_counter(
            WAL_QUARANTINE_KEY,
            "Corrupt WAL suffixes renamed aside preserving the prefix.",
            ln,
        )
        self.degraded = p.new_gauge(
            WAL_DEGRADED_KEY,
            "Whether the WAL is refusing appends (1 = degraded).",
            ln,
        )
        self.degraded.add(0)
        self.degraded_transitions = p.new_counter(
            WAL_DEGRADED_TOTAL_KEY,
            "Transitions into wal_degraded (append path unsatisfiable).",
            ln,
        )


class MetricsRequestPool(_Bundle):
    """Parity: reference pkg/api/metrics.go:172-237 (7 instruments)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_of_elements = p.new_gauge(
            "pool_count_of_elements", "Number of elements in the consensus request pool.", ln
        )
        self.count_of_elements_all = p.new_counter(
            "pool_count_of_elements_all", "Total amount of elements in the pool.", ln
        )
        self.count_of_fail_add_request = p.new_counter(
            "pool_count_of_fail_add_request", "Submissions the pool rejected.", ln
        )
        self.count_of_delete_request = p.new_counter(
            "pool_count_of_delete_request", "Elements removed from the pool.", ln
        )
        self.count_leader_forward_request = p.new_counter(
            "pool_count_leader_forward_request", "Requests forwarded to the leader.", ln
        )
        self.count_timeout_two_step = p.new_counter(
            "pool_count_timeout_two_step", "Complaint-stage timeouts.", ln
        )
        self.latency_of_elements = p.new_histogram(
            "pool_latency_of_elements", "Time requests spend in the pool.", ln
        )


class MetricsBlacklist(_Bundle):
    """Parity: reference pkg/api/metrics.go:258-297 (2 instruments)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count = p.new_gauge(
            "blacklist_count", "Nodes in the blacklist.", ln
        )
        self.node_id_in_blacklist = p.new_gauge(
            "node_id_in_blacklist", "Whether this node id is blacklisted.", ln
        )


class MetricsConsensus(_Bundle):
    """Parity: reference pkg/api/metrics.go:319-344 (2 instruments), plus
    the decision-pipelining instruments (consensus_tpu addition)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_consensus_reconfig = p.new_counter(
            "consensus_reconfig", "Reconfigurations applied.", ln
        )
        self.latency_sync = p.new_histogram(
            "consensus_latency_sync", "Duration of synchronization rounds.", ln
        )
        # --- decision pipelining (pipeline_depth > 1) -------------------
        self.in_flight_depth = p.new_gauge(
            "consensus_in_flight_depth",
            "Proposal slots currently moving through the 3-phase pipeline.",
            ln,
        )
        self.count_verify_launches = p.new_counter(
            "consensus_verify_launches",
            "Batched commit-signature verification launches (cross-slot "
            "coalescing makes this grow slower than decisions).",
            ln,
        )
        self.cross_slot_verify_batch = p.new_histogram(
            "consensus_cross_slot_verify_batch",
            "Commit signatures drained per batched verify launch.",
            ln,
        )
        self.wal_records_per_fsync = p.new_gauge(
            "consensus_wal_records_per_fsync",
            "Group-commit coalescing ratio: WAL records made durable per "
            "fsync in the most recent flush window.",
            ln,
        )
        # --- half-aggregated quorum certs (cert_mode="half-agg") --------
        self.wal_cert_bytes = p.new_counter(
            WAL_CERT_BYTES_KEY,
            "Encoded quorum-cert bytes persisted to the WAL.",
            ln,
        )
        self.net_cert_bytes = p.new_counter(
            NET_CERT_BYTES_KEY,
            "Encoded quorum-cert bytes broadcast in pre-prepares.",
            ln,
        )
        self.cert_bytes_per_cert = p.new_histogram(
            CERT_BYTES_PER_CERT_KEY,
            "Encoded bytes per quorum cert assembled or received.",
            ln,
        )
        self.cert_aggregate_launches = p.new_counter(
            CERT_AGGREGATE_LAUNCHES_KEY,
            "Half-aggregated cert checks (one MSM launch each).",
            ln,
        )
        self.cert_fallback_bisections = p.new_counter(
            CERT_FALLBACK_BISECTIONS_KEY,
            "Cert aggregations abandoned to bisection + full-tuple fallback.",
            ln,
        )


class MetricsView(_Bundle):
    """Parity: reference pkg/api/metrics.go:448-518 (12 instruments)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.view_number = p.new_gauge(
            "view_number", "Current view number.", ln
        )
        self.leader_id = p.new_gauge(
            "view_leader_id", "Current leader id.", ln
        )
        self.proposal_sequence = p.new_gauge(
            "view_proposal_sequence", "In-progress proposal sequence.", ln
        )
        self.decisions_in_view = p.new_gauge(
            "view_decisions", "Decisions made in the current view.", ln
        )
        self.phase = p.new_gauge(
            "view_phase", "Current 3-phase state.", ln
        )
        self.count_txs_in_batch = p.new_gauge(
            "view_count_txs_in_batch", "Transactions in the current batch.", ln
        )
        self.count_batch_all = p.new_counter(
            "view_count_batch_all", "Batches decided in total.", ln
        )
        self.count_txs_all = p.new_counter(
            "view_count_txs_all", "Transactions decided in total.", ln
        )
        self.size_of_batch = p.new_counter(
            "view_size_batch", "Decided bytes in total.", ln
        )
        self.latency_batch_processing = p.new_histogram(
            "view_latency_batch_processing", "Pre-prepare to commit latency.", ln
        )
        self.latency_batch_save = p.new_histogram(
            "view_latency_batch_save", "Application delivery latency.", ln
        )
        self.count_batch_sig_verifications = p.new_counter(
            "view_count_batch_sig_verifications",
            "Signature verifications drained into device batches "
            "(consensus_tpu addition: the TPU offload volume).",
            ln,
        )


class MetricsSync(_Bundle):
    """Catch-up (state transfer) instruments — consensus_tpu addition; the
    reference has no sync subsystem to measure (Fabric's block puller lives
    outside the library)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_chunks_fetched = p.new_counter(
            "sync_count_chunks_fetched", "Verified chunks applied during catch-up.", ln
        )
        self.count_decisions_fetched = p.new_counter(
            "sync_count_decisions_fetched", "Decisions applied during catch-up.", ln
        )
        self.count_sig_verifications = p.new_counter(
            "sync_count_sig_verifications",
            "Quorum-cert signatures drained into batched verifier calls.",
            ln,
        )
        self.sigs_per_chunk = p.new_histogram(
            "sync_sigs_per_chunk", "Signatures batch-verified per chunk.", ln
        )
        self.latency_catchup = p.new_histogram(
            "sync_latency_catchup", "Duration of one catch-up (sync) call.", ln
        )
        self.count_peer_demotions = p.new_counter(
            "sync_count_peer_demotions",
            "Peer score demotions (failed fetches + forged chunks).",
            ln,
        )
        self.sync_cert_bytes = p.new_counter(
            SYNC_CERT_BYTES_KEY,
            "Encoded quorum-cert bytes received in sync catch-up chunks.",
            ln,
        )


class MetricsNetwork(_Bundle):
    """Injected network adversary events — consensus_tpu addition, fed by
    ``SimNetwork`` (testing/network.py) when a bundle is attached, so chaos
    runs are attributable: how much of the schedule's adversary budget
    actually landed on the wire."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_dropped = p.new_counter(
            NET_DROPPED_KEY,
            "Messages dropped by injection (loss rolls, mutate/filter drops).",
            ln,
        )
        self.count_duplicated = p.new_counter(
            NET_DUPLICATED_KEY, "Messages delivered twice by injection.", ln
        )
        self.count_reordered = p.new_counter(
            NET_REORDERED_KEY, "Messages held back past later sends.", ln
        )
        self.count_replayed = p.new_counter(
            NET_REPLAYED_KEY, "Stale captured messages re-delivered.", ln
        )
        # Real-transport reconnect path (net/transport.py): a TcpComm with
        # this bundle attached books every bounded-retry outcome here.
        self.count_reconnect_attempts = p.new_counter(
            NET_RECONNECT_ATTEMPTS_KEY,
            "TCP peer (re)connect attempts, including retries.",
            ln,
        )
        self.count_reconnect_success = p.new_counter(
            NET_RECONNECT_SUCCESS_KEY,
            "TCP peer (re)connects that completed the HELLO handshake.",
            ln,
        )
        self.count_send_retried = p.new_counter(
            NET_SEND_RETRIED_KEY,
            "Frames re-sent after a mid-frame abrupt close.",
            ln,
        )
        self.count_send_dropped = p.new_counter(
            NET_SEND_DROPPED_KEY,
            "Frames dropped after exhausting connect/send retries.",
            ln,
        )
        # Listener-hardening guard (net/framing.py): a ListenerGuard with
        # this bundle attached books every defense event here.  The
        # malformed counter carries a "kind" label (framing.MALFORMED_KINDS)
        # so with_labels(kind) yields per-kind child series.
        self.count_malformed = p.new_counter(
            NET_MALFORMED_KEY,
            "Provably-malformed inbound frames booked as strikes.",
            extend_label_names(("kind",), label_names),
        )
        self.count_handshake_timeout = p.new_counter(
            NET_HANDSHAKE_TIMEOUT_KEY,
            "Inbound connections dropped for never completing the handshake.",
            ln,
        )
        self.count_peer_banned = p.new_counter(
            NET_PEER_BANNED_KEY,
            "Peers temporarily banned after crossing the strike limit.",
            ln,
        )
        self.count_conn_rejected = p.new_counter(
            NET_CONN_REJECTED_KEY,
            "Inbound connections refused at accept (ban or quota).",
            ln,
        )


class MetricsObs(_Bundle):
    """Observability-plane instruments — consensus_tpu addition, fed by the
    ``obs`` sampler/detectors (consensus_tpu/obs/).  One counter per anomaly
    detector plus the sample count; the pinned names live in
    :data:`PINNED_METRIC_KEYS` so they appear in a fresh ``Metrics.dump()``
    even before the first sample."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_samples = p.new_counter(
            OBS_SAMPLES_KEY, "Observability-plane samples taken.", ln
        )
        self.count_anomaly_commit_stall = p.new_counter(
            OBS_ANOMALY_COMMIT_STALL_KEY,
            "Commit-stall detector firings (pending work, no ledger growth).",
            ln,
        )
        self.count_anomaly_view_change_storm = p.new_counter(
            OBS_ANOMALY_VIEW_CHANGE_STORM_KEY,
            "View-change-storm detector firings.",
            ln,
        )
        self.count_anomaly_leader_flap = p.new_counter(
            OBS_ANOMALY_LEADER_FLAP_KEY,
            "Leader-flap detector firings.",
            ln,
        )
        self.count_anomaly_sync_lag = p.new_counter(
            OBS_ANOMALY_SYNC_LAG_KEY,
            "Sync-lag-divergence detector firings.",
            ln,
        )
        self.count_anomaly_verify_collapse = p.new_counter(
            OBS_ANOMALY_VERIFY_COLLAPSE_KEY,
            "Verify-launch-rate-collapse detector firings.",
            ln,
        )
        self.count_anomaly_membership_churn = p.new_counter(
            OBS_ANOMALY_MEMBERSHIP_CHURN_KEY,
            "Membership-churn detector firings.",
            ln,
        )
        self.count_anomaly_admission_overload = p.new_counter(
            OBS_ANOMALY_ADMISSION_OVERLOAD_KEY,
            "Ingress-admission-overload detector firings.",
            ln,
        )
        self.count_anomaly_dedup_storm = p.new_counter(
            OBS_ANOMALY_DEDUP_STORM_KEY,
            "Ingress duplicate-retry-storm detector firings.",
            ln,
        )
        self.count_anomaly_engine_degraded = p.new_counter(
            OBS_ANOMALY_ENGINE_DEGRADED_KEY,
            "Engine-degraded detector firings (supervised engine below its "
            "configured rung).",
            ln,
        )
        self.count_anomaly_wal_corruption = p.new_counter(
            OBS_ANOMALY_WAL_CORRUPTION_KEY,
            "WAL-corruption detector firings (quarantine or learner fence).",
            ln,
        )
        self.count_anomaly_wal_stall = p.new_counter(
            OBS_ANOMALY_WAL_STALL_KEY,
            "WAL-stall detector firings (degraded: appends refused).",
            ln,
        )
        self.count_anomaly_cross_group_stall = p.new_counter(
            OBS_ANOMALY_CROSS_GROUP_STALL_KEY,
            "Cross-group-stall detector firings (a 2PC transaction stuck "
            "unresolved past the stall window).",
            ln,
        )
        self.count_anomaly_wire_abuse = p.new_counter(
            OBS_ANOMALY_WIRE_ABUSE_KEY,
            "Wire-abuse detector firings (a listener booked new guard "
            "defense events since the last sample).",
            ln,
        )

    def anomaly_counter(self, kind: str) -> Counter:
        """The pinned counter for detector ``kind`` (its short name, e.g.
        ``commit_stall``) — fails loudly on an unknown kind."""
        return getattr(self, f"count_anomaly_{kind}")


class MetricsMembership(_Bundle):
    """Membership-epoch instruments — consensus_tpu addition, fed by the
    facade's epoch gate (consensus.py) and the joining-node bootstrap driver
    (membership/bootstrap.py).  The epoch gauge tracks the configuration a
    replica is SERVING (it lags the cluster's newest epoch while the replica
    is catching up); stale-epoch drops count ingress traffic carrying a
    different epoch — a removed node's zombie sends land here instead of
    perturbing the protocol."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.epoch = p.new_gauge(
            MEMBERSHIP_EPOCH_KEY,
            "Membership epoch this replica is serving.",
            ln,
        )
        self.count_stale_epoch_dropped = p.new_counter(
            MEMBERSHIP_STALE_EPOCH_DROPPED_KEY,
            "Inbound messages dropped at ingress for carrying another epoch "
            "or a non-member sender.",
            ln,
        )
        self.count_join_attempts = p.new_counter(
            MEMBERSHIP_JOIN_ATTEMPTS_KEY,
            "Join-bootstrap sync attempts (first try included).",
            ln,
        )
        self.count_join_retries = p.new_counter(
            MEMBERSHIP_JOIN_RETRIES_KEY,
            "Join-bootstrap sync retries (attempts after the first).",
            ln,
        )


class MetricsSidecar(_Bundle):
    """Multi-tenant verification-sidecar instruments — consensus_tpu
    addition, fed by ``net.sidecar.VerifySidecarServer``.  Per-tenant series
    are children of these pinned names (``with_labels(tenant)`` ->
    ``name{tenant}`` in the in-memory provider), so the aggregate names stay
    stable for dashboards while isolation tests can read one tenant out."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_admission_accepted = p.new_counter(
            SIDECAR_ADMISSION_ACCEPTED_KEY,
            "Verification batches admitted to a tenant queue.",
            ln,
        )
        self.count_admission_rejects = p.new_counter(
            SIDECAR_ADMISSION_REJECTS_KEY,
            "Batches rejected at admission because the tenant queue was full.",
            ln,
        )
        self.admission_queue_depth = p.new_gauge(
            SIDECAR_ADMISSION_QUEUE_DEPTH_KEY,
            "Signatures queued across tenant queues at the last admission.",
            ln,
        )
        self.count_wave_launches = p.new_counter(
            SIDECAR_WAVE_LAUNCHES_KEY,
            "Cross-tenant waves launched on the sidecar engine.",
            ln,
        )
        self.count_wave_signatures = p.new_counter(
            SIDECAR_WAVE_SIGNATURES_KEY,
            "Signatures verified across all sidecar waves.",
            ln,
        )
        self.count_wave_tenants = p.new_counter(
            SIDECAR_WAVE_TENANTS_KEY,
            "Tenants sharing a wave, summed over waves.",
            ln,
        )


class MetricsIngress(_Bundle):
    """Ingress-plane instruments — consensus_tpu addition, fed by the
    admission layer (ingress/admission.py), the placement fleet
    (ingress/placement.py), and the open-loop trace driver
    (ingress/driver.py).  ``offered = admitted + rate_limited + dedup_hits``
    holds by construction; the reroute counter tracks structured
    ``TenantAdmissionReject`` retries hopping to the hash ring's next
    candidate."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_offered = p.new_counter(
            INGRESS_OFFERED_KEY,
            "Client requests offered to the ingress admission layer.",
            ln,
        )
        self.count_admitted = p.new_counter(
            INGRESS_ADMITTED_KEY,
            "Client requests admitted past rate limiting and dedup.",
            ln,
        )
        self.count_rate_limited = p.new_counter(
            INGRESS_RATE_LIMITED_KEY,
            "Client requests rejected by the per-client token bucket.",
            ln,
        )
        self.count_dedup_hits = p.new_counter(
            INGRESS_DEDUP_HITS_KEY,
            "Duplicate client requests absorbed by the dedup cache.",
            ln,
        )
        self.count_reroutes = p.new_counter(
            INGRESS_REROUTE_KEY,
            "Admitted batches rerouted to the next fleet candidate after a "
            "structured admission reject.",
            ln,
        )
        self.fleet_size = p.new_gauge(
            INGRESS_FLEET_SIZE_KEY,
            "Verifier fleet servers currently in the placement ring.",
            ln,
        )
        self.commit_latency = p.new_histogram(
            INGRESS_COMMIT_LATENCY_KEY,
            "Sim-seconds from open-loop arrival to fleet commit.",
            ln,
        )


class MetricsEngine(_Bundle):
    """Engine-supervision instruments — consensus_tpu addition, fed by
    ``models.supervisor.EngineSupervisor``.  Per-fault-class degrade series
    are children of the pinned degrade name (``with_labels(reason)`` ->
    ``engine_degrade_total{reason}`` in the in-memory provider); the rung
    gauge tracks where on the ladder the supervisor is currently serving
    (0 = as configured, last rung = host twin)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_degrade = p.new_counter(
            ENGINE_DEGRADE_KEY,
            "Supervised engine degrades down the ladder.",
            extend_label_names(("reason",), label_names),
        )
        self.count_recovered = p.new_counter(
            ENGINE_RECOVERED_KEY,
            "Supervised engine re-promotions after a breaker re-closed.",
            ln,
        )
        self.count_crosscheck = p.new_counter(
            ENGINE_CROSSCHECK_KEY,
            "Sampled host cross-checks run against device verdicts.",
            ln,
        )
        self.count_crosscheck_mismatch = p.new_counter(
            ENGINE_CROSSCHECK_MISMATCH_KEY,
            "Host cross-checks that contradicted the device verdict.",
            ln,
        )
        self.rung = p.new_gauge(
            ENGINE_RUNG_KEY,
            "Current degrade-ladder rung (0 = as configured).",
            ln,
        )
        self.count_compile_cache_hits = p.new_counter(
            ENGINE_COMPILE_CACHE_HITS_KEY,
            "Engine constructions that reused a memoized compiled kernel.",
            ln,
        )
        self.count_compile_cache_misses = p.new_counter(
            ENGINE_COMPILE_CACHE_MISSES_KEY,
            "Engine constructions that traced a kernel fresh.",
            ln,
        )


class MetricsGroups(_Bundle):
    """Consensus-sharding instruments — consensus_tpu addition, fed by the
    ingress :class:`~consensus_tpu.groups.router.GroupRouter` (routed
    counter + directory gauge), the shared
    :class:`~consensus_tpu.models.engine.FairShareWaveFormer` (one wave-span
    observation per fused launch; the multi-group counter bumps when a
    launch serves two or more groups — the cross-GROUP coalescing win), and
    the cross-group 2PC machinery (started/committed/aborted lifecycle)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.count_routed = p.new_counter(
            GROUPS_ROUTED_KEY,
            "Admitted requests routed to their owning consensus group.",
            ln,
        )
        self.group_count = p.new_gauge(
            GROUPS_COUNT_KEY,
            "Consensus groups currently in the placement directory.",
            ln,
        )
        self.wave_span = p.new_histogram(
            GROUPS_WAVE_SPAN_KEY,
            "Distinct consensus groups sharing one fused verify launch.",
            ln,
        )
        self.count_wave_multi_group = p.new_counter(
            GROUPS_WAVE_MULTI_KEY,
            "Fused verify launches serving two or more groups.",
            ln,
        )
        self.count_twopc_started = p.new_counter(
            GROUPS_TWOPC_STARTED_KEY,
            "Cross-group atomic transactions entering the prepare phase.",
            ln,
        )
        self.count_twopc_committed = p.new_counter(
            GROUPS_TWOPC_COMMITTED_KEY,
            "Cross-group atomic transactions decided commit by every group.",
            ln,
        )
        self.count_twopc_aborted = p.new_counter(
            GROUPS_TWOPC_ABORTED_KEY,
            "Cross-group atomic transactions decided abort by every group.",
            ln,
        )


class MetricsViewChange(_Bundle):
    """Parity: reference pkg/api/metrics.go:548-578 (3 instruments)."""

    def __init__(self, p: Provider, label_names: Sequence[str] = ()) -> None:
        ln = extend_label_names((), label_names)
        self.current_view = p.new_gauge(
            "viewchange_current_view", "View-changer current view.", ln
        )
        self.next_view = p.new_gauge(
            "viewchange_next_view", "View being changed to.", ln
        )
        self.real_view = p.new_gauge(
            "viewchange_real_view", "Last installed view.", ln
        )


class Metrics:
    """The full bundle set handed through the facade.

    Parity: reference pkg/api/metrics.go:70-104."""

    def __init__(
        self,
        provider: Optional[Provider] = None,
        label_names: Sequence[str] = (),
    ) -> None:
        provider = provider or NoopProvider()
        self.provider = provider
        self.request_pool = MetricsRequestPool(provider, label_names)
        self.blacklist = MetricsBlacklist(provider, label_names)
        self.consensus = MetricsConsensus(provider, label_names)
        self.view = MetricsView(provider, label_names)
        self.view_change = MetricsViewChange(provider, label_names)
        self.wal = MetricsWAL(provider, label_names)
        self.sync = MetricsSync(provider, label_names)
        self.network = MetricsNetwork(provider, label_names)
        self.obs = MetricsObs(provider, label_names)
        self.membership = MetricsMembership(provider, label_names)
        self.sidecar = MetricsSidecar(provider, label_names)
        self.ingress = MetricsIngress(provider, label_names)
        self.engine = MetricsEngine(provider, label_names)
        self.groups = MetricsGroups(provider, label_names)

    def with_labels(self, *values: str) -> "Metrics":
        """Bind embedder label values on every bundle (e.g. the channel id).
        Values are positional in SORTED label-name order (the order
        ``extend_label_names`` stores them).  Parity: reference per-bundle
        With()."""
        import copy

        clone = copy.copy(self)
        for k, v in vars(self).items():
            if isinstance(v, _Bundle):
                setattr(clone, k, v.with_labels(*values))
        return clone


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Provider",
    "NoopProvider",
    "InMemoryProvider",
    "Metrics",
    "MetricsRequestPool",
    "MetricsBlacklist",
    "MetricsConsensus",
    "MetricsView",
    "MetricsViewChange",
    "MetricsWAL",
    "MetricsSync",
    "MetricsNetwork",
    "MetricsObs",
    "MetricsMembership",
    "MetricsSidecar",
    "MetricsIngress",
    "MetricsEngine",
    "MetricsGroups",
    "extend_label_names",
    "VERIFY_LAUNCH_BATCH_KEY",
    "WAL_RECORDS_PER_FSYNC_KEY",
    "NET_DROPPED_KEY",
    "NET_DUPLICATED_KEY",
    "NET_REORDERED_KEY",
    "NET_REPLAYED_KEY",
    "NET_INJECTED_KEYS",
    "NET_RECONNECT_ATTEMPTS_KEY",
    "NET_RECONNECT_SUCCESS_KEY",
    "NET_SEND_RETRIED_KEY",
    "NET_SEND_DROPPED_KEY",
    "NET_RECONNECT_KEYS",
    "NET_MALFORMED_KEY",
    "NET_HANDSHAKE_TIMEOUT_KEY",
    "NET_PEER_BANNED_KEY",
    "NET_CONN_REJECTED_KEY",
    "NET_ABUSE_KEYS",
    "OBS_SAMPLES_KEY",
    "OBS_ANOMALY_COMMIT_STALL_KEY",
    "OBS_ANOMALY_VIEW_CHANGE_STORM_KEY",
    "OBS_ANOMALY_LEADER_FLAP_KEY",
    "OBS_ANOMALY_SYNC_LAG_KEY",
    "OBS_ANOMALY_VERIFY_COLLAPSE_KEY",
    "OBS_ANOMALY_MEMBERSHIP_CHURN_KEY",
    "OBS_ANOMALY_ADMISSION_OVERLOAD_KEY",
    "OBS_ANOMALY_DEDUP_STORM_KEY",
    "OBS_ANOMALY_ENGINE_DEGRADED_KEY",
    "OBS_ANOMALY_WAL_CORRUPTION_KEY",
    "OBS_ANOMALY_WAL_STALL_KEY",
    "OBS_ANOMALY_CROSS_GROUP_STALL_KEY",
    "OBS_ANOMALY_WIRE_ABUSE_KEY",
    "OBS_ANOMALY_KEYS",
    "WAL_FSYNC_RETRY_KEY",
    "WAL_SCRUB_RUNS_KEY",
    "WAL_SCRUB_RECORDS_KEY",
    "WAL_SCRUB_CORRUPTIONS_KEY",
    "WAL_QUARANTINE_KEY",
    "WAL_DEGRADED_KEY",
    "WAL_DEGRADED_TOTAL_KEY",
    "WAL_STORAGE_KEYS",
    "INGRESS_OFFERED_KEY",
    "INGRESS_ADMITTED_KEY",
    "INGRESS_RATE_LIMITED_KEY",
    "INGRESS_DEDUP_HITS_KEY",
    "INGRESS_REROUTE_KEY",
    "INGRESS_FLEET_SIZE_KEY",
    "INGRESS_COMMIT_LATENCY_KEY",
    "INGRESS_KEYS",
    "MEMBERSHIP_EPOCH_KEY",
    "MEMBERSHIP_STALE_EPOCH_DROPPED_KEY",
    "MEMBERSHIP_JOIN_ATTEMPTS_KEY",
    "MEMBERSHIP_JOIN_RETRIES_KEY",
    "MEMBERSHIP_KEYS",
    "SIDECAR_ADMISSION_ACCEPTED_KEY",
    "SIDECAR_ADMISSION_REJECTS_KEY",
    "SIDECAR_ADMISSION_QUEUE_DEPTH_KEY",
    "SIDECAR_WAVE_LAUNCHES_KEY",
    "SIDECAR_WAVE_SIGNATURES_KEY",
    "SIDECAR_WAVE_TENANTS_KEY",
    "SIDECAR_KEYS",
    "WAL_CERT_BYTES_KEY",
    "SYNC_CERT_BYTES_KEY",
    "NET_CERT_BYTES_KEY",
    "CERT_BYTES_PER_CERT_KEY",
    "CERT_AGGREGATE_LAUNCHES_KEY",
    "CERT_FALLBACK_BISECTIONS_KEY",
    "CERT_KEYS",
    "ENGINE_DEGRADE_KEY",
    "ENGINE_RECOVERED_KEY",
    "ENGINE_CROSSCHECK_KEY",
    "ENGINE_CROSSCHECK_MISMATCH_KEY",
    "ENGINE_RUNG_KEY",
    "ENGINE_KEYS",
    "GROUPS_ROUTED_KEY",
    "GROUPS_COUNT_KEY",
    "GROUPS_WAVE_SPAN_KEY",
    "GROUPS_WAVE_MULTI_KEY",
    "GROUPS_TWOPC_STARTED_KEY",
    "GROUPS_TWOPC_COMMITTED_KEY",
    "GROUPS_TWOPC_ABORTED_KEY",
    "GROUPS_KEYS",
    "PINNED_METRIC_KEYS",
]
