"""Metrics: provider abstraction, no-op and in-memory implementations, and
the five instrument bundles the protocol reports into.

Parity: reference pkg/metrics/provider.go:11-18 (Provider / Counter / Gauge /
Histogram), pkg/metrics/disabled/provider.go (no-op), and
pkg/api/metrics.go:70-578 (the 5 bundles / 28 instruments, same names).
An embedder passes its own Provider (e.g. Prometheus-backed) to the facade;
the default is no-op.
"""

from __future__ import annotations

import abc
from typing import Optional


class Counter(abc.ABC):
    @abc.abstractmethod
    def add(self, delta: float = 1.0) -> None: ...


class Gauge(abc.ABC):
    @abc.abstractmethod
    def set(self, value: float) -> None: ...

    @abc.abstractmethod
    def add(self, delta: float = 1.0) -> None: ...


class Histogram(abc.ABC):
    @abc.abstractmethod
    def observe(self, value: float) -> None: ...


class Provider(abc.ABC):
    """Parity: reference pkg/metrics/provider.go:11-18."""

    @abc.abstractmethod
    def new_counter(self, name: str, help: str = "") -> Counter: ...

    @abc.abstractmethod
    def new_gauge(self, name: str, help: str = "") -> Gauge: ...

    @abc.abstractmethod
    def new_histogram(self, name: str, help: str = "") -> Histogram: ...


class _NoopInstrument(Counter, Gauge, Histogram):
    def add(self, delta: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


class NoopProvider(Provider):
    """Parity: reference pkg/metrics/disabled/provider.go:13-17."""

    _instrument = _NoopInstrument()

    def new_counter(self, name: str, help: str = "") -> Counter:
        return self._instrument

    def new_gauge(self, name: str, help: str = "") -> Gauge:
        return self._instrument

    def new_histogram(self, name: str, help: str = "") -> Histogram:
        return self._instrument


class _MemInstrument(Counter, Gauge, Histogram):
    def __init__(self) -> None:
        self.value = 0.0
        self.observations: list[float] = []

    def add(self, delta: float = 1.0) -> None:
        self.value += delta

    def set(self, value: float) -> None:
        self.value = value

    def observe(self, value: float) -> None:
        self.observations.append(value)


class InMemoryProvider(Provider):
    """Collects values in plain dicts — for tests and the bench harness."""

    def __init__(self) -> None:
        self.instruments: dict[str, _MemInstrument] = {}

    def _get(self, name: str) -> _MemInstrument:
        return self.instruments.setdefault(name, _MemInstrument())

    def new_counter(self, name: str, help: str = "") -> Counter:
        return self._get(name)

    def new_gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name)

    def new_histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(name)

    def value(self, name: str) -> float:
        # Strict read: a misspelled/unwired name fails instead of
        # vacuously returning 0.
        return self.instruments[name].value

    def observations(self, name: str) -> list[float]:
        return self.instruments[name].observations


# --- instrument bundles (names mirror reference pkg/api/metrics.go) --------


class MetricsRequestPool:
    """Parity: reference pkg/api/metrics.go:172-237 (7 instruments)."""

    def __init__(self, p: Provider) -> None:
        self.count_of_elements = p.new_gauge(
            "pool_count_of_elements", "Number of elements in the consensus request pool."
        )
        self.count_of_elements_all = p.new_counter(
            "pool_count_of_elements_all", "Total amount of elements in the pool."
        )
        self.count_of_fail_add_request = p.new_counter(
            "pool_count_of_fail_add_request", "Submissions the pool rejected."
        )
        self.count_of_delete_request = p.new_counter(
            "pool_count_of_delete_request", "Elements removed from the pool."
        )
        self.count_leader_forward_request = p.new_counter(
            "pool_count_leader_forward_request", "Requests forwarded to the leader."
        )
        self.count_timeout_two_step = p.new_counter(
            "pool_count_timeout_two_step", "Complaint-stage timeouts."
        )
        self.latency_of_elements = p.new_histogram(
            "pool_latency_of_elements", "Time requests spend in the pool."
        )


class MetricsBlacklist:
    """Parity: reference pkg/api/metrics.go:258-297 (2 instruments)."""

    def __init__(self, p: Provider) -> None:
        self.count = p.new_gauge("blacklist_count", "Nodes in the blacklist.")
        self.node_id_in_blacklist = p.new_gauge(
            "node_id_in_blacklist", "Whether this node id is blacklisted."
        )


class MetricsConsensus:
    """Parity: reference pkg/api/metrics.go:319-344 (2 instruments)."""

    def __init__(self, p: Provider) -> None:
        self.count_consensus_reconfig = p.new_counter(
            "consensus_reconfig", "Reconfigurations applied."
        )
        self.latency_sync = p.new_histogram(
            "consensus_latency_sync", "Duration of synchronization rounds."
        )


class MetricsView:
    """Parity: reference pkg/api/metrics.go:448-518 (12 instruments)."""

    def __init__(self, p: Provider) -> None:
        self.view_number = p.new_gauge("view_number", "Current view number.")
        self.leader_id = p.new_gauge("view_leader_id", "Current leader id.")
        self.proposal_sequence = p.new_gauge(
            "view_proposal_sequence", "In-progress proposal sequence."
        )
        self.decisions_in_view = p.new_gauge(
            "view_decisions", "Decisions made in the current view."
        )
        self.phase = p.new_gauge("view_phase", "Current 3-phase state.")
        self.count_txs_in_batch = p.new_gauge(
            "view_count_txs_in_batch", "Transactions in the current batch."
        )
        self.count_batch_all = p.new_counter(
            "view_count_batch_all", "Batches decided in total."
        )
        self.count_txs_all = p.new_counter(
            "view_count_txs_all", "Transactions decided in total."
        )
        self.size_of_batch = p.new_counter("view_size_batch", "Decided bytes in total.")
        self.latency_batch_processing = p.new_histogram(
            "view_latency_batch_processing", "Pre-prepare to commit latency."
        )
        self.latency_batch_save = p.new_histogram(
            "view_latency_batch_save", "Application delivery latency."
        )
        self.count_batch_sig_verifications = p.new_counter(
            "view_count_batch_sig_verifications",
            "Signature verifications drained into device batches "
            "(consensus_tpu addition: the TPU offload volume).",
        )


class MetricsViewChange:
    """Parity: reference pkg/api/metrics.go:548-578 (3 instruments)."""

    def __init__(self, p: Provider) -> None:
        self.current_view = p.new_gauge("viewchange_current_view", "View-changer current view.")
        self.next_view = p.new_gauge("viewchange_next_view", "View being changed to.")
        self.real_view = p.new_gauge("viewchange_real_view", "Last installed view.")


class Metrics:
    """The full bundle set handed through the facade.

    Parity: reference pkg/api/metrics.go:70-104."""

    def __init__(self, provider: Optional[Provider] = None) -> None:
        provider = provider or NoopProvider()
        self.provider = provider
        self.request_pool = MetricsRequestPool(provider)
        self.blacklist = MetricsBlacklist(provider)
        self.consensus = MetricsConsensus(provider)
        self.view = MetricsView(provider)
        self.view_change = MetricsViewChange(provider)


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Provider",
    "NoopProvider",
    "InMemoryProvider",
    "Metrics",
    "MetricsRequestPool",
    "MetricsBlacklist",
    "MetricsConsensus",
    "MetricsView",
    "MetricsViewChange",
]
