"""Replica configuration: batching, pool, timeout cascade, view-change and
heartbeat tuning, rotation, and the TPU crypto-batching knobs.

Parity: reference pkg/types/config.go:15-188 (Configuration, DefaultConfig,
Validate).  Times are float seconds (the runtime clock is injectable, so tests
use a simulated clock rather than shrinking these).  TPU-specific additions
(`crypto_*`) are new — they tune the batch signature-verification engine and
have no reference counterpart.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class TraceConfig:
    """Decision-lifecycle tracing knob (no reference counterpart).

    Default-off; when enabled the consensus facade builds a
    ``trace.Tracer`` over the injected scheduler clock, so traces stay
    deterministic under ``SimScheduler``.  ``capacity`` bounds the event
    ring — oldest events are overwritten, memory never grows.
    """

    enabled: bool = False
    capacity: int = 65536


@dataclass(frozen=True)
class ObsConfig:
    """Cluster observability plane knob (no reference counterpart).

    Default-off, like :class:`TraceConfig`.  When enabled the test harness
    (``testing.app.Cluster(obs=...)``) installs an in-memory metrics
    provider on every node and arms a :class:`~consensus_tpu.obs.sampler.
    ClusterSampler` on the shared scheduler: every ``sample_interval``
    sim-seconds it snapshots each node's ``Metrics.dump()`` plus derived
    health fields into a bounded ring of ``ring_capacity`` samples and
    evaluates the anomaly detectors.  ``flight_samples`` bounds how many
    trailing samples a flight-recorder bundle carries.
    """

    enabled: bool = False
    sample_interval: float = 1.0
    ring_capacity: int = 4096
    flight_samples: int = 64
    #: Optional ``consensus_tpu.obs.detectors.DetectorThresholds`` override
    #: (held opaque here: config must not import the obs package).
    detector_thresholds: object = None

    def validate(self) -> None:
        errs = []
        if self.sample_interval <= 0:
            errs.append("obs.sample_interval must be positive")
        if self.ring_capacity < 1:
            errs.append("obs.ring_capacity must be >= 1")
        if self.flight_samples < 1:
            errs.append("obs.flight_samples must be >= 1")
        if errs:
            raise ValueError("invalid configuration: " + "; ".join(errs))


@dataclass(frozen=True)
class CompileCacheConfig:
    """Compilation-cache knobs for the batch crypto engines (no reference
    counterpart).

    ``enabled`` governs the in-process compiled-kernel memo
    (parallel/sharding.py ``compiled_kernel``): engines built over the same
    ``(kernel, topology[, shape])`` key share one traced jit wrapper, so a
    fleet restart or supervisor ladder rebuild books ZERO new compiles in
    the kernel ledger instead of a retrace storm.  ``persistent_dir`` (when
    non-empty) additionally wires jax's persistent compilation cache to
    that directory via :func:`consensus_tpu.parallel.topology.
    apply_compile_cache`, so even a fresh PROCESS skips the XLA backend
    compile; ``min_compile_time_secs`` filters which compiles are worth
    persisting.  Both caches change only construction latency, never
    verdicts.
    """

    enabled: bool = True
    persistent_dir: str = ""
    min_compile_time_secs: float = 1.0

    def validate(self) -> None:
        if self.min_compile_time_secs < 0:
            raise ValueError(
                "invalid configuration: "
                "compile_cache.min_compile_time_secs must be >= 0"
            )


@dataclass(frozen=True)
class Configuration:
    # --- identity -------------------------------------------------------
    self_id: int = 0

    # --- batching (leader) ---------------------------------------------
    # Parity: reference pkg/types/config.go:21-29,94-96 (defaults 100 / 10MB / 50ms).
    request_batch_max_count: int = 100
    request_batch_max_bytes: int = 10 * 1024 * 1024
    request_batch_max_interval: float = 0.050

    # --- message ingress ------------------------------------------------
    incoming_message_buffer_size: int = 200

    # --- request pool + timeout cascade ---------------------------------
    # Parity: reference pkg/types/config.go:37-55.
    request_pool_size: int = 400
    request_max_bytes: int = 10 * 1024
    request_forward_timeout: float = 2.0
    request_complain_timeout: float = 20.0
    request_auto_remove_timeout: float = 180.0
    submit_timeout: float = 5.0

    # --- view change ----------------------------------------------------
    # Parity: reference pkg/types/config.go:57-66.
    view_change_resend_interval: float = 5.0
    view_change_timeout: float = 20.0
    speed_up_view_change: bool = False

    # --- heartbeats / failure detection ---------------------------------
    # Parity: reference pkg/types/config.go:68-75.
    leader_heartbeat_timeout: float = 60.0
    leader_heartbeat_count: int = 10
    num_of_ticks_behind_before_syncing: int = 10

    # --- state transfer -------------------------------------------------
    collect_timeout: float = 1.0

    # --- leader rotation ------------------------------------------------
    # Parity: reference pkg/types/config.go:77-84,109-111 (defaults: rotation
    # on, 3 decisions per leader).
    leader_rotation: bool = True
    decisions_per_leader: int = 3

    # --- lifecycle ------------------------------------------------------
    sync_on_start: bool = False

    # --- decision pipelining (no reference counterpart) -----------------
    # Bounded window of in-flight proposal slots.  1 keeps the reference's
    # single-in-flight semantics; >1 lets the leader pre-prepare seq n+1
    # before decide(n) while commit/delivery stay sequence-ordered.
    # Pipelining requires a static leader: rotation counts decisions per
    # leader against checkpoint certificates that a pipelined window does
    # not produce in order, so depth > 1 demands leader_rotation off.
    pipeline_depth: int = 1

    # --- TPU crypto engine (no reference counterpart) -------------------
    # Minimum number of pending verifications before the engine prefers the
    # TPU path over the CPU fallback, and the micro-batch coalescing window.
    crypto_tpu_min_batch: int = 16
    crypto_batch_window: float = 0.002
    # Pad verification batches up to the next power of two (stable XLA shapes,
    # avoids recompilation across batch sizes).
    crypto_pad_pow2: bool = True
    # Randomized Ed25519 batch verification (one shared-doubling aggregate
    # check per batch, bisection fallback on failure — models/ed25519.py
    # Ed25519RandomizedBatchVerifier).  Default off: all replicas in a
    # cluster must agree on this flag, since batch verdicts on adversarial
    # torsion-component signatures can differ from the strict kernel's
    # (SAFETY.md §7).
    batch_verify_mode: bool = False
    # Quorum-certificate encoding (models/aggregate.py).  "full" keeps the
    # seed's n-full-signature certs bit-for-bit; "half-agg" assembles
    # half-aggregated Ed25519 certs — (R₁..Rₙ, s_agg), ~32n+32 bytes
    # instead of ~64n — on the wire, in the WAL, in view-change proofs,
    # and in sync chunks, verified in ONE MSM launch.  All replicas in a
    # cluster must agree on this flag (a half-agg cert is not verifiable
    # by a full-mode replica's strict path and vice versa — the
    # multi-batch contradiction guard fails loud on mixed groups).
    cert_mode: str = "full"
    # Whole-pipeline-on-device verification (models/fused.py): the engine's
    # host prep (SHA-512 challenge hashing, mod-L reduction, canonical-range
    # checks, digit recoding) moves into the verify launch itself — the host
    # only slices bytes into SHA-512 block layout.  Verdicts are bit-identical
    # to the host-prep engines on every accept/reject class (SAFETY.md §10),
    # so like mesh_shards this knob changes only WHERE the work runs, never
    # the verdict — replicas in a cluster may differ freely.  Ed25519-only
    # (engine_for_config rejects device_prep with the p256 curve).
    device_prep: bool = False
    # Device-mesh width for the batch engine (parallel/sharding.py): 1 keeps
    # today's single-device engines bit-for-bit; >1 selects the sharded
    # engines (shard_map over a 1-D mesh, batch axis partitioned, validity
    # reduced with one psum).  All replicas in a cluster may pick DIFFERENT
    # shard counts freely — sharding changes only the launch topology, never
    # the verdict (the host-mesh parity gate pins this).
    mesh_shards: int = 1
    # Device-mesh TOPOLOGY for the batch engine (parallel/topology.py): ()
    # defers to mesh_shards (a 1-D mesh); a non-empty tuple of per-axis
    # device counts — (2, 4) lays 8 devices out as a named ("slice",
    # "batch") 2-D mesh — selects an N-D layout at the same shard count.
    # Like mesh_shards this is per-replica free: topology changes which ICI
    # links the reduction tree rides, never the per-lane math or the
    # verdict (the 2-D host-mesh parity gate pins this).  When both are
    # set, the axes product must equal mesh_shards.
    mesh_topology: tuple = ()
    # Engine compilation caching (CompileCacheConfig above): default-on
    # in-process kernel memo + optional persistent XLA cache directory.
    compile_cache: CompileCacheConfig = field(default=CompileCacheConfig())
    # Engine supervision (models/supervisor.py): wrap the configured engine
    # in an EngineSupervisor — fault-classed circuit breakers (launch
    # timeout / launch raise / wrong answer) over an explicit degrade
    # ladder (fused → unfused device → host twin; N mesh shards → single
    # device → host) with automatic re-promotion.  Like mesh_shards and
    # device_prep this changes only WHERE verification runs, never the
    # verdict (a degraded rung and the host twin are verdict-identical —
    # SAFETY.md §12), so replicas may differ freely.
    engine_supervision: bool = False
    # Sampled host cross-check cadence under supervision: every k-th launch
    # is recomputed on the big-int host twin and a contradiction trips the
    # wrong-answer breaker (0 = off).  Launch-counter based, never random,
    # so fixed-seed runs cross-check identical launches every replay.
    engine_crosscheck_interval: int = 0

    # --- membership epochs (no reference counterpart) -------------------
    # Stamp outbound consensus traffic with the sender's membership epoch
    # (wire.EpochTagged) and drop inbound traffic from other epochs at the
    # facade ingress — counted under the pinned membership_stale_epoch_
    # dropped metric, with a trace instant, instead of corrupting
    # collectors or provoking spurious view changes.  Default off: tagging
    # wraps every wire message, so all replicas in a cluster must agree on
    # this flag (a tagged message is still UNWRAPPED by a non-tagging
    # receiver, but an untagged sender gets no protection).
    epoch_tagging: bool = False

    # --- decision-lifecycle tracing (no reference counterpart) ----------
    trace: TraceConfig = field(default=TraceConfig())

    def validate(self) -> None:
        """Cross-field validation. Parity: reference pkg/types/config.go:116-188."""
        errs = []
        if self.self_id == 0:
            errs.append("self_id must be set (nonzero)")
        if self.request_batch_max_count <= 0:
            errs.append("request_batch_max_count must be positive")
        if self.request_batch_max_bytes <= 0:
            errs.append("request_batch_max_bytes must be positive")
        if self.request_batch_max_interval <= 0:
            errs.append("request_batch_max_interval must be positive")
        if self.request_max_bytes <= 0:
            errs.append("request_max_bytes must be positive")
        if self.request_batch_max_bytes < self.request_max_bytes:
            errs.append("request_batch_max_bytes must be >= request_max_bytes")
        if self.incoming_message_buffer_size <= 0:
            errs.append("incoming_message_buffer_size must be positive")
        if self.request_pool_size <= 0:
            errs.append("request_pool_size must be positive")
        if self.submit_timeout <= 0:
            errs.append("submit_timeout must be positive")
        if self.request_forward_timeout <= 0:
            errs.append("request_forward_timeout must be positive")
        if self.request_complain_timeout <= 0:
            errs.append("request_complain_timeout must be positive")
        if self.request_auto_remove_timeout <= 0:
            errs.append("request_auto_remove_timeout must be positive")
        if not (
            self.request_forward_timeout
            <= self.request_complain_timeout
            <= self.request_auto_remove_timeout
        ):
            errs.append(
                "timeout cascade must satisfy forward <= complain <= auto_remove"
            )
        if self.view_change_resend_interval <= 0:
            errs.append("view_change_resend_interval must be positive")
        if self.view_change_timeout <= 0:
            errs.append("view_change_timeout must be positive")
        if self.view_change_resend_interval > self.view_change_timeout:
            errs.append("view_change_resend_interval must be <= view_change_timeout")
        if self.leader_heartbeat_timeout <= 0:
            errs.append("leader_heartbeat_timeout must be positive")
        if self.leader_heartbeat_count <= 0:
            errs.append("leader_heartbeat_count must be positive")
        if self.num_of_ticks_behind_before_syncing <= 0:
            errs.append("num_of_ticks_behind_before_syncing must be positive")
        if self.collect_timeout <= 0:
            errs.append("collect_timeout must be positive")
        if self.leader_rotation and self.decisions_per_leader <= 0:
            errs.append("decisions_per_leader must be positive when rotating")
        if not self.leader_rotation and self.decisions_per_leader != 0:
            errs.append("decisions_per_leader must be zero when rotation is off")
        if self.pipeline_depth < 1:
            errs.append("pipeline_depth must be >= 1")
        if self.mesh_shards < 1:
            errs.append("mesh_shards must be >= 1")
        if self.mesh_topology:
            if any(int(a) < 1 for a in self.mesh_topology):
                errs.append("mesh_topology axes must all be >= 1")
            else:
                product = 1
                for a in self.mesh_topology:
                    product *= int(a)
                if self.mesh_shards != 1 and product != self.mesh_shards:
                    errs.append(
                        "mesh_topology axes product must equal mesh_shards "
                        "when both are set"
                    )
        try:
            self.compile_cache.validate()
        except ValueError as exc:
            errs.append(str(exc).replace("invalid configuration: ", ""))
        if self.engine_crosscheck_interval < 0:
            errs.append("engine_crosscheck_interval must be >= 0")
        if self.engine_crosscheck_interval and not self.engine_supervision:
            errs.append(
                "engine_crosscheck_interval requires engine_supervision"
            )
        if self.cert_mode not in ("full", "half-agg"):
            errs.append('cert_mode must be "full" or "half-agg"')
        if self.crypto_tpu_min_batch < 1:
            errs.append("crypto_tpu_min_batch must be >= 1")
        if self.pipeline_depth > 1 and self.leader_rotation:
            errs.append("pipeline_depth > 1 requires leader_rotation off")
        if self.trace.capacity < 1:
            errs.append("trace.capacity must be >= 1")
        if errs:
            raise ValueError("invalid configuration: " + "; ".join(errs))

    def with_(self, **kw) -> "Configuration":
        return replace(self, **kw)


def default_config(self_id: int) -> Configuration:
    """A validated default configuration for ``self_id``.

    Parity: reference pkg/types/config.go:93-114.
    """
    cfg = Configuration(self_id=self_id)
    cfg.validate()
    return cfg


__all__ = [
    "CompileCacheConfig",
    "Configuration",
    "ObsConfig",
    "TraceConfig",
    "default_config",
]
